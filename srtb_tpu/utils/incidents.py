"""Incident bundles: self-contained evidence dumps on escalation.

When a run escalates — :class:`LadderExhausted`,
:class:`ReinitBudgetExceeded`, :class:`WatchdogEscalation`, a wedged
sink, a failed fleet lane, manifest-recovery LOSS — the counters say
*that* it happened; the bundle says *what* happened: the flight
recorder's recent past, the offending segment's full causal trace, the
active plan identity, the config, a metrics snapshot and the last
journal spans, all in one directory an operator (or a bug report) can
carry away whole.

Layout (one directory per incident)::

    <incident_dir>/incident_NNN_<kind>/
        incident.json     kind, reason, wall time, trace_id, stream
        events.jsonl      flight-recorder tail (EventHub.dump format)
        trace.jsonl       events filtered to the offending trace_id
        plan.json         plan_name, plan_signature, ladder level
        config.json       full Config snapshot
        metrics.json      metrics registry snapshot
        extra.json        caller-provided evidence (only when given —
                          e.g. canary verdict + quality timeline)
        spans_tail.jsonl  last spans of the telemetry journal

Bundles are published ATOMICALLY with the repo's temp+rename
convention (the whole directory is assembled under ``.srtb_tmp`` and
renamed into place — a crash mid-dump leaves a temp dir the next
recorder construction sweeps, never a half-bundle that looks whole),
**rate-limited** (``incident_min_interval_s`` between bundles — an
escalation storm must not turn the incident dir into its own outage)
and **bounded in count** (``incident_max_bundles`` directories kept;
beyond that new incidents are counted as ``incidents_suppressed``
and only logged — the FIRST escalations of an outage carry the causal
story, and an unbounded dump directory on a wedged disk is exactly
the failure mode the pipeline is trying to survive).

Dumping is best-effort by contract: a failure to write a bundle logs
and counts (``incident_dump_failures``) but never masks or replaces
the escalation it was documenting.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import threading
import time

from srtb_tpu.utils import events
from srtb_tpu.utils.logging import log
from srtb_tpu.utils.metrics import metrics

# matches io/writers.TMP_SUFFIX (not imported: the recorder must stay
# importable without the sink stack)
TMP_SUFFIX = ".srtb_tmp"

BUNDLE_SCHEMA_VERSION = 1

# tail size of the journal snapshot: enough spans to cover the flight
# recorder's horizon without re-shipping a 64 MB journal per incident
SPANS_TAIL_LINES = 200


def _json_default(o):
    try:
        return list(o)
    except TypeError:
        return repr(o)


class IncidentRecorder:
    """Per-pipeline handle on the (filesystem-global) incident
    directory.  ``None`` when ``Config.incident_dir`` is empty — the
    zero-cost-off None-hook pattern shared with the sanitizer and
    fault injector."""

    # rate-limit state is keyed on the DIRECTORY, not the recorder
    # instance: N fleet lanes each own a recorder pointing at the same
    # incident_dir, and a fleet-wide outage (shared device halt) fails
    # them near-simultaneously — per-instance clocks would let N
    # duplicate bundles burn the whole bounded budget in one second,
    # exactly the storm the limiter exists to prevent
    _last_dump_by_dir: dict = {}
    _rate_lock = threading.Lock()

    def __init__(self, directory: str, max_bundles: int = 8,
                 min_interval_s: float = 30.0):
        self.directory = os.path.abspath(directory)
        self.max_bundles = max(1, int(max_bundles))
        self.min_interval_s = float(min_interval_s)
        os.makedirs(directory, exist_ok=True)
        # sweep half-assembled bundles from a previous life (the
        # atomic-rename contract: anything still under .srtb_tmp never
        # became a bundle)
        for name in os.listdir(directory):
            if name.endswith(TMP_SUFFIX):
                shutil.rmtree(os.path.join(directory, name),
                              ignore_errors=True)

    @classmethod
    def from_config(cls, cfg) -> "IncidentRecorder | None":
        d = str(getattr(cfg, "incident_dir", "") or "")
        if not d:
            return None
        return cls(
            d,
            max_bundles=int(getattr(cfg, "incident_max_bundles", 8)
                            or 8),
            min_interval_s=float(getattr(cfg, "incident_min_interval_s",
                                         30.0)))

    # ------------------------------------------------------- dumping

    def _existing(self) -> list[str]:
        try:
            return sorted(n for n in os.listdir(self.directory)
                          if n.startswith("incident_")
                          and not n.endswith(TMP_SUFFIX))
        except OSError:
            return []

    def dump(self, kind: str, reason: str = "",
             trace: int | None = None, stream: str = "",
             cfg=None, processor=None,
             journal_path: str = "",
             extra=None) -> str | None:
        """Write one bundle; returns its directory, or None when
        rate-limited / bounded / failed.  Never raises.  ``extra`` is
        an optional JSON-able payload landing as ``extra.json`` — the
        escalation site's own evidence (e.g. the canary verdict plus
        the recent quality timeline)."""
        try:
            return self._dump(kind, reason, trace, stream, cfg,
                              processor, journal_path, extra)
        except Exception as e:  # noqa: BLE001 - best-effort contract
            metrics.add("incident_dump_failures")
            log.error(f"[incident] bundle dump failed ({kind}): {e!r}")
            return None

    def _dump(self, kind, reason, trace, stream, cfg, processor,
              journal_path, extra=None) -> str | None:
        now = time.monotonic()
        with self._rate_lock:
            last = self._last_dump_by_dir.get(self.directory, 0.0)
            if last and now - last < self.min_interval_s:
                rate_limited = True
            else:
                # claim the slot atomically: two lanes failing in the
                # same instant must not both pass the check
                self._last_dump_by_dir[self.directory] = now
                rate_limited = False
        if rate_limited:
            metrics.add("incidents_suppressed")
            log.warning(f"[incident] {kind}: rate-limited "
                        f"(< {self.min_interval_s:g}s since the last "
                        "bundle)")
            return None
        existing = self._existing()
        if len(existing) >= self.max_bundles:
            # give the claimed rate slot back: a count-suppressed
            # attempt must not also rate-limit a later incident into
            # a dir the operator has since cleared
            with self._rate_lock:
                if self._last_dump_by_dir.get(self.directory) == now:
                    self._last_dump_by_dir[self.directory] = last
            metrics.add("incidents_suppressed")
            log.warning(
                f"[incident] {kind}: {len(existing)} bundle(s) already "
                f"kept (incident_max_bundles={self.max_bundles}); "
                "suppressing — the earliest escalations hold the story")
            return None
        if trace is None:
            trace = events.current()[0]
        seq = 0
        for name in existing:
            try:
                seq = max(seq, int(name.split("_")[1]) + 1)
            except (IndexError, ValueError):
                continue
        safe_kind = "".join(c if c.isalnum() or c in "-_" else "_"
                            for c in str(kind)) or "incident"
        final = os.path.join(self.directory,
                             f"incident_{seq:03d}_{safe_kind}")
        tmp = final + TMP_SUFFIX
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)

        def put(name: str, obj) -> None:
            with open(os.path.join(tmp, name), "w") as f:
                json.dump(obj, f, sort_keys=True, indent=1,
                          default=_json_default)
                f.write("\n")

        put("incident.json", {
            "schema": BUNDLE_SCHEMA_VERSION,
            "kind": str(kind),
            "reason": str(reason),
            "ts": time.time(),
            "trace_id": int(trace or 0),
            "stream": str(stream or ""),
            "pid": os.getpid(),
        })
        hub = events.hub
        n_ev = n_tr = 0
        if hub is not None:
            n_ev = hub.dump_jsonl(os.path.join(tmp, "events.jsonl"))
            if trace:
                n_tr = hub.dump_jsonl(os.path.join(tmp, "trace.jsonl"),
                                      trace=int(trace))
        if processor is not None:
            plan = {"plan_name": getattr(processor, "plan_name", None)}
            sig = getattr(processor, "plan_signature", None)
            if callable(sig):
                try:
                    plan["plan_signature"] = sig()
                except Exception as e:  # noqa: BLE001 - a retired
                    # processor raises loudly by design; the bundle
                    # still names the plan
                    plan["plan_signature"] = f"<unavailable: {e!r}>"
            # a fleet lane's bundle must report ITS OWN ladder level:
            # the flat gauge is last-writer-wins across lanes, so a
            # named stream reads its labeled twin
            plan["plan_ladder_level"] = int(metrics.get(
                "plan_ladder_level",
                labels={"stream": stream} if stream else None))
            put("plan.json", plan)
        if cfg is not None:
            try:
                snap = dataclasses.asdict(cfg)
            except TypeError:
                snap = {k: v for k, v in vars(cfg).items()
                        if not k.startswith("_")}
            put("config.json", snap)
        put("metrics.json", metrics.snapshot())
        if extra is not None:
            put("extra.json", extra)
        jp = journal_path or (getattr(cfg, "telemetry_journal_path", "")
                              if cfg is not None else "")
        if jp and os.path.exists(jp):
            try:
                # bounded tail read: the active journal can be tens
                # of MB, and the escalation path must not materialize
                # it whole — seek to a byte budget generous enough
                # for SPANS_TAIL_LINES spans and split there
                with open(jp, "rb") as f:
                    f.seek(0, os.SEEK_END)
                    size = f.tell()
                    budget = SPANS_TAIL_LINES * 4096
                    f.seek(max(0, size - budget))
                    chunk = f.read()
                lines = chunk.splitlines(keepends=True)
                if size > budget and lines:
                    lines = lines[1:]  # drop the torn first line
                with open(os.path.join(tmp, "spans_tail.jsonl"),
                          "wb") as f:
                    f.writelines(lines[-SPANS_TAIL_LINES:])
            except OSError as e:
                log.warning(f"[incident] journal tail unavailable: {e}")
        os.replace(tmp, final)
        metrics.add("incident_bundles")
        if stream:
            metrics.add("incident_bundles", labels={"stream": stream})
        events.emit("incident", trace=int(trace or 0),
                    stream=str(stream or ""),
                    info=os.path.basename(final))
        log.error(f"[incident] {kind}: bundle written to {final} "
                  f"({n_ev} events, {n_tr} on the offending trace)")
        return final
