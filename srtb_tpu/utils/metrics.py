"""Runtime metrics/observability.

The reference's observability is logs: packet-loss rates
(io/udp/udp_receiver.hpp:154-164), allocator sizes, per-pipe timestamps
(SURVEY.md §5.5).  Here metrics are first-class typed instruments:

- flat **counters/gauges** (``add``/``set``) covering the quantities
  BASELINE.md tracks (segments/s, Msamples/s, loss rate, detections);
- bounded-bucket **histograms** with interpolated p50/p95/p99 (per-stage
  wall-clock — the "profile per-stage, then attack the dominant pass"
  loop of PERF.md, always-on);
- **sliding windows** for rates over the last N seconds (a stalled
  observation shows 0 seg/s immediately instead of a slowly decaying
  lifetime average).

One registry (:data:`metrics`) feeds the JSON snapshot
(``/metrics.json``), the Prometheus text exposition (``/metrics``), and
the segment-span journal (utils/telemetry.py).
"""

from __future__ import annotations

import bisect
import collections
import json
import math
import re
import threading
import time

# Exponential-ish bounds from 0.5 ms to 2 min: host stage times span
# ~1 ms (sink push) to ~minutes (a 2^30 cold compile inside the first
# dispatch); the overflow bucket catches anything slower.
DEFAULT_TIME_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)


class Histogram:
    """Bounded-bucket histogram (Prometheus cumulative-bucket semantics)
    with linearly interpolated quantiles.

    ``bounds`` are upper bucket edges; one overflow bucket is implicit.
    Quantiles interpolate within the owning bucket (the first bucket
    interpolates from 0, the overflow bucket clamps to the highest
    finite edge — the same convention as PromQL's histogram_quantile,
    so the /metrics view and the in-process view agree).
    """

    __slots__ = ("name", "labels", "bounds", "_counts", "sum", "count",
                 "_lock")

    def __init__(self, name: str, buckets=DEFAULT_TIME_BUCKETS,
                 labels: dict | None = None):
        if not buckets:
            raise ValueError("histogram needs at least one bucket edge")
        self.name = name
        self.labels = dict(labels or {})
        self.bounds = tuple(sorted(float(b) for b in buckets))
        self._counts = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[i] += 1
            self.sum += value
            self.count += 1

    def quantile(self, q: float) -> float:
        """Interpolated q-quantile (q in [0, 1]); NaN when empty."""
        with self._lock:
            counts = list(self._counts)
            total = self.count
        if total == 0:
            return math.nan
        rank = q * total
        cum = 0.0
        for i, c in enumerate(counts):
            if cum + c >= rank and c > 0:
                if i >= len(self.bounds):       # overflow bucket
                    return self.bounds[-1]
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i]
                return lo + (hi - lo) * (rank - cum) / c
            cum += c
        return self.bounds[-1]

    def percentiles(self) -> dict:
        return {"p50": self.quantile(0.50), "p95": self.quantile(0.95),
                "p99": self.quantile(0.99)}

    def cumulative_buckets(self) -> list[tuple[float, int]]:
        """[(upper_edge, cumulative_count)] including (+inf, total)."""
        with self._lock:
            counts = list(self._counts)
        out = []
        cum = 0
        for edge, c in zip(self.bounds, counts):
            cum += c
            out.append((edge, cum))
        out.append((math.inf, cum + counts[-1]))
        return out


class SlidingWindow:
    """Sum/rate of increments over the trailing ``window_s`` seconds.

    A lifetime average hides a stall for minutes; the window answers
    "what is the pipeline doing *now*".  ``clock`` is injectable for
    deterministic tests.
    """

    __slots__ = ("name", "window_s", "_clock", "_events", "_start",
                 "_lock")

    def __init__(self, name: str, window_s: float = 10.0,
                 clock=time.monotonic):
        if window_s <= 0:
            raise ValueError("window_s must be positive")
        self.name = name
        self.window_s = float(window_s)
        self._clock = clock
        self._events: collections.deque = collections.deque()
        self._start = clock()
        self._lock = threading.Lock()

    def _prune(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._events and self._events[0][0] < cutoff:
            self._events.popleft()

    def add(self, value: float = 1.0) -> None:
        now = self._clock()
        with self._lock:
            self._events.append((now, value))
            self._prune(now)

    def sum(self) -> float:
        now = self._clock()
        with self._lock:
            self._prune(now)
            return float(sum(v for _, v in self._events))

    def rate(self) -> float:
        """Per-second rate over the window (over the elapsed time while
        younger than one window, so early readings aren't diluted)."""
        now = self._clock()
        with self._lock:
            self._prune(now)
            total = sum(v for _, v in self._events)
        denom = min(self.window_s, max(now - self._start, 1e-9))
        return float(total) / denom


def _label_key(labels: dict | None) -> tuple:
    return tuple(sorted((labels or {}).items()))


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        # labeled scalar series (multi-tenant fleet: the same counter
        # name per stream, e.g. segments_dropped{stream="beam3"}),
        # keyed (name, sorted-label-items).  Deliberately SEPARATE
        # from the flat series: a labeled bump never moves the
        # process-wide total — call sites that want both bump both,
        # so single-stream dashboards keep their exact semantics.
        self._labeled: dict[tuple, float] = {}
        self._histograms: dict[tuple, Histogram] = {}
        self._windows: dict[str, SlidingWindow] = {}
        self._start = time.monotonic()

    def add(self, name: str, value: float = 1.0,
            labels: dict | None = None) -> None:
        with self._lock:
            if labels:
                key = (name, _label_key(labels))
                self._labeled[key] = self._labeled.get(key, 0.0) + value
            else:
                self._counters[name] = (self._counters.get(name, 0.0)
                                        + value)

    def set(self, name: str, value: float,
            labels: dict | None = None) -> None:
        with self._lock:
            if labels:
                self._labeled[(name, _label_key(labels))] = value
            else:
                self._counters[name] = value

    def get(self, name: str, labels: dict | None = None) -> float:
        with self._lock:
            if labels:
                return self._labeled.get((name, _label_key(labels)),
                                         0.0)
            return self._counters.get(name, 0.0)

    def labeled_series(self, name: str) -> list:
        """[(labels_dict, value)] for every labeled series of ``name``
        (sorted by label key for determinism)."""
        with self._lock:
            out = [(lk, v) for (n, lk), v in self._labeled.items()
                   if n == name]
        return [(dict(lk), v) for lk, v in sorted(out)]

    def by_label(self, name: str, label: str = "stream") -> dict:
        """label-value -> metric value over the labeled series of
        ``name`` (e.g. per-stream loss: ``by_label(
        "segments_dropped")`` -> {"beam3": 2.0, ...})."""
        return {d[label]: v for d, v in self.labeled_series(name)
                if label in d}

    def histogram(self, name: str, buckets=DEFAULT_TIME_BUCKETS,
                  labels: dict | None = None) -> Histogram:
        """Get-or-create; (name, labels) identify the series.  Buckets
        are fixed at creation (first caller wins, like Prometheus
        clients)."""
        key = (name, _label_key(labels))
        with self._lock:
            h = self._histograms.get(key)
            if h is None:
                h = self._histograms[key] = Histogram(
                    name, buckets=buckets, labels=labels)
        return h

    def window(self, name: str, window_s: float = 10.0) -> SlidingWindow:
        """Get-or-create a sliding-window rate (first caller fixes the
        window length)."""
        with self._lock:
            w = self._windows.get(name)
            if w is None:
                w = self._windows[name] = SlidingWindow(
                    name, window_s=window_s)
        return w

    def reset(self) -> None:
        """Clear all instruments and restart the clock (tests; a fresh
        observation run)."""
        with self._lock:
            self._counters.clear()
            self._labeled.clear()
            self._histograms.clear()
            self._windows.clear()
            self._start = time.monotonic()

    def _scalar_series(self):
        """Counters + derived scalars (lifetime and windowed loss rate,
        lifetime Msamples/s, elapsed), plus the instrument lists — ONE
        computation shared by snapshot() and prometheus() so the JSON
        and Prometheus views can never drift apart."""
        with self._lock:
            out = dict(self._counters)
            labeled = dict(self._labeled)
            hists = list(self._histograms.values())
            windows = list(self._windows.values())
        elapsed = time.monotonic() - self._start
        out["elapsed_s"] = elapsed
        if "samples" in out and elapsed > 0:
            out["msamples_per_sec"] = out["samples"] / elapsed / 1e6
        if "packets_total" in out and out["packets_total"] > 0:
            out["packet_loss_rate"] = (
                out.get("packets_lost", 0.0) / out["packets_total"])
        by_name = {w.name: w for w in windows}
        if "packets_total" in by_name and "packets_lost" in by_name:
            total_w = by_name["packets_total"].sum()
            if total_w > 0:
                out["packet_loss_rate_window"] = (
                    by_name["packets_lost"].sum() / total_w)
        # pool-wide aggregates: any family with device-labeled series
        # grows flat _pool_sum/_pool_max twins (sum/max across pool
        # members) — the control tower's "whole fleet" view, rendered
        # as ordinary families with their own contiguous HELP/TYPE
        # pairs so strict expfmt parsers stay happy
        pool: dict[str, list] = {}
        for (n, lk), v in labeled.items():
            if any(k == "device" for k, _v in lk):
                pool.setdefault(n, []).append(v)
        for n, vals in pool.items():
            out[n + "_pool_sum"] = float(sum(vals))
            out[n + "_pool_max"] = float(max(vals))
        return out, labeled, windows, hists

    def snapshot(self) -> dict:
        out, labeled, windows, hists = self._scalar_series()
        for (name, lk), v in sorted(labeled.items()):
            out[name + self._prom_labels(dict(lk))] = v
        for w in windows:
            out[f"{w.name}_per_sec_{w.window_s:g}s"] = w.rate()
        for h in hists:
            base = "_".join([h.name] + [str(v) for _, v
                                        in sorted(h.labels.items())])
            if h.count:
                p = h.percentiles()
                out[f"{base}_p50"] = p["p50"]
                out[f"{base}_p95"] = p["p95"]
                out[f"{base}_p99"] = p["p99"]
                out[f"{base}_mean"] = h.sum / h.count
            out[f"{base}_count"] = h.count
        return out

    def to_json(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)

    # ---- Prometheus text exposition (format version 0.0.4) ----

    # HELP text per family (exposition-format conformance: every
    # family gets a # HELP + # TYPE pair; unknown names fall back to
    # a generic line so third-party counters are still conformant).
    # Newlines/backslashes would need escaping per the format — keep
    # these single-line.
    _HELP = {
        "segments": "Segments drained end-to-end (lifetime)",
        "samples": "Baseband samples processed (lifetime)",
        "signals": "Segments whose detection gate fired",
        "segments_dropped": "Whole segments shed as accounted loss",
        "packets_total": "UDP packets expected (counter-derived)",
        "packets_lost": "UDP packets lost (counter gaps)",
        "packet_loss_rate": "Lifetime packet loss fraction",
        "packet_loss_rate_window": "Windowed packet loss fraction",
        "msamples_per_sec": "Lifetime megasamples per second",
        "elapsed_s": "Seconds since registry start/reset",
        "inflight_depth": "Dispatched-through-sink segments in flight",
        "degrade_level": "Sink-side degradation ladder level",
        "plan_ladder_level": "Compute demotion ladder level",
        "plan_demotions": "Self-healing plan demotions",
        "plan_promotions": "Self-healing promotion probes taken",
        "device_reinits": "Backend reinitializations after halts",
        "retries_total": "Guarded-operation retries (all sites)",
        "watchdog_requeues": "In-flight segments cancelled+requeued",
        "worker_restarts": "Supervised worker restarts",
        "shed_waterfalls": "Waterfall dumps withheld by degradation",
        "shed_baseband": "Sheddable sink pushes skipped",
        "data_loss_total": "Data-loss-classified faults (retried)",
        "faults_injected": "Deterministic fault-plan firings",
        "h2d_bytes": "Host-to-device bytes staged",
        "ring_cold_dispatches": "Ingest-ring cold (full-upload) "
                                "dispatches",
        "recovered_segments": "Segments rescued by manifest recovery",
        "replayed_skips": "Sink pushes skipped as already committed",
        "rolled_back_intents": "Uncommitted artifacts rolled back",
        "manifest_loss_flags": "Unrecoverable-loss flags from "
                               "manifest recovery",
        "incident_bundles": "Incident bundles written",
        "incidents_suppressed": "Incident dumps suppressed "
                                "(rate/count bound)",
        "incident_dump_failures": "Incident bundle writes that failed",
        "slo_burn_rate": "SLO error-budget burn rate (1.0 = spending "
                         "exactly the budget)",
        "slo_state": "SLO objective state (0 ok / 1 degraded / "
                     "2 burning)",
        "fleet_plan_compiles": "Shared plan-cache processor builds",
        "fleet_plan_cache_hits": "Shared plan-cache hits",
        "fleet_admitted": "Streams admitted by the fleet gate",
        "fleet_queued": "Streams queued behind fleet capacity",
        "fleet_rejected": "Streams rejected by admission",
        "fleet_running": "Streams currently running in the fleet",
        "fleet_queued_depth": "Streams waiting in the admission queue",
        "fleet_sheds": "Fleet fairness force-shed transitions",
        "batched_dispatches": "Cross-stream batched device dispatches",
        "batched_segments": "Segments dispatched inside a "
                            "cross-stream batch",
        "batch_size": "Formed cross-stream batch sizes (histogram)",
        "fleet_idle_waits": "Idle scheduler rounds parked on the "
                            "event-driven wakeup",
        "fleet_pool_size": "Pool members the fleet places lanes "
                           "across",
        "fleet_device_state": "Pool member state (0 ok / 1 draining "
                              "/ 2 halted)",
        "fleet_device_lanes": "Live lanes placed on a pool member",
        "fleet_readmitted": "Live-migration re-admissions on a "
                            "target pool member",
        "fleet_batch_device_guard": "Batch offers re-routed solo by "
                                    "the post-migration membership "
                                    "guard",
        "migrations": "Lane live-migrations between pool members",
        "device_drains": "Pool members drained (halt, SLO rebalance "
                         "source, rolling restart)",
        "fleet_restores": "Fleet fairness restore transitions",
        "fleet_shed_streams": "Streams currently force-shed",
        "fleet_streams_total": "Streams submitted to the fleet",
        "stage_seconds": "Per-stage host wall clock (seconds)",
        "device_seconds": "Per-segment dispatch-to-ready device wall "
                          "(upper bound)",
        "achieved_msamps": "Last segment device-time Msamples/s "
                           "(lower bound)",
        "achieved_gbps": "Last segment modeled HBM GB/s over device "
                         "time (lower bound)",
        "roofline_frac": "Last segment achieved_gbps over the "
                         "configured HBM peak (lower bound)",
        "compile_seconds": "Cumulative trace+compile wall "
                           "(first-dispatch upper bound + AOT-miss "
                           "compiles)",
        "last_compile_ms": "Most recent trace+compile event "
                           "(milliseconds)",
        "plan_compiles": "First-dispatch trace+compile events",
        "aot_cache_hits": "AOT executable cache loads (no compile)",
        "aot_cache_misses": "AOT executable cache misses (compiled + "
                            "persisted)",
        "profile_captures": "On-demand jax.profiler captures written",
        "quality_zap_fraction": "Fraction of spectrum bins zapped by "
                                "RFI mitigation (last segment)",
        "quality_bandpass_mean": "Mean coarse-bandpass power "
                                 "(last segment)",
        "quality_bandpass_var": "Coarse-bandpass power variance "
                                "(last segment)",
        "quality_sk_mean": "Mean spectral-kurtosis estimate over "
                           "channels (last segment)",
        "quality_sk_max": "Max spectral-kurtosis estimate over "
                          "channels (last segment)",
        "quality_dead_frac": "Fraction of channels below the dead "
                             "threshold (last segment)",
        "quality_hot_frac": "Fraction of channels above the hot "
                            "threshold (last segment)",
        "quality_drift_score": "Bandpass EWMA drift score in sigmas "
                               "(last segment)",
        "quality_drift_alerts": "Bandpass drift-detector alerts",
        "canary_injected": "Pulse-injection canaries injected",
        "canary_checked": "Canary recoveries checked at drain",
        "canary_failed": "Canary sensitivity-gate failures",
        "canary_last_snr": "Recovered S/N of the last checked canary",
        "canary_expected_snr": "Expected canary S/N reference "
                               "(configured or auto-calibrated)",
        "canary_sensitivity_ratio": "Last recovered/expected canary "
                                    "S/N ratio",
        "detection_health_state": "End-to-end detection health "
                                  "(0 ok / 1 degraded)",
        "last_segment_monotonic": "Monotonic stamp of the last "
                                  "drained segment",
        "last_segment_unix": "Wall-clock stamp of the last drained "
                             "segment",
        "segment_pool_in_use": "Reader buffer-pool buffers in use",
        "file_bytes_read": "Bytes read from baseband input files",
    }

    @classmethod
    def _help_line(cls, prom_name: str, bare: str) -> str:
        text = cls._HELP.get(bare)
        if text is None and bare.startswith("retries_"):
            text = f"Guarded-operation retries at site {bare[8:]}"
        elif text is None and bare.startswith("worker_restarts_"):
            text = f"Supervised restarts of component {bare[16:]}"
        elif text is None and bare.endswith("_per_sec"):
            text = f"Windowed rate of {bare[:-8]} per second"
        elif text is None and bare.endswith("_pool_sum"):
            text = f"Sum of {bare[:-9]} across pool members"
        elif text is None and bare.endswith("_pool_max"):
            text = f"Max of {bare[:-9]} across pool members"
        if text is None:
            text = "srtb_tpu runtime metric"
        return f"# HELP {prom_name} {text}"

    @staticmethod
    def _prom_name(name: str) -> str:
        return "srtb_" + re.sub(r"[^a-zA-Z0-9_]", "_", name)

    @staticmethod
    def _prom_labels(labels: dict) -> str:
        if not labels:
            return ""
        def esc(v):
            return str(v).replace("\\", r"\\").replace('"', r'\"') \
                         .replace("\n", r"\n")
        inner = ",".join(f'{k}="{esc(v)}"'
                         for k, v in sorted(labels.items()))
        return "{" + inner + "}"

    def prometheus(self) -> str:
        """Render every instrument in the Prometheus text format: flat
        counters/gauges as gauges (we don't track which are monotonic),
        windows as gauges, histograms with cumulative ``_bucket``/
        ``_sum``/``_count`` series.  The scalar set matches
        /metrics.json exactly (derived series like packet_loss_rate
        and msamples_per_sec included), so an alert written against
        either endpoint sees the other's values too."""
        scalars, labeled, windows, hists = self._scalar_series()
        lines = []

        def val(v: float) -> str:
            return f"{v:.17g}"

        labeled_by_name: dict[str, list] = {}
        for (n, lk), v in sorted(labeled.items()):
            labeled_by_name.setdefault(n, []).append((lk, v))
        for k in sorted(scalars):
            name = self._prom_name(k)
            lines.append(self._help_line(name, k))
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {val(scalars[k])}")
            # labeled samples of the SAME family must stay adjacent
            # to the flat sample: the exposition format requires one
            # contiguous group per metric (strict parsers reject a
            # re-opened family)
            for lk, v in labeled_by_name.pop(k, []):
                lines.append(
                    f"{name}{self._prom_labels(dict(lk))} {val(v)}")
        for bare in sorted(labeled_by_name):
            name = self._prom_name(bare)
            lines.append(self._help_line(name, bare))
            lines.append(f"# TYPE {name} gauge")
            for lk, v in labeled_by_name[bare]:
                lines.append(
                    f"{name}{self._prom_labels(dict(lk))} {val(v)}")
        for w in windows:
            name = self._prom_name(w.name) + "_per_sec"
            lines.append(self._help_line(name, w.name + "_per_sec"))
            lines.append(f"# TYPE {name} gauge")
            lines.append(
                f'{name}{{window_s="{w.window_s:g}"}} {val(w.rate())}')
        for hname in sorted({h.name for h in hists}):
            name = self._prom_name(hname)
            lines.append(self._help_line(name, hname))
            lines.append(f"# TYPE {name} histogram")
            for h in hists:
                if h.name != hname:
                    continue
                for edge, cum in h.cumulative_buckets():
                    le = "+Inf" if math.isinf(edge) else f"{edge:g}"
                    labels = dict(h.labels, le=le)
                    lines.append(
                        f"{name}_bucket{self._prom_labels(labels)} {cum}")
                lbl = self._prom_labels(h.labels)
                lines.append(f"{name}_sum{lbl} {val(h.sum)}")
                lines.append(f"{name}_count{lbl} {h.count}")
        return "\n".join(lines) + "\n"


metrics = Metrics()
