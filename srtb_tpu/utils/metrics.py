"""Runtime metrics/observability.

The reference's observability is logs: packet-loss rates
(io/udp/udp_receiver.hpp:154-164), allocator sizes, per-pipe timestamps
(SURVEY.md §5.5).  Here metrics are first-class counters with a one-line
summary and optional JSON export, covering the quantities BASELINE.md
tracks (segments/s, Msamples/s, loss rate, detections).
"""

from __future__ import annotations

import json
import threading
import time


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._start = time.monotonic()

    def add(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + value

    def set(self, name: str, value: float) -> None:
        with self._lock:
            self._counters[name] = value

    def get(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def reset(self) -> None:
        """Clear all counters and restart the clock (tests; a fresh
        observation run)."""
        with self._lock:
            self._counters.clear()
            self._start = time.monotonic()

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self._counters)
        elapsed = time.monotonic() - self._start
        out["elapsed_s"] = elapsed
        if "samples" in out and elapsed > 0:
            out["msamples_per_sec"] = out["samples"] / elapsed / 1e6
        if "packets_total" in out and out["packets_total"] > 0:
            out["packet_loss_rate"] = (
                out.get("packets_lost", 0.0) / out["packets_total"])
        return out

    def to_json(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)


metrics = Metrics()
