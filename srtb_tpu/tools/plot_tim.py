"""Offline time-series plotting helper (ref: src/plot_tim.py).

Reads raw float32 ``.tim`` files written by WriteSignalSink.
"""

from __future__ import annotations

import glob
import sys

import numpy as np
from srtb_tpu.utils.platform import apply_platform_env


def main(argv=None) -> int:
    apply_platform_env()
    argv = sys.argv[1:] if argv is None else argv
    paths = []
    for pattern in (argv or ["*.tim"]):
        paths.extend(glob.glob(pattern))
    for p in sorted(paths):
        ts = np.fromfile(p, dtype="<f4")
        out_path = p + ".png"
        try:
            import matplotlib
            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
            fig, ax = plt.subplots(figsize=(12, 4))
            ax.plot(ts, linewidth=0.5)
            ax.set_xlabel("time sample")
            ax.set_ylabel("power (mean-subtracted)")
            fig.savefig(out_path, dpi=120)
            plt.close(fig)
            print(out_path)
        except ImportError:
            print(f"{p}: n={ts.size} max={ts.max():.3f} "
                  f"mean={ts.mean():.3f} std={ts.std():.3f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
