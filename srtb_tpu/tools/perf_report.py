"""Render the perf trajectory from a perf ledger (utils/perf_ledger).

Groups records by (metric unit, shape, plan) and prints each group's
time-ordered trajectory — value, platform, git sha, host fingerprint,
compile time and roofline fraction where recorded — as markdown
tables (default) or one JSON document.  This is the queryable form of
the history PERF.md narrates and BENCH_r0*.json only hints at; seed
it with ``python -m srtb_tpu.tools.perf_ledger LEDGER --import
BENCH_r0*.json``.

Usage: python -m srtb_tpu.tools.perf_report LEDGER.jsonl
           [--format md|json] [--source bench,import,...]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from srtb_tpu.utils import perf_ledger as PL


def _group_key(rec: dict) -> str:
    shape = rec.get("shape") or {}
    log2n = shape.get("log2n", 0)
    plan = rec.get("plan") or "?"
    return f"{rec.get('unit', '?')} @ 2^{log2n} [{plan}]"


def trajectory(records: list[dict]) -> dict:
    """group key -> time-ordered rows.  Failed rounds (value 0) stay
    in the trajectory: an outage is history too."""
    groups: dict[str, list[dict]] = {}
    for rec in sorted(records, key=lambda r: r.get("ts", 0.0)):
        extra = rec.get("extra") or {}
        row = {
            "ts": rec.get("ts", 0.0),
            "when": time.strftime(
                "%Y-%m-%d %H:%M",
                time.localtime(rec.get("ts", 0.0))),
            "value": rec.get("value", 0.0),
            "source": rec.get("source", ""),
            "platform": rec.get("platform", ""),
            "git_sha": rec.get("git_sha", ""),
            "host_fp": rec.get("host_fp", ""),
            "n_samples": len(rec.get("samples_s") or []),
        }
        for k in ("compile_s", "roofline_frac", "overlap", "ring",
                  "import_key", "error", "segments"):
            if k in extra:
                row[k] = extra[k]
        groups.setdefault(_group_key(rec), []).append(row)
    return groups


def report(path: str, sources: list[str] | None = None) -> dict:
    records = PL.load(path)
    if sources:
        records = [r for r in records if r.get("source") in sources]
    groups = trajectory(records)
    out = {"ledger": path, "records": len(records), "groups": {}}
    for key, rows in sorted(groups.items()):
        measured = [r["value"] for r in rows if r["value"] > 0]
        out["groups"][key] = {
            "rows": rows,
            "best": max(measured) if measured else 0.0,
            "latest": measured[-1] if measured else 0.0,
            "failed_rounds": sum(1 for r in rows if r["value"] <= 0),
        }
    return out


def _md(rep: dict) -> str:
    lines = [f"# Perf trajectory — {rep['ledger']}", "",
             f"{rep['records']} perf records."]
    for key, g in rep["groups"].items():
        lines += ["", f"## {key}", "",
                  ("all rounds failed — no measured value yet"
                   if not g["best"] and g["failed_rounds"] else
                   f"best {g['best']}, latest {g['latest']}"
                   + (f", {g['failed_rounds']} failed round(s)"
                      if g["failed_rounds"] else "")),
                  "",
                  "| when | value | source | platform | git | host | "
                  "reps | note |", "|---|---|---|---|---|---|---|---|"]
        for r in g["rows"]:
            note = r.get("error", "")[:40] or (
                f"roofline {r['roofline_frac']}"
                if "roofline_frac" in r else "")
            lines.append(
                f"| {r['when']} | {r['value']} | {r['source']} | "
                f"{r['platform']} | {r['git_sha'][:8]} | "
                f"{r['host_fp'][:6]} | {r['n_samples']} | {note} |")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("ledger")
    p.add_argument("--format", choices=("md", "json"), default="md")
    p.add_argument("--source", default="",
                   help="comma-separated source filter "
                        "(bench,steady,gate,import)")
    args = p.parse_args(argv)
    sources = [s for s in args.source.split(",") if s] or None
    rep = report(args.ledger, sources)
    if not rep["records"]:
        # empty / missing / filtered-to-nothing ledger: a clear note,
        # not a failure — dashboards render before the first record
        # lands (same contract as telemetry_report on a fresh journal)
        note = {"note": f"no perf records in {args.ledger} yet",
                "records": 0}
        print(json.dumps(note) if args.format == "json"
              else f"# Perf trajectory\n\n{note['note']}\n")
        return 0
    if args.format == "json":
        print(json.dumps(rep, sort_keys=True))
    else:
        print(_md(rep))
    return 0


if __name__ == "__main__":
    sys.exit(main())
