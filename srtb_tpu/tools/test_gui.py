"""GUI smoke test: synthetic spectra through the real waterfall service.

The analog of the reference's ``test-gui`` binary
(ref: src/test-gui.cpp:1-128), which pumps generated spectra into the
real image provider to exercise the GUI path without a telescope: this
tool synthesizes dynamic spectra (drifting tones + noise, plus a
dispersed-sweep frame), pushes them through :class:`WaterfallService` in
both provider modes (simple per-segment frames and the legacy scrolling
provider), writes the PNGs, and can briefly serve them over the HTTP
viewer.

Usage:
  python -m srtb_tpu.tools.test_gui [--out DIR] [--frames N]
         [--streams S] [--scroll-lines K] [--http-port P] [--serve-s SEC]
"""

from __future__ import annotations

import argparse
import os
import time

import numpy as np

from srtb_tpu.config import Config
from srtb_tpu.utils.logging import log
from srtb_tpu.utils.platform import apply_platform_env


def synthetic_frame(n_freq: int, n_time: int, seed: int,
                    kind: str = "tones") -> np.ndarray:
    """One synthetic [2, F, T] (re, im) dynamic spectrum.

    ``tones``: noise + a few drifting carriers (test-gui.cpp's moving
    peak); ``sweep``: a quadratic frequency sweep, the shape of a
    dispersed pulse after imperfect dedispersion.
    """
    rng = np.random.default_rng(seed)
    wf = rng.standard_normal((2, n_freq, n_time)).astype(np.float32)
    f = np.arange(n_freq, dtype=np.float32)[:, None]
    t = np.arange(n_time, dtype=np.float32)[None, :]
    if kind == "tones":
        for i in range(3):
            center = (0.2 + 0.3 * i) * n_freq + \
                (n_freq / 8.0) * np.sin(2 * np.pi * (t / n_time + i / 3.0))
            wf[0] += 8.0 * np.exp(-0.5 * ((f - center) / 1.5) ** 2)
    else:
        center = n_freq * (0.9 - 0.8 * (t / n_time) ** 2)
        wf[0] += 10.0 * np.exp(-0.5 * ((f - center) / 2.0) ** 2)
    return wf


def main(argv=None) -> int:
    apply_platform_env()
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default="test_gui_out")
    p.add_argument("--frames", type=int, default=8)
    p.add_argument("--streams", type=int, default=2)
    p.add_argument("--freq", type=int, default=256)
    p.add_argument("--time", type=int, default=512)
    p.add_argument("--scroll-lines", type=int, default=16,
                   help="lines per frame for the scrolling provider pass "
                        "(0 disables it)")
    p.add_argument("--http-port", type=int, default=0)
    p.add_argument("--serve-s", type=float, default=2.0)
    args = p.parse_args(argv)

    from srtb_tpu.gui.waterfall import WaterfallService

    os.makedirs(args.out, exist_ok=True)
    base = dict(baseband_input_count=1 << 12, baseband_input_bits=8,
                baseband_reserve_sample=False,
                gui_pixmap_width=640, gui_pixmap_height=360)

    written = []
    # pass 1: simple per-segment provider (SimpleSpectrumImageProvider)
    svc = WaterfallService(Config(**base), args.freq, args.time,
                           out_dir=args.out)
    for i in range(args.frames):
        for s in range(args.streams):
            kind = "sweep" if (i + s) % 3 == 2 else "tones"
            svc.push(synthetic_frame(args.freq, args.time, 97 * i + s,
                                     kind), data_stream_id=s)
            path = svc.render_pending()
            if path:
                written.append(path)

    # pass 2: legacy scrolling provider with the 3n+1 scheduler
    if args.scroll_lines > 0:
        svc2 = WaterfallService(Config(gui_scroll_lines=args.scroll_lines,
                                       **base),
                                args.freq, args.time, out_dir=args.out)
        for i in range(args.frames):
            for s in range(args.streams):
                svc2.push(synthetic_frame(args.freq, args.time,
                                          31 * i + s), data_stream_id=s)
            path = svc2.render_pending()
            if path:
                written.append(path)

    uniq = sorted(set(written))
    log.info(f"[test_gui] wrote {len(uniq)} image file(s) under "
             f"{args.out}: {[os.path.basename(u) for u in uniq]}")
    if not uniq:
        log.error("[test_gui] no frames rendered")
        return 1

    if args.http_port:
        from srtb_tpu.gui.server import WaterfallHTTPServer
        server = WaterfallHTTPServer(args.out, port=args.http_port).start()
        log.info(f"[test_gui] serving {args.out} on port "
                 f"{server.port} for {args.serve_s:.0f}s")
        time.sleep(args.serve_s)
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
