"""Fleet chaos soak: cross-stream blast-radius gate.

The multi-tenant contract of :mod:`srtb_tpu.pipeline.fleet` is that a
faulty stream's blast radius is exactly itself.  This harness proves
it end-to-end: N seeded streams (distinct baseband, shared plan
family) run (1) each SOLO through the single-stream ``Pipeline`` —
the golden reference — and then (2) together through a
``StreamFleet`` with a fault plan injected into ONE victim stream
(stream-selector scoped, e.g. ``victim:dispatch:oom@1``).  The gate:

- **(a) healthy isolation**: every healthy stream's final output set
  (relative paths + SHA-256) is BIT-identical to its solo golden run
  — scheduling N tenants onto one device, with a neighbor faulting,
  changed nothing for the innocent;
- **(b) victim accounting**: the victim's loss is accounted-only
  (drained + dropped == source segments, nothing vanishes), its
  detection DECISIONS match its solo run exactly (recovery may change
  the plan, never the science), and the demotions/sheds are
  attributed to the victim's stream id in the v8 journal (healthy
  journals carry zero);
- **(c) shared plan economy**: the fleet's plan cache records exactly
  ONE compile for the shared plan family across all streams
  (``hits == N - 1``).

``--batch B`` runs the soak with cross-tenant continuous batching
armed (``fleet_batch_max=B``): the gate swaps healthy bit-identity
for the documented vmap contract (``.bin`` baseband still bitwise,
float artifacts — waterfall ``.npy``, time-series ``.tim`` —
``np.allclose``, detection DECISIONS still exact) and adds the
batching-economy checks: journal records carry ``batch_size``, the
journal-derived device dispatch count is at most half the drained
segment count, and the victim's faults never retire a neighbor out
of the shared batch group.

``--selftest`` proves the gate is sharp: an UNSCOPED fault plan (no
stream selector — it arms in every lane) must FAIL the healthy-
journal attribution check, and a scoped single-oom run must pass.

``--ab`` instead runs the steady-state single-stream A/B (fleet
engine with N=1 vs the solo ``Pipeline``) and reports both medians —
the PERF.md round-15 measurement.

``--migrate`` runs the ELASTIC-POOL migration soak instead: a seeded
2-device virtual pool (``fleet_devices=2``), a mid-run scoped device
kill (``--kill-device IDX --kill-at K`` arms the pool's deterministic
virtual halt) or an operator rolling restart (``--rolling``).  The
gate: every victim lane resumes on the surviving member and its final
output set (relative paths + SHA-256) is BIT-identical to its solo
golden, loss is zero (the in-flight window re-dispatches cold from
retained host buffers), the ingest ring records exactly ONE extra
cold dispatch per migration (``ring_cold_dispatches == streams +
migrations``), the journal is v11 with every record device-stamped
and victim journals ending on the survivor's label, and — the scoped
HALT-domain pin — the pool records exactly one compile per member
with zero healthy-lane demotions, recompiles or fleet-wide reinits.

Usage::

    python -m srtb_tpu.tools.fleet_soak [--streams N] [--segments N]
        [--log2n N] [--plan PLAN] [--batch B] [--selftest]
        [--ab [--reps R]]
        [--migrate [--kill-device IDX] [--kill-at K] [--rolling]]

Exit 0 on a passing gate (or sharp selftest), 1 on any failure.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

import numpy as np


class SoakFailure(AssertionError):
    """One broken fleet invariant (the gate)."""


def _stream_names(n: int) -> list[str]:
    # stream0 is always the victim (matching the default --plan)
    return [f"stream{i}" for i in range(n)]


def make_deterministic_source(cfg):
    """File source with offset-derived timestamps, so artifact names
    reproduce across the solo and fleet runs (same convention as
    tools/crash_soak.py)."""
    from srtb_tpu.io.file_input import BasebandFileReader

    class DeterministicTimestampReader(BasebandFileReader):
        def __next__(self):
            offset = self.logical_offset
            work = super().__next__()
            work.timestamp = 1_700_000_000_000_000_000 + offset
            return work

    return DeterministicTimestampReader(cfg)


def _cfg(tmp: str, name: str, run_dir: str, n: int, **extra):
    from srtb_tpu.config import Config
    base = dict(
        baseband_input_count=n, baseband_input_bits=8,
        baseband_freq_low=1405.0, baseband_bandwidth=64.0,
        baseband_sample_rate=128e6, dm=0.05,
        input_file_path=os.path.join(tmp, f"bb_{name}.bin"),
        baseband_output_file_prefix=os.path.join(run_dir, "out_"),
        spectrum_channel_count=64,
        # every segment must write artifacts (deterministically) so
        # the bit-identical union is a real comparison, not vacuous
        mitigate_rfi_average_method_threshold=1000.0,
        mitigate_rfi_spectral_kurtosis_threshold=50.0,
        signal_detect_signal_noise_threshold=1.5,
        signal_detect_max_boxcar_length=8,
        baseband_reserve_sample=True,
        writer_thread_count=0,
        fft_strategy="four_step",
        inflight_segments=2,
        retry_backoff_base_s=0.001,
        checkpoint_path=os.path.join(run_dir, "ck.json"),
        run_manifest_path=os.path.join(run_dir, "manifest.jsonl"),
    )
    base.update(extra)
    return Config(**base)


def _synthesize(tmp: str, names: list[str], n: int, segments: int,
                seed: int) -> None:
    from srtb_tpu.io.synth import make_dispersed_baseband
    for i, name in enumerate(names):
        make_dispersed_baseband(
            n * segments, 1405.0, 64.0, 0.05,
            pulse_positions=[n // 2 + j * n for j in range(segments)],
            pulse_amp=30.0, nbits=8, seed=seed * 1000 + i,
        ).tofile(os.path.join(tmp, f"bb_{name}.bin"))


class _DecisionTap:
    """Pass-through sink recording detection decisions (rides NEXT TO
    the real writer sinks, so artifacts still land on disk)."""

    wants_waterfall = False

    def __init__(self):
        self.out = []

    def push(self, work, positive):
        det = work.detect
        self.out.append((np.asarray(det.signal_counts).copy(),
                         np.asarray(det.zero_count).copy(),
                         bool(positive)))


# the documented vmap tolerance (the archive micro-batch precedent,
# tools/archive_replay.py): batching stacks segments into one vmapped
# program, which may reassociate float32 reductions — detection
# decisions and .bin baseband bytes stay exact, float artifacts stay
# numerically close with an amplitude-relative absolute term
VMAP_RTOL = 1e-5
VMAP_ATOL_FRAC = 1e-4


def _load_float(path: str):
    """Float artifact loader for the vmap-tolerance comparison; None
    for artifact kinds that have no float representation."""
    if path.endswith(".npy"):
        return np.load(path)
    if path.endswith(".tim"):
        return np.fromfile(path, dtype=np.float32)
    return None


def _artifacts_close(solo_dir: str, fleet_dir: str, solo_map: dict,
                     fleet_map: dict) -> str | None:
    """Batched-mode output comparison: identical relative-name sets,
    ``.bin`` bitwise, float artifacts within the vmap tolerance.
    Returns a failure description, or None when the gate holds."""
    if set(fleet_map) != set(solo_map):
        return (f"output name sets differ (fleet {sorted(fleet_map)} "
                f"vs solo {sorted(solo_map)})")
    for rel in sorted(solo_map):
        if fleet_map[rel] == solo_map[rel]:
            continue  # bitwise identical — always acceptable
        if rel.endswith(".bin"):
            return (f"{rel}: baseband .bin bytes differ (batching "
                    "must not touch raw capture)")
        a = _load_float(os.path.join(fleet_dir, rel))
        b = _load_float(os.path.join(solo_dir, rel))
        if a is None or b is None:
            return f"{rel}: differs and is not a float artifact"
        atol = VMAP_ATOL_FRAC * max(float(np.abs(b).max()), 1.0)
        if a.shape != b.shape or not np.allclose(
                a, b, rtol=VMAP_RTOL, atol=atol):
            return (f"{rel}: float artifact outside the vmap "
                    f"tolerance (rtol={VMAP_RTOL}, atol={atol:g})")
    return None


def _solo_run(cfg) -> tuple:
    """One golden single-stream run; returns (stats, decisions)."""
    from srtb_tpu.io.writers import WriteSignalSink
    from srtb_tpu.pipeline.runtime import Pipeline
    from srtb_tpu.utils.metrics import metrics
    metrics.reset()
    tap = _DecisionTap()
    sinks = [WriteSignalSink(cfg), tap]
    with Pipeline(cfg, source=make_deterministic_source(cfg),
                  sinks=sinks) as pipe:
        stats = pipe.run()
    return stats, tap.out


def run_soak(streams: int = 3, segments: int = 5, log2n: int = 13,
             plan: str | None = None, seed: int = 0,
             tmpdir: str | None = None, batch: int = 0,
             extra_cfg: dict | None = None) -> dict:
    """One full soak (solo goldens + fleet run + the gate).  Returns
    the report dict; raises :class:`SoakFailure` on any broken
    invariant.  ``batch >= 2`` arms cross-tenant continuous batching
    (``fleet_batch_max=batch``) and swaps healthy bit-identity for
    the vmap-tolerance contract plus the batching-economy checks.
    ``extra_cfg`` overrides land on the FLEET lanes only (the solo
    goldens stay canonical) — race_soak uses it to arm ``tsan=1``."""
    from srtb_tpu.io.writers import WriteSignalSink
    from srtb_tpu.pipeline.fleet import StreamFleet, StreamSpec
    from srtb_tpu.resilience.faults import parse_plan
    from srtb_tpu.tools.crash_soak import snapshot_outputs
    from srtb_tpu.utils.metrics import metrics

    tmp = tmpdir or tempfile.mkdtemp(prefix="srtb_fleet_")
    n = 1 << log2n
    batch = max(0, int(batch))
    names = _stream_names(streams)
    victim = names[0]
    if plan is None:
        plan = (f"{victim}:dispatch:oom@1,"
                f"{victim}:sink_write:raise@2,"
                f"{victim}:fetch:stall=0.05@3")
    specs_parsed = parse_plan(plan)
    victims = {s.stream for s in specs_parsed if s.stream is not None}
    n_demote = sum(1 for s in specs_parsed
                   if s.action in ("oom", "compile_fail"))
    _synthesize(tmp, names, n, segments, seed)

    # ---- solo goldens (per-stream run dirs, identical rel names)
    solo_out: dict[str, dict] = {}
    solo_dec: dict[str, list] = {}
    solo_segs: dict[str, int] = {}
    for name in names:
        run_dir = os.path.join(tmp, f"solo_{name}")
        os.makedirs(run_dir, exist_ok=True)
        stats, dec = _solo_run(_cfg(tmp, name, run_dir, n))
        solo_out[name] = snapshot_outputs(run_dir)
        solo_dec[name] = dec
        # overlap-save re-reads reserved tails, so the stream yields
        # MORE segments than the synthesized count — the solo run is
        # the authority on how many a lossless run drains
        solo_segs[name] = int(stats.segments)
        if not solo_out[name]:
            raise SoakFailure(
                f"solo run of {name} wrote NO artifacts — the "
                "bit-identical gate would be vacuous")

    # ---- fleet run, victim faulted
    metrics.reset()
    specs = []
    taps: dict[str, _DecisionTap] = {}
    jpaths: dict[str, str] = {}
    for name in names:
        run_dir = os.path.join(tmp, f"fleet_{name}")
        os.makedirs(run_dir, exist_ok=True)
        jpaths[name] = os.path.join(tmp, f"journal_{name}.jsonl")
        cfg = _cfg(tmp, name, run_dir, n, fault_plan=plan,
                   telemetry_journal_path=jpaths[name],
                   fleet_batch_max=batch, **(extra_cfg or {}))
        taps[name] = _DecisionTap()
        specs.append(StreamSpec(
            name=name, cfg=cfg,
            source=make_deterministic_source(cfg),
            sinks=[WriteSignalSink(cfg), taps[name]]))
    fleet = StreamFleet(specs)
    results = fleet.run()
    fleet.close()
    compiles, hits = fleet.plans.compiles, fleet.plans.hits
    dropped_by = metrics.by_label("segments_dropped")

    def check(cond, msg):
        if not cond:
            raise SoakFailure(msg)

    for name in names:
        check(results[name].status == "done",
              f"stream {name} did not finish: {results[name].status} "
              f"({results[name].error!r})")

    # (a) healthy streams: outputs equal to solo — bit-identical when
    # batching is off, the vmap-tolerance contract when it is on
    # (batching folds several tenants into one vmapped dispatch, so
    # float artifacts may differ in the last bits; .bin baseband and
    # detection decisions must not)
    for name in names:
        if name in victims:
            continue
        fleet_dir = os.path.join(tmp, f"fleet_{name}")
        fleet_set = snapshot_outputs(fleet_dir)
        if batch >= 2:
            why = _artifacts_close(os.path.join(tmp, f"solo_{name}"),
                                   fleet_dir, solo_out[name],
                                   fleet_set)
            check(why is None,
                  f"healthy stream {name} (batched): {why}")
        else:
            check(fleet_set == solo_out[name],
                  f"healthy stream {name}: fleet output set differs "
                  f"from its solo golden run (fleet "
                  f"{sorted(fleet_set)} vs solo "
                  f"{sorted(solo_out[name])})")
        for i, (a, b) in enumerate(zip(taps[name].out,
                                       solo_dec[name])):
            check(np.array_equal(a[0], b[0])
                  and np.array_equal(a[1], b[1]) and a[2] == b[2],
                  f"healthy stream {name}: decision differs at "
                  f"segment {i}")

    # (b) victim: accounted-only loss, decisions exact, journal
    # attribution
    for name in victims:
        res = results[name]
        vdropped = int(dropped_by.get(name, 0))
        check(res.drained + vdropped == solo_segs[name],
              f"victim {name}: loss not accounted — {res.drained} "
              f"drained + {vdropped} dropped != {solo_segs[name]} "
              "source segments")
        for i, (a, b) in enumerate(zip(taps[name].out,
                                       solo_dec[name])):
            check(np.array_equal(a[0], b[0])
                  and np.array_equal(a[1], b[1]) and a[2] == b[2],
                  f"victim {name}: detection decision differs at "
                  f"segment {i} (recovery changed the science)")
    recs_by: dict[str, list] = {}
    for name in names:
        recs = [json.loads(line) for line in open(jpaths[name])
                if line.strip().startswith("{")]
        recs_by[name] = recs
        check(recs and all(r.get("stream") == name and r["v"] == 11
                           for r in recs),
              f"stream {name}: journal records not stream-stamped")
        total_demote = int(recs[-1].get("plan_demotions", 0))
        if name in victims:
            check(total_demote == n_demote,
                  f"victim {name}: journal plan_demotions "
                  f"{total_demote} != {n_demote} injected")
        else:
            check(total_demote == 0,
                  f"healthy stream {name}: journal attributes "
                  f"{total_demote} demotions — the victim's fault "
                  "leaked into a neighbor's books")

    # (d) batching economy (batched soak only): every drained segment
    # is journaled, batched ones carry batch_size, and the implied
    # device dispatch count — each record contributes 1/batch_size of
    # a dispatch — shows real cross-tenant amortization
    batched_dispatches = int(metrics.get("batched_dispatches"))
    batched_segments = int(metrics.get("batched_segments"))
    dispatch_est = 0.0
    total_recs = 0
    for name in names:
        for r in recs_by[name]:
            total_recs += 1
            b = int(r.get("batch_size", 1) or 1)
            check(b >= 1, f"stream {name}: journal batch_size {b}")
            dispatch_est += 1.0 / b
    dispatch_est = round(dispatch_est)
    if batch >= 2:
        check(batched_dispatches >= 1,
              "batched soak recorded no batched_dispatches — the "
              "batch former never fired")
        check(batched_segments >= 2 * batched_dispatches,
              f"batched_segments {batched_segments} < 2x "
              f"batched_dispatches {batched_dispatches}")
        check(dispatch_est * 2 <= total_recs,
              f"journal-implied device dispatches {dispatch_est} > "
              f"half of {total_recs} drained segments — batching "
              "amortized too little")
    else:
        check(batched_dispatches == 0 and all(
                  "batch_size" not in r
                  for name in names for r in recs_by[name]),
              "unbatched soak journaled batch_size fields")

    # (c) shared plan cache: one compile per family
    check(compiles == 1,
          f"plan cache recorded {compiles} compiles for one shared "
          "plan family (expected exactly 1)")
    check(hits == streams - 1,
          f"plan cache hits {hits} != {streams - 1} "
          "(every non-first stream must reuse the shared plan)")

    return {
        "streams": streams, "segments": segments, "plan": plan,
        "victims": sorted(victims),
        "drained": {k: results[k].drained for k in names},
        "dropped": {k: int(dropped_by.get(k, 0)) for k in names},
        "plan_compiles": compiles, "plan_cache_hits": hits,
        "fleet_batch_max": batch,
        "batched_dispatches": batched_dispatches,
        "batched_segments": batched_segments,
        "device_dispatches_est": dispatch_est,
        "journaled_segments": total_recs,
        "ok": True,
    }


def selftest(log2n: int = 12) -> list[str]:
    """Prove the gate is sharp.  (a) an UNSCOPED oom (no stream
    selector) arms in every lane, so healthy lanes demote too and the
    journal-attribution check must fail; (b) the scoped default plan
    must pass (the gate is not simply failing everything)."""
    failures = []
    try:
        run_soak(streams=2, segments=3, log2n=log2n,
                 plan="dispatch:oom@1")
        failures.append(
            "gate passed an UNSCOPED fault plan — cross-stream "
            "fault leakage went unnoticed")
    except SoakFailure:
        pass  # caught, as required
    try:
        run_soak(streams=2, segments=3, log2n=log2n,
                 plan="stream0:dispatch:oom@1")
    except Exception as e:  # noqa: BLE001 - reported, not raised
        failures.append(f"scoped single-oom soak did not pass: {e!r}")
    return failures


def run_migrate(streams: int = 3, segments: int = 6, log2n: int = 13,
                seed: int = 0, kill_device: int = 1, kill_at: int = 2,
                rolling: bool = False, tmpdir: str | None = None,
                extra_cfg: dict | None = None) -> dict:
    """Elastic-pool migration soak: solo goldens, then the same
    streams on a seeded 2-device VIRTUAL pool with either a scoped
    mid-run device kill (driver (a): the pool's deterministic
    ``schedule_halt``) or an operator rolling restart (driver (c)).
    Lanes run with ``inflight_segments=1`` so the cold-dispatch
    arithmetic is exact: one ring cold per lane start plus exactly
    one per migration.  ``extra_cfg`` overrides land on the FLEET
    lanes only (race_soak arms ``tsan=1`` there).  Raises
    :class:`SoakFailure` on any broken invariant; returns the report
    dict."""
    import threading
    import time as _time

    from srtb_tpu.io.writers import WriteSignalSink
    from srtb_tpu.pipeline.fleet import StreamFleet, StreamSpec
    from srtb_tpu.tools.crash_soak import snapshot_outputs
    from srtb_tpu.utils import termination
    from srtb_tpu.utils.metrics import metrics

    tmp = tmpdir or tempfile.mkdtemp(prefix="srtb_migrate_")
    n = 1 << log2n
    names = _stream_names(streams)
    _synthesize(tmp, names, n, segments, seed)

    # ---- solo goldens (inflight 1, matching the fleet lanes)
    solo_out: dict[str, dict] = {}
    solo_dec: dict[str, list] = {}
    solo_segs: dict[str, int] = {}
    for name in names:
        run_dir = os.path.join(tmp, f"solo_{name}")
        os.makedirs(run_dir, exist_ok=True)
        stats, dec = _solo_run(
            _cfg(tmp, name, run_dir, n, inflight_segments=1))
        solo_out[name] = snapshot_outputs(run_dir)
        solo_dec[name] = dec
        solo_segs[name] = int(stats.segments)
        if not solo_out[name]:
            raise SoakFailure(
                f"solo run of {name} wrote NO artifacts — the "
                "bit-identical gate would be vacuous")

    # ---- fleet run on the 2-device virtual pool
    metrics.reset()
    specs = []
    taps: dict[str, _DecisionTap] = {}
    jpaths: dict[str, str] = {}
    for name in names:
        run_dir = os.path.join(tmp, f"fleet_{name}")
        os.makedirs(run_dir, exist_ok=True)
        jpaths[name] = os.path.join(tmp, f"journal_{name}.jsonl")
        cfg = _cfg(tmp, name, run_dir, n, fleet_devices=2,
                   inflight_segments=1,
                   telemetry_journal_path=jpaths[name],
                   **(extra_cfg or {}))
        taps[name] = _DecisionTap()
        specs.append(StreamSpec(
            name=name, cfg=cfg,
            source=make_deterministic_source(cfg),
            sinks=[WriteSignalSink(cfg), taps[name]]))
    fleet = StreamFleet(specs)
    pool_size = len(fleet.pool)
    if pool_size != 2:
        raise SoakFailure(
            f"fleet built a {pool_size}-member pool (fleet_devices=2 "
            "requested) — the migration soak needs a 2-device pool")
    trigger: threading.Thread | None = None
    fired = threading.Event()
    if rolling:
        # operator path: a tagged side thread waits for steady state
        # (a few dispatches landed) then queues the rolling restart —
        # the scheduler thread does the actual drains
        def _roll_trigger():
            while not fired.is_set():
                if fleet.pool.total_dispatches >= max(1, kill_at):
                    fleet.rolling_restart()
                    fired.set()
                    return
                _time.sleep(0.001)
        trigger = threading.Thread(
            target=_roll_trigger, name="migrate-soak-roll",
            daemon=True)
        termination.tag_thread(trigger)
        trigger.start()
    else:
        fleet.pool.schedule_halt(kill_device,
                                 after_dispatches=max(1, kill_at))
    results = fleet.run()
    pool_compiles = fleet.pool.compiles
    if trigger is not None:
        fired.set()
        trigger.join(timeout=10)
    fleet.close()
    dropped_by = metrics.by_label("segments_dropped")
    migs = int(metrics.get("migrations"))
    drains = int(metrics.get("device_drains"))
    ring_cold = int(metrics.get("ring_cold_dispatches"))

    def check(cond, msg):
        if not cond:
            raise SoakFailure(msg)

    for name in names:
        check(results[name].status == "done",
              f"stream {name} did not finish: {results[name].status} "
              f"({results[name].error!r})")

    # (a) lossless resume: zero drops, every source segment drained
    for name in names:
        vdropped = int(dropped_by.get(name, 0))
        check(vdropped == 0,
              f"stream {name}: {vdropped} segment(s) dropped — "
              "migration must be lossless (cold re-dispatch, not "
              "shed)")
        check(results[name].drained == solo_segs[name],
              f"stream {name}: drained {results[name].drained} != "
              f"{solo_segs[name]} solo source segments")

    # (b) bit-identity for EVERY stream — victims included: the
    # migrated lane's outputs (paths + SHA-256) and detection
    # decisions match its solo golden exactly
    for name in names:
        fleet_set = snapshot_outputs(os.path.join(tmp, f"fleet_{name}"))
        check(fleet_set == solo_out[name],
              f"stream {name}: fleet output set differs from its "
              f"solo golden (fleet {sorted(fleet_set)} vs solo "
              f"{sorted(solo_out[name])})")
        check(len(taps[name].out) == len(solo_dec[name]),
              f"stream {name}: {len(taps[name].out)} decisions vs "
              f"{len(solo_dec[name])} solo")
        for i, (a, b) in enumerate(zip(taps[name].out,
                                       solo_dec[name])):
            check(np.array_equal(a[0], b[0])
                  and np.array_equal(a[1], b[1]) and a[2] == b[2],
                  f"stream {name}: decision differs at segment {i} "
                  "(migration changed the science)")

    # (c) migration accounting: drivers fired, victims resumed on the
    # survivor, exactly one extra ring cold dispatch per migration
    per_lane_migs = {name: int(results[name].extras.get(
        "migrations", 0)) for name in names}
    check(migs >= 1,
          "no migration happened — the kill/rolling driver never "
          "fired (did the run finish before the trigger?)")
    check(migs == sum(per_lane_migs.values()),
          f"migrations counter {migs} != per-lane sum "
          f"{sum(per_lane_migs.values())}")
    check(ring_cold == streams + migs,
          f"ring_cold_dispatches {ring_cold} != {streams} lane "
          f"starts + {migs} migrations — a migration must cost "
          "EXACTLY one cold re-arm")
    if rolling:
        check(fired.is_set(), "rolling trigger thread never fired")
        check(drains == pool_size,
              f"device_drains {drains} != {pool_size} pool members "
              "(rolling restart drains each member once)")
    else:
        killed = fleet.pool.devices[kill_device].label
        check(drains == 1,
              f"device_drains {drains} != 1 (one scoped kill)")
        victims = [n for n in names if per_lane_migs[n] > 0]
        check(victims,
              "scoped kill produced no victim lanes — nothing was "
              f"placed on {killed}?")
        for name in victims:
            check(results[name].extras.get("device") != killed,
                  f"victim {name} finished on {killed} — it never "
                  "resumed on the survivor")
        # the scoped HALT-domain pin: one compile per member, no
        # survivor recompile (migrants REJOIN the survivor's plan
        # family), no demotions, no fleet-wide reinit
        check(pool_compiles == pool_size,
              f"pool recorded {pool_compiles} compiles for "
              f"{pool_size} members — a scoped halt must not "
              "recompile the survivor's plans")
    check(int(metrics.get("device_reinits")) == 0,
          "a scoped device halt escalated to a fleet-wide reinit")
    check(int(metrics.get("plan_demotions")) == 0,
          "migration demoted a lane's plan — resume must rejoin the "
          "target's shared family at rung 0")

    # (d) journal: v11, every record device-stamped, victim journals
    # END on a surviving member's label
    killed_label = (None if rolling
                    else fleet.pool.devices[kill_device].label)
    for name in names:
        recs = [json.loads(line) for line in open(jpaths[name])
                if line.strip().startswith("{")]
        check(recs and all(r["v"] == 11 and r.get("device")
                           for r in recs),
              f"stream {name}: journal records missing v11 device "
              "stamps")
        check(len(recs) == solo_segs[name],
              f"stream {name}: {len(recs)} journal records != "
              f"{solo_segs[name]} drained segments")
        if killed_label is not None and per_lane_migs[name] > 0:
            check(recs[-1]["device"] != killed_label,
                  f"victim {name}: journal ends on the KILLED member "
                  f"{killed_label}")
            check(len({r["device"] for r in recs}) >= 2,
                  f"victim {name}: journal never switched device "
                  "labels across the migration boundary")

    return {
        "streams": streams, "segments": segments,
        "mode": "rolling" if rolling else "kill",
        "kill_device": None if rolling else kill_device,
        "kill_at": kill_at, "migrations": migs,
        "per_lane_migrations": per_lane_migs,
        "device_drains": drains,
        "ring_cold_dispatches": ring_cold,
        "pool_compiles": pool_compiles,
        "drained": {k: results[k].drained for k in names},
        "ok": True,
    }


def run_ab(segments: int = 20, log2n: int = 13, reps: int = 3) -> dict:
    """Steady-state single-stream A/B: fleet engine with N=1 vs the
    solo Pipeline, same config/data, median-of-reps seg/s each."""
    import time

    from srtb_tpu.io.writers import WriteSignalSink
    from srtb_tpu.pipeline.fleet import StreamFleet, StreamSpec
    from srtb_tpu.pipeline.runtime import Pipeline
    from srtb_tpu.utils.metrics import metrics

    tmp = tempfile.mkdtemp(prefix="srtb_fleet_ab_")
    n = 1 << log2n
    _synthesize(tmp, ["ab"], n, segments, seed=0)

    def one_solo() -> float:
        run_dir = tempfile.mkdtemp(dir=tmp)
        cfg = _cfg(tmp, "ab", run_dir, n, checkpoint_path="",
                   run_manifest_path="")
        metrics.reset()
        t0 = time.perf_counter()
        with Pipeline(cfg, source=make_deterministic_source(cfg),
                      sinks=[WriteSignalSink(cfg)]) as pipe:
            stats = pipe.run()
        return stats.segments / (time.perf_counter() - t0)

    def one_fleet() -> float:
        run_dir = tempfile.mkdtemp(dir=tmp)
        cfg = _cfg(tmp, "ab", run_dir, n, checkpoint_path="",
                   run_manifest_path="")
        metrics.reset()
        t0 = time.perf_counter()
        fleet = StreamFleet([StreamSpec(
            name="ab", cfg=cfg, source=make_deterministic_source(cfg),
            sinks=[WriteSignalSink(cfg)])])
        res = fleet.run()
        fleet.close()
        return res["ab"].drained / (time.perf_counter() - t0)

    solo = sorted(one_solo() for _ in range(reps))[reps // 2]
    fleet = sorted(one_fleet() for _ in range(reps))[reps // 2]
    return {"solo_seg_per_s": round(solo, 2),
            "fleet_n1_seg_per_s": round(fleet, 2),
            "delta_pct": round((fleet - solo) / solo * 100, 2),
            "segments": segments, "log2n": log2n, "reps": reps}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="fleet-soak",
        description="multi-tenant fleet blast-radius gate "
                    "(see srtb_tpu/tools/fleet_soak.py)")
    ap.add_argument("--streams", type=int, default=3)
    ap.add_argument("--segments", type=int, default=5)
    ap.add_argument("--log2n", type=int, default=13)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--plan", default=None,
                    help="explicit fault plan (stream-selector scoped;"
                         " default faults stream0)")
    ap.add_argument("--batch", type=int, default=0,
                    help="fleet_batch_max for a batched soak (>= 2 "
                         "arms cross-tenant continuous batching; the "
                         "gate switches to the vmap-tolerance "
                         "contract + batching-economy checks)")
    ap.add_argument("--selftest", action="store_true",
                    help="prove the gate catches cross-stream leakage")
    ap.add_argument("--ab", action="store_true",
                    help="single-stream A/B: fleet N=1 vs Pipeline")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--migrate", action="store_true",
                    help="elastic-pool migration soak: 2-device "
                         "virtual pool, scoped mid-run device kill "
                         "(or --rolling), bit-identical resume gate")
    ap.add_argument("--kill-device", type=int, default=1,
                    help="pool member index the scheduled halt kills")
    ap.add_argument("--kill-at", type=int, default=2,
                    help="member dispatch count the halt fires after "
                         "(rolling: pool dispatch count that triggers "
                         "the restart)")
    ap.add_argument("--rolling", action="store_true",
                    help="drive migration via an operator rolling "
                         "restart instead of a device kill")
    args = ap.parse_args(argv)

    if args.selftest:
        fails = selftest()
        for f in fails:
            print(f"fleet-soak selftest: {f}", file=sys.stderr)
        print("fleet-soak selftest: "
              + ("FAILED" if fails else
                 "OK — cross-stream leakage fails the gate"))
        return 1 if fails else 0
    if args.ab:
        print(json.dumps(run_ab(segments=args.segments * 4,
                                log2n=args.log2n, reps=args.reps),
                         sort_keys=True))
        return 0
    if args.migrate:
        try:
            report = run_migrate(
                streams=args.streams, segments=args.segments,
                log2n=args.log2n, seed=args.seed,
                kill_device=args.kill_device, kill_at=args.kill_at,
                rolling=args.rolling)
        except SoakFailure as e:
            print(json.dumps({"ok": False, "failure": str(e)}))
            print(f"fleet-soak: MIGRATION GATE FAILED — {e}",
                  file=sys.stderr)
            return 1
        print(json.dumps(report, sort_keys=True))
        return 0
    try:
        report = run_soak(streams=args.streams, segments=args.segments,
                          log2n=args.log2n, plan=args.plan,
                          seed=args.seed, batch=args.batch)
    except SoakFailure as e:
        print(json.dumps({"ok": False, "failure": str(e)}))
        print(f"fleet-soak: GATE FAILED — {e}", file=sys.stderr)
        return 1
    print(json.dumps(report, sort_keys=True))
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
