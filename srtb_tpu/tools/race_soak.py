"""Seeded schedule-perturbation race soak for the fleet.

A race that survives fleet_soak's chaos runs may simply never have
seen the losing interleave: the scheduler thread and the per-lane sink
threads are fast, so the windows between a check and its act are
nanoseconds wide.  This harness arms the runtime concurrency checker
(``Config.tsan``, analysis/tsan.py) and installs a
:class:`~srtb_tpu.analysis.tsan.SchedulePerturber` that injects
deterministic sleeps at instrumented lock acquisition points — the
windows widen by ~3 orders of magnitude, reproducibly: the decision
for occurrence ``k`` of site ``s`` is a pure hash of ``(seed, s, k)``,
so the same seed yields the same perturbation schedule.

Under that perturbation it runs the full multi-stream fleet +
batch-former + chaos soak (tools/fleet_soak.py, unchanged gates) with
a deadline, then a second perturbed phase: the elastic-pool rolling-
restart migration soak (``fleet_soak.run_migrate``), whose scheduler-
thread drains, tagged trigger thread (``termination.tag_thread``) and
sink-pipe threads interleave with the perturbation sleeps — live
migration must stay bit-identical under any interleave.  Checks:

- every fleet_soak invariant still holds (bit-identical healthy
  outputs / vmap tolerance when batched, accounted-only victim loss,
  journal attribution, plan-cache economy) — perturbation may reorder
  thread interleavings, never results;
- **no deadlock within the deadline** — on expiry every live thread's
  stack (with its creation site) is dumped and the soak fails;
- the lockdep layer stayed quiet: an order cycle or ownership
  violation raises :class:`TsanError` out of the run;
- **schedule determinism**: the recorded perturbation journal replays
  exactly against a fresh perturber with the same seed.

``--selftest`` proves the checker is sharp: a deliberately inverted
acquisition order through the instrumented locks must raise
:class:`TsanError`, and the same pairs taken in a consistent global
order must not.

Usage::

    python -m srtb_tpu.tools.race_soak [--streams N] [--segments N]
        [--log2n N] [--seed N] [--batch B] [--plan PLAN]
        [--deadline S] [--selftest]

Exit 0 on a passing gate (or sharp selftest), 1 on any failure.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading

from srtb_tpu.analysis.tsan import (SchedulePerturber, Tsan, TsanError,
                                    install_perturber,
                                    uninstall_perturber)


class RaceSoakFailure(AssertionError):
    """One broken race-soak invariant (deadline, determinism, or a
    propagated fleet_soak gate failure)."""


def run_race_soak(streams: int = 2, segments: int = 4,
                  log2n: int = 12, seed: int = 0, batch: int = 2,
                  plan: str | None = None,
                  deadline_s: float = 300.0,
                  rate: float = 0.25) -> dict:
    """One perturbed soak.  Returns the report dict; raises
    :class:`RaceSoakFailure` (deadline/determinism) or propagates
    :class:`TsanError` / fleet_soak's ``SoakFailure``."""
    from srtb_tpu.tools.fleet_soak import run_migrate, run_soak
    from srtb_tpu.utils import termination

    if plan is None:
        # one injected stall on the victim's fetch: long enough to
        # push its sink idle and exercise the event-driven wakeup
        # under perturbation, no demotions (stall is not a device
        # fault), so the journal gate expects plan_demotions == 0
        plan = "stream0:fetch:stall=0.05@1"
    perturber = SchedulePerturber(seed, rate=rate)
    out: dict = {}
    err: list = []

    def _worker():
        try:
            out["report"] = run_soak(
                streams=streams, segments=segments, log2n=log2n,
                plan=plan, seed=seed, batch=batch,
                extra_cfg={"tsan": True,
                           # generous linger + wide lane windows so
                           # 2-stream batches keep forming even when
                           # perturbation sleeps stagger the lanes
                           # (the batching-economy gate stays armed)
                           "fleet_batch_linger_ms": 50.0,
                           "inflight_segments": 4})
        except BaseException as e:  # noqa: BLE001 — reported below
            err.append(e)

    def _mig_worker():
        # phase 2: live migration under perturbation — a rolling
        # restart of a 2-device virtual pool, its trigger thread
        # tagged for the deadline gate's stack dumps.  The unchanged
        # run_migrate gates (bit-identical resume, exact cold-
        # dispatch arithmetic, v11 device-stamped journals) must hold
        # under every widened interleave.
        try:
            out["migrate"] = run_migrate(
                streams=streams, segments=max(segments, 5),
                log2n=log2n, seed=seed, rolling=True, kill_at=3,
                extra_cfg={"tsan": True})
        except BaseException as e:  # noqa: BLE001 — reported below
            err.append(e)

    install_perturber(perturber)
    try:
        for tname, target in (("race-soak-run", _worker),
                              ("race-soak-migrate", _mig_worker)):
            t = threading.Thread(target=target, name=tname,
                                 daemon=True)
            termination.tag_thread(t)
            t.start()
            t.join(deadline_s)
            if t.is_alive():
                # the deadlock gate: dump every live thread with its
                # creation site, then fail loudly
                stacks = termination.format_thread_stacks(
                    threading.enumerate())
                raise RaceSoakFailure(
                    f"race soak ({tname}) did not finish within "
                    f"{deadline_s:.0f}s — deadlock or livelock under "
                    f"perturbation; live threads:\n{stacks}")
            if err:
                break
    finally:
        uninstall_perturber()
    if err:
        raise err[0]

    # schedule determinism: the recorded journal must replay exactly
    # against a fresh perturber with the same seed (decide() is a
    # pure hash — this pins that no wall-clock or RNG state leaked in)
    replay = SchedulePerturber(seed, rate=rate)
    for site, k in perturber.journal:
        if not replay.decide(site, k):
            raise RaceSoakFailure(
                f"perturbation journal does not replay: site "
                f"{site!r} occurrence {k} was perturbed live but a "
                f"fresh perturber with seed {seed} declines it")
    report = dict(out["report"])
    mig = out.get("migrate", {})
    report.update({
        "seed": seed, "perturb_rate": rate,
        "perturbs": len(perturber.journal),
        "perturb_sites": sorted({s for s, _k in perturber.journal}),
        "migrations": mig.get("migrations"),
        "migrate_ring_cold": mig.get("ring_cold_dispatches"),
        "migrate_device_drains": mig.get("device_drains"),
    })
    if not perturber.journal:
        raise RaceSoakFailure(
            "perturber never fired — the fleet ran with no "
            "instrumented acquisitions (Config.tsan not armed?)")
    return report


def selftest() -> list[str]:
    """Prove the checker is sharp.  (a) a deliberate lock-order
    inversion through the instrumented locks must raise TsanError;
    (b) the same locks taken in one consistent global order must not
    (the trap is not simply firing on every nesting); (c) the
    perturber's schedule is seed-deterministic."""
    failures = []

    # (a) inversion: A->B on record, then B->A must trap BEFORE
    # acquiring (no actual deadlock needed — single-threaded proof)
    ts = Tsan()
    a, b = ts.lock("selftest.A"), ts.lock("selftest.B")
    with a:
        with b:
            pass
    try:
        with b:
            with a:
                pass
        failures.append(
            "lockdep passed a deliberate lock-order inversion "
            "(A->B then B->A) — the cycle trap is not firing")
    except TsanError:
        pass  # caught, as required

    # (b) consistent order: same pairs, one global order, no trap
    ts2 = Tsan()
    a2, b2 = ts2.lock("selftest.A"), ts2.lock("selftest.B")
    try:
        for _ in range(3):
            with a2:
                with b2:
                    pass
    except TsanError as e:
        failures.append(
            f"lockdep trapped a CONSISTENT acquisition order: {e}")

    # (c) determinism: two perturbers, same seed, same decisions
    p1 = SchedulePerturber(7, rate=0.5)
    p2 = SchedulePerturber(7, rate=0.5)
    sites = [("x", k) for k in range(64)] + [("y", k)
                                             for k in range(64)]
    if [p1.decide(s, k) for s, k in sites] \
            != [p2.decide(s, k) for s, k in sites]:
        failures.append("perturber schedule differs across two "
                        "instances with the same seed")
    if all(not p1.decide(s, k) for s, k in sites):
        failures.append("perturber at rate=0.5 never fires")
    return failures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="race-soak",
        description="seeded schedule-perturbation fleet soak "
                    "(see srtb_tpu/tools/race_soak.py)")
    ap.add_argument("--streams", type=int, default=2)
    ap.add_argument("--segments", type=int, default=4)
    ap.add_argument("--log2n", type=int, default=12)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--batch", type=int, default=2,
                    help="fleet_batch_max (>= 2 arms the batch "
                         "former; 0 disables)")
    ap.add_argument("--plan", default=None,
                    help="fault plan (default: one injected stall on "
                         "stream0's fetch)")
    ap.add_argument("--deadline", type=float, default=300.0,
                    help="deadlock deadline for the whole soak (s)")
    ap.add_argument("--rate", type=float, default=0.25,
                    help="perturbation probability per acquisition")
    ap.add_argument("--selftest", action="store_true",
                    help="prove lockdep catches an injected "
                         "lock-order inversion")
    args = ap.parse_args(argv)

    if args.selftest:
        fails = selftest()
        for f in fails:
            print(f"race-soak selftest: {f}", file=sys.stderr)
        print("race-soak selftest: "
              + ("FAILED" if fails else
                 "OK — injected inversion trips the checker"))
        return 1 if fails else 0
    try:
        report = run_race_soak(
            streams=args.streams, segments=args.segments,
            log2n=args.log2n, seed=args.seed, batch=args.batch,
            plan=args.plan, deadline_s=args.deadline, rate=args.rate)
    except (RaceSoakFailure, TsanError) as e:
        print(json.dumps({"ok": False, "failure": str(e)}))
        print(f"race-soak: GATE FAILED — {e}", file=sys.stderr)
        return 1
    except AssertionError as e:  # fleet_soak.SoakFailure
        print(json.dumps({"ok": False, "failure": str(e)}))
        print(f"race-soak: FLEET GATE FAILED under perturbation — "
              f"{e}", file=sys.stderr)
        return 1
    print(json.dumps(report, sort_keys=True))
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
