"""Render a flight-recorder dump as Chrome-trace / Perfetto JSON.

*Implementing CUDA Streams into AstroAccelerate* (PAPERS.md) argued
its overlap wins from hand-read profiler timelines; this tool gets the
same picture for free from our own causal trace: feed it an events
JSONL (``Config.events_dump_path``, or an incident bundle's
``events.jsonl``) and open the output in ``chrome://tracing`` or
https://ui.perfetto.dev —

- **one track per stream per thread**: each stream (fleet lane, or
  the solo pipeline) is a trace *process*, each of its threads a
  *track*, so a fleet's lanes sit side by side and the solo engine's
  main/sink split is visible;
- **stage slices**: ``stage.ingest`` / ``stage.dispatch`` /
  ``stage.fetch`` / ``stage.sink`` render as duration ("X") slices —
  overlap efficiency and wedge gaps become *visible* instead of
  inferred from ``overlap_hidden_ms`` aggregates;
- **flow arrows follow ``trace_id``**: every segment's journey is an
  arrow chain ingest -> dispatch -> fetch -> sink, crossing the
  engine-thread/sink-thread boundary (and lane threads in a fleet);
- **decisions as instants**: retries, fault classifications,
  heal/demote/promote/reinit, degrade/admission/shed, watchdog,
  supervisor restarts, ring transitions, manifest records and
  incident markers render as instant events on the thread where they
  happened, so "what did the healer do, exactly when" reads straight
  off the timeline.

Usage::

    python -m srtb_tpu.tools.trace_export EVENTS.jsonl [--out OUT.json]
    python -m srtb_tpu.tools.trace_export BUNDLE_DIR   [--out OUT.json]

``--validate`` only schema-checks the input/output (the CI gate —
no Perfetto needed): exit 0 when the rendered document is structurally
valid Chrome-trace JSON with matched flow bindings.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# event types rendered as duration slices (everything else = instant)
STAGE_TYPES = ("stage.ingest", "stage.dispatch", "stage.fetch",
               "stage.sink")


def load_events(path: str) -> list[dict]:
    """Read a flight-recorder dump (EventHub.dump_jsonl format); a
    directory is treated as an incident bundle (its events.jsonl)."""
    if os.path.isdir(path):
        path = os.path.join(path, "events.jsonl")
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "type" in rec and "t" in rec:
                out.append(rec)
    out.sort(key=lambda e: e["t"])
    return out


def render(events: list[dict]) -> dict:
    """Events -> Chrome-trace document (JSON-object format)."""
    if not events:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t0 = min(e["t"] for e in events)

    def us(t: float) -> float:
        return round((t - t0) * 1e6, 3)

    # pid per stream ("" = the solo pipeline), tid per (pid, thread)
    pids: dict[str, int] = {}
    tids: dict[tuple, int] = {}
    out: list[dict] = []

    def pid_of(stream: str) -> int:
        if stream not in pids:
            pids[stream] = len(pids) + 1
            out.append({"name": "process_name", "ph": "M",
                        "pid": pids[stream], "tid": 0,
                        "args": {"name": (f"stream:{stream}"
                                          if stream else "pipeline")}})
        return pids[stream]

    def tid_of(pid: int, thread: str) -> int:
        key = (pid, thread)
        if key not in tids:
            tids[key] = sum(1 for (p, _t) in tids if p == pid) + 1
            out.append({"name": "thread_name", "ph": "M", "pid": pid,
                        "tid": tids[key], "args": {"name": thread}})
        return tids[key]

    # slices + instants
    located: dict[int, list[tuple[float, int, int, str]]] = {}
    for e in events:
        stream = str(e.get("stream") or "")
        thread = str(e.get("thread") or "?")
        pid = pid_of(stream)
        tid = tid_of(pid, thread)
        etype = e["type"]
        trace = int(e.get("trace") or 0)
        args = {"trace_id": trace, "segment": e.get("seg", -1)}
        if e.get("info"):
            args["info"] = e["info"]
        if etype in STAGE_TYPES:
            dur_us = max(float(e.get("dur_ms") or 0.0) * 1e3, 0.001)
            start = us(e["t"]) - dur_us  # emitted at stage END
            out.append({"name": etype.split(".", 1)[1], "cat": "stage",
                        "ph": "X", "ts": round(start, 3),
                        "dur": round(dur_us, 3), "pid": pid,
                        "tid": tid, "args": args})
            if trace > 0:
                located.setdefault(trace, []).append(
                    (us(e["t"]) - dur_us / 2, pid, tid, etype))
        else:
            # heal/degrade/retry/manifest/... as thread-scoped instants
            out.append({"name": etype, "cat": "event", "ph": "i",
                        "s": "t", "ts": us(e["t"]), "pid": pid,
                        "tid": tid, "args": args})

    # flow arrows: one chain per trace_id across its stage slices —
    # the ingest -> dispatch -> fetch -> sink causal story, crossing
    # thread (and in a fleet, lane) boundaries
    for trace, points in sorted(located.items()):
        if len(points) < 2:
            continue
        points.sort()
        for i, (ts, pid, tid, _etype) in enumerate(points):
            ph = "s" if i == 0 else ("f" if i == len(points) - 1
                                     else "t")
            ev = {"name": "segment", "cat": "flow", "ph": ph,
                  "id": trace, "ts": round(ts, 3), "pid": pid,
                  "tid": tid}
            if ph == "f":
                ev["bp"] = "e"
            out.append(ev)
    return {"traceEvents": out, "displayTimeUnit": "ms",
            "otherData": {"source": "srtb_tpu flight recorder",
                          "streams": sorted(pids)}}


def validate(doc: dict) -> list[str]:
    """Structural Chrome-trace schema check (the CI gate).  Returns a
    list of problems (empty = valid): traceEvents is a list; every
    event carries ph/ts(or metadata)/pid/tid; X events have numeric
    dur >= 0; flow chains are well-formed (every id has exactly one
    "s" and one "f", "f" carries bp="e")."""
    problems = []
    evs = doc.get("traceEvents")
    if not isinstance(evs, list):
        return ["traceEvents is not a list"]
    flows: dict[int, list[str]] = {}
    for i, e in enumerate(evs):
        if not isinstance(e, dict):
            problems.append(f"event {i}: not an object")
            continue
        ph = e.get("ph")
        if ph not in ("X", "i", "M", "s", "t", "f"):
            problems.append(f"event {i}: unknown ph {ph!r}")
            continue
        if not isinstance(e.get("pid"), int) \
                or not isinstance(e.get("tid"), int):
            problems.append(f"event {i}: missing pid/tid")
        if ph != "M" and not isinstance(e.get("ts"), (int, float)):
            problems.append(f"event {i}: missing ts")
        if ph == "X":
            d = e.get("dur")
            if not isinstance(d, (int, float)) or d < 0:
                problems.append(f"event {i}: X without valid dur")
        if ph in ("s", "t", "f"):
            flows.setdefault(int(e.get("id", -1)), []).append(ph)
            if ph == "f" and e.get("bp") != "e":
                problems.append(f"event {i}: flow finish without "
                                "bp='e'")
    for fid, phs in flows.items():
        if phs.count("s") != 1 or phs.count("f") != 1:
            problems.append(
                f"flow {fid}: needs exactly one start + one finish "
                f"(got {phs})")
    return problems


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("events", help="events JSONL (or incident bundle "
                                  "directory)")
    p.add_argument("--out", default="",
                   help="output path (default: <events>.trace.json)")
    p.add_argument("--validate", action="store_true",
                   help="schema-check only; exit 1 on problems")
    args = p.parse_args(argv)
    events = load_events(args.events)
    if not events:
        print(json.dumps({"error": f"no events in {args.events}"}),
              file=sys.stderr)
        return 1
    doc = render(events)
    problems = validate(doc)
    if problems:
        for msg in problems:
            print(f"INVALID: {msg}", file=sys.stderr)
        return 1
    if args.validate:
        n_flow = sum(1 for e in doc["traceEvents"]
                     if e.get("cat") == "flow")
        print(f"valid Chrome-trace JSON: "
              f"{len(doc['traceEvents'])} events "
              f"({n_flow} flow bindings, "
              f"{len(doc['otherData']['streams'])} stream lane(s))")
        return 0
    out = args.out or (args.events.rstrip("/") + ".trace.json")
    with open(out, "w") as f:
        json.dump(doc, f)
    print(f"wrote {out}: {len(doc['traceEvents'])} trace events "
          f"from {len(events)} recorder events "
          f"(open in chrome://tracing or ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
