"""FFT benchmark harness: size sweep across strategies.

The analog of the reference's FFT wrapper benchmark procedure
(ref: tests/test-fft_wrappers.cpp:69-78, sweep n = 2^0..2^26 via env
vars).  Prints one JSON line per (size, strategy) with steady-state
timings; use it to tune ops.fft.LARGE_FFT_THRESHOLD / cfg.fft_strategy on
new hardware.

Usage: python -m srtb_tpu.tools.fft_bench [min_log2 [max_log2 [strategies]]]
(strategies: comma list from monolithic,four_step,mxu,pallas,pallas2)
"""
# srtb-lint: disable-file=recompile-hazard (bench harness: each (size,
# strategy) case jits one lambda once, then times steady-state repeats)

from __future__ import annotations

import json
import sys
import time

import numpy as np
from srtb_tpu.utils.platform import apply_platform_env


def bench_one(n: int, strategy: str, reps: int = 5) -> float | None:
    import jax
    import jax.numpy as jnp

    from srtb_tpu.ops import fft as F

    rng = np.random.default_rng(0)
    x = jax.device_put(rng.standard_normal(n).astype(np.float32))

    fn = jax.jit(lambda v: jnp.abs(F.segment_rfft(v, strategy)))
    try:
        jax.block_until_ready(fn(x))
    except Exception as e:
        print(f"# n=2^{n.bit_length()-1} {strategy}: {e}", file=sys.stderr)
        return None
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(x))
        times.append(time.perf_counter() - t0)
    return min(times)


def main(argv=None) -> int:
    apply_platform_env()
    argv = sys.argv[1:] if argv is None else argv
    lo = int(argv[0]) if len(argv) > 0 else 20
    hi = int(argv[1]) if len(argv) > 1 else 27
    strategies = ("monolithic", "four_step", "mxu", "pallas",
                  "pallas2")
    if len(argv) > 2:
        strategies = tuple(argv[2].split(","))
    for log2n in range(lo, hi + 1):
        n = 1 << log2n
        for strategy in strategies:
            dt = bench_one(n, strategy)
            if dt is None:
                continue
            print(json.dumps({
                "n": n, "log2n": log2n, "strategy": strategy,
                "ms": round(dt * 1e3, 3),
                "gsamples_per_s": round(n / dt / 1e9, 3),
            }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
