"""Summarize an xprof/XLA profiler trace into per-op and per-stage time.

The reference's profiling story is ad-hoc timing logs
(ref: SURVEY.md §5.1, e.g. udp_receiver_pipe.hpp:130-153 push-time
measurement); on TPU the native tool is the jax profiler's xplane trace
(`SRTB_BENCH_TRACE_DIR`), but the official converter
(tensorboard_plugin_profile) is version-locked to its TensorFlow build
and unusable in this image.  This tool reads the `.xplane.pb` wire
format directly — XSpace > XPlane > XLine > XEvent plus the metadata
maps are plain nested length-delimited messages, so a ~100-line stdlib
varint parser is enough and can never rot against a protobuf runtime.

Output: one JSON line per device plane with total time bucketed into
pipeline stages (fft / unpack / rfi+chirp / waterfall+sk / detect /
transpose+copy / other — matched on XLA fusion names), then the top-N
ops.  This is the "profile per-stage, then attack the dominant pass"
loop of PERF.md, automated.

Usage: python -m srtb_tpu.tools.trace_summary TRACE_DIR_OR_PB [--top N]
"""

from __future__ import annotations

import json
import pathlib
import re
import sys

# ---- minimal protobuf wire-format reader (varint + length-delimited) ----


def _varint(buf: memoryview, i: int):
    x = 0
    shift = 0
    while True:
        b = buf[i]
        i += 1
        x |= (b & 0x7F) << shift
        if not b & 0x80:
            return x, i
        shift += 7


def _fields(buf: memoryview):
    """Yield (field_number, wire_type, value) over one message.  LEN
    fields yield memoryviews; varints yield ints; 32/64-bit yield raw
    bytes (unused here but must be skipped correctly)."""
    i = 0
    n = len(buf)
    while i < n:
        tag, i = _varint(buf, i)
        field, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _varint(buf, i)
        elif wt == 2:
            ln, i = _varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = buf[i:i + 4]
            i += 4
        elif wt == 1:
            v = buf[i:i + 8]
            i += 8
        else:  # groups (3/4) never appear in xplane
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, v


def _map_entry(buf: memoryview):
    """protobuf map<int64, Message> entry: key=1 varint, value=2 LEN."""
    key, val = 0, b""
    for f, _, v in _fields(buf):
        if f == 1:
            key = v
        elif f == 2:
            val = v
    return key, val


def _name_of(meta_buf: memoryview) -> str:
    """XEventMetadata / XStatMetadata: name = field 2 (string)."""
    for f, wt, v in _fields(meta_buf):
        if f == 2 and wt == 2:
            return bytes(v).decode("utf-8", "replace")
    return ""


def _event_str_stats(ev_buf: memoryview, stat_names: dict[int, str]):
    """XEvent.stats (field 4): {stat_name: str_value} for string stats —
    on TPU device planes xprof attaches e.g. hlo_category / hlo_op to
    every event, which names opaque "fusion.N" events semantically."""
    out = {}
    for f, wt, v in _fields(ev_buf):
        if f != 4 or wt != 2:
            continue
        sid, sval = 0, None
        for sf, swt, sv in _fields(v):
            if sf == 1 and swt == 0:        # XStat.metadata_id
                sid = sv
            elif sf == 5 and swt == 2:      # XStat.str_value
                sval = bytes(sv).decode("utf-8", "replace")
        if sval is not None and sid in stat_names:
            out[stat_names[sid]] = sval
    return out


def parse_xspace(path: str):
    """-> [(plane_name, {(op_name, hlo_category): total_duration_ps})]

    hlo_category is "" when the trace carries no per-event category
    stat (host planes, CPU traces)."""
    raw = memoryview(pathlib.Path(path).read_bytes())
    planes = []
    for f, wt, plane in _fields(raw):
        if f != 1 or wt != 2:   # XSpace.planes
            continue
        name = ""
        meta: dict[int, str] = {}
        stat_names: dict[int, str] = {}
        lines = []
        for pf, pwt, pv in _fields(plane):
            if pf == 2 and pwt == 2:        # XPlane.name
                name = bytes(pv).decode("utf-8", "replace")
            elif pf == 3 and pwt == 2:      # XPlane.lines
                lines.append(pv)
            elif pf == 4 and pwt == 2:      # XPlane.event_metadata
                k, v = _map_entry(pv)
                meta[k] = _name_of(memoryview(v))
            elif pf == 5 and pwt == 2:      # XPlane.stat_metadata
                k, v = _map_entry(pv)
                stat_names[k] = _name_of(memoryview(v))
        want_stats = {sid for sid, nm in stat_names.items()
                      if nm in ("hlo_category", "hlo_op")}
        ops: dict[tuple[str, str], int] = {}
        for line in lines:
            for lf, lwt, lv in _fields(line):
                if lf != 4 or lwt != 2:     # XLine.events
                    continue
                mid, dur = 0, 0
                for ef, _, ev in _fields(lv):
                    if ef == 1:             # XEvent.metadata_id
                        mid = ev
                    elif ef == 3:           # XEvent.duration_ps
                        dur = ev
                cat = ""
                if want_stats:
                    stats = _event_str_stats(lv, stat_names)
                    cat = stats.get("hlo_category", "")
                key = (meta.get(mid, f"#{mid}"), cat)
                ops[key] = ops.get(key, 0) + dur
        planes.append((name, ops))
    return planes


# ---- stage bucketing (XLA fusion/op names -> pipeline stages) ----

_BUCKETS = [
    ("fft", re.compile(r"fft|dft", re.I)),
    # the Pallas kernels carry their python function names
    ("pallas_fft", re.compile(r"fft_rows|pass1|pass2|mxu", re.I)),
    ("unpack+pack", re.compile(r"unpack|planes|pack|convert|bitcast", re.I)),
    ("rfi+chirp", re.compile(r"rfi|chirp|dedisperse|zap", re.I)),
    ("waterfall+sk", re.compile(r"waterfall|sk_|kurtosis|stats", re.I)),
    ("detect", re.compile(r"detect|boxcar|time_series|cumsum|reduce-window",
                          re.I)),
    ("transpose/copy", re.compile(r"transpose|copy|reshape|concatenate|"
                                  r"slice|gather|dynamic", re.I)),
]


def bucket(op: str, category: str = "") -> str:
    """Prefer the per-event hlo_category stat (semantic even for opaque
    "fusion.N" names on TPU device planes); an opaque category ("loop
    fusion", "custom-call") or none falls through to the name regexes,
    so a broad name pattern can never override a semantic category."""
    if category:
        for name, pat in _BUCKETS:
            if pat.search(category):
                return name
    for name, pat in _BUCKETS:
        if pat.search(op):
            return name
    if category:
        return f"hlo:{category}"
    return "other"


def summarize(path: str, top: int = 15):
    """One summary dict per plane that carries events."""
    out = []
    for plane, ops in parse_xspace(path):
        if not ops:
            continue
        total = sum(ops.values())
        if total == 0:
            continue
        stages: dict[str, int] = {}
        for (op, cat), dur in ops.items():
            b = bucket(op, cat)
            stages[b] = stages.get(b, 0) + dur
        top_ops = sorted(ops.items(), key=lambda kv: -kv[1])[:top]
        out.append({
            "plane": plane,
            "total_ms": round(total / 1e9, 3),
            "stages_ms": {k: round(v / 1e9, 3)
                          for k, v in sorted(stages.items(),
                                             key=lambda kv: -kv[1])},
            "top_ops": [{"op": op[:120],
                         **({"cat": cat} if cat else {}),
                         "ms": round(d / 1e9, 3),
                         "pct": round(100.0 * d / total, 1)}
                        for (op, cat), d in top_ops],
        })
    return out


def find_xplanes(root: str):
    p = pathlib.Path(root)
    if p.is_file():
        return [p]
    return sorted(p.rglob("*.xplane.pb"))


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    top = 15
    if "--top" in argv:
        i = argv.index("--top")
        top = int(argv[i + 1])
        del argv[i:i + 2]
    if not argv:
        print("usage: trace_summary TRACE_DIR_OR_PB [--top N]",
              file=sys.stderr)
        return 2
    paths = find_xplanes(argv[0])
    if not paths:
        print(json.dumps({"error": f"no .xplane.pb under {argv[0]}"}))
        return 1
    for path in paths:
        for summary in summarize(str(path), top):
            summary["file"] = str(path)
            print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
