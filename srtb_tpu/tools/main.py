"""Main pipeline entry point (ref: src/main.cpp:88-333).

Usage:
    python -m srtb_tpu.tools.main --config_file_name srtb_config.cfg \
        [--key value ...]

Input selection follows the reference (main.cpp:241-271): if
``input_file_path`` exists, read from file; otherwise start UDP receivers.
The GUI equivalent (waterfall PNG service) activates with ``gui_enable``.
"""

from __future__ import annotations

import os
import sys

from srtb_tpu.config import Config
from srtb_tpu.ops import dedisperse as dd
from srtb_tpu.pipeline.runtime import Pipeline
from srtb_tpu.utils.logging import log
from srtb_tpu.utils.termination import install_termination_handler
from srtb_tpu.utils.platform import apply_platform_env


def main(argv=None) -> int:
    apply_platform_env()
    install_termination_handler()
    cfg = Config.from_args(argv)
    if cfg.distributed_num_processes > 1:
        from srtb_tpu.parallel.distributed import (
            maybe_initialize_from_config)
        maybe_initialize_from_config(cfg)
    if cfg.fft_fftw_wisdom_path != "off":
        from srtb_tpu.utils.compile_cache import enable_compile_cache
        enable_compile_cache(cfg.fft_fftw_wisdom_path)
    log.info(f"[main] nsamps_reserved = {dd.nsamps_reserved(cfg)}")
    if cfg.telemetry_journal_path:
        log.info("[main] segment-span journal -> "
                 f"{cfg.telemetry_journal_path} (summarize with "
                 "python -m srtb_tpu.tools.telemetry_report)")

    sinks = None
    waterfall_service = None
    gui_server = None
    if cfg.gui_http_port and not cfg.gui_enable:
        # a live viewer port only makes sense with frames being rendered
        log.info("[main] gui_http_port set: enabling the waterfall service")
        cfg.gui_enable = True
    if cfg.gui_enable:
        from srtb_tpu.gui.waterfall import WaterfallService
        n_spec = cfg.baseband_input_count // 2
        nchan = min(cfg.spectrum_channel_count, n_spec)
        out_dir = os.path.dirname(cfg.baseband_output_file_prefix) or "."
        waterfall_service = WaterfallService(
            cfg, in_freq=nchan, in_time=n_spec // nchan, out_dir=out_dir)
        if cfg.gui_http_port:
            from srtb_tpu.gui.server import WaterfallHTTPServer
            from srtb_tpu.resilience.supervisor import Supervisor
            gui_server = WaterfallHTTPServer(
                out_dir, port=cfg.gui_http_port,
                health_stale_after_s=cfg.health_stale_after_s,
                fleet_store_dir=getattr(cfg, "obs_store_dir", ""),
                # the configured restart budget covers the GUI server
                # too (config.py: supervisor_max_restarts, 0 = give up
                # on the first crash); best-effort, so fatal crashes
                # restart as well — GUI death never ends the run
                supervisor=Supervisor(
                    "gui_server",
                    max_restarts=cfg.supervisor_max_restarts,
                    window_s=cfg.supervisor_window_s,
                    restart_fatal=True)).start()

    if cfg.input_file_path and os.path.exists(cfg.input_file_path):
        source = None  # Pipeline builds the file reader
    elif cfg.input_file_path:
        log.error(f"[main] input file {cfg.input_file_path} not found")
        return 1
    elif len(cfg.udp_receiver_port) > 1:
        from srtb_tpu.io.udp import MultiUdpSource
        source = MultiUdpSource(cfg)
    else:
        from srtb_tpu.io.udp import UdpReceiverSource
        source = UdpReceiverSource(cfg)

    if cfg.dm_list:
        # multi-chip DM-trial search mode
        from srtb_tpu.pipeline.runtime import DMSearchPipeline
        search = DMSearchPipeline(cfg, source=source)
        stats = search.run()
        log.info(f"[main] dm search done: {stats.segments} segments, "
                 f"{stats.signals} with signal; trials in "
                 f"{search.trials_path}")
        return 0

    pipe = Pipeline(cfg, source=source, sinks=sinks)
    if waterfall_service is not None:
        class _Tap:
            def push(self, work, has_signal):
                if work.waterfall is not None:
                    waterfall_service.push(work.waterfall,
                                           work.segment.data_stream_id)
                    waterfall_service.render_pending()
        pipe.sinks.append(_Tap())

    try:
        stats = pipe.run()
    finally:
        pipe.close()
    if gui_server is not None:
        gui_server.stop()
    log.info(f"[main] done: {stats.segments} segments, "
             f"{stats.signals} with signal, "
             f"{stats.msamples_per_sec:.1f} Msamples/s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
