"""Live operator console: the fleet status as a terminal dashboard.

Renders :func:`srtb_tpu.obs.status.fleet_status` — pool member
states, per-stream SLO burn, roofline gauges, batch occupancy, the
migration timeline, drift alerts — as fixed-width text that reads at
a glance over ssh.  Two data paths:

- ``--url http://host:port`` polls a running ``gui/server.py``'s
  ``/fleet`` endpoint (the in-process registry view: live gauges +
  store tail);
- ``--store DIR`` reads a rollup-store directory directly — works
  with no server and no live process, e.g. against the store an
  aggregator wrote on another host (live-gauge sections render empty;
  the rollup/timeline sections carry the content).

``--once`` prints one frame and exits (CI smoke);  ``--json`` emits
the raw status dict instead of the rendering (scripting).

Usage::

    python -m srtb_tpu.tools.console --url http://localhost:8080
    python -m srtb_tpu.tools.console --store /obs/store --once
"""

from __future__ import annotations

import argparse
import json
import sys
import time

BAR_WIDTH = 24


def _bar(frac: float, width: int = BAR_WIDTH) -> str:
    frac = min(1.0, max(0.0, float(frac)))
    n = int(round(frac * width))
    return "[" + "#" * n + "-" * (width - n) + "]"


def render(status: dict) -> str:
    """One console frame from a fleet_status dict (missing sections
    render as their empty forms — a thin status is not an error)."""
    lines = []

    devices = status.get("devices") or {}
    pool = status.get("pool") or {}
    lines.append(f"POOL  members={pool.get('members', len(devices))} "
                 f"migrations={pool.get('migrations', 0)} "
                 f"drains={pool.get('device_drains', 0)} "
                 f"reinits={pool.get('device_reinits', 0)}")
    for dev, d in sorted(devices.items()):
        lines.append(f"  {dev:<8} {d.get('state', '?'):<9} "
                     f"lanes={d.get('lanes', 0)} "
                     f"drains={d.get('drains', 0)} "
                     f"migrations={d.get('migrations', 0)}")

    streams = status.get("streams") or {}
    slo = status.get("slo") or {}
    if streams:
        lines.append("STREAMS")
        for name, s in sorted(streams.items()):
            burn = ""
            for obj, st in sorted((slo.get(name) or {}).items()):
                if isinstance(st, dict):
                    burn += (f" {obj}:{st.get('state', '?')}"
                             f"({st.get('burn_fast', 0):.2f}x)")
            lines.append(
                f"  {name:<12} seg={s.get('segments', 0):<6} "
                f"drop={s.get('dropped', 0):<4} "
                f"mig={s.get('migrations', 0):<3} "
                f"roofline={s.get('roofline_frac', 0.0):.3f}"
                f"{burn}")

    roof = status.get("roofline") or {}
    lines.append(f"ROOFLINE {_bar(roof.get('frac', 0.0))} "
                 f"{roof.get('frac', 0.0):.1%} of HBM peak  "
                 f"({roof.get('msamps', 0.0)} Msamp/s, "
                 f"{roof.get('gbps', 0.0)} GB/s)")

    batch = status.get("batch") or {}
    lines.append(f"BATCH occupancy={batch.get('occupancy', 0.0):.2f} "
                 f"seg/dispatch "
                 f"({batch.get('segments', 0)} segments over "
                 f"{batch.get('dispatches', 0)} dispatches)")

    drift = status.get("drift") or {}
    lines.append(f"DRIFT score={drift.get('score', 0.0):.3f} "
                 f"alerts={drift.get('alerts', 0)}")

    store = status.get("store") or {}
    timeline = store.get("timeline") or []
    if timeline:
        lines.append("TIMELINE (fleet events, newest last)")
        for ev in timeline:
            lines.append(f"  t={ev.get('ts', 0.0):>12.3f} "
                         f"{ev.get('kind', '?'):<18} "
                         f"stream={ev.get('stream') or '-':<12} "
                         f"{ev.get('info', '')}")
    digests = store.get("digests") or {}
    if digests:
        lines.append("ROLLUPS (quantiles from the long-horizon store)")
        for key, p in sorted(digests.items()):
            lines.append(f"  {key:<24} p50={p.get('p50', 0):>9.3f} "
                         f"p95={p.get('p95', 0):>9.3f} "
                         f"p99={p.get('p99', 0):>9.3f} "
                         f"n={p.get('n', 0)}")
    return "\n".join(lines) + "\n"


def _fetch(url: str) -> dict:
    import urllib.request
    with urllib.request.urlopen(url.rstrip("/") + "/fleet",
                                timeout=10) as resp:
        return json.loads(resp.read().decode())


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--url", default="",
                     help="gui/server.py base URL (polls /fleet)")
    src.add_argument("--store", default="",
                     help="rollup-store directory (serverless mode)")
    p.add_argument("--once", action="store_true",
                   help="one frame, then exit")
    p.add_argument("--json", action="store_true",
                   help="emit the raw status dict, not the rendering")
    p.add_argument("--interval", type=float, default=2.0)
    args = p.parse_args(argv)
    while True:
        try:
            if args.url:
                status = _fetch(args.url)
            else:
                from srtb_tpu.obs.status import fleet_status
                status = fleet_status(store_dir=args.store)
        except OSError as e:
            print(f"console: status fetch failed: {e}",
                  file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(status, sort_keys=True))
        else:
            print(render(status), end="")
        if args.once:
            return 0
        time.sleep(max(0.2, args.interval))


if __name__ == "__main__":
    sys.exit(main())
