"""CLI driver for the archive replay engine (pipeline/archive.py).

Replay recorded baseband files at full device occupancy — no pacing,
deep micro-batch, files fanned across fleet lanes — with exactly-once
manifest-backed outputs and deterministic resume: re-running the same
command after a crash resumes every file from its checkpoint and the
final output set is bit-identical to an uninterrupted run.

Usage::

    python -m srtb_tpu.tools.archive_replay \
        --files "obs1.bin,obs2.bin" --out-dir replay_out \
        [--config srtb_config.cfg] [--set key=value ...] \
        [--lanes 2] [--micro-batch 4] [--fleet-batch B] \
        [--inflight 8] [--max-segments N] [--no-waterfall]

``--set`` applies config options on top of ``--config`` (same syntax
as the config file, e.g. ``--set search_mode=periodicity``).

``--selftest`` runs the CI gate: two synthetic files, a mid-run
SIGTERM steered into a sink-write window of one lane, a resumed
replay to completion, and the union of outputs compared path+SHA-256
bit-identical against per-file streamed golden runs (plus fsck-clean
manifests and a no-orphan-temps sweep).  Exit 0 on pass.
"""

from __future__ import annotations

import argparse
import glob as globlib
import hashlib
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

_FIRING_MARK = "[faults] firing"
CHILD_TIMEOUT_S = 300.0


class ReplayFailure(AssertionError):
    """One broken archive-replay invariant (the selftest gate)."""


def _expand_files(arg: str) -> list[str]:
    files: list[str] = []
    for part in (p.strip() for p in arg.split(",")):
        if not part:
            continue
        matches = sorted(globlib.glob(part))
        files.extend(matches if matches else [part])
    return files


def _base_cfg(args) -> "Config":
    from srtb_tpu.config import Config
    cfg = Config()
    if args.config:
        cfg.load_file(args.config)
    for kv in args.set or []:
        if "=" not in kv:
            raise SystemExit(f"--set expects key=value, got {kv!r}")
        key, value = kv.split("=", 1)
        if not cfg.set_option(key, value):
            raise SystemExit(f"--set: unknown config option {key!r}")
    if args.fault_plan:
        cfg.fault_plan = args.fault_plan
    return cfg


def run_replay(args) -> int:
    from srtb_tpu.pipeline.archive import ArchiveReplay

    files = _expand_files(args.files)
    if not files:
        raise SystemExit("no input files (--files)")
    engine = ArchiveReplay(
        _base_cfg(args), files, args.out_dir,
        lanes=args.lanes, micro_batch=args.micro_batch,
        inflight=args.inflight,
        keep_waterfall=not args.no_waterfall,
        max_segments_per_file=args.max_segments or None,
        fleet_batch=args.fleet_batch)
    report = engine.run().as_dict()
    print(json.dumps(report, sort_keys=True), flush=True)
    return 0 if report["ok"] else 1


# ----------------------------------------------------------------
# selftest: the archive-replay CI gate
# ----------------------------------------------------------------

def _sha_map(dirpath: str, bookkeeping_suffixes=(".ck.json",
                                                 ".ck.json.bak",
                                                 ".manifest.jsonl",
                                                 ".journal.jsonl")) -> dict:
    """relative artifact name -> sha256 (bookkeeping excluded)."""
    out = {}
    for name in sorted(os.listdir(dirpath)):
        p = os.path.join(dirpath, name)
        if not os.path.isfile(p) or \
                any(name.endswith(s) for s in bookkeeping_suffixes):
            continue
        h = hashlib.sha256()
        with open(p, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        out[name] = h.hexdigest()
    return out


def _science_cfg(n: int) -> dict:
    """The selftest's science config (the crash-soak recipe: every
    segment positive and writing artifacts, so every kill window has
    writes to land in and every segment joins the equality union)."""
    return dict(
        baseband_input_count=n, baseband_input_bits=8,
        baseband_freq_low=1405.0, baseband_bandwidth=64.0,
        baseband_sample_rate=128e6, dm=0.05,
        spectrum_channel_count=64,
        mitigate_rfi_average_method_threshold=1000.0,
        mitigate_rfi_spectral_kurtosis_threshold=50.0,
        signal_detect_signal_noise_threshold=1.5,
        signal_detect_max_boxcar_length=8,
        baseband_reserve_sample=True,
        writer_thread_count=0,
        fft_strategy="four_step")


def _make_archive_file(tmp: str, tag: str, n: int, segments: int,
                       seed: int) -> str:
    from srtb_tpu.config import Config
    from srtb_tpu.io.synth import make_dispersed_baseband
    from srtb_tpu.ops import dedisperse as dd

    probe = Config(**_science_cfg(n))
    reserved = int(dd.nsamps_reserved(probe))
    stride = max(1, n - reserved)
    total = n * segments
    pulses = [reserved + i * stride + stride // 2
              for i in range((total - reserved) // stride + 1)
              if reserved + i * stride + stride // 2 < total]
    path = os.path.join(tmp, f"{tag}.bin")
    make_dispersed_baseband(total, 1405.0, 64.0, 0.05,
                            pulse_positions=pulses, pulse_amp=40.0,
                            nbits=8, seed=seed).tofile(path)
    return path


def _spawn_replay(files: list[str], out_dir: str, n: int,
                  fault_plan: str = "", kill_on: str | None = None,
                  micro_batch: int = 2, inflight: int = 4,
                  timeout_s: float = CHILD_TIMEOUT_S) -> dict:
    """One archive_replay subprocess; with ``kill_on`` set, SIGTERM it
    as soon as that marker appears on its merged output (the archive
    analog of the crash soak's steered SIGKILL — SIGTERM's default
    disposition kills the process with no cleanup, mid-stall)."""
    cmd = [sys.executable, "-m", "srtb_tpu.tools.archive_replay",
           "--files", ",".join(files), "--out-dir", out_dir,
           "--micro-batch", str(micro_batch),
           "--inflight", str(inflight), "--lanes", "2"]
    for k, v in sorted(_science_cfg(n).items()):
        # bools ride the config-file syntax (0/1), like load_file
        cmd += ["--set", f"{k}={int(v) if isinstance(v, bool) else v}"]
    if fault_plan:
        cmd += ["--fault-plan", fault_plan]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True,
                            bufsize=1, env=env)
    backstop = threading.Timer(timeout_s, proc.kill)
    backstop.daemon = True
    backstop.start()
    killed = False
    report = None
    lines: list[str] = []
    try:
        for line in proc.stdout:
            lines.append(line.rstrip("\n"))
            if line.startswith("{"):
                try:
                    report = json.loads(line)
                except ValueError:
                    pass
            if kill_on is not None and not killed and kill_on in line:
                time.sleep(0.25)   # land the signal INSIDE the stall
                proc.terminate()   # SIGTERM: dies mid-write, no cleanup
                killed = True
        rc = proc.wait()
    finally:
        backstop.cancel()
        proc.stdout.close()
    return {"rc": rc, "killed": killed, "report": report,
            "lines": lines}


def run_selftest(segments: int = 4, log2n: int = 13,
                 tmpdir: str | None = None) -> dict:
    """The archive-replay gate (ci.sh), two legs:

    1. **exactly-once leg** (micro_batch=1): a 2-file fleet-fanned
       replay killed mid-run by a steered SIGTERM, then resumed to
       completion — final output set (paths + SHA-256) BIT-IDENTICAL
       to per-file streamed goldens, fsck-clean manifests, no orphan
       temps.  Unbatched lanes run the exact programs the streamed
       golden ran, so bitwise equality is the honest bar here.
    2. **micro-batch leg** (micro_batch=2): the vmapped batch plan is
       a different XLA program, so the repo's established contract
       applies (test_overlap): same artifact SET (identical
       decisions), raw .bin dumps bit-identical, float artifacts
       (.tim/.npy) allclose within the documented tolerance.
    """
    import numpy as np

    from srtb_tpu.config import Config
    from srtb_tpu.pipeline.archive import ArchiveReplay
    from srtb_tpu.pipeline.runtime import Pipeline
    from srtb_tpu.tools.fsck import fsck

    tmp = tmpdir or tempfile.mkdtemp(prefix="srtb_archive_")
    n = 1 << log2n

    def check(cond, msg):
        if not cond:
            raise ReplayFailure(msg)

    files = [_make_archive_file(tmp, f"bb{i}", n, segments, seed=i)
             for i in range(2)]

    # ---- per-file STREAMED goldens: the solo serial engine, no
    # batching, no fleet — the reference outputs the replay must hit
    # byte-for-byte.  Deterministic timestamps give both sides the
    # same artifact names.
    golden_dir = os.path.join(tmp, "golden")
    os.makedirs(golden_dir, exist_ok=True)
    golden_segments = 0
    for i, f in enumerate(files):
        cfg = Config(**_science_cfg(n)).replace(
            input_file_path=f,
            baseband_output_file_prefix=os.path.join(
                golden_dir, f"bb{i}_"),
            deterministic_timestamps=True,
            micro_batch_segments=1, inflight_segments=2)
        with Pipeline(cfg) as pipe:
            stats = pipe.run()
        golden_segments += stats.segments
        check(stats.signals > 0, f"golden run of {f} detected "
              "nothing — the gate would compare empty output sets")
    golden_map = _sha_map(golden_dir)
    check(golden_map, "golden runs produced no artifacts")

    # ---- leg 1: replay killed mid-run.  A stream-scoped sink_write
    # stall parks lane bb0's sink thread between fetch and artifact
    # write; SIGTERM lands inside the stall (no cleanup, the manifest
    # holds uncommitted state).  micro_batch=1: these lanes dispatch
    # the exact programs the goldens ran, so the equality below is
    # bitwise.
    replay_dir = os.path.join(tmp, "replay")
    os.makedirs(replay_dir, exist_ok=True)
    res = _spawn_replay(files, replay_dir, n,
                        fault_plan="bb0:sink_write:stall=30@1",
                        kill_on=_FIRING_MARK,
                        micro_batch=1, inflight=4)
    check(res["killed"], "the steered SIGTERM never fired (fault "
          "marker not seen):\n" + "\n".join(res["lines"][-15:]))
    check(res["rc"] != 0, "child exited 0 despite the mid-run kill")

    # the kill must land mid-file: a resume that has nothing to do
    # would gate nothing
    ck_path = os.path.join(replay_dir, "bb0.ck.json")
    done = 0
    if os.path.exists(ck_path):
        with open(ck_path) as f:
            done = int(json.load(f).get("segments_done", 0))
    check(done < segments, "kill landed after bb0 completed — "
          "nothing left to resume (tighten the fault index)")

    # ---- resumed replay to completion: checkpoints resume each
    # file, manifest recovery rolls back uncommitted artifacts
    res2 = _spawn_replay(files, replay_dir, n, micro_batch=1,
                         inflight=4)
    check(res2["rc"] == 0, "resumed replay failed:\n"
          + "\n".join(res2["lines"][-15:]))
    report = res2["report"]
    check(report is not None and report["ok"],
          f"resumed replay report not ok: {report}")

    # ---- gates ----
    for i in range(2):
        man = os.path.join(replay_dir, f"bb{i}.manifest.jsonl")
        check(os.path.exists(man), f"missing manifest {man}")
        rep = fsck(man, os.path.join(replay_dir, f"bb{i}.ck.json"))
        check(rep["clean"], f"fsck NOT clean for bb{i}: "
              f"errors={rep['errors']} loss={rep['loss']}")
    orphans = [f for f in os.listdir(replay_dir)
               if f.endswith(".srtb_tmp")]
    check(not orphans, f"orphan temps survive the resume: {orphans}")

    replay_map = _sha_map(replay_dir)
    missing = sorted(set(golden_map) - set(replay_map))
    extra = sorted(set(replay_map) - set(golden_map))
    check(not missing, f"artifacts LOST vs streamed golden: {missing}")
    check(not extra, f"duplicate/unknown artifacts vs golden: {extra}")
    differing = sorted(k for k in golden_map
                       if golden_map[k] != replay_map[k])
    check(not differing, "artifact bytes differ from the streamed "
          f"golden: {differing}")

    # ---- leg 2: the micro-batched throughput mode (in-process —
    # nothing crashes here).  Decisions must be IDENTICAL (same
    # artifact set, raw .bin dumps bitwise equal); float artifacts
    # carry the vmapped plan's documented tolerance.
    batch_dir = os.path.join(tmp, "batch")
    batch_rep = ArchiveReplay(Config(**_science_cfg(n)), files,
                              batch_dir, lanes=2, micro_batch=2,
                              inflight=4).run()
    check(batch_rep.failed == 0,
          f"micro-batched replay leg failed: {batch_rep.as_dict()}")
    batch_map = _sha_map(batch_dir)
    check(set(batch_map) == set(golden_map),
          "micro-batched replay wrote a different artifact set "
          "(decisions drifted): only-batch="
          f"{sorted(set(batch_map) - set(golden_map))} only-golden="
          f"{sorted(set(golden_map) - set(batch_map))}")
    for name in sorted(golden_map):
        gp = os.path.join(golden_dir, name)
        bp = os.path.join(batch_dir, name)
        if name.endswith(".npy"):
            a, b = np.load(gp), np.load(bp)
            np.testing.assert_allclose(
                b, a, rtol=1e-5, atol=1e-3 * np.abs(a).max(),
                err_msg=f"micro-batched {name} beyond tolerance")
        elif name.endswith(".tim"):
            a = np.fromfile(gp, dtype=np.float32)
            b = np.fromfile(bp, dtype=np.float32)
            np.testing.assert_allclose(
                b, a, rtol=1e-5, atol=1e-4 * np.abs(a).max(),
                err_msg=f"micro-batched {name} beyond tolerance")
        else:  # raw baseband dumps are input bytes: bitwise
            check(golden_map[name] == batch_map[name],
                  f"micro-batched raw dump {name} differs bitwise")

    return {
        "ok": True, "files": 2, "segments": golden_segments,
        "artifacts": len(golden_map), "killed_mid_run": True,
        "bb0_segments_at_kill": done,
        "replay_seg_s": report.get("segments_per_sec"),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="archive-replay",
        description="full-throughput archive replay of recorded "
                    "baseband files (see srtb_tpu/pipeline/archive.py)")
    ap.add_argument("--files", default="",
                    help="comma-separated file paths / globs")
    ap.add_argument("--out-dir", default="archive_out")
    ap.add_argument("--config", default="",
                    help="config file applied before --set overrides")
    ap.add_argument("--set", action="append", default=[],
                    metavar="KEY=VALUE",
                    help="config override (repeatable)")
    ap.add_argument("--lanes", type=int, default=2,
                    help="files replayed concurrently (fleet lanes)")
    ap.add_argument("--micro-batch", type=int, default=4)
    ap.add_argument("--fleet-batch", type=int, default=0,
                    help="cross-tenant batch width: fold ready "
                         "segments from DIFFERENT files into one "
                         "vmapped dispatch (needs --micro-batch 1; "
                         "0 = off)")
    ap.add_argument("--inflight", type=int, default=8)
    ap.add_argument("--max-segments", type=int, default=0,
                    help="cap segments per file (0 = whole file)")
    ap.add_argument("--no-waterfall", action="store_true",
                    help="drop waterfalls before the sinks (detect-"
                         "only replay)")
    ap.add_argument("--fault-plan", default="",
                    help=argparse.SUPPRESS)  # selftest steering
    ap.add_argument("--selftest", action="store_true",
                    help="run the CI gate (synthetic 2-file replay + "
                         "SIGTERM resume, bit-identical to goldens)")
    ap.add_argument("--segments", type=int, default=4,
                    help="selftest: segments per synthetic file")
    ap.add_argument("--log2n", type=int, default=13,
                    help="selftest: segment size exponent")
    args = ap.parse_args(argv)

    if args.selftest:
        try:
            report = run_selftest(segments=args.segments,
                                  log2n=args.log2n)
        except ReplayFailure as e:
            print(json.dumps({"ok": False, "failure": str(e)}))
            print(f"archive-replay: GATE FAILED — {e}",
                  file=sys.stderr)
            return 1
        print(json.dumps(report, sort_keys=True))
        return 0

    return run_replay(args)


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
