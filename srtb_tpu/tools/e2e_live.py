"""Live UDP -> device -> candidates end-to-end harness.

The reference runs its whole graph off live packets in one process
(ref: src/main.cpp:261-271 composes udp_receiver_pipe -> unpack -> fft
-> rfi -> dedisperse -> ... -> write_signal_pipe; README.md:320-322
documents the production deployment).  Ingest soak (udp_soak) and
file-fed compute (bench.py) each prove half of that; this harness
proves the composition: a paced loopback sender streams dispersed-pulse
baseband packets at a multiple of the real-time wire rate, a
UdpReceiverSource assembles segments, the ThreadedPipeline overlaps
device dispatch with drain, candidates land on disk, and /metrics is
live-served over HTTP throughout.

Emits ONE JSON line (append with --out E2E_LIVE.jsonl).  Throughput is
reported under TWO explicitly-labeled denominators (they differ, and an
ambiguous single number invites the wrong comparison):

  window   -- the offered-load window only: samples drained / wall time
              between "compile done, senders released" and pipeline
              completion.  This is the keep-up-with-the-wire claim and
              the number to compare against rate_x.
  lifetime -- samples / process elapsed since metrics.reset() at harness
              start, i.e. including jit compile and warmup.  This is
              what an operator computing "bytes on disk / wall clock of
              the observation" would see.

  {"harness": "e2e_live", "seconds": window wall, "rate_x": sender pace,
   "segments": N, "msamples_per_s_window": ..., "vs_realtime_window": ...,
   "lifetime_seconds": ..., "msamples_per_s_lifetime": ...,
   "vs_realtime_lifetime": ..., "packets_total": ..., "packets_lost": ...,
   "loss_rate": ..., "signals": ..., "deadline_hits": 0,
   "metrics_http": {...}}

Zero loss + vs_realtime_window >= rate_x means the process kept up with the
offered load end to end; deadline_hits is 0 by construction when the
line is emitted at all (a tripped segment_deadline_s aborts loudly,
the reference's fail-fast philosophy).
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import struct
import sys
import threading
import time

from srtb_tpu.config import Config
from srtb_tpu.io import formats
from srtb_tpu.utils.logging import log
from srtb_tpu.utils.platform import apply_platform_env


def _sender(port: int, fmt, payload_segment: bytes, pace_pps: float,
            started: threading.Event, stop: threading.Event):
    """Stream ``payload_segment`` cyclically as counter-sequential packets
    at ``pace_pps``, then trail off slowly so the receiver's in-progress
    block completes (same flush trick as udp_soak)."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.connect(("127.0.0.1", port))
    payload = fmt.payload_bytes
    n_slices = len(payload_segment) // payload
    header_size = fmt.packet_header_size

    def send(c):
        head = struct.pack("<Q", c) + b"\x00" * (header_size - 8) \
            if header_size >= 8 else b""
        off = (c % n_slices) * payload
        try:
            sock.send(head + payload_segment[off:off + payload])
        except OSError:
            pass  # kernel buffer overflow surfaces as counter-gap loss

    started.wait()
    chunk = 32
    t0 = time.perf_counter()
    c = 0
    while not stop.is_set():
        send(c)
        c += 1
        if c % chunk == 0:
            lag = c / pace_pps - (time.perf_counter() - t0)
            if lag > 0:
                time.sleep(lag)
    for _ in range(4 * 64):  # flush any partially-assembled block
        send(c)
        c += 1
        time.sleep(0.0005)
    sock.close()


def run(args) -> dict:
    import numpy as np

    from srtb_tpu.gui.server import WaterfallHTTPServer
    from srtb_tpu.io.synth import make_dispersed_baseband
    from srtb_tpu.io.udp import UdpReceiverSource
    from srtb_tpu.pipeline.runtime import ThreadedPipeline
    from srtb_tpu.utils.metrics import metrics

    n = 1 << args.log2n
    ports = [args.port + i for i in range(args.receivers)]
    cfg = Config(
        baseband_input_count=n,
        baseband_input_bits=2,
        baseband_format_type="fastmb_roach2",
        baseband_freq_low=1405.0 + 32.0,
        baseband_bandwidth=-64.0,
        baseband_sample_rate=128e6,
        dm=-478.80,
        spectrum_channel_count=1 << args.log2chan,
        signal_detect_signal_noise_threshold=8.0,
        signal_detect_max_boxcar_length=64,
        mitigate_rfi_spectral_kurtosis_threshold=1.05,
        baseband_reserve_sample=False,
        baseband_output_file_prefix=args.prefix,
        udp_receiver_address=["127.0.0.1"] * len(ports),
        udp_receiver_port=ports,
        udp_packet_provider=args.provider,
        udp_receiver_rcvbuf_bytes=args.rcvbuf_bytes,
        segment_deadline_s=args.deadline_s,
        fft_strategy=args.fft_strategy,
    )
    fmt = formats.resolve(cfg.baseband_format_type)
    metrics.reset()

    # one segment of J1644-parameter baseband with a centered dispersed
    # pulse, streamed cyclically -> every assembled segment carries a
    # detectable pulse wherever the cycle boundary lands... conservative:
    # pulses at 1/4 and 3/4 so any rotation keeps one intact
    seg_bytes = cfg.segment_bytes(1)
    payload_segment = make_dispersed_baseband(
        n, cfg.baseband_freq_low, cfg.baseband_bandwidth, cfg.dm,
        pulse_positions=[n // 4, 3 * n // 4], pulse_amp=40.0,
        nbits=2, seed=5).tobytes()
    assert len(payload_segment) == seg_bytes

    real_time_bps = cfg.baseband_sample_rate * 2 / 8  # 2-bit payload
    pace_pps = args.rate_x * real_time_bps / fmt.payload_bytes
    if args.max_segments > 0:
        # explicit cap (overload runs: the auto formula assumes the
        # pipeline keeps up, which is exactly what an overload test
        # disproves — the run must still terminate)
        expected_segments = args.max_segments
    else:
        expected_segments = max(1, int(
            args.seconds * args.rate_x * cfg.baseband_sample_rate / n)) \
            * len(ports)  # each receiver contributes its own stream

    started = threading.Event()
    stop = threading.Event()
    senders = [threading.Thread(
        target=_sender, args=(port, fmt, payload_segment, pace_pps,
                              started, stop),
        name=f"e2e-live-sender-{port}", daemon=True) for port in ports]
    for s in senders:
        s.start()

    # serve the directory the WaterfallService writes frames into, not
    # the file prefix itself (with the default prefix /tmp/e2e_live/out_
    # that "directory" doesn't exist and /frames.json stays empty)
    http_srv = WaterfallHTTPServer(os.path.dirname(args.prefix) or ".",
                                   port=args.http_port).start()
    if len(ports) > 1:
        # the reference's production shape: one udp_receiver_pipe per
        # polarization (ref: main.cpp:261-271) -> MultiUdpSource
        from srtb_tpu.io.udp import MultiUdpSource
        src = MultiUdpSource(cfg)
    else:
        src = UdpReceiverSource(cfg)
    # lossy waterfall tap (the reference streams its QML waterfall from
    # the same live pipeline, ref: main.cpp + spectrum_image_provider):
    # keep the device handle, but fetch + render at most every
    # --gui_min_interval_s so a slow render can never backpressure the
    # wire-rate drain — frames in between are simply dropped
    waterfall_service = None
    gui_frames = [0]
    if args.gui:
        import glob

        from srtb_tpu.gui.waterfall import WaterfallService
        # clear stale frames from a prior run of the same prefix: the
        # served-frames self-check below must count THIS run's renders,
        # not last run's leftovers
        for old in glob.glob(os.path.join(
                os.path.dirname(args.prefix) or ".",
                "waterfall_s*_*.png")):
            try:
                os.remove(old)
            except OSError:
                pass
        n_spec = n // 2
        nchan = min(cfg.spectrum_channel_count, n_spec)
        waterfall_service = WaterfallService(
            cfg, in_freq=nchan, in_time=n_spec // nchan,
            out_dir=os.path.dirname(args.prefix) or ".")
    # keep_waterfall stays False: only the tap (wants_waterfall) sees
    # the handle — the candidate writer must NOT start dumping a
    # full waterfall .npy per positive segment during a rate benchmark
    pipe = ThreadedPipeline(cfg, source=src, keep_waterfall=False)
    if waterfall_service is not None:
        last_render = [0.0]

        class _LossyTap:
            wants_waterfall = True

            def push(self, work, has_signal):
                now = time.perf_counter()
                if (work.waterfall is None
                        or now - last_render[0] < args.gui_min_interval_s):
                    return
                last_render[0] = now
                waterfall_service.push(work.waterfall,
                                       work.segment.data_stream_id)
                waterfall_service.render_pending()
                gui_frames[0] += 1
        pipe.sinks.append(_LossyTap())
    try:
        # compile BEFORE offering load: the first jit of the segment
        # program takes seconds (CPU) to minutes (TPU tunnel), during
        # which nothing drains and the kernel socket buffer overflows —
        # measured 2.9% startup loss at even 0.05x rate without this
        warm = np.frombuffer(payload_segment, dtype=np.uint8)
        wf, det = pipe.processor.process(warm)
        np.asarray(det.signal_counts)
        del wf, det
        log.info("[e2e_live] pipeline compiled; starting offered load")
        started.set()
        t0 = time.perf_counter()
        stats = pipe.run(max_segments=expected_segments)
        wall = time.perf_counter() - t0
    finally:
        stop.set()
        for s in senders:
            s.join(timeout=5)
        src.close()
        pipe.close()

    # live /metrics snapshot over real HTTP, part of what this proves
    import urllib.request
    with urllib.request.urlopen(
            f"http://127.0.0.1:{http_srv.port}/metrics.json",
            timeout=10) as r:
        metrics_http = json.loads(r.read().decode())
    gui_frames_served = None
    if args.gui:
        # self-verifying: the server must actually list the frames the
        # tap rendered (regression guard for serving the wrong
        # directory, where /frames.json stayed empty forever)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{http_srv.port}/frames.json",
                timeout=10) as r:
            streams = json.loads(r.read().decode()).get("streams", {})
        gui_frames_served = sum(len(v) for v in streams.values())
    http_srv.stop()

    total = metrics_http.get("packets_total", 0.0)
    lost = metrics_http.get("packets_lost", 0.0)
    # window: the offered-load window (post-compile); lifetime: metrics
    # clock since reset() at harness start, incl. compile/warmup.  Both
    # labeled — see module docstring for which claim each supports.
    window_msps = stats.samples / wall / 1e6 if wall else 0.0
    lifetime_s = metrics_http.get("elapsed_s", 0.0)
    lifetime_msps = metrics_http.get("msamples_per_sec", 0.0)
    out = {
        "harness": "e2e_live",
        "seconds": round(wall, 1),
        "rate_x": args.rate_x,
        "log2n": args.log2n,
        "receivers": len(ports),
        "provider": args.provider,
        "segments": stats.segments,
        "msamples_per_s_window": round(window_msps, 1),
        "vs_realtime_window": round(window_msps * 1e6
                                    / cfg.baseband_sample_rate, 3),
        "lifetime_seconds": round(lifetime_s, 1),
        "msamples_per_s_lifetime": round(lifetime_msps, 1),
        "vs_realtime_lifetime": round(lifetime_msps * 1e6
                                      / cfg.baseband_sample_rate, 3),
        "packets_total": int(total),
        "packets_lost": int(lost),
        "loss_rate": round(lost / total, 6) if total else None,
        "signals": stats.signals,
        "deadline_s": args.deadline_s,
        "deadline_hits": 0,  # a hit aborts before this line is reached
        "gui_frames": gui_frames[0] if waterfall_service else None,
        "gui_frames_served": gui_frames_served,
        "metrics_http": metrics_http,
    }
    try:
        import jax
        out["platform"] = jax.default_backend()
    except Exception:  # pragma: no cover
        pass
    return out


def main(argv=None) -> int:
    apply_platform_env()
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seconds", type=float, default=60.0,
                   help="offered-load duration (sender keeps this pace)")
    p.add_argument("--rate_x", type=float, default=2.0,
                   help="sender pace as a multiple of the 128 MSa/s "
                        "real-time wire rate")
    p.add_argument("--log2n", type=int, default=24)
    p.add_argument("--log2chan", type=int, default=11)
    p.add_argument("--port", type=int, default=42150)
    p.add_argument("--receivers", type=int, default=1,
                   help="N receivers on ports port..port+N-1 "
                        "(MultiUdpSource, the reference's per-pol shape)")
    p.add_argument("--http_port", type=int, default=0)
    p.add_argument("--provider", default="recvmmsg",
                   choices=["recvmmsg", "packet_ring", "recvfrom",
                            "asyncio"])
    p.add_argument("--deadline_s", type=float, default=0.0)
    p.add_argument("--rcvbuf_bytes", type=int, default=1 << 28,
                   help="SO_RCVBUF request for the receiver sockets "
                        "(small values make overload surface as prompt "
                        "accounted loss)")
    p.add_argument("--max_segments", type=int, default=0,
                   help="stop after this many drained segments "
                        "(0 = derive from --seconds and --rate_x; "
                        "required for overload runs, where the offered "
                        "load exceeds the compute rate by design)")
    p.add_argument("--fft_strategy", default="auto")
    p.add_argument("--gui", action="store_true",
                   help="lossy waterfall tap + renderer during the run")
    p.add_argument("--gui_min_interval_s", type=float, default=0.5)
    p.add_argument("--prefix", default="/tmp/e2e_live/out_")
    p.add_argument("--out", default="",
                   help="append the JSON line to this file too")
    args = p.parse_args(argv)

    import os
    os.makedirs(os.path.dirname(args.prefix) or ".", exist_ok=True)
    result = run(args)
    line = json.dumps(result)
    print(line)
    if args.out:
        with open(args.out, "a") as f:
            f.write(json.dumps({
                "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                **result}) + "\n")
    log.info(f"[e2e_live] {result['segments']} segments, "
             f"{result['vs_realtime_window']}x real-time (window), "
             f"{result['vs_realtime_lifetime']}x (lifetime), "
             f"loss {result['loss_rate']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
