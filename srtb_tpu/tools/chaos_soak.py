"""Chaos soak: seeded randomized device-fault runs with an
accounted-loss-only gate.

``udp_soak --fault-plan`` injects ONE hand-written plan; this harness
*generates* fault plans from a seed (site x action x segment index,
including the device-fault classes ``oom`` / ``compile_fail`` /
``device_halt`` that exercise the self-healing compute ladder,
resilience/demote.py) and runs the full pipeline end-to-end three
times:

1. **clean, ladder off** — the reference output;
2. **clean, ladder armed** — must be BIT-identical to (1): arming the
   self-healing machinery on a healthy run costs nothing and changes
   nothing (the zero-cost-off acceptance);
3. **chaos** — the generated plan injected, healing armed.

The gate then asserts the self-healing contract:

- the run completes and every planned fault actually fired;
- loss is accounted-only: every source segment is either drained or
  counted in ``segments_dropped`` (nothing vanishes);
- every drained segment's detection DECISIONS (signal counts, zapped-
  channel counts, positives) equal the clean run's exactly, and the
  detection time series matches within the demoted plans' documented
  tolerance (the fused/unfused/staged/monolithic parity bounds of
  tests/test_fusion.py) — recovery may change the plan, never the
  science;
- the recovery counters match the injected plan EXACTLY:
  ``plan_demotions`` == injected oom+compile faults,
  ``device_reinits`` == injected halts, and the retry counter covers
  the transient injections — silent recovery is indistinguishable
  from a pipeline that never faults, so the soak demands the books
  balance to the fault.

``--selftest`` proves the gate itself is sharp: a fault class the
healer does NOT handle (an injected fatal; a device fault with
healing disabled) must fail the soak, not pass it.

Pool-scoped halts: a plan entry ``device:halt@K`` is NOT a pipeline
fault-injector spec — it schedules the elastic pool's deterministic
virtual halt (``pipeline/pool.py``) on one pool member after K of
ITS dispatches.  Entries are stripped from the pipeline plan and run
as a fourth phase: one stream on a ``len(entries)+1``-member virtual
pool, entry i armed on member i, so every halt has a survivor to
drain onto.  The gate: the run completes with zero loss, decisions
(and time series — migration stays at rung 0) BIT-equal the clean
reference, ``device_drains`` matches the scheduled halts exactly,
every halt produced a live migration, and no halt escalated to a
fleet-wide reinit.

Usage::

    python -m srtb_tpu.tools.chaos_soak [--seed N] [--segments N]
        [--faults N] [--plan PLAN] [--log2n N] [--promote-after N]
        [--selftest]

Exit 0 on a passing soak (or sharp selftest), 1 on any gate failure.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import tempfile

import numpy as np

# actions the generator may schedule, with rough weights: device
# faults are the point of this harness, the PR-4 classes keep their
# recovery paths soaked alongside
_ACTIONS = ("oom", "compile_fail", "device_halt", "raise", "corrupt",
            "stall")
_WEIGHTS = (3, 3, 2, 2, 1, 1)
_DEVICE = ("oom", "compile_fail", "device_halt")
_DEVICE_SITES = ("h2d", "dispatch", "fetch")
_HOST_SITES = ("ingest", "h2d", "dispatch", "fetch", "sink_write",
               "checkpoint")


class SoakFailure(AssertionError):
    """One broken soak invariant (the gate)."""


def _base_cfg(tmp: str, n: int, tag: str, **extra):
    from srtb_tpu.config import Config
    return Config(
        baseband_input_count=n, baseband_input_bits=8,
        baseband_freq_low=1405.0, baseband_bandwidth=64.0,
        baseband_sample_rate=128e6, dm=0.05,
        input_file_path=os.path.join(tmp, "bb.bin"),
        baseband_output_file_prefix=os.path.join(tmp, tag + "_"),
        spectrum_channel_count=64,
        mitigate_rfi_average_method_threshold=100.0,
        mitigate_rfi_spectral_kurtosis_threshold=2.0,
        baseband_reserve_sample=True,  # overlap-save: the ring rung is live
        writer_thread_count=0,
        fft_strategy="four_step",
        inflight_segments=2,
        retry_backoff_base_s=0.001,
        **extra)


def generate_plan(seed: int, segments: int, faults: int,
                  max_demotions: int, max_halts: int) -> str:
    """Seeded random fault plan: distinct (site, index) pairs, device
    actions only at device sites, demotable/halt fault counts capped
    so the configured ladder and reinit budget can absorb the whole
    plan (the gate asserts exact counter matches, which requires every
    injected fault to be recoverable by construction)."""
    rng = random.Random(seed)
    entries, used = [], set()
    demotions = halts = 0
    attempts = 0
    while len(entries) < faults and attempts < 200:
        attempts += 1
        action = rng.choices(_ACTIONS, weights=_WEIGHTS)[0]
        if action in ("oom", "compile_fail") \
                and demotions >= max_demotions:
            continue
        if action == "device_halt" and halts >= max_halts:
            continue
        site = rng.choice(_DEVICE_SITES if action in _DEVICE
                          else _HOST_SITES)
        # index >= 1 keeps the first segment clean (the cold dispatch
        # that arms the ring); < segments so every fault fires
        index = rng.randrange(1, segments)
        if (site, index) in used:
            continue
        used.add((site, index))
        if action in ("oom", "compile_fail"):
            demotions += 1
        elif action == "device_halt":
            halts += 1
        arg = "=0.05" if action == "stall" else ""
        entries.append(f"{site}:{action}{arg}@{index}")
    return ",".join(entries)


def _split_pool_plan(plan: str) -> tuple[list[int], str]:
    """Split ``device:halt@K`` pool-scoped entries out of a fault
    plan.  Returns (halt dispatch counts, remaining pipeline plan)."""
    halts, rest = [], []
    for ent in plan.split(","):
        ent = ent.strip()
        if not ent:
            continue
        if ent.startswith("device:halt@"):
            halts.append(int(ent.rsplit("@", 1)[1]))
        else:
            rest.append(ent)
    return halts, ",".join(rest)


class _CaptureSink:
    def __init__(self):
        self.out = []

    def push(self, work, positive):
        det = work.detect
        self.out.append((np.asarray(det.signal_counts).copy(),
                         np.asarray(det.zero_count).copy(),
                         np.asarray(det.time_series).copy(),
                         bool(positive)))


def _run(cfg, max_segments=None):
    from srtb_tpu.pipeline.runtime import Pipeline
    from srtb_tpu.utils.metrics import metrics
    metrics.reset()
    sink = _CaptureSink()
    with Pipeline(cfg, sinks=[sink]) as pipe:
        stats = pipe.run(max_segments)
        unfired = pipe.faults.unfired() if pipe.faults else []
    counters = {k: metrics.get(k) for k in (
        "plan_demotions", "plan_promotions", "device_reinits",
        "retries_total", "segments_dropped", "data_loss_total",
        "faults_injected", "ring_cold_dispatches")}
    metrics.reset()
    return stats, sink, counters, unfired


def _run_pool_phase(tmp: str, n: int, pool_halts: list[int]) -> tuple:
    """The ``device:halt@K`` phase: one stream on a virtual pool with
    one member per scheduled halt plus a survivor; entry i arms member
    i's deterministic halt after K_i of its dispatches.  Returns
    (result, sink, counters)."""
    from srtb_tpu.pipeline.fleet import StreamFleet, StreamSpec
    from srtb_tpu.utils.metrics import metrics
    metrics.reset()
    members = len(pool_halts) + 1
    cfg = _base_cfg(tmp, n, "pool", fleet_devices=members)
    sink = _CaptureSink()
    fleet = StreamFleet([StreamSpec(name="chaos", cfg=cfg,
                                    sinks=[sink])])
    for i, k in enumerate(pool_halts):
        fleet.pool.schedule_halt(i, after_dispatches=k)
    results = fleet.run()
    fleet.close()
    counters = {k: int(metrics.get(k)) for k in (
        "device_drains", "migrations", "device_reinits",
        "segments_dropped", "plan_demotions")}
    metrics.reset()
    return results["chaos"], sink, counters


def run_soak(seed: int = 0, segments: int = 6, faults: int = 4,
             log2n: int = 14, plan: str | None = None,
             promote_after: int = 0, tmpdir: str | None = None) -> dict:
    """One full soak (three runs + the gate).  Returns the report
    dict; raises :class:`SoakFailure` on any broken invariant."""
    from srtb_tpu.io.synth import make_dispersed_baseband
    from srtb_tpu.resilience.demote import ladder_rungs
    from srtb_tpu.resilience.faults import parse_plan

    tmp = tmpdir or tempfile.mkdtemp(prefix="srtb_chaos_")
    n = 1 << log2n
    make_dispersed_baseband(
        n * segments, 1405.0, 64.0, 0.05,
        pulse_positions=[n // 2 + i * n for i in range(segments)],
        pulse_amp=30.0, nbits=8, seed=seed,
    ).tofile(os.path.join(tmp, "bb.bin"))

    probe = _base_cfg(tmp, n, "probe")
    rungs = ladder_rungs(probe)
    if plan is None:
        plan = generate_plan(seed, segments, faults,
                             max_demotions=len(rungs), max_halts=3)
    # device:halt@K entries are POOL-scoped (pipeline/pool.py), not
    # fault-injector specs: strip them here, run them as phase 4
    pool_halts, pipe_plan = _split_pool_plan(plan)
    specs = parse_plan(pipe_plan) if pipe_plan else []
    n_demote = sum(1 for s in specs
                   if s.action in ("oom", "compile_fail"))
    n_halt = sum(1 for s in specs if s.action == "device_halt")
    n_transient = sum(1 for s in specs
                      if s.action in ("raise", "corrupt"))
    if n_demote > len(rungs):
        raise SoakFailure(
            f"plan demotes {n_demote}x but only {len(rungs)} rungs "
            f"exist — an unabsorbable plan cannot gate exact counters")

    # run 1: clean reference, self-healing OFF
    off, sink_off, _, _ = _run(_base_cfg(
        tmp, n, "off", plan_ladder="off", device_reinit_max=0))
    # run 2: clean, self-healing ARMED — must change nothing
    on, sink_on, c_on, _ = _run(_base_cfg(tmp, n, "on"))
    # run 3: chaos
    chaos_cfg = _base_cfg(
        tmp, n, "chaos", fault_plan=pipe_plan,
        promote_after_segments=promote_after,
        device_reinit_max=max(1, n_halt),
        checkpoint_path=os.path.join(tmp, "chaos_ck.json"),
        telemetry_journal_path=os.path.join(tmp, "chaos.jsonl"))
    stats, sink, counters, unfired = _run(chaos_cfg)

    def check(cond, msg):
        if not cond:
            raise SoakFailure(msg)

    # zero-cost-off: arming the ladder on a clean run is bit-identical
    check(on.segments == off.segments,
          f"ladder-armed clean run segment count {on.segments} != "
          f"ladder-off {off.segments}")
    for i, (a, b) in enumerate(zip(sink_on.out, sink_off.out)):
        for x, y in zip(a[:3], b[:3]):
            check(np.array_equal(np.asarray(x), np.asarray(y)),
                  f"ladder-armed clean run differs at segment {i}: "
                  "arming self-healing must be bit-identical")
        check(a[3] == b[3], f"clean-run positive flag differs at {i}")
    check(c_on["plan_demotions"] == 0 and c_on["device_reinits"] == 0,
          "clean run recorded demotions/reinits")

    # chaos completed with accounted-only loss
    check(unfired == [], f"planned faults never fired: {unfired}")
    drained = len(sink.out)
    dropped = int(counters["segments_dropped"])
    check(drained + dropped == off.segments,
          f"loss not accounted: {drained} drained + {dropped} dropped "
          f"!= {off.segments} source segments")

    # recovered output parity: decisions exact, time series within the
    # demoted plans' documented tolerance (tests/test_fusion.py)
    for i, (a, b) in enumerate(zip(sink.out, sink_off.out)):
        check(np.array_equal(a[0], b[0]),
              f"segment {i}: signal_counts differ after recovery")
        check(np.array_equal(a[1], b[1]),
              f"segment {i}: zero_count differs after recovery")
        check(a[3] == b[3], f"segment {i}: positive flag differs")
        scale = float(np.abs(b[2]).max()) or 1.0
        if not np.allclose(a[2], b[2], rtol=0, atol=1e-3 * scale):
            raise SoakFailure(
                f"segment {i}: time series out of documented "
                f"tolerance after recovery (max delta "
                f"{float(np.abs(a[2] - b[2]).max()):.3g} vs atol "
                f"{1e-3 * scale:.3g})")

    # counters match the injected plan exactly
    check(int(counters["plan_demotions"]) == n_demote,
          f"plan_demotions {int(counters['plan_demotions'])} != "
          f"{n_demote} injected oom/compile faults")
    check(int(counters["device_reinits"]) == n_halt,
          f"device_reinits {int(counters['device_reinits'])} != "
          f"{n_halt} injected halts")
    check(int(counters["faults_injected"]) == len(specs),
          f"faults_injected {int(counters['faults_injected'])} != "
          f"{len(specs)} planned")
    check(int(counters["retries_total"]) >= n_transient,
          f"retries_total {int(counters['retries_total'])} < "
          f"{n_transient} injected transient faults")

    # phase 4: pool-scoped device halts — every scheduled halt drains
    # its member onto a survivor via live migration, losslessly and
    # bit-identically (migration stays at rung 0, so even the time
    # series is exact, unlike the demoted-plan tolerance above)
    pool_counters: dict = {}
    if pool_halts:
        pres, psink, pool_counters = _run_pool_phase(tmp, n, pool_halts)
        check(pres.status == "done",
              f"pool phase did not finish: {pres.status} "
              f"({pres.error!r})")
        check(len(psink.out) + pool_counters["segments_dropped"]
              == off.segments,
              f"pool phase loss not accounted: {len(psink.out)} "
              f"drained + {pool_counters['segments_dropped']} dropped "
              f"!= {off.segments} source segments")
        check(pool_counters["segments_dropped"] == 0,
              f"pool phase dropped "
              f"{pool_counters['segments_dropped']} segment(s) — a "
              "scoped halt migrates, it must not shed")
        for i, (a, b) in enumerate(zip(psink.out, sink_off.out)):
            check(np.array_equal(a[0], b[0])
                  and np.array_equal(a[1], b[1])
                  and np.array_equal(a[2], b[2]) and a[3] == b[3],
                  f"pool phase segment {i}: output differs from the "
                  "clean reference — migration must be bit-identical")
        check(pool_counters["device_drains"] == len(pool_halts),
              f"device_drains {pool_counters['device_drains']} != "
              f"{len(pool_halts)} scheduled pool halts")
        check(pool_counters["migrations"] >= len(pool_halts),
              f"migrations {pool_counters['migrations']} < "
              f"{len(pool_halts)} scheduled halts — a halt failed to "
              "drain its lane onto the survivor")
        check(pool_counters["device_reinits"] == 0,
              "a pool-scoped halt escalated to a fleet-wide reinit "
              "despite a healthy survivor")
        check(pool_counters["plan_demotions"] == 0,
              "the pool phase demoted a plan — migration must rejoin "
              "the survivor's family at rung 0")

    return {
        "seed": seed, "segments": int(off.segments), "plan": plan,
        "pool_halts": pool_halts,
        "pool_counters": pool_counters,
        "rungs": [r.step for r in rungs],
        "drained": drained, "dropped": dropped,
        "plan_demotions": int(counters["plan_demotions"]),
        "plan_promotions": int(counters["plan_promotions"]),
        "device_reinits": int(counters["device_reinits"]),
        "retries": int(counters["retries_total"]),
        "ok": True,
    }


def selftest(log2n: int = 12) -> list[str]:
    """Prove the gate catches what it exists to catch.  Probes (a)
    and (c) inject fault classes the armed machinery does NOT handle
    and demand the soak fails loudly; probe (b) proves the gate is
    not simply failing everything.  Returns failure strings (empty =
    the gate is sharp)."""
    failures = []
    # (a) an unhandled fault class: injected FATAL — no recovery
    # mechanism covers it, so the soak must NOT come back ok (either
    # the fatal escapes the pipeline or the gate flags the loss)
    try:
        run_soak(seed=1, segments=3, log2n=log2n,
                 plan="dispatch:fatal@1")
        failures.append(
            "gate passed a run with an injected FATAL fault — an "
            "unhandled fault class went unnoticed")
    except Exception:
        pass  # caught, as required
    # (b) sanity: one oom with healing armed must recover cleanly
    try:
        run_soak(seed=2, segments=3, log2n=log2n,
                 plan="dispatch:oom@1")
    except Exception as e:  # noqa: BLE001 - reported, not raised
        failures.append(f"single-oom probe did not recover with "
                        f"healing armed: {e!r}")
    # (c) a device fault with self-healing DISABLED must escalate —
    # device faults must never be swallowed when nothing handles them
    from srtb_tpu.io.synth import make_dispersed_baseband
    tmp = tempfile.mkdtemp(prefix="srtb_chaos_self_")
    n = 1 << log2n
    make_dispersed_baseband(n * 3, 1405.0, 64.0, 0.05,
                            pulse_positions=n, nbits=8
                            ).tofile(os.path.join(tmp, "bb.bin"))
    try:
        _run(_base_cfg(tmp, n, "nh", plan_ladder="off",
                       device_reinit_max=0,
                       fault_plan="dispatch:oom@1"))
        failures.append(
            "an injected oom with self-healing DISABLED did not kill "
            "the run — device faults are being swallowed somewhere")
    except Exception:
        pass  # escalated, as required when healing is off
    return failures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="chaos-soak",
        description="seeded randomized device-fault soak "
                    "(see srtb_tpu/tools/chaos_soak.py)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--segments", type=int, default=6)
    ap.add_argument("--faults", type=int, default=4,
                    help="fault count for the generated plan")
    ap.add_argument("--plan", default=None,
                    help="explicit fault plan (overrides generation)")
    ap.add_argument("--log2n", type=int, default=14)
    ap.add_argument("--promote-after", type=int, default=0,
                    help="promotion probe after N healthy segments")
    ap.add_argument("--selftest", action="store_true",
                    help="prove the gate catches unhandled fault "
                         "classes")
    args = ap.parse_args(argv)

    if args.selftest:
        fails = selftest()
        for f in fails:
            print(f"chaos-soak selftest: {f}", file=sys.stderr)
        print("chaos-soak selftest: "
              + ("FAILED" if fails else
                 "OK — unhandled fault classes fail the gate"))
        return 1 if fails else 0

    try:
        report = run_soak(seed=args.seed, segments=args.segments,
                          faults=args.faults, log2n=args.log2n,
                          plan=args.plan,
                          promote_after=args.promote_after)
    except SoakFailure as e:
        print(json.dumps({"ok": False, "failure": str(e)}))
        print(f"chaos-soak: GATE FAILED — {e}", file=sys.stderr)
        return 1
    print(json.dumps(report, sort_keys=True))
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
