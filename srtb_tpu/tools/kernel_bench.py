"""Per-kernel micro-benchmarks.

The reference publishes exactly one set of kernel timings: its GUI
resample kernel (``resample_spectrum_3``, one work-group per output
pixel) at wg=64 takes ~16.6 ms on an AMD Radeon VII and ~59.9 ms on an
NVIDIA RTX A4000 (ref: spectrum/simplify_spectrum.hpp:449-455).  This
tool times the srtb_tpu equivalents — the resample-as-two-matmuls MXU
formulation plus the other hot kernels — with the same methodology as
bench.py (compile once, min over repeats, block_until_ready).

Usage:
    python -m srtb_tpu.tools.kernel_bench [--log2n 28] [--reps 5]

Prints one JSON line per kernel:
    {"kernel": ..., "ms": ..., "shape": ..., "gsamples_per_s": ...}

Each bench case intentionally builds a fresh jitted lambda: the case IS
the compile+run cycle being measured, and every lambda is jitted once
then timed over repeats — the per-call-recompile hazard srtb-lint
flags does not apply to this harness.
"""
# srtb-lint: disable-file=recompile-hazard (bench harness: one jit per
# case by design, see docstring)

from __future__ import annotations

import argparse
import json
import time

import numpy as np
from srtb_tpu.utils.platform import apply_platform_env


# Iterations of the on-device timing loop per host sync.  Remote-tunnel
# TPU runtimes (axon) cost ~60-65 ms per dispatch+sync round trip —
# enough to bury every sub-10 ms kernel (and `block_until_ready` alone
# is not even a reliable sync there: single-dispatch timings came back
# physically impossible, e.g. 24 us for a 536 MB-read matmul).  The
# timer therefore runs INNER_ITERS executions inside one jitted
# lax.scan, each iteration's input carrying a data dependency on the
# previous output (defeats any client-side pipelining or dedup), and
# pays one host fetch per measurement.
_INNER_ITERS = 16


def _time(fn, *args, reps=5):
    """Best-of-reps mean kernel time over a dependency-chained on-device
    loop.  The chaining adds one read+write copy of args[0] per
    iteration — a known, stated bias (e.g. +~1.3 ms for a 512 MB input
    at HBM speed), far smaller than the ~60 ms per-sync RTT it avoids.
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def loop(*args_):
        def body(c, _):
            a = args_[0] + c.astype(args_[0].dtype)  # depend on prev iter
            out = fn(a, *args_[1:])
            leaf = jax.tree_util.tree_leaves(out)[0]
            nxt = jnp.real(jnp.ravel(leaf)[0]).astype(jnp.float32)
            # exactly-zero carry the simplifier cannot prove is zero
            # (x*0 folds for integer kernels and DCEs the whole body)
            zero = nxt - jax.lax.optimization_barrier(nxt)
            return zero, ()

        c, _ = jax.lax.scan(body, jnp.float32(0.0), None,
                            length=_INNER_ITERS)
        return c

    np.asarray(loop(*args))                  # compile + warm + sync
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(loop(*args))
        best = min(best, time.perf_counter() - t0)
    return best / _INNER_ITERS


def main(argv=None) -> int:
    apply_platform_env()
    p = argparse.ArgumentParser()
    p.add_argument("--log2n", type=int, default=28,
                   help="segment size driving the kernel shapes")
    p.add_argument("--reps", type=int, default=5)
    p.add_argument("--pixmap", type=str, default="1080x1920",
                   help="resample output HxW (reference GUI default)")
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from srtb_tpu.ops import dedisperse as dd
    from srtb_tpu.ops import detect as det
    from srtb_tpu.ops import rfi
    from srtb_tpu.ops import spectrum as sp
    from srtb_tpu.ops import unpack as U

    n = 1 << args.log2n
    n_spec = n // 2
    nchan = 1 << 11                      # J1644 config: 2**11 channels
    wlen = n_spec // nchan
    out_h, out_w = (int(x) for x in args.pixmap.split("x"))
    reps = args.reps
    rng = np.random.default_rng(0)
    results = []

    def record(kernel, seconds, shape, samples):
        rec = {"kernel": kernel, "ms": round(seconds * 1e3, 3),
               "shape": shape,
               "gsamples_per_s": round(samples / seconds / 1e9, 2)}
        results.append(rec)
        print(json.dumps(rec), flush=True)

    # ---- resample + normalize + colormap (the published-numbers kernel)
    power = jax.device_put(
        rng.random((nchan, wlen), dtype=np.float32))
    w_freq = jax.device_put(sp.freq_area_weights(nchan, out_h))
    w_time = jax.device_put(sp.time_interp_weights(wlen, out_w))

    @jax.jit
    def resample_only(pw, wf, wt):
        return sp.resample_spectrum(pw, wf, wt)

    dt = _time(resample_only, power, w_freq, w_time, reps=reps)
    record("resample_spectrum (2 matmuls, MXU)", dt,
           f"[{nchan},{wlen}]->[{out_h},{out_w}]", nchan * wlen)

    @jax.jit
    def resample_full(pw, wf, wt):
        img = sp.resample_spectrum(pw, wf, wt)
        img = sp.normalize_by_average(img)
        return sp.generate_pixmap(img)

    dt = _time(resample_full, power, w_freq, w_time, reps=reps)
    record("resample+normalize+colormap", dt,
           f"[{nchan},{wlen}]->[{out_h},{out_w}]", nchan * wlen)

    # ---- 2-bit unpack + window (blocked field order) ----
    # The product unpack (ops/unpack.py) interleaves fields into sample
    # order; standalone, XLA materializes its [bytes, 4] intermediate
    # whose minor dim pads 4 -> 128 lanes (16x HBM, OOM at segment
    # sizes).  In the pipeline the interleave always fuses into the FFT
    # feed (proved by the 2^30 runs, where the padded form would be
    # 128 GB), so the honest standalone throughput measurement is the
    # same bit-extract + window traffic in a lane-dense blocked order.
    raw = jax.device_put(rng.integers(0, 256, n // 4, dtype=np.uint8))
    win_b = jax.device_put(
        rng.random(n, dtype=np.float32).reshape(n // 512, 512) + 0.5)

    @jax.jit
    def unpack2_blocked(b, w):
        b2 = b.reshape(-1, 128).astype(jnp.int32)
        fields = [((b2 >> s) & 3).astype(jnp.float32)
                  for s in (6, 4, 2, 0)]
        return jnp.concatenate(fields, axis=-1) * w

    dt = _time(unpack2_blocked, raw, win_b, reps=reps)
    record("unpack 2-bit + window (blocked order)", dt,
           f"[{n // 4}]u8->[{n}]f32", n)

    # ---- front-fused pass 1 (staged_ffuse tentpole): raw bytes ->
    # blocked intermediate in ONE kernel (in-kernel unpack + even/odd
    # pack + column FFT + four-step twiddle) vs the separate
    # unpack-then-pass1 chain it replaces (XLA unpack + pack_even_odd
    # materializing the spectrum-sized z, then the packed pass-1
    # kernel).  Interpret-mode on CPU (functional smoke); real Mosaic
    # on accelerators — THE ffuse probe rows the FFUSE_MOSAIC_OK flag
    # in ops/pallas_fft2 waits on (tools_tpu_r9_queue.sh).
    from srtb_tpu.ops import fft as F
    from srtb_tpu.ops import pallas_fft2 as pf2
    m_half = n // 2
    if pf2.ffuse_factor(m_half) is not None:
        interp = jax.default_backend() in ("cpu",)
        ffuse_raw = jax.device_put(
            rng.integers(0, 256, n // 4, dtype=np.uint8))
        fused_front = jax.jit(lambda b: pf2.pass1_front(
            b, m=m_half, streams=1, variant="simple", nbits=2,
            interpret=interp)[0])
        try:
            dt = _time(fused_front, ffuse_raw, reps=reps)
            record("unpack + even/odd + FFT pass 1 (ffuse, 1 kernel)",
                   dt, f"[{n // 4}]u8->[{m_half}]c64-blocked", n)

            fn1, fn2 = pf2.ffuse_factor(m_half)

            def separate(b):
                z = F.pack_even_odd(U.unpack(b, 2, None))
                return pf2.pass1_2d(jnp.real(z).reshape(fn1, fn2),
                                    jnp.imag(z).reshape(fn1, fn2),
                                    interpret=interp)[0]
            dt = _time(jax.jit(separate), ffuse_raw, reps=reps)
            record("unpack -> pack -> FFT pass 1 (separate, z "
                   "materialized)", dt,
                   f"[{n // 4}]u8->[{m_half}]c64-blocked", n)
        except Exception as e:  # pragma: no cover
            print(json.dumps({"kernel": "ffuse pass1", "error": str(e)}))

    # complex arrays are built on device from real transfers: some TPU
    # runtimes (axon tunnel) cannot transfer complex64 host<->device, and
    # one failed complex transfer poisons all later transfers
    spec_re = jax.device_put(rng.standard_normal(n_spec, dtype=np.float32))
    spec_im = jax.device_put(rng.standard_normal(n_spec, dtype=np.float32))
    to_c = jax.jit(jax.lax.complex)
    spec_c = to_c(spec_re, spec_im)

    # ---- chirp multiply (precomputed bank) ----
    f_min, f_c, df = 1405.0, 1437.0, 64.0 / n_spec
    chirp = jnp.asarray(dd.chirp_factor_host_ri(n_spec, f_min, df, f_c,
                                                -478.80))
    mul = jax.jit(lambda s, c: dd.dedisperse(
        s[None], jax.lax.complex(c[0], c[1]))[0])
    dt = _time(mul, spec_c, chirp, reps=reps)
    record("chirp multiply (HBM bank)", dt, f"[{n_spec}]c64", n_spec)

    # ---- df64 on-the-fly chirp (Pallas, TPU only) ----
    if jax.default_backend() not in ("cpu",):
        from srtb_tpu.ops import pallas_kernels as pk
        spec_ri = jnp.stack([spec_re, spec_im])
        pallas_mul = jax.jit(lambda s: pk.dedisperse_df64(
            s, f_min, df, f_c, -478.80))
        try:
            dt = _time(pallas_mul, spec_ri, reps=reps)
            record("chirp multiply (Pallas df64 in-kernel)", dt,
                   f"[{n_spec}]c64", n_spec)
        except Exception as e:  # pragma: no cover
            print(json.dumps({"kernel": "pallas df64", "error": str(e)}))
        # A/B the round-3 anchored-Taylor rewrite against the exact
        # per-element df64 division chains it replaced (save/restore the
        # knob: a user-exported value must survive, and the first chirp
        # record above already honored it)
        import os
        prior = os.environ.get("SRTB_PALLAS_CHIRP_EXACT")
        os.environ["SRTB_PALLAS_CHIRP_EXACT"] = "1"
        try:
            exact_mul = jax.jit(lambda s: pk.dedisperse_df64(
                s, f_min, df, f_c, -478.80))
            dt = _time(exact_mul, spec_ri, reps=reps)
            record("chirp multiply (Pallas df64 exact, pre-anchor)", dt,
                   f"[{n_spec}]c64", n_spec)
        except Exception as e:  # pragma: no cover
            print(json.dumps({"kernel": "pallas df64 exact",
                              "error": str(e)}))
        finally:
            if prior is None:
                del os.environ["SRTB_PALLAS_CHIRP_EXACT"]
            else:
                os.environ["SRTB_PALLAS_CHIRP_EXACT"] = prior

    # ---- fused RFI-s1 + df64 chirp (Pallas, one HBM pass) ----
    if jax.default_backend() not in ("cpu",):
        from srtb_tpu.ops import pallas_kernels as pk
        fused_rfi = jax.jit(lambda s: pk.rfi_s1_dedisperse_df64(
            s, 1.5, 0.125, f_min, df, f_c, -478.80))
        try:
            dt = _time(fused_rfi, spec_ri, reps=reps)
            record("RFI s1 + chirp (Pallas fused)", dt,
                   f"[{n_spec}]c64", n_spec)
        except Exception as e:  # pragma: no cover
            print(json.dumps({"kernel": "pallas rfi+chirp",
                              "error": str(e)}))
        # the jnp sequence it replaces
        seq = jax.jit(lambda s, c: dd.dedisperse(
            rfi.mitigate_rfi_average_and_normalize(
                s[None], 1.5, 0.125),
            jax.lax.complex(c[0], c[1]))[0])
        dt = _time(seq, spec_c, chirp, reps=reps)
        record("RFI s1 + chirp (jnp + bank)", dt, f"[{n_spec}]c64", n_spec)

    # ---- fused spectrum-tail epilogue: Hermitian post + RFI s1 + chirp
    # in ONE write (the spectrum-pass-fusion tentpole) vs the unfused
    # hermitian -> s1 -> chirp sweep sequence.  spec_c stands in for the
    # packed C2C output zf (same size/statistics); runs on any backend —
    # the fusion is XLA-level, not Pallas.
    from srtb_tpu.ops import fft as F

    unfused_tail = jax.jit(lambda zf, c: dd.dedisperse(
        rfi.mitigate_rfi_average_and_normalize(
            F.hermitian_rfft_post(zf, drop_nyquist=True)[None], 1.5,
            0.125),
        jax.lax.complex(c[0], c[1]))[0])
    dt = _time(unfused_tail, spec_c, chirp, reps=reps)
    record("R2C tail: hermitian + RFI s1 + chirp (unfused sweeps)", dt,
           f"[{n_spec}]c64", n_spec)

    cw = jax.jit(lambda c: jnp.stack([
        jnp.real(jax.lax.complex(c[0], c[1])
                 * F._iota_phase(n_spec, 2 * n_spec, -1.0)),
        jnp.imag(jax.lax.complex(c[0], c[1])
                 * F._iota_phase(n_spec, 2 * n_spec, -1.0))]))(chirp)

    def fused_tail(zf, c, cwb):
        epi = lambda z, s: rfi.mitigate_rfi_s1_given_mean(  # noqa: E731
            s, rfi.mean_power_packed(z), 1.5, 0.125)
        return F.hermitian_rfft_post(
            zf, drop_nyquist=True, epilogue=epi,
            premul=(jax.lax.complex(c[0], c[1]),
                    jax.lax.complex(cwb[0], cwb[1])))
    dt = _time(jax.jit(fused_tail), spec_c, chirp, cw, reps=reps)
    record("R2C tail: fused epilogue + chirp-twiddle premul (1 write)",
           dt, f"[{n_spec}]c64", n_spec)

    # ---- spectral kurtosis on the waterfall ----
    wf_re = jax.device_put(
        rng.standard_normal((nchan, wlen)).astype(np.float32))
    wf_im = jax.device_put(
        rng.standard_normal((nchan, wlen)).astype(np.float32))
    wf_c = to_c(wf_re, wf_im)

    # ---- waterfall backward C2C: XLA vs Pallas VMEM rows ----
    # (reuses the wf_re/wf_im pair: each is 256 MB+ at segment sizes)
    from srtb_tpu.ops import pallas_fft as pf
    xla_rows = jax.jit(lambda r, i: jnp.fft.ifft(
        jax.lax.complex(r, i), axis=-1, norm="forward"))
    dt = _time(xla_rows, wf_re, wf_im, reps=reps)
    record("waterfall C2C (XLA ifft)", dt, f"[{nchan},{wlen}]c64", n_spec)
    if jax.default_backend() not in ("cpu",) and pf.supported(wlen, nchan):
        prows = jax.jit(lambda r, i: pf.fft_rows_ri(r, i, inverse=True))
        try:
            dt = _time(prows, wf_re, wf_im, reps=reps)
            record("waterfall C2C (Pallas VMEM rows)", dt,
                   f"[{nchan},{wlen}]c64", n_spec)
        except Exception as e:  # pragma: no cover
            print(json.dumps({"kernel": "pallas fft_rows",
                              "error": str(e)}))
    sk = jax.jit(lambda w: rfi.mitigate_rfi_spectral_kurtosis(w[None], 1.05)[0])
    dt = _time(sk, wf_c, reps=reps)
    record("spectral kurtosis zap", dt, f"[{nchan},{wlen}]c64", n_spec)

    # ---- fused Pallas SK zap + time series (vs sk + detect ts pass) ----
    if jax.default_backend() not in ("cpu",):
        from srtb_tpu.ops import pallas_kernels as pk
        if pk.sk_tiling_ok(nchan, wlen):
            wf_ri = jnp.stack([wf_re, wf_im])
            fused = jax.jit(lambda w: pk.sk_zap_timeseries(w, 1.05))
            try:
                dt = _time(fused, wf_ri, reps=reps)
                record("SK zap + time series (Pallas fused)", dt,
                       f"[{nchan},{wlen}]c64", n_spec)
            except Exception as e:  # pragma: no cover
                print(json.dumps({"kernel": "pallas sk", "error": str(e)}))

    # ---- fully-fused waterfall tail: C2C + dewindow + SK decide + zap
    # + time series in ONE kernel (pf.fft_rows_skzap_ri) vs the 2-kernel
    # chain (fft_rows_stats_ri + sk_apply_timeseries) it supersedes —
    # the "fused SK+ts read" attribution row for the ≤4-pass plans
    if jax.default_backend() not in ("cpu",) and pf.supported(wlen, nchan):
        from srtb_tpu.ops import pallas_kernels as pk
        skzap = jax.jit(lambda r, i: pf.fft_rows_skzap_ri(
            r, i, 1.05, inverse=True))
        try:
            dt = _time(skzap, wf_re, wf_im, reps=reps)
            record("waterfall C2C + SK zap + ts (Pallas skzap, 1 kernel)",
                   dt, f"[{nchan},{wlen}]c64", n_spec)
        except Exception as e:  # pragma: no cover
            print(json.dumps({"kernel": "pallas skzap", "error": str(e)}))

        def two_kernel(r, i):
            yr, yi, s2p, s4p = pf.fft_rows_stats_ri(r, i, inverse=True)
            zap = pk.sk_zap_decision(s2p.sum(-1), s4p.sum(-1),
                                     r.shape[-1], 1.05)
            return pk.sk_apply_timeseries(jnp.stack([yr, yi]), zap)
        try:
            dt = _time(jax.jit(two_kernel), wf_re, wf_im, reps=reps)
            record("waterfall C2C + SK zap + ts (stats + apply, "
                   "2 kernels)", dt, f"[{nchan},{wlen}]c64", n_spec)
        except Exception as e:  # pragma: no cover
            print(json.dumps({"kernel": "pallas stats+apply",
                              "error": str(e)}))

    # ---- detection chain (time series + boxcar ladder) ----
    detect = jax.jit(lambda w: det.detect(w[None], 0, 8.0, 256))
    dt = _time(detect, wf_c, reps=reps)
    record("detect (ts + boxcar ladder 256)", dt, f"[{nchan},{wlen}]c64",
           n_spec)

    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
