"""Per-kernel micro-benchmarks.

The reference publishes exactly one set of kernel timings: its GUI
resample kernel (``resample_spectrum_3``, one work-group per output
pixel) at wg=64 takes ~16.6 ms on an AMD Radeon VII and ~59.9 ms on an
NVIDIA RTX A4000 (ref: spectrum/simplify_spectrum.hpp:449-455).  This
tool times the srtb_tpu equivalents — the resample-as-two-matmuls MXU
formulation plus the other hot kernels — with the same methodology as
bench.py (compile once, min over repeats, block_until_ready).

Usage:
    python -m srtb_tpu.tools.kernel_bench [--log2n 28] [--reps 5]

Prints one JSON line per kernel:
    {"kernel": ..., "ms": ..., "shape": ..., "gsamples_per_s": ...}
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np
from srtb_tpu.utils.platform import apply_platform_env


def _time(fn, *args, reps=5):
    import jax
    out = fn(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    return best


def main(argv=None) -> int:
    apply_platform_env()
    p = argparse.ArgumentParser()
    p.add_argument("--log2n", type=int, default=28,
                   help="segment size driving the kernel shapes")
    p.add_argument("--reps", type=int, default=5)
    p.add_argument("--pixmap", type=str, default="1080x1920",
                   help="resample output HxW (reference GUI default)")
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from srtb_tpu.ops import dedisperse as dd
    from srtb_tpu.ops import detect as det
    from srtb_tpu.ops import rfi
    from srtb_tpu.ops import spectrum as sp
    from srtb_tpu.ops import unpack as U

    n = 1 << args.log2n
    n_spec = n // 2
    nchan = 1 << 11                      # J1644 config: 2**11 channels
    wlen = n_spec // nchan
    out_h, out_w = (int(x) for x in args.pixmap.split("x"))
    reps = args.reps
    rng = np.random.default_rng(0)
    results = []

    def record(kernel, seconds, shape, samples):
        rec = {"kernel": kernel, "ms": round(seconds * 1e3, 3),
               "shape": shape,
               "gsamples_per_s": round(samples / seconds / 1e9, 2)}
        results.append(rec)
        print(json.dumps(rec), flush=True)

    # ---- resample + normalize + colormap (the published-numbers kernel)
    power = jax.device_put(
        rng.random((nchan, wlen), dtype=np.float32))
    w_freq = jax.device_put(sp.freq_area_weights(nchan, out_h))
    w_time = jax.device_put(sp.time_interp_weights(wlen, out_w))

    @jax.jit
    def resample_only(pw, wf, wt):
        return sp.resample_spectrum(pw, wf, wt)

    dt = _time(resample_only, power, w_freq, w_time, reps=reps)
    record("resample_spectrum (2 matmuls, MXU)", dt,
           f"[{nchan},{wlen}]->[{out_h},{out_w}]", nchan * wlen)

    @jax.jit
    def resample_full(pw, wf, wt):
        img = sp.resample_spectrum(pw, wf, wt)
        img = sp.normalize_by_average(img)
        return sp.generate_pixmap(img)

    dt = _time(resample_full, power, w_freq, w_time, reps=reps)
    record("resample+normalize+colormap", dt,
           f"[{nchan},{wlen}]->[{out_h},{out_w}]", nchan * wlen)

    # ---- 2-bit unpack + window ----
    raw = jax.device_put(rng.integers(0, 256, n // 4, dtype=np.uint8))
    win = jax.device_put(np.hamming(n).astype(np.float32))
    unpack2 = jax.jit(lambda b, w: U.unpack(b, 2, w))
    dt = _time(unpack2, raw, win, reps=reps)
    record("unpack 2-bit + window", dt, f"[{n // 4}]u8->[{n}]f32", n)

    # ---- chirp multiply (precomputed bank) ----
    spec_c = jax.device_put(
        (rng.standard_normal(n_spec, dtype=np.float32)
         + 1j * rng.standard_normal(n_spec, dtype=np.float32)
         ).astype(np.complex64))
    f_min, f_c, df = 1405.0, 1437.0, 64.0 / n_spec
    chirp = jnp.asarray(dd.chirp_factor_host_ri(n_spec, f_min, df, f_c,
                                                -478.80))
    mul = jax.jit(lambda s, c: dd.dedisperse(
        s[None], jax.lax.complex(c[0], c[1]))[0])
    dt = _time(mul, spec_c, chirp, reps=reps)
    record("chirp multiply (HBM bank)", dt, f"[{n_spec}]c64", n_spec)

    # ---- df64 on-the-fly chirp (Pallas, TPU only) ----
    if jax.default_backend() not in ("cpu",):
        from srtb_tpu.ops import pallas_kernels as pk
        spec_ri = jnp.stack([jnp.real(spec_c), jnp.imag(spec_c)])
        pallas_mul = jax.jit(lambda s: pk.dedisperse_df64(
            s, f_min, df, f_c, -478.80))
        try:
            dt = _time(pallas_mul, spec_ri, reps=reps)
            record("chirp multiply (Pallas df64 in-kernel)", dt,
                   f"[{n_spec}]c64", n_spec)
        except Exception as e:  # pragma: no cover
            print(json.dumps({"kernel": "pallas df64", "error": str(e)}))

    # ---- spectral kurtosis on the waterfall ----
    wf_c = jax.device_put(
        (rng.standard_normal((nchan, wlen), dtype=np.float32)
         + 1j * rng.standard_normal((nchan, wlen), dtype=np.float32)
         ).astype(np.complex64))
    sk = jax.jit(lambda w: rfi.mitigate_rfi_spectral_kurtosis(w[None], 1.05)[0])
    dt = _time(sk, wf_c, reps=reps)
    record("spectral kurtosis zap", dt, f"[{nchan},{wlen}]c64", n_spec)

    # ---- detection chain (time series + boxcar ladder) ----
    detect = jax.jit(lambda w: det.detect(w[None], 0, 8.0, 256))
    dt = _time(detect, wf_c, reps=reps)
    record("detect (ts + boxcar ladder 256)", dt, f"[{nchan},{wlen}]c64",
           n_spec)

    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
