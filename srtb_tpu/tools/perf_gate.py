"""Noise-aware perf regression gate (the computed ±4%).

PERF.md's methodology was a hand-run paired A/B judged against an
eyeballed "±4% CPU noise floor".  This tool formalizes it:

- a **calibrated mini-bench** (:func:`capture`): a short serial
  pipeline run over a synthetic baseband file whose per-segment host
  wall clock (from the telemetry journal's span records, warmup
  dropped) yields *per-rep samples*, plus a fixed NumPy calibration
  workload that measures how fast this host is today;
- a **statistical verdict** (utils/perf_stats.py): Mann-Whitney over
  the two sample sets + a bootstrap CI of the median effect + a
  noise floor COMPUTED from the observed scatter — regression only
  when all three agree;
- a **checked-in baseline** protocol: ``--write-baseline`` captures
  samples + calibration on the reference host; ``--baseline`` re-runs
  the identical mini-bench and compares.  On a different host the
  baseline samples are rescaled by the calibration ratio and the
  required effect floor is raised (``CROSS_HOST_MIN_EFFECT``) —
  cross-host comparisons are smoke detection, not precision timing;
- ``--selftest`` proves the gate's teeth: a deterministic slowdown
  injected into the dispatch path via the existing ``Config.fault_plan``
  stall machinery MUST fail the gate, and a clean rerun MUST pass.

Exit codes: 0 pass, 1 regression (or selftest failure), 2 usage/error.

Usage:
  python -m srtb_tpu.tools.perf_gate --selftest
  python -m srtb_tpu.tools.perf_gate --write-baseline PERF_BASELINE.json
  python -m srtb_tpu.tools.perf_gate --baseline PERF_BASELINE.json \
      [--min-effect 0.5] [--ledger LEDGER.jsonl]
  python -m srtb_tpu.tools.perf_gate --a A.json --b B.json
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import tempfile
import time

import numpy as np

from srtb_tpu.utils import perf_ledger as PL
from srtb_tpu.utils import perf_stats as PS

BASELINE_TYPE = "perf_baseline"
BASELINE_VERSION = 1
# a calibrated cross-host comparison carries scheduling/turbo/cache
# noise the within-host floor cannot see: require at least this much
# computed slowdown before failing CI on a different machine
CROSS_HOST_MIN_EFFECT = 0.5


def calibration_workload(reps: int = 5) -> float:
    """Median seconds of a fixed, deterministic NumPy workload (FFT +
    matmul over seeded data) — the "how fast is this host today"
    yardstick used to rescale baseline samples across hosts.  Runs
    the same bytes every time, everywhere."""
    rng = np.random.default_rng(1234)
    x = rng.standard_normal(1 << 16).astype(np.complex64)
    m = rng.standard_normal((256, 256)).astype(np.float32)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        y = np.fft.fft(x)
        z = m @ m
        s = float(np.abs(y).sum() + z.sum())
        times.append(time.perf_counter() - t0)
        assert math.isfinite(s)
    times.sort()
    return times[len(times) // 2]


def _mini_cfg(tmp: str, n: int, channels: int, fault_plan: str = ""):
    from srtb_tpu.config import Config
    journal = os.path.join(tmp, "gate_journal.jsonl")
    return Config(
        baseband_input_count=n, baseband_input_bits=8,
        baseband_freq_low=1405.0, baseband_bandwidth=64.0,
        baseband_sample_rate=128e6, dm=0.0,
        input_file_path=os.path.join(tmp, "gate_bb.bin"),
        baseband_output_file_prefix=os.path.join(tmp, "gate_out_"),
        spectrum_channel_count=channels,
        mitigate_rfi_average_method_threshold=100.0,
        mitigate_rfi_spectral_kurtosis_threshold=2.0,
        baseband_reserve_sample=False, writer_thread_count=0,
        fft_strategy="four_step",
        # serial window: each sample is one segment's full host wall
        # clock with no overlap smearing — the honest A/B leg
        inflight_segments=1,
        telemetry_journal_path=journal,
        fault_plan=fault_plan)


def capture(segments: int = 20, warmup: int = 4, log2n: int = 13,
            channels: int = 32, fault_plan: str = "") -> dict:
    """Run the mini-bench once and return its sample set: per-segment
    host seconds (journal span stage sums, first ``warmup`` segments
    dropped — they carry trace/compile), the calibration time, and
    the identity fields a baseline needs."""
    from srtb_tpu.io.synth import make_dispersed_baseband
    from srtb_tpu.pipeline.runtime import Pipeline
    from srtb_tpu.tools import telemetry_report as TR
    from srtb_tpu.utils.metrics import metrics

    n = 1 << log2n
    total = segments + warmup
    with tempfile.TemporaryDirectory(prefix="srtb_perf_gate_") as tmp:
        cfg = _mini_cfg(tmp, n, channels, fault_plan=fault_plan)
        make_dispersed_baseband(
            n * total, 1405.0, 64.0, 0.0, pulse_positions=n // 2,
            nbits=8).tofile(cfg.input_file_path)
        metrics.reset()
        with Pipeline(cfg, sinks=[]) as pipe:
            stats = pipe.run()
            plan = getattr(pipe.processor, "plan_name", "")
            sig = pipe.processor.plan_signature()
        recs = TR.load(cfg.telemetry_journal_path)
    if stats.segments != total or len(recs) < total:
        raise RuntimeError(
            f"mini-bench expected {total} segments, drained "
            f"{stats.segments} with {len(recs)} journal spans")
    samples = [sum((r.get("stages_ms") or {}).values()) / 1e3
               for r in recs[warmup:]]
    return {
        "samples_s": samples,
        "calib_s": calibration_workload(),
        "host_fp": PL.host_fingerprint(),
        "git_sha": PL.git_sha(),
        "plan": plan,
        "plan_signature_sha": PL.signature_sha(sig),
        "shape": {"log2n": log2n, "channels": channels,
                  "segments": segments, "warmup": warmup},
    }


def stall_plan(segments: int, warmup: int, stall_s: float) -> str:
    """A deterministic uniform slowdown: one ``dispatch:stall`` fault
    entry per MEASURED segment (each fires exactly once), riding the
    existing fault-injection machinery — the injected regression
    travels the same guarded dispatch path a real one would."""
    return ",".join(f"dispatch:stall={stall_s:g}@{i}"
                    for i in range(warmup, warmup + segments))


def _load_samples(path: str) -> list[float]:
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, list):
        return [float(x) for x in doc]
    return [float(x) for x in doc["samples_s"]]


def gate(baseline: dict, current: dict, alpha: float = 0.05,
         min_effect: float = 0.0) -> dict:
    """Compare a captured baseline against a current capture.  When
    host fingerprints differ, baseline samples are rescaled by the
    calibration ratio and ``min_effect`` is raised to
    ``CROSS_HOST_MIN_EFFECT`` — the smoke-alarm mode."""
    a = list(baseline["samples_s"])
    cross_host = baseline.get("host_fp") != current.get("host_fp")
    scale = 1.0
    uncalibrated = False
    if cross_host:
        min_effect = max(min_effect, CROSS_HOST_MIN_EFFECT)
        if baseline.get("calib_s") and current.get("calib_s"):
            scale = current["calib_s"] / baseline["calib_s"]
            a = [s * scale for s in a]
        else:
            # raw samples from different-speed hosts are incomparable
            # at ANY floor: a 2x-slower host "regresses" by the host
            # ratio.  Flag it — main() refuses the verdict (exit 2)
            # instead of emitting a guaranteed-false one.
            uncalibrated = True
    verdict = PS.compare(a, current["samples_s"], alpha=alpha,
                         min_effect=min_effect)
    if uncalibrated:
        verdict["uncalibrated_cross_host"] = True
        verdict["regression"] = verdict["improvement"] = False
    verdict.update(cross_host=cross_host,
                   calibration_scale=round(scale, 4),
                   baseline_host=baseline.get("host_fp", ""),
                   current_host=current.get("host_fp", ""),
                   baseline_git=baseline.get("git_sha", ""),
                   current_git=current.get("git_sha", ""),
                   plan=current.get("plan", ""))
    return verdict


def _emit(obj) -> None:
    print(json.dumps(obj, sort_keys=True))
    sys.stdout.flush()


def _ledger_record(ledger_path: str, cap: dict, source: str) -> None:
    if not ledger_path:
        return
    samples = cap["samples_s"]
    med = float(np.median(samples))
    n = 1 << cap["shape"]["log2n"]
    rec = PL.make_record(
        source, n / med / 1e6, "Msamples/s",
        plan=cap["plan"], shape=cap["shape"],
        platform="cpu" if os.environ.get("JAX_PLATFORMS") == "cpu"
        else "", samples_s=samples,
        extra={"calib_s": cap["calib_s"]})
    # the capture already hashed the full signature — the ledger keys
    # comparability on it, so it must ride along
    rec["plan_signature_sha"] = cap.get("plan_signature_sha", "")
    PL.PerfLedger(ledger_path).append(rec)


def selftest(args) -> int:
    """Prove the gate has teeth AND doesn't bite clean runs:
    (1) two clean captures compare within the computed floor — pass;
    (2) a capture with a deterministic dispatch stall per measured
    segment (Config.fault_plan) must flag REGRESSION."""
    kw = dict(segments=args.segments, warmup=args.warmup,
              log2n=args.log2n, channels=args.channels)
    clean_a = capture(**kw)
    clean_b = capture(**kw)
    clean = gate(clean_a, clean_b, alpha=args.alpha)
    if clean["regression"]:
        # by construction a clean/clean comparison fails with
        # probability ~alpha/2 (plus real mid-run throttling on shared
        # CI): one independent recapture drops the flake rate to
        # ~(alpha/2)^2 while a GENUINE environment shift still fails
        # both legs
        clean_b = capture(**kw)
        clean = gate(clean_a, clean_b, alpha=args.alpha)
        clean["retried"] = True
    # stall sized from the clean median: unambiguous (~3x) without
    # wasting wall clock on big shapes
    stall_s = max(0.02, 2.0 * float(np.median(clean_a["samples_s"])))
    stalled = capture(fault_plan=stall_plan(args.segments, args.warmup,
                                            stall_s), **kw)
    slow = gate(clean_a, stalled, alpha=args.alpha)
    ok = (not clean["regression"]) and slow["regression"]
    _emit({"selftest": "ok" if ok else "FAILED",
           "clean": {k: clean[k] for k in
                     ("effect", "p", "noise_floor", "regression")},
           "stalled": {k: slow[k] for k in
                       ("effect", "p", "noise_floor", "regression")},
           "stall_s": stall_s,
           "detail": ("injected dispatch stall flagged, clean rerun "
                      "inside the computed floor" if ok else
                      "gate verdicts did not match expectations")})
    return 0 if ok else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--baseline", help="checked-in baseline JSON to "
                                      "gate the current tree against")
    p.add_argument("--write-baseline", metavar="PATH",
                   help="capture the mini-bench and write a baseline")
    p.add_argument("--a", help="sample-set JSON (reference)")
    p.add_argument("--b", help="sample-set JSON (candidate)")
    p.add_argument("--selftest", action="store_true")
    p.add_argument("--alpha", type=float, default=0.05)
    p.add_argument("--min-effect", type=float, default=0.0,
                   help="extra required effect on top of the computed "
                        "noise floor (fractional, e.g. 0.5 = 50%%)")
    p.add_argument("--segments", type=int, default=20)
    p.add_argument("--warmup", type=int, default=4)
    p.add_argument("--log2n", type=int, default=13)
    p.add_argument("--channels", type=int, default=32)
    p.add_argument("--ledger", default="",
                   help="append captures to this perf ledger")
    args = p.parse_args(argv)

    try:
        if args.selftest:
            return selftest(args)
        if args.a and args.b:
            verdict = PS.compare(_load_samples(args.a),
                                 _load_samples(args.b),
                                 alpha=args.alpha,
                                 min_effect=args.min_effect)
            _emit(verdict)
            return 1 if verdict["regression"] else 0
        if args.write_baseline:
            cap = capture(segments=args.segments, warmup=args.warmup,
                          log2n=args.log2n, channels=args.channels)
            doc = {"type": BASELINE_TYPE, "v": BASELINE_VERSION,
                   "ts": time.time(), **cap}
            with open(args.write_baseline, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
                f.write("\n")
            _ledger_record(args.ledger, cap, "gate")
            _emit({"baseline": args.write_baseline,
                   "n_samples": len(cap["samples_s"]),
                   "median_s": float(np.median(cap["samples_s"])),
                   "calib_s": cap["calib_s"],
                   "host_fp": cap["host_fp"]})
            return 0
        if args.baseline:
            with open(args.baseline) as f:
                base = json.load(f)
            shape = base.get("shape") or {}
            cap = capture(
                segments=int(shape.get("segments", args.segments)),
                warmup=int(shape.get("warmup", args.warmup)),
                log2n=int(shape.get("log2n", args.log2n)),
                channels=int(shape.get("channels", args.channels)))
            _ledger_record(args.ledger, cap, "gate")
            verdict = gate(base, cap, alpha=args.alpha,
                           min_effect=args.min_effect)
            _emit(verdict)
            if verdict.get("uncalibrated_cross_host"):
                # a meaningless comparison is an ERROR, not a pass:
                # the baseline lacks calib_s on a different host
                return 2
            return 1 if verdict["regression"] else 0
        p.print_usage(sys.stderr)
        return 2
    except (OSError, ValueError, KeyError, RuntimeError) as e:
        _emit({"error": f"{type(e).__name__}: {e}"})
        return 2


if __name__ == "__main__":
    sys.exit(main())
