"""CLI driver for the compile-time HLO plan auditor.

Usage (CI runs exactly this, plus ``--selftest``)::

    python -m srtb_tpu.tools.plan_audit

AOT-lowers every plan family (``srtb_tpu/analysis/hlo_audit.py``),
audits the compiled artifacts — spectrum-sized HBM round trips vs the
declared ``hbm_passes`` floor, donation/aliasing tables, f64/callback/
collective/copy flags — and diffs the resulting plan cards against the
checked-in baseline ``srtb_tpu/analysis/plan_cards.json``.

Exit code 0 when every card matches the baseline and every invariant
check passes, 1 on any regression or failed check, 2 on usage errors.
Accept an intentional change with ``--write-baseline`` (per-plan notes
in the baseline's ``notes`` map are carried forward, same workflow as
srtb-lint).  Nothing executes on any device: the audit lowers and
compiles only, and runs on the CPU backend in CI.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _parse_plans(arg: str | None):
    from srtb_tpu.analysis.hlo_audit import PLAN_KEYS
    if not arg or arg == "all":
        return list(PLAN_KEYS)
    return [k.strip() for k in arg.split(",") if k.strip()]


def main(argv=None) -> int:
    from srtb_tpu.analysis import hlo_audit as HA

    ap = argparse.ArgumentParser(
        prog="plan-audit",
        description="compile-time HLO plan auditor "
                    "(see srtb_tpu/analysis/hlo_audit.py)")
    ap.add_argument("--plans", default="all",
                    help="comma-separated plan family keys (default all)")
    ap.add_argument("--log2n", type=int, default=HA.DEFAULT_LOG2N,
                    help="audit segment size exponent")
    ap.add_argument("--channels", type=int, default=HA.DEFAULT_CHANNELS,
                    help="audit spectrum_channel_count")
    ap.add_argument("--baseline", default=HA.DEFAULT_BASELINE,
                    help="plan-card baseline JSON (default: checked-in)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="skip the baseline diff (checks still gate)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept current cards into --baseline "
                         "(existing notes are kept)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--out", default="",
                    help="also write the full (informational) cards "
                         "to this JSON path")
    ap.add_argument("--list-plans", action="store_true")
    ap.add_argument("--selftest", action="store_true",
                    help="prove the auditor catches a dropped donation "
                         "and an injected extra spectrum pass")
    ap.add_argument("--verbose", "-v", action="store_true")
    args = ap.parse_args(argv)

    if args.list_plans:
        for spec in HA.PLAN_FAMILIES:
            print(f"{spec.key}: {spec.desc}")
        return 0

    import jax
    backend = jax.default_backend()
    if backend != "cpu":
        print(f"plan-audit: note: auditing on backend {backend!r}; the "
              "checked-in baseline is a CPU-CI artifact", file=sys.stderr)

    if args.selftest:
        failures = HA.selftest(log2n=args.log2n, channels=args.channels)
        for f in failures:
            print(f"plan-audit selftest: {f}", file=sys.stderr)
        print("plan-audit selftest: "
              + ("FAILED" if failures else
                 "OK — dropped donation, injected extra spectrum "
                 "pass, and un-fused ffuse unpack all move the "
                 "audited cards"))
        return 1 if failures else 0

    try:
        keys = _parse_plans(args.plans)
        cards = HA.audit_families(keys, log2n=args.log2n,
                                  channels=args.channels)
    except KeyError as e:
        print(f"plan-audit: {e.args[0]}", file=sys.stderr)
        return 2

    if args.out:
        with open(args.out, "w") as f:
            json.dump({"cards": cards}, f, indent=2, sort_keys=True,
                      default=str)
            f.write("\n")

    bad_checks = HA.failed_checks(cards)

    if args.write_baseline:
        old = HA.CardBaseline.load(args.baseline)
        HA.CardBaseline.from_cards(cards, old=old).save(args.baseline)
        print(f"plan-audit: wrote {len(cards)} plan card(s) to "
              f"{args.baseline}")
        for c in bad_checks:
            print(f"plan-audit: warning: baselined with failing check "
                  f"-> {c}", file=sys.stderr)
        return 0

    regressions, new_plans, stale, ladder_failures = [], [], [], []
    if not args.no_baseline:
        baseline = HA.CardBaseline.load(args.baseline)
        regressions, new_plans, stale = HA.diff_cards(cards, baseline)
        if set(keys) == set(HA.PLAN_KEYS):
            # the self-healing demotion ladder must only land on
            # carded plan families — checked against the same baseline
            # the cards diff against, so a --write-baseline accepting
            # a new family also arms the ladder to use it.  Subset
            # runs skip it (same convention as staleness: a partial
            # baseline cannot judge the whole ladder).
            ladder_failures = HA.audit_ladder(
                baseline, log2n=args.log2n, channels=args.channels)
        else:
            stale = []  # subset runs cannot judge staleness

    problems = bad_checks + regressions + ladder_failures \
        + [f"{k}: not in baseline (run --write-baseline to accept)"
           for k in new_plans] \
        + [f"{k}: stale baseline entry (plan no longer audited)"
           for k in stale]

    if args.format == "json":
        print(json.dumps({
            "cards": {k: HA.stable_view(c) for k, c in cards.items()},
            "failed_checks": bad_checks,
            "regressions": regressions,
            "ladder_failures": ladder_failures,
            "new_plans": new_plans,
            "stale_baseline": stale,
        }, indent=2, sort_keys=True))
    else:
        for p in problems:
            print(p)
        if args.verbose:
            for k, c in sorted(cards.items()):
                progs = c["programs"]
                passes = "+".join(str(p["spectrum_passes"])
                                  for p in progs.values())
                don = {n: p["donation"] for n, p in progs.items()
                       if p["donation"]["declared"]}
                print(f"{k}: plan={c['plan_name']} "
                      f"declared={c['declared_hbm_passes']} "
                      f"audited={c['total_spectrum_passes']} ({passes}) "
                      f"donation={don if don else 'none'}")
        summary = (f"plan-audit: {len(cards)} plan(s), "
                   f"{len(bad_checks)} failed check(s), "
                   f"{len(regressions)} regression(s), "
                   f"{len(ladder_failures)} uncarded ladder target(s), "
                   f"{len(new_plans)} unbaselined, {len(stale)} stale")
        print(summary, file=sys.stderr if problems else sys.stdout)
    return 1 if problems else 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
