"""CLI entry point: ``python -m srtb_tpu.tools.lint srtb_tpu/``.

Thin wrapper over :mod:`srtb_tpu.analysis.lint` (kept under tools/ so
the operator-facing commands all live in one namespace).  See
``--list-rules`` for the rule set and ``srtb_tpu/analysis/__init__.py``
for pragma / baseline syntax.
"""

from srtb_tpu.analysis.lint import main

if __name__ == "__main__":
    raise SystemExit(main())
