"""Loopback UDP ingest soak: measure the receiver's sustained packet rate,
throughput and loss accounting against the real-time requirement.

The J1644-4559 configuration needs 128 MSa/s x 2 bit = 32 MB/s = 0.256
Gbit/s of baseband off the wire (ref: srtb_config_1644-4559.cfg:22-29);
deployment notes in the reference tune 2 GiB socket buffers and ~4096-byte
MTUs for this (ref: README.md:260-291).  This tool blasts
counter-sequential packets over loopback as fast as the sender can and
reports what the receiver actually sustained.

Usage:
    python -m srtb_tpu.tools.udp_soak [--packets N] \
        [--impl native|packet_ring|python|continuous] \
        [--fault-plan "ingest:raise@3,..."]

``--fault-plan`` arms the resilience fault injector on the receive
loop (site ``ingest``, index = block number) and wraps each
``receive_block`` in the default retry policy — the soak-level proof
that ingest survives scheduled transient faults with the retries
accounted in the output (``retries`` field).

Prints one JSON line:
  {"pps": ..., "gbps": ..., "payload_bytes": ..., "received": ...,
   "lost": ..., "loss_rate": ..., "required_gbps": 0.256, "margin": ...}
"""

from __future__ import annotations

import argparse
import json
import socket
import struct
import threading
import time

import numpy as np

from srtb_tpu.io import formats, udp

# 128 MSa/s * 2 bit / 8 = 32 MB/s of payload
REQUIRED_GBPS = 128e6 * 2 / 8 * 8 / 1e9


def _sender(port: int, fmt, n_packets: int, started: threading.Event,
            pace_pps: float = 0.0):
    """Blast (or pace) counter-sequential packets, then trail off with a
    slow flush so in-progress blocks at the receiver always complete even
    when tail packets of the main burst were dropped."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.connect(("127.0.0.1", port))
    payload = b"\xab" * fmt.payload_bytes
    header_size = fmt.packet_header_size

    def send(c):
        if header_size >= 8:
            header = struct.pack("<Q", c) + b"\x00" * (header_size - 8)
        else:
            header = b""
        try:
            sock.send(header + payload)
        except OSError:
            pass  # receiver-side buffer overflow shows up as loss

    started.wait()
    chunk = 32
    t0 = time.perf_counter()
    for c in range(n_packets):
        send(c)
        if pace_pps and c % chunk == chunk - 1:
            target = (c + 1) / pace_pps
            lag = target - (time.perf_counter() - t0)
            if lag > 0:
                time.sleep(lag)
    # flush: paced trailer packets push any partially-assembled block over
    # its boundary (these arrive after the timed region ends)
    for c in range(n_packets, n_packets + 4 * 64):
        send(c)
        time.sleep(0.0005)
    sock.close()


def run_soak(n_packets: int = 20000, impl: str = "auto",
             packets_per_block: int = 64, port: int = 42100,
             pace_gbps: float = 0.0, fault_plan: str = "") -> dict:
    """``pace_gbps > 0`` throttles the sender to that payload rate —
    used to demonstrate loss-free ingest at the real-time requirement;
    0 blasts at full speed to find the ceiling."""
    from srtb_tpu.resilience.faults import FaultInjector
    from srtb_tpu.resilience.retry import RetryPolicy, retry_call
    from srtb_tpu.utils.metrics import metrics
    fmt = formats.FASTMB_ROACH2  # 8-byte counter header + 4096-byte payload
    if impl == "auto":
        # capability probe, not lib presence: sandboxes without the
        # recvmmsg syscall soak through the Python receiver
        impl = "native" if udp.native_available() else "python"
    if impl == "native":
        rx = udp.NativeBlockReceiver("127.0.0.1", port, fmt)
    elif impl == "packet_ring":
        rx = udp.PacketRingReceiver("", port, fmt, interface="lo")
    elif impl == "continuous":
        rx = udp.PythonContinuousReceiver("127.0.0.1", port, fmt,
                                          rcvbuf_bytes=1 << 28)
    else:
        rx = udp.PythonBlockReceiver("127.0.0.1", port, fmt,
                                     rcvbuf_bytes=1 << 28)

    pace_pps = pace_gbps * 1e9 / 8 / fmt.payload_bytes if pace_gbps else 0.0
    started = threading.Event()
    sender = threading.Thread(target=_sender,
                              args=(port, fmt, n_packets, started,
                                    pace_pps))
    sender.start()

    injector = FaultInjector.from_plan(fault_plan)
    policy = RetryPolicy(backoff_base_s=0.001)
    retries_before = metrics.get("retries_total")

    block = np.zeros(packets_per_block * fmt.payload_bytes, dtype=np.uint8)
    n_blocks = n_packets // packets_per_block
    started.set()
    t0 = time.perf_counter()
    received_payload_bytes = 0
    for i in range(n_blocks - 1):  # leave sender headroom for the tail
        if injector is None:
            rx.receive_block(block)
        else:
            def guarded(index=i):
                injector.fire("ingest", index)
                return rx.receive_block(block)
            retry_call(guarded, policy, "ingest")
        received_payload_bytes += block.nbytes
    dt = time.perf_counter() - t0
    sender.join()
    total, lost = rx.total_packets, rx.lost_packets
    rx.close()

    gbps = received_payload_bytes * 8 / dt / 1e9
    pps = received_payload_bytes / fmt.payload_bytes / dt
    return {
        "impl": impl,
        "pace_gbps": pace_gbps,
        "fault_plan": fault_plan,
        "retries": int(metrics.get("retries_total") - retries_before),
        "pps": round(pps),
        "gbps": round(gbps, 3),
        "payload_bytes": fmt.payload_bytes,
        "received": int(total),
        "lost": int(lost),
        "loss_rate": round(lost / max(total + lost, 1), 5),
        "required_gbps": round(REQUIRED_GBPS, 3),
        "margin": round(gbps / REQUIRED_GBPS, 1),
        "seconds": round(dt, 3),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--packets", type=int, default=20000)
    p.add_argument("--impl", default="auto",
                   choices=["auto", "native", "packet_ring", "python",
                            "continuous"])
    p.add_argument("--port", type=int, default=42100)
    p.add_argument("--pace-gbps", type=float, default=0.0)
    p.add_argument("--fault-plan", default="",
                   help="resilience fault plan for the receive loop "
                        "(site 'ingest', index = block number)")
    args = p.parse_args(argv)
    print(json.dumps(run_soak(args.packets, args.impl, port=args.port,
                              pace_gbps=args.pace_gbps,
                              fault_plan=args.fault_plan)))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
