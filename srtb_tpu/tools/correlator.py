"""Offline 2-file cross-correlator (ref: src/correlator.cpp:35-152).

corr = |iFFT( norm * F1 * conj(F2) )| with norm = input_size^-1.5,
written as raw float32 (byte-compatible with the reference's corr.bin).
"""

from __future__ import annotations

import os
import sys
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from srtb_tpu.utils.logging import log
from srtb_tpu.utils.platform import apply_platform_env


# module-level jit (srtb-lint recompile-hazard caught the old
# per-call jax.jit(nested_fn)(...) spelling, which recompiled the FFT
# pair on every correlate() call); complex_count is static, the norm
# coefficient rides along as a traced scalar
@partial(jax.jit, static_argnums=(2,))
def _corr(a, b, complex_count, norm_coeff):
    fa = jnp.fft.rfft(a.astype(jnp.float32))[:complex_count]
    fb = jnp.fft.rfft(b.astype(jnp.float32))[:complex_count]
    prod = (norm_coeff * fa) * jnp.conj(fb)
    # unnormalized backward C2C, like the reference's BACKWARD plan
    corr = jnp.fft.ifft(prod, norm="forward")
    return jnp.abs(corr)


def correlate(x1: np.ndarray, x2: np.ndarray) -> np.ndarray:
    """Cross-correlation magnitude of two 8-bit sample streams
    (ref: correlator.cpp:109-140).  Returns float32 [n/2]."""
    input_size = min(x1.size, x2.size)
    complex_count = input_size // 2
    real_count = complex_count * 2
    norm_coeff = np.float32(input_size ** -1.5)
    out = _corr(jnp.asarray(x1[:real_count]),
                jnp.asarray(x2[:real_count]),
                complex_count, norm_coeff)
    return jax.device_get(out)


def main(argv=None) -> int:
    apply_platform_env()
    argv = sys.argv[1:] if argv is None else argv
    in_file_1 = argv[0] if len(argv) > 0 else "pol_1.bin"
    in_file_2 = argv[1] if len(argv) > 1 else "pol_2.bin"
    out_file = argv[2] if len(argv) > 2 else "/dev/shm/corr.bin"
    log.info(f"[correlator] reading {os.path.abspath(in_file_1)}")
    log.info(f"[correlator] reading {os.path.abspath(in_file_2)}")
    x1 = np.fromfile(in_file_1, dtype=np.uint8)
    x2 = np.fromfile(in_file_2, dtype=np.uint8)
    out = correlate(x1, x2)
    out.astype("<f4").tofile(out_file)
    log.info(f"[correlator] wrote {out.size} samples to {out_file}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
