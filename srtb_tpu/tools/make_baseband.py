"""Generate a synthetic dispersed-pulse baseband file (demo / test data).

The reference's end-to-end check needs a recorded pulsar baseband; this
tool produces an equivalent artifact from nothing:

    python -m srtb_tpu.tools.make_baseband --out /tmp/demo.bin \
        --n "2 ** 22" --freq_low 1405 --bandwidth 64 --dm 60 \
        --pulses "2**20, 3*2**20" --nbits 2

then run the pipeline on it with matching --dm and watch the detections:

    python -m srtb_tpu.tools.main --input_file_path /tmp/demo.bin \
        --baseband_input_count "2 ** 21" --baseband_input_bits 2 \
        --baseband_freq_low 1405 --baseband_bandwidth 64 --dm 60 ...
"""

from __future__ import annotations

import argparse
import sys

from srtb_tpu.io.synth import make_dispersed_baseband
from srtb_tpu.utils.expression import parse_expression
from srtb_tpu.utils.logging import log
from srtb_tpu.utils.platform import apply_platform_env


def main(argv=None) -> int:
    apply_platform_env()
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", required=True)
    p.add_argument("--n", default="2 ** 22",
                   help="total samples (expression ok)")
    p.add_argument("--freq_low", default="1405")
    p.add_argument("--bandwidth", default="64")
    p.add_argument("--dm", default="60")
    p.add_argument("--pulses", default="",
                   help="comma-separated sample positions (expressions); "
                        "default: one pulse mid-file")
    p.add_argument("--nbits", type=int, default=8,
                   choices=[1, 2, 4, 8, 16])
    p.add_argument("--pulse_amp", type=float, default=40.0)
    p.add_argument("--pulse_width", type=int, default=32)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    n = int(parse_expression(args.n))
    positions = [int(parse_expression(s)) for s in args.pulses.split(",") if s.strip()] \
        or [n // 2]
    data = make_dispersed_baseband(
        n, float(parse_expression(args.freq_low)), float(parse_expression(args.bandwidth)),
        float(parse_expression(args.dm)), positions, nbits=args.nbits,
        pulse_amp=args.pulse_amp, pulse_width=args.pulse_width,
        seed=args.seed)
    data.tofile(args.out)
    log.info(f"[make_baseband] wrote {data.nbytes} bytes "
             f"({n} samples @ {args.nbits} bit, dm {args.dm}, "
             f"pulses at {positions}) -> {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
