"""Offline spectrum plotting helper (ref: src/plot_spectrum.py).

Reads the ``<prefix><counter>.<i>.npy`` complex waterfalls written by
WriteSignalSink and renders dynamic-spectrum images (matplotlib if
available, else the built-in PNG writer).
"""

from __future__ import annotations

import glob
import sys

import numpy as np
from srtb_tpu.utils.platform import apply_platform_env


def plot_one(path: str) -> str:
    wf = np.load(path)
    power = np.abs(wf) ** 2
    out_path = path + ".png"
    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        fig, ax = plt.subplots(figsize=(12, 7))
        ax.imshow(power, aspect="auto", origin="lower",
                  interpolation="nearest")
        ax.set_xlabel("time sample")
        ax.set_ylabel("frequency channel")
        fig.savefig(out_path, dpi=120)
        plt.close(fig)
    except ImportError:
        from srtb_tpu.gui.waterfall import write_png
        from srtb_tpu.ops import spectrum as sp
        import jax.numpy as jnp
        img = power / (2 * max(power.mean(), 1e-30))
        pix = np.asarray(sp.generate_pixmap(jnp.asarray(
            img.astype(np.float32))))
        write_png(out_path, pix)
    return out_path


def main(argv=None) -> int:
    apply_platform_env()
    argv = sys.argv[1:] if argv is None else argv
    paths = []
    for pattern in (argv or ["*.npy"]):
        paths.extend(glob.glob(pattern))
    for p in sorted(paths):
        print(plot_one(p))
    return 0


if __name__ == "__main__":
    sys.exit(main())
