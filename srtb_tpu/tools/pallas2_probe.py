"""Isolated Mosaic acceptance + timing probe for the fused two-pass
Pallas C2C (ops/pallas_fft2) at one size.

One JSON line out: block sizes, the plan's VMEM budget, compile time,
steady-state ms, and the f64-oracle relative error.  Run by the
hardware queue per size (2^24..2^29 — the round-3 advisor requires the
padded-footprint block sizing validated at the flagship sizes before
those blocks become defaults), and directly for tuning:

    python -m srtb_tpu.tools.pallas2_probe --log2m 29
    SRTB_PALLAS2_VMEM_MB=48 python -m srtb_tpu.tools.pallas2_probe --log2m 29
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--log2m", type=int, default=24)
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--tol", type=float, default=3e-5)
    p.add_argument("--interpret", action="store_true",
                   help="interpret-mode smoke off-TPU (CI only — cannot "
                        "prove Mosaic acceptance or VMEM fit)")
    args = p.parse_args(argv)

    from srtb_tpu.utils.platform import apply_platform_env
    apply_platform_env()
    import numpy as np
    import jax.numpy as jnp
    from srtb_tpu.ops import pallas_fft2 as pf2

    m = 1 << args.log2m
    out = {"probe": "pallas2_mosaic", "log2m": args.log2m}
    try:
        # inside the try: a bad SRTB_PALLAS2_* env value must land as
        # ok:false JSON (the queue's artifact contract), not a traceback
        fac = pf2._factor(m)
        if fac is None:
            out.update(ok=False, error="unsupported size")
            print(json.dumps(out))
            return 1
        n1, n2 = fac
        bb, rb = pf2._block_cols(n1, n2), pf2._block_rows(n2, n1)
        out.update(bb=bb, rb=rb, vmem_mb=pf2._vmem_budget() >> 20)
        rng = np.random.default_rng(0)
        x = (rng.standard_normal(m)
             + 1j * rng.standard_normal(m)).astype(np.complex64)
        xr = jnp.asarray(x.real.copy())
        xi = jnp.asarray(x.imag.copy())
        import jax

        # jit the whole two-pass composition: the timing must rank block
        # plans by kernel time, not per-call eager dispatch overhead
        import functools
        f = jax.jit(functools.partial(pf2.fft2_c2c_ri,
                                      interpret=args.interpret))
        t0 = time.perf_counter()
        yr, yi = f(xr, xi)
        # sync on a tiny slice so compile_s is compile+execute, not the
        # full-size tunnel fetch (2x2 GiB at 2^29) that follows
        np.asarray(yr[..., :8])
        out["compile_s"] = round(time.perf_counter() - t0, 1)
        # split re/im host fetch (complex fetch is UNIMPLEMENTED on axon)
        got = np.asarray(yr) + 1j * np.asarray(yi)
        want = np.fft.fft(x.astype(np.complex128))
        err = float(np.abs(got - want).max() / np.abs(want).max())
        out["rel_err"] = err
        out["ok"] = err < args.tol
        t0 = time.perf_counter()
        for _ in range(args.reps):
            yr, yi = f(xr, xi)
        np.asarray(yr[..., :8])
        out["ms"] = round((time.perf_counter() - t0) / args.reps * 1e3, 2)
    except Exception as e:  # land the failure as data, not a stack trace
        out["ok"] = False
        out["error"] = f"{type(e).__name__}: {e}"[:400]
    print(json.dumps(out))
    return 0 if out.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
