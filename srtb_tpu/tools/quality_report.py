"""Render the science observatory from a span journal.

``telemetry_report`` summarizes *how fast* the pipeline ran; this tool
summarizes *how good the data was* and *whether the instrument could
still see*.  It reads the same JSONL span journal (utils/telemetry.py,
schema v9) and reports, per stream:

- **data quality** (the ``quality`` extra the per-segment epilogue
  journals — srtb_tpu/quality/stats.py): zapped-channel fraction,
  bandpass mean/variance, spectral-kurtosis summary, dead/hot channel
  fractions, and the EWMA bandpass-drift score, each as min/mean/max
  over the run plus the drift-alert count;
- **RFI occupancy map**: the coarse per-bin zero-fraction averaged
  over the run, rendered as a text heat strip (worst bins called out
  numerically) — which parts of the band the zapper was eating;
- **canary verdicts** (the ``canary`` extra — srtb_tpu/quality/
  canary.py): every checked injection with recovered vs expected S/N
  and the sensitivity ratio, plus the failure count — the run's
  end-to-end proof the detection chain could still recover a known
  dispersed pulse.

Pre-v9 records (no ``quality``/``canary`` fields) drop out of the
sections tolerantly, like every other telemetry_report section.

Usage: python -m srtb_tpu.tools.quality_report JOURNAL.jsonl
           [--format json|md]

Exit 0 with a note when the journal holds no quality/canary records
yet (quality_stats off, canary off, or a just-started run).
"""

from __future__ import annotations

import argparse
import json

from srtb_tpu.tools.telemetry_report import load

# text heat strip glyphs, cold to hot (occupancy 0 -> 1)
_RAMP = " .:-=+*#%@"

QUALITY_FIELDS = ("zap_frac", "bandpass_mean", "bandpass_var",
                  "sk_mean", "sk_max", "dead_frac", "hot_frac",
                  "drift_score")


def _agg(vals: list[float]) -> dict:
    return {"min": round(min(vals), 5), "mean": round(
        sum(vals) / len(vals), 5), "max": round(max(vals), 5)}


def quality_stats(records: list[dict]) -> dict:
    """stream -> field -> {min, mean, max} over the run, plus the
    drift-alert count and the segment count carrying quality data."""
    by_stream: dict[str, list[dict]] = {}
    for r in records:
        q = r.get("quality")
        if isinstance(q, dict):
            by_stream.setdefault(str(r.get("stream", "")), []).append(q)
    out = {}
    for s, qs in sorted(by_stream.items()):
        st = {"records": len(qs),
              "drift_alerts": sum(1 for q in qs if q.get("drift_alert"))}
        for f in QUALITY_FIELDS:
            vals = [float(q[f]) for q in qs if f in q]
            if vals:
                st[f] = _agg(vals)
        out[s] = st
    return out


def occupancy_map(records: list[dict]) -> dict:
    """stream -> run-mean occupancy per coarse bin (+ the worst bins).
    Bin counts can change across a reconfigure; the map keeps the most
    common length and averages the records that match it."""
    by_stream: dict[str, list[list[float]]] = {}
    for r in records:
        q = r.get("quality")
        if isinstance(q, dict) and q.get("occupancy"):
            by_stream.setdefault(str(r.get("stream", "")),
                                 []).append(q["occupancy"])
    out = {}
    for s, occs in sorted(by_stream.items()):
        lengths: dict[int, int] = {}
        for o in occs:
            lengths[len(o)] = lengths.get(len(o), 0) + 1
        n = max(lengths, key=lambda k: lengths[k])
        kept = [o for o in occs if len(o) == n]
        mean = [round(sum(o[i] for o in kept) / len(kept), 4)
                for i in range(n)]
        worst = sorted(range(n), key=lambda i: -mean[i])[:4]
        out[s] = {"bins": n, "mean": mean,
                  "worst": [{"bin": i, "occupancy": mean[i]}
                            for i in worst if mean[i] > 0]}
    return out


def canary_stats(records: list[dict]) -> dict:
    """stream -> every checked canary verdict (injection-only marks —
    a replayed canary skipping its exactly-once check — are counted
    but not tabulated) plus the pass/fail totals."""
    by_stream: dict[str, dict] = {}
    for r in records:
        c = r.get("canary")
        if not isinstance(c, dict):
            continue
        st = by_stream.setdefault(str(r.get("stream", "")), {
            "injected": 0, "checked": 0, "failed": 0, "verdicts": []})
        st["injected"] += 1
        if "ratio" not in c:
            continue  # injection mark without a verdict (replay)
        st["checked"] += 1
        if not c.get("ok", True):
            st["failed"] += 1
        st["verdicts"].append({
            "segment": int(c.get("segment", -1)),
            "snr": float(c.get("snr", 0.0)),
            "expected": float(c.get("expected", 0.0)),
            "ratio": float(c.get("ratio", 0.0)),
            "ok": bool(c.get("ok", True)),
            "calibrated": bool(c.get("calibrated", False)),
        })
    return by_stream


def report(path: str) -> dict:
    records = load(path)
    return {
        "journal": path,
        "records": len(records),
        "quality": quality_stats(records),
        "occupancy": occupancy_map(records),
        "canary": canary_stats(records),
    }


def _strip(mean: list[float]) -> str:
    return "".join(
        _RAMP[min(len(_RAMP) - 1, int(max(0.0, min(1.0, v))
                                      * (len(_RAMP) - 1) + 0.5))]
        for v in mean)


def _md(rep: dict) -> str:
    lines = [f"# Quality report — {rep['journal']}", "",
             f"{rep['records']} segment spans."]
    for s, st in rep["quality"].items():
        title = f"stream {s!r}" if s else "run"
        lines += ["", f"## Data quality ({title})", "",
                  f"{st['records']} quality spans, "
                  f"{st['drift_alerts']} bandpass drift alert(s).", "",
                  "| stat | min | mean | max |", "|---|---|---|---|"]
        for f in QUALITY_FIELDS:
            if f in st:
                a = st[f]
                lines.append(f"| {f} | {a['min']} | {a['mean']} | "
                             f"{a['max']} |")
        occ = rep["occupancy"].get(s)
        if occ:
            lines += ["", f"RFI occupancy ({occ['bins']} coarse bins, "
                      "run mean, low->high frequency):", "",
                      f"    [{_strip(occ['mean'])}]"]
            for w in occ["worst"]:
                lines.append(f"- bin {w['bin']}: "
                             f"{w['occupancy']:.1%} zapped")
    for s, st in sorted(rep["canary"].items()):
        title = f"stream {s!r}" if s else "run"
        lines += ["", f"## Canary ({title})", "",
                  f"{st['injected']} injected, {st['checked']} checked, "
                  f"{st['failed']} failed.", ""]
        if st["verdicts"]:
            lines += ["| segment | S/N | expected | ratio | verdict |",
                      "|---|---|---|---|---|"]
            for v in st["verdicts"]:
                verdict = ("calibrated" if v["calibrated"]
                           else "ok" if v["ok"] else "FAILED")
                lines.append(
                    f"| {v['segment']} | {v['snr']:.2f} | "
                    f"{v['expected']:.2f} | {v['ratio']:.3f} | "
                    f"{verdict} |")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("journal")
    p.add_argument("--format", choices=("md", "json"), default="md")
    args = p.parse_args(argv)
    rep = report(args.journal)
    if not (rep["quality"] or rep["canary"]):
        # no science-observatory data (yet): a clear note, not a
        # failure — quality_stats/canary may simply be off
        note = {"note": "no quality/canary spans in "
                        f"{args.journal} yet", "records": rep["records"]}
        print(json.dumps(note) if args.format == "json"
              else f"# Quality report\n\n{note['note']}\n")
        return 0
    if args.format == "json":
        print(json.dumps(rep, sort_keys=True))
    else:
        print(_md(rep))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
