"""Evaluate the hardware queue's decision tree against PERF_TPU.jsonl.

The r4 queue (tools_tpu_r4_queue.sh) ends with a decision tree written
as comments; if the tunnel recovers while no session is attached, the
watcher fires the queue and commits raw rows — but nobody reads them
until the next session.  This tool turns the latest rows into the
decisions the tree prescribes, so the recovery commit carries its own
conclusions:

    python -m srtb_tpu.tools.queue_decisions [--perf PERF_TPU.jsonl]
        [--out DECISIONS_r4.md]

It only REPORTS (markdown + one JSON line); applying a flip stays a
reviewed edit.  Decisions covered: pallas2 as auto strategy, best 2^30
plan vs the 1.4 s target, blocked-planes Mosaic flag, MXU precision
default, dense rows helper default, warm-compile target.
"""

from __future__ import annotations

import argparse
import json
import sys


def load_rows(path: str) -> dict:
    """variant -> latest row (parsed)."""
    rows = {}
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                v = rec.get("variant")
                if v:
                    rows[v] = rec  # later lines win
    except OSError:
        pass
    return rows


def _result(row):
    if not row:
        return None
    r = row.get("result")
    return r if isinstance(r, dict) else None


def _value(row):
    r = _result(row)
    return r.get("value") if r else None


def evaluate(rows: dict) -> list[dict]:
    """One dict per decision: {decision, verdict, evidence, action}."""
    out = []

    def add(decision, verdict, evidence, action=""):
        out.append({"decision": decision, "verdict": verdict,
                    "evidence": evidence, "action": action})

    # ---- pallas2 as the auto strategy for n in [2^25, 2^30) ----
    probes = {k: _result(rows[k]) for k in rows
              if k.startswith("pallas2_mosaic_probe_")}
    probe_ok = {k: bool(r and r.get("ok")) for k, r in probes.items()}
    base = _value(rows.get("baseline"))
    p2 = _value(rows.get("pallas2"))
    # "is not None": a failed bench's 0.0 row is PRESENT data (a KEEP
    # verdict with evidence), not a missing row
    if probes and base is not None and p2 is not None:
        all_ok = all(probe_ok.values())
        if all_ok and base > 0 and p2 >= 1.2 * base:
            add("pallas2 auto-default", "FLIP",
                f"sweep all ok; pipeline {p2:.0f} vs baseline {base:.0f} "
                f"Msamples/s (>= 1.2x)",
                "make ops/fft.resolve_strategy 'auto' pick pallas2 for "
                "n in [2^25, 2^30); rerun default bench")
        else:
            add("pallas2 auto-default", "KEEP monolithic",
                f"sweep ok: {probe_ok}; pipeline {p2} vs baseline {base}")
    elif probes:
        add("pallas2 auto-default", "INCOMPLETE",
            f"probe sweep: {probe_ok}; pipeline rows missing")

    # ---- best 2^30 plan vs the <= 1.4 s/segment target ----
    plans = {}
    for k in ("n2_30", "n2_30_pallas_legs", "n2_30_pallas2",
              "n2_30_pallas2_full", "staged_blocked_pallas2_probe",
              "fused_2_30_pallas2_probe"):
        r = _result(rows.get(k))
        if r and r.get("segment_time_s") is not None:
            plans[k] = r["segment_time_s"]
    if plans:
        best = min(plans, key=plans.get)
        if plans[best] <= 1.4:
            add("2^30 default plan", "FLIP",
                f"{best} at {plans[best]:.2f} s/segment (<= 1.4 target)",
                f"make the {best} plan the n >= 2^30 default "
                "(pipeline/segment.py plan selection)")
        else:
            add("2^30 default plan", "KEEP",
                f"best {best} at {plans[best]:.2f} s (> 1.4 target); "
                f"all: {plans}")

    # ---- blocked-planes unpack Mosaic flag ----
    r = _result(rows.get("planes_unpack_mosaic_probe"))
    rc = rows.get("planes_unpack_mosaic_probe", {}).get("rc")
    if r and r.get("ok") and rc == 0:
        add("PLANES_UNPACK_MOSAIC_OK", "FLIP", "probe compiled + matched",
            "set ops/pallas_kernels.PLANES_UNPACK_MOSAIC_OK = True")
    elif rc is not None:
        add("PLANES_UNPACK_MOSAIC_OK", "KEEP False", f"probe rc={rc}")

    # ---- MXU precision default (one queue variant per precision) ----
    prec = {}
    for k in ("mxu_precision_probe_high", "mxu_precision_probe_highest"):
        r = _result(rows.get(k))
        if r:
            prec[r.get("prec")] = r
    if "high" in prec and "highest" in prec:
        hi = prec["high"]
        if hi.get("rel_err", 1) <= 2e-6:
            add("SRTB_MXU_PRECISION default", "FLIP to high",
                f"high: rel_err {hi['rel_err']:.2e}, {hi.get('ms')} ms vs "
                f"highest {prec['highest'].get('ms')} ms",
                "flip the default in ops/mxu_fft")
        else:
            add("SRTB_MXU_PRECISION default", "KEEP highest",
                f"high rel_err {hi.get('rel_err')}")

    # (the dense-vs-classic rows-helper A/B retired in round 5: real
    # Mosaic rejects the spellings' minor-lb reshapes, so one legal
    # spelling remains — see ops/pallas_fft.vmem_fft_rows)

    # ---- warm-compile restart target ----
    warm = _result(rows.get("cache_warm"))
    if warm and warm.get("compile_s") is not None:
        if warm["compile_s"] <= 10:
            add("warm restart", "MET",
                f"cache_warm compile_s {warm['compile_s']} <= 10 s")
        else:
            add("warm restart", "NOT MET — document remote-compile cache "
                "bypass", f"cache_warm compile_s {warm['compile_s']}")

    # ---- AOT executable-cache warm restart (round 5) ----
    for key, label in (("aot_warm", "AOT warm restart (2^27)"),
                       ("aot_warm_30", "AOT warm restart (2^30 staged)")):
        r = _result(rows.get(key))
        if r and r.get("compile_s") is not None:
            if not r.get("aot_active", False):
                add(label, "INVALID — AOT cache never engaged",
                    f"{key} row lacks aot_active=true (cache inactive "
                    "on this backend?); compile_s is non-AOT evidence")
            elif r["compile_s"] <= 10:
                add(label, "MET",
                    f"{key} compile_s {r['compile_s']} <= 10 s",
                    "recommend aot_plan_path in the production config; "
                    "record the warm number in PERF.md")
            else:
                add(label, "NOT MET",
                    f"{key} compile_s {r['compile_s']} > 10 s — "
                    "profile deserialize_and_load vs executable load")

    if not out:
        add("(no decisions)", "NO DATA",
            "no recognized variant rows in the perf log")
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--perf", default="PERF_TPU.jsonl")
    p.add_argument("--out", default="")
    args = p.parse_args(argv)
    decisions = evaluate(load_rows(args.perf))
    if args.out:
        with open(args.out, "w") as f:
            f.write("# Hardware-queue decisions (auto-generated)\n\n")
            f.write("Generated by `srtb_tpu.tools.queue_decisions` from "
                    f"`{args.perf}`.\n\n")
            f.write("| decision | verdict | evidence | action |\n")
            f.write("|---|---|---|---|\n")
            for d in decisions:
                f.write(f"| {d['decision']} | {d['verdict']} | "
                        f"{d['evidence']} | {d['action']} |\n")
    print(json.dumps({"probe": "queue_decisions",
                      "flips": [d["decision"] for d in decisions
                                if d["verdict"].startswith("FLIP")],
                      "decisions": decisions}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
