"""Offline verifier/repairer for a run's durable-output invariants.

The run manifest (io/manifest.py) is the WAL that makes sink outputs
exactly-once across process death; this tool is its filesystem checker
— run it after a crash, before archiving an observation, or in CI:

- **WAL integrity**: every record's CRC32 verifies; a torn tail (the
  record being appended when the process died) is reported and, with
  ``--repair``, truncated — exactly what startup recovery would do;
- **artifact integrity**: every committed artifact exists with the
  committed size AND content CRC32 (the whole file is read — fsck is
  the deep check, startup recovery only stats);
- **rollback debt**: uncommitted intents whose temp or renamed file is
  still on disk, and append files longer than their committed prefix
  (torn appends); ``--repair`` rolls both back;
- **checkpoint agreement**: the checkpoint file parses, its CRC
  verifies, and its ``segments_done`` never EXCEEDS the manifest's
  last consistency-point record — ``StreamCheckpoint.update`` seals
  the manifest first, so "checkpoint ahead of manifest" is always
  corruption (``--repair`` rewrites the checkpoint from the
  manifest's record);
- **loss**: committed-but-missing artifacts below the checkpoint are
  unrecoverable (the resume will never re-drain them) — reported,
  never "repaired" away.

Usage::

    python -m srtb_tpu.tools.fsck MANIFEST [--checkpoint CKPT]
        [--repair] [--format json|text]
    python -m srtb_tpu.tools.fsck --selftest

Exit codes: 0 = clean (or everything repaired), 1 = inconsistencies
found (unrepaired, or unrepairable loss), 2 = cannot verify at all
(missing/unreadable manifest, usage error).

``--selftest`` proves the verifier is sharp on a synthetic run dir: a
forged WAL CRC, a deleted committed artifact and a checkpoint ahead of
the manifest must each fail the check, and the untouched dir must
pass.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import zlib

from srtb_tpu.io import manifest as M

EXIT_CLEAN = 0
EXIT_ERRORS = 1
EXIT_UNVERIFIABLE = 2

_CHUNK = 1 << 22


def _file_crc32(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_CHUNK)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
    return crc


def _load_checkpoint(path: str) -> tuple[dict | None, list[str]]:
    """(state, errors): parse + CRC-verify the checkpoint file without
    the StreamCheckpoint fallbacks — fsck reports what IS on disk."""
    errors = []
    try:
        with open(path) as f:
            data = json.load(f)
    except FileNotFoundError:
        return None, errors
    except (json.JSONDecodeError, OSError, ValueError) as e:
        return None, [f"checkpoint {path} unreadable: {e}"]
    if not isinstance(data, dict):
        return None, [f"checkpoint {path} malformed: not an object"]
    crc = data.pop("crc", None)
    if crc is not None and M.record_crc(data) != crc:
        return None, [f"checkpoint {path} CRC mismatch: corrupt state"]
    return data, errors


def fsck(manifest_path: str, checkpoint_path: str | None = None,
         repair: bool = False) -> dict:
    """One verification pass.  Returns the report dict (``errors`` is
    what is wrong NOW, ``repaired`` what --repair fixed, ``loss`` what
    nothing can fix); raises ``FileNotFoundError`` when the manifest
    itself is absent."""
    if not os.path.exists(manifest_path):
        raise FileNotFoundError(manifest_path)
    errors: list[str] = []
    repaired: list[str] = []
    loss: list[str] = []

    # the checkpoint file loads FIRST (read-only): its segments_done
    # is the floor hint that keeps --repair exactly as conservative as
    # the runtime's own startup recovery in the corrupted-WAL gap
    ck_state = None
    ck_errors: list[str] = []
    if checkpoint_path:
        ck_state, ck_errors = _load_checkpoint(checkpoint_path)
        if ck_state is None:
            # the designed fallback: a crash between update()'s two
            # renames leaves only the previous generation as .bak
            bak_state, _bak_errors = _load_checkpoint(
                checkpoint_path + ".bak")
            if bak_state is not None:
                ck_state, ck_errors = bak_state, []
    ck_hint = int(ck_state.get("segments_done", 0)) if ck_state else 0

    scan = M.scan_manifest(manifest_path)
    if scan.torn:
        msg = (f"torn WAL tail: {scan.total_bytes - scan.valid_bytes} "
               f"byte(s) from line {scan.bad_line} fail CRC/parse")
        if repair:
            with open(manifest_path, "rb+") as f:
                f.truncate(scan.valid_bytes)
            repaired.append(msg + " -> truncated")
            scan = M.scan_manifest(manifest_path)
        else:
            errors.append(msg)
    # effective floor: same max(WAL, checkpoint file) rule as startup
    # recovery, so fsck's below/above-floor classification predicts
    # exactly what recovery would do (the raw disagreement itself is
    # still reported by the checkpoint-ahead check below)
    floor = max(scan.checkpoint_floor(), ck_hint)

    complete: set = set()
    for key, grp in sorted(scan.groups.items()):
        if M.group_complete(grp):
            ok = True
            for art in grp.artifacts.values():
                if not art.committed:
                    continue
                prefix = (f"segment {key[1]} sink {key[2]}: "
                          f"{os.path.basename(art.path)}")
                if art.mode == "append":
                    continue  # verified via the committed prefix below
                try:
                    size = os.path.getsize(art.path)
                except OSError:
                    ok = False
                    (loss if key[1] < floor else errors).append(
                        f"{prefix} committed but missing")
                    continue
                if art.length is not None and size != art.length:
                    ok = False
                    errors.append(f"{prefix} size {size} != committed "
                                  f"{art.length}")
                elif art.crc32 is not None \
                        and _file_crc32(art.path) != art.crc32:
                    ok = False
                    errors.append(f"{prefix} content CRC mismatch")
            if ok:
                complete.add(key)
        else:
            msg = (f"segment {key[1]} sink {key[2]}: uncommitted "
                   "intent(s)" if not grp.done else
                   f"segment {key[1]} sink {key[2]}: group incomplete")
            if key[1] < floor:
                loss.append(msg + " under the checkpoint (ordering "
                            "contract violated upstream)")
            elif repair:
                repaired.append(msg + " -> rolled back")
            else:
                errors.append(msg + " (startup recovery or --repair "
                              "rolls this back)")

    # orphan files of rollback-due groups (only meaningful pre-repair)
    for key, grp in scan.groups.items():
        if key in complete or key[1] < floor:
            continue
        for art in grp.artifacts.values():
            if art.mode == "append":
                continue
            for p in (art.path + M.TMP_SUFFIX, art.path):
                if os.path.exists(p) and not repair and not art.committed:
                    errors.append(
                        f"orphan from uncommitted intent on disk: "
                        f"{os.path.basename(p)}")

    # append files vs their committed prefix (complete groups only)
    for p, target in M.append_committed_lengths(
            scan, complete_keys=complete).items():
        try:
            size = os.path.getsize(p)
        except OSError:
            size = 0
        if size > target:
            msg = (f"append file {os.path.basename(p)}: {size - target} "
                   f"byte(s) beyond the committed prefix {target}")
            if repair:
                with open(p, "rb+") as f:
                    f.truncate(target)
                repaired.append(msg + " -> truncated")
            else:
                errors.append(msg)
        elif size < target:
            loss.append(f"append file {os.path.basename(p)}: {size} < "
                        f"committed prefix {target} (bytes lost)")

    if repair:
        # apply the rollbacks fsck promised above (same engine, same
        # checkpoint-floor guard, as the pipeline runs at startup)
        rep = M.recover(manifest_path, apply=True,
                        checkpoint_floor_hint=ck_hint)
        for act in rep.rolled_back:
            repaired.append(f"recovery: {act}")
        for msg in rep.missing:
            loss.append(f"recovery: {msg}")

    # checkpoint <-> manifest agreement
    if checkpoint_path:
        errors.extend(ck_errors)
        last = scan.last_checkpoint
        manifest_done = int(last["segments_done"]) if last else 0
        if ck_state is not None:
            file_done = int(ck_state.get("segments_done", 0))
            if file_done > manifest_done:
                msg = (f"checkpoint ahead of manifest: file claims "
                       f"{file_done} segment(s) done, manifest sealed "
                       f"{manifest_done}")
                if repair and last is not None:
                    from srtb_tpu.pipeline.checkpoint import \
                        StreamCheckpoint
                    ck = StreamCheckpoint(checkpoint_path)
                    ck.update(manifest_done, int(last["offset"]))
                    repaired.append(msg + " -> rewrote checkpoint from "
                                    "the manifest record")
                else:
                    errors.append(msg)
        elif ck_state is None and not ck_errors and manifest_done > 0:
            # the manifest sealed progress but the checkpoint file (and
            # its .bak) is simply gone: a fresh process would restart
            # from segment 0 — the manifest done-set keeps that
            # idempotent, but a deleted checkpoint is worth flagging
            errors.append(
                f"checkpoint {checkpoint_path} missing while the "
                f"manifest sealed {manifest_done} segment(s)")

    report = {
        "manifest": manifest_path,
        "records": scan.records,
        "groups": len(scan.groups),
        "complete_groups": len(complete),
        "checkpoint_floor": floor,
        "errors": errors,
        "loss": loss,
        "repaired": repaired,
        "clean": not errors and not loss,
    }
    return report


# ----------------------------------------------------------------
# selftest
# ----------------------------------------------------------------

def _build_run_dir(tmp: str) -> tuple[str, str]:
    """Synthetic committed run: two artifacts + one append + sealed
    checkpoint.  Returns (manifest_path, checkpoint_path)."""
    from srtb_tpu.pipeline.checkpoint import StreamCheckpoint
    mpath = os.path.join(tmp, "manifest.jsonl")
    ckpath = os.path.join(tmp, "ck.json")
    m = M.RunManifest.open(mpath)
    payloads = {
        os.path.join(tmp, "out_100.bin"): b"baseband-bytes" * 32,
        os.path.join(tmp, "out_100.0.npy"): b"npy-bytes" * 16,
    }
    key = (0, 0, "0:WriteSignalSink")
    for p, payload in payloads.items():
        m.intent(key, p)
        with open(p, "wb") as f:
            f.write(payload)
        m.commit(key, p, len(payload), zlib.crc32(payload))
    m.sink_done(key)
    akey = (0, 1, "1:WriteAllSink")
    apath = os.path.join(tmp, "out_stream0.bin")
    chunk = b"append-chunk" * 8
    m.intent(akey, apath, mode="append", offset=0)
    with open(apath, "wb") as f:
        f.write(chunk)
    m.commit(akey, apath, len(chunk), zlib.crc32(chunk), offset=0)
    m.sink_done(akey)
    ck = StreamCheckpoint(ckpath, manifest=m)
    ck.update(2, 8192)
    m.close()
    return mpath, ckpath


def selftest() -> list[str]:
    """Prove fsck catches what it exists to catch.  Returns failure
    strings (empty = the verifier is sharp)."""
    failures = []
    base = tempfile.mkdtemp(prefix="srtb_fsck_self_")

    def fresh(tag: str) -> tuple[str, str, str]:
        d = os.path.join(base, tag)
        os.makedirs(d)
        mpath, ckpath = _build_run_dir(d)
        return d, mpath, ckpath

    # (0) the untouched dir must pass — the gate is not just failing
    # everything
    d, mpath, ckpath = fresh("clean")
    rep = fsck(mpath, ckpath)
    if not rep["clean"]:
        failures.append(f"clean synthetic run did not verify: {rep}")

    # (a) forged WAL CRC: flip one byte inside a record body
    d, mpath, ckpath = fresh("forge")
    with open(mpath, "rb+") as f:
        data = f.read()
        i = data.index(b'"commit"')
        f.seek(i)
        f.write(b'"cOmmit"')
    rep = fsck(mpath, ckpath)
    if rep["clean"]:
        failures.append("forged WAL CRC went unnoticed")

    # (b) a committed artifact deleted out from under the manifest
    d, mpath, ckpath = fresh("missing")
    os.unlink(os.path.join(d, "out_100.bin"))
    rep = fsck(mpath, ckpath)
    if rep["clean"]:
        failures.append("deleted committed artifact went unnoticed")

    # (c) checkpoint ahead of the manifest: rewrite the checkpoint
    # file claiming more progress than the manifest ever sealed
    d, mpath, ckpath = fresh("ahead")
    from srtb_tpu.pipeline.checkpoint import StreamCheckpoint
    StreamCheckpoint(ckpath).update(99, 1 << 20)
    rep = fsck(mpath, ckpath)
    if rep["clean"]:
        failures.append("checkpoint ahead of the manifest went "
                        "unnoticed")

    # (d) content corruption at unchanged size (the deep CRC check)
    d, mpath, ckpath = fresh("bitrot")
    p = os.path.join(d, "out_100.bin")
    with open(p, "rb+") as f:
        f.seek(3)
        b = f.read(1)
        f.seek(3)
        f.write(bytes([b[0] ^ 0xFF]))
    rep = fsck(mpath, ckpath)
    if rep["clean"]:
        failures.append("flipped artifact byte (same size) went "
                        "unnoticed")

    shutil.rmtree(base, ignore_errors=True)
    return failures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="fsck",
        description="verify/repair a run's durable-output invariants "
                    "(see srtb_tpu/tools/fsck.py)")
    ap.add_argument("manifest", nargs="?",
                    help="run-manifest WAL path (Config.run_manifest_path)")
    ap.add_argument("--checkpoint", default=None,
                    help="checkpoint state file to cross-check "
                         "(Config.checkpoint_path)")
    ap.add_argument("--repair", action="store_true",
                    help="truncate the torn WAL tail, roll back "
                         "uncommitted intents/appends, rewrite a "
                         "checkpoint that ran ahead of the manifest")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text")
    ap.add_argument("--selftest", action="store_true",
                    help="prove the verifier catches a forged CRC, a "
                         "deleted committed artifact and a checkpoint "
                         "ahead of the manifest")
    args = ap.parse_args(argv)

    if args.selftest:
        fails = selftest()
        for f in fails:
            print(f"fsck selftest: {f}", file=sys.stderr)
        print("fsck selftest: "
              + ("FAILED" if fails else
                 "OK — forged CRC, deleted artifact, bit rot and a "
                 "checkpoint ahead of the manifest all fail the check"))
        return EXIT_ERRORS if fails else EXIT_CLEAN

    if not args.manifest:
        ap.print_usage(sys.stderr)
        return EXIT_UNVERIFIABLE
    try:
        rep = fsck(args.manifest, args.checkpoint, repair=args.repair)
    except FileNotFoundError:
        print(f"fsck: manifest {args.manifest} does not exist",
              file=sys.stderr)
        return EXIT_UNVERIFIABLE
    if args.format == "json":
        print(json.dumps(rep, sort_keys=True))
    else:
        state = "clean" if rep["clean"] else "NOT CLEAN"
        print(f"fsck {rep['manifest']}: {state} — {rep['records']} "
              f"record(s), {rep['complete_groups']}/{rep['groups']} "
              f"group(s) complete, checkpoint floor "
              f"{rep['checkpoint_floor']}")
        for e in rep["errors"]:
            print(f"  error: {e}")
        for e in rep["loss"]:
            print(f"  LOSS: {e}")
        for r in rep["repaired"]:
            print(f"  repaired: {r}")
    return EXIT_CLEAN if rep["clean"] else EXIT_ERRORS


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
