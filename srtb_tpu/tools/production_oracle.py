"""Full-production-parameter float64 oracle slice (round-3 verdict #8).

The reference validates end-to-end on real recordings at its flagship
configuration (ref: README.md:9-19, userspace/srtb_config_1644-4559.cfg:
2^30-sample segments, 2^15 channels, |DM| 478.80, inverted 64 MHz band
at 1405-1469 MHz).  The repo's f64 crosscheck runs that chain at 2^16;
this tool runs it ONCE at the real geometry — device pipeline (staged
plan) vs the same independent float64 transliteration the crosscheck
uses — and records max-error numbers as a committed artifact, so
numerical health at the flagship shape is pinned before hardware time
is spent there.

    python -m srtb_tpu.tools.production_oracle [--log2n 30]
        [--log2chan 15] [--out artifacts/production_oracle.json]

CPU, hours acceptable; ~60 GB peak host RAM at 2^30 (the oracle's
complex128 intermediates).  One JSON line to stdout, artifact to --out.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _import_oracle():
    """The float64 oracle lives with the tests (tests/oracle_utils.py)
    so it can never drift from what CI enforces; this diagnostics tool
    borrows it from a source checkout."""
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    tests_dir = os.path.join(here, "tests")
    if not os.path.isdir(tests_dir):
        raise RuntimeError(
            "production_oracle needs a source checkout (tests/ with "
            "oracle_utils.py next to srtb_tpu/)")
    if tests_dir not in sys.path:
        sys.path.insert(0, tests_dir)
    import oracle_utils
    return oracle_utils


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--log2n", type=int, default=30)
    p.add_argument("--log2chan", type=int, default=15)
    p.add_argument("--out", default="artifacts/production_oracle.json")
    p.add_argument("--pulse_amp", type=float, default=30.0)
    p.add_argument("--progress", action="store_true",
                   help="timestamped per-phase progress on stderr (a "
                        "2^30 run takes hours on a small host; without "
                        "this the process is a black box)")
    args = p.parse_args(argv)

    def mark(msg):
        if args.progress:
            print(f"[production_oracle +{time.monotonic() - t_start:.0f}s]"
                  f" {msg}", file=sys.stderr, flush=True)
    t_start = time.monotonic()

    from srtb_tpu.utils.platform import apply_platform_env
    apply_platform_env()
    import numpy as np

    ou = _import_oracle()
    from srtb_tpu.config import Config
    from srtb_tpu.io.synth import make_dispersed_baseband
    from srtb_tpu.pipeline.segment import (SegmentProcessor,
                                           waterfall_to_numpy)

    n = 1 << args.log2n
    # the J1644-4559 flagship parameters (ref: srtb_config_1644-4559.cfg)
    # at the strict-parity thresholds tier (1e9: no RFI threshold flips,
    # so f32-vs-f64 decision jitter cannot mask numeric drift)
    cfg = Config(
        baseband_input_count=n,
        baseband_input_bits=2,
        baseband_format_type="simple",
        baseband_freq_low=1405.0 + 32.0,
        baseband_bandwidth=-64.0,
        baseband_sample_rate=128e6,
        dm=-478.80,
        spectrum_channel_count=1 << args.log2chan,
        signal_detect_signal_noise_threshold=6.0,
        signal_detect_max_boxcar_length=256,
        mitigate_rfi_average_method_threshold=1e9,
        mitigate_rfi_spectral_kurtosis_threshold=1e9,
        baseband_reserve_sample=False,
    )

    if args.progress:
        import jax
        jax.config.update("jax_log_compiles", True)

    t0 = time.perf_counter()
    mark("synth start")
    raw = make_dispersed_baseband(
        n, cfg.baseband_freq_low, cfg.baseband_bandwidth, cfg.dm,
        pulse_positions=n // 2, pulse_amp=args.pulse_amp, nbits=2)
    synth_s = time.perf_counter() - t0
    mark(f"synth done ({synth_s:.0f}s); building SegmentProcessor")

    # ---- device chain (the staged plan is the n >= 2^30 default) ----
    t0 = time.perf_counter()
    proc = SegmentProcessor(cfg)
    mark(f"processor built (staged={proc.staged}); running device chain")
    wf_ri, res = proc.process(raw)
    mark("device programs dispatched; fetching results")
    wf_dev = waterfall_to_numpy(wf_ri)[0]   # stream 0: [F, T] complex64
    ts_dev = np.asarray(res.time_series)[0]
    counts_dev = np.asarray(res.signal_counts)[0]
    device_s = time.perf_counter() - t0
    mark(f"device done ({device_s:.0f}s); starting float64 oracle")

    # ---- float64 oracle over the identical bytes ----
    t0 = time.perf_counter()
    x = ou.oracle_unpack(raw, cfg.baseband_input_bits)
    del raw
    wf_o, ts_o, nzap_o = ou.oracle_stream_chain(x, cfg)
    del x
    oracle_s = time.perf_counter() - t0
    mark(f"oracle done ({oracle_s:.0f}s); comparing")

    wf_scale = float(np.abs(wf_o).max())
    ts_scale = float(np.abs(ts_o).max())
    # stream the waterfall comparison row-block-wise: a whole-array
    # |wf_dev - wf_o| would add another 8 GiB complex128 temporary.
    # The same pass accumulates the f64 frequency-sum of the *device*
    # (f32) waterfall: the pivot that decomposes the time-series error
    # into its two causes (see ts gates below).
    wf_err = 0.0
    blk = 1 << 11
    ts_f64_of_f32 = np.zeros(wf_o.shape[1], dtype=np.float64)
    for i in range(0, wf_o.shape[0], blk):
        w32 = wf_dev[i:i + blk]
        d = np.abs(w32.astype(np.complex128) - wf_o[i:i + blk])
        wf_err = max(wf_err, float(d.max()))
        ts_f64_of_f32 += (w32.real.astype(np.float64) ** 2
                          + w32.imag.astype(np.float64) ** 2).sum(axis=0)
    ts_raw_max = float(ts_f64_of_f32.max())
    ts_f64_of_f32 -= ts_f64_of_f32.mean()
    ts_err = float(np.abs(ts_dev.astype(np.float64) - ts_o).max())

    # ---- per-quantity gates (round-4 verdict weak #2) ----
    # wf: f32 FFT-chain rounding; measured 5.1e-7 relative at the
    # flagship shape (round 4) -> 1e-5 keeps 20x headroom while being
    # 800x tighter than the old shared 8e-3.
    wf_gate = 1e-5 * wf_scale
    # ts splits into two separately-gated causes — summation-ordering
    # error (deterministic pairwise-tree bound) and the waterfall's own
    # f32 error propagated through |.|^2.  The formulas live in ONE
    # place, ops.detect.time_series_error_gates, shared with the CI
    # assertion in tests/test_reference_crosscheck.py.
    from srtb_tpu.ops.detect import time_series_error_gates
    k_ch, t_len = wf_o.shape
    ts_sum_err = float(np.abs(ts_dev.astype(np.float64)
                              - ts_f64_of_f32).max())
    ts_prop_err = float(np.abs(ts_f64_of_f32 - ts_o).max())
    ts_sum_gate, ts_prop_gate = time_series_error_gates(
        k_ch, t_len, ts_raw_max, wf_err)

    out = {
        "probe": "production_oracle",
        "log2n": args.log2n,
        "channels": cfg.spectrum_channel_count,
        "dm": cfg.dm,
        "staged": bool(getattr(proc, "staged", True)),
        "wf_max_rel_err": wf_err / wf_scale if wf_scale else 0.0,
        "ts_max_rel_err": ts_err / ts_scale if ts_scale else 0.0,
        "ts_sum_rel_err": ts_sum_err / ts_scale if ts_scale else 0.0,
        "ts_prop_rel_err": ts_prop_err / ts_scale if ts_scale else 0.0,
        "ts_raw_max": ts_raw_max,
        "gates": {
            "wf": wf_gate / wf_scale if wf_scale else 0.0,
            "ts_sum": ts_sum_gate / ts_scale if ts_scale else 0.0,
            "ts_prop": ts_prop_gate / ts_scale if ts_scale else 0.0,
        },
        "signal_counts": [int(c) for c in np.ravel(counts_dev)],
        "oracle_sk_zapped_rows": int(nzap_o),
        "synth_s": round(synth_s, 1),
        "device_s": round(device_s, 1),
        "oracle_s": round(oracle_s, 1),
        "platform": os.environ.get("JAX_PLATFORMS", ""),
        "ok": bool(wf_err <= wf_gate
                   and ts_sum_err <= ts_sum_gate
                   and ts_prop_err <= ts_prop_gate),
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
