"""Plot the DM-search SNR curve from a ``*dm_trials.jsonl`` record.

The classic pulsar-search acceptance artifact: peak S/N per DM trial,
peaking at the true dispersion measure.  The reference searches a single
configured DM in production (ref: srtb_config_1644-4559.cfg:22); the DM
grid (`--dm_list`) is this repo's scale-out addition, and this plot is
its visual proof — the curve must peak at the injected DM and fall off
to the sides (decoherence from the DM error, ref dispersion math:
coherent_dedispersion.hpp:87-128).

Usage: python -m srtb_tpu.tools.plot_dm_curve TRIALS.jsonl [OUT.png]
"""

from __future__ import annotations

import json
import sys

from srtb_tpu.utils.platform import apply_platform_env


def plot(trials_path: str, out_path: str | None = None) -> str:
    records = []
    with open(trials_path) as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    if not records:
        raise SystemExit(f"no trial records in {trials_path}")
    out_path = out_path or trials_path + ".png"

    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(9, 5.5))
    for rec in records:
        ax.plot(rec["dm_list"], rec["peak_snr"], marker="o",
                label=f"segment {rec['segment']}")
        ax.axvline(rec["best_dm"], color="0.7", lw=0.8, zorder=0)
    ax.set_xlabel("trial DM (pc cm$^{-3}$)")
    ax.set_ylabel("peak S/N")
    best = max(records, key=lambda r: r["best_snr"])
    ax.set_title(f"DM search: best {best['best_dm']} "
                 f"(S/N {best['best_snr']:.1f})")
    ax.legend(loc="best", fontsize=8)
    ax.grid(alpha=0.3)
    fig.tight_layout()
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path


def main(argv=None) -> int:
    apply_platform_env()
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print(__doc__, file=sys.stderr)
        return 2
    print(plot(argv[0], argv[1] if len(argv) > 1 else None))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
