"""Perf-ledger CLI: inspect the ledger and backfill legacy history.

``--import`` ingests the driver-captured legacy artifacts
(``BENCH_r0*.json`` — one file per bench round, a JSON object whose
``parsed`` field holds bench.py's emitted line) into the append-only
ledger (utils/perf_ledger.py), so the perf trajectory starts populated
instead of empty.  Idempotent: every imported record carries an
``import_key`` (file basename + round) and re-runs skip keys already
present.  Rounds that died before emitting a metric line (rc != 0, no
``parsed``) are recorded as value-0 failure records — the trajectory
must show the outage rounds, not silently skip them.

Usage:
  python -m srtb_tpu.tools.perf_ledger LEDGER.jsonl            # summary
  python -m srtb_tpu.tools.perf_ledger LEDGER.jsonl --import BENCH_r0*.json
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
import sys

from srtb_tpu.utils import perf_ledger as PL


def _import_one(path: str, seen: set) -> dict | None:
    """One legacy artifact -> one ledger record (or None when its
    import_key is already in the ledger / the file is not a legacy
    round artifact)."""
    base = os.path.basename(path)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or "rc" not in doc:
        return None
    key = f"{base}#n{doc.get('n', 0)}"
    if key in seen:
        return None
    parsed = doc.get("parsed") or {}
    # file mtime orders the trajectory when the artifact itself has no
    # timestamp (the legacy rounds don't)
    try:
        ts = os.path.getmtime(path)
    except OSError:
        ts = None
    extra = {"import_key": key, "rc": int(doc.get("rc", -1))}
    # provenance note: the legacy artifact does not record which host/
    # commit produced it — stamping the IMPORTER's identity would
    # fabricate comparability the gate's calibration logic then
    # trusts, so both records pass explicit blank provenance
    if parsed.get("value") is not None:
        shape = {"log2n": int(parsed.get("log2n", 0) or 0)}
        for k in ("compile_s", "segment_time_s", "achieved_gbps",
                  "model_hbm_gb", "roofline_frac", "vs_baseline",
                  "overlap", "hbm_passes", "fused_tail", "ring"):
            if k in parsed:
                extra[k] = parsed[k]
        return PL.make_record(
            "import", float(parsed["value"]),
            str(parsed.get("unit", "Msamples/s/chip")),
            plan=str(parsed.get("plan", "")),
            shape=shape, platform=str(parsed.get("platform", "")),
            extra=extra, ts=ts, host_fp="", git_sha_value="")
    # failed round: value 0, the error preserved (truncated) — the
    # trajectory must show the outage, not skip it
    err = parsed.get("error") or (doc.get("tail") or "")[-200:]
    extra["error"] = str(err)[:300]
    return PL.make_record("import", 0.0, "Msamples/s/chip",
                          extra=extra, ts=ts, host_fp="",
                          git_sha_value="")


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("ledger", help="ledger JSONL path")
    p.add_argument("--import", dest="imports", nargs="+", default=None,
                   metavar="GLOB",
                   help="legacy BENCH_r0*.json files/globs to ingest")
    args = p.parse_args(argv)

    ledger = PL.PerfLedger(args.ledger)
    if args.imports:
        existing = ledger.load()
        seen = PL.import_keys(existing)
        paths = []
        for pat in args.imports:
            hits = sorted(glob.glob(pat))
            if not hits and os.path.exists(pat):
                hits = [pat]
            paths.extend(hits)
        imported = skipped = 0
        for path in paths:
            rec = _import_one(path, seen)
            if rec is None:
                skipped += 1
                continue
            ledger.append(rec)
            seen.add(rec["extra"]["import_key"])
            imported += 1
        print(json.dumps({"imported": imported, "skipped": skipped,
                          "ledger": args.ledger}))
        return 0 if imported or skipped else 1

    records = ledger.load()
    ok = [r for r in records if r["value"] > 0]
    out = {"ledger": args.ledger, "records": len(records),
           "measured": len(ok),
           "sources": sorted({r["source"] for r in records})}
    if ok:
        vals = [r["value"] for r in ok]
        out["best"] = max(vals)
        out["latest"] = ok[-1]["value"]
        out["geomean"] = round(
            math.exp(sum(math.log(v) for v in vals) / len(vals)), 3)
    print(json.dumps(out, sort_keys=True))
    return 0 if records else 1


if __name__ == "__main__":
    sys.exit(main())
