"""Summarize a segment-span telemetry journal (utils/telemetry.py).

``trace_summary`` attributes *device* time from an xprof trace; this
tool is its host-side complement: it reads the JSONL span journal the
pipeline writes (one record per segment) and reports

- a per-stage wall-clock table with exact p50/p95/p99 (computed from
  the raw per-segment samples, unlike the bounded-bucket /metrics
  histograms, so it doubles as their ground truth);
- a throughput timeline (segments/s, Msamples/s, detections, loss
  deltas per time bin) — the "profile per-stage, then attack the
  dominant pass" loop of PERF.md, runnable on any past observation;
- overlap efficiency of the async engine (schema-v2 spans): how much
  host/transfer time hid under device compute vs how much device wait
  blocked the drain loop, plus in-flight depth statistics;
- resilience activity (schema-v3 spans): cumulative retry / watchdog-
  requeue / worker-restart counts, shed dumps and the degradation-
  level profile — how hard the run had to fight to stay alive;
- compute health (schema-v4 spans): plan demotions / promotions /
  device reinits, the ladder-level profile and the active-plan
  timeline — which execution plan each part of the run actually
  computed on after self-healing.
- durability (schema-v5 spans): manifest crash-recovery activity —
  segments recovered beyond the checkpoint, sink pushes skipped on
  replay, uncommitted intents rolled back (all zero on a run that
  never crashed).
- fleet (schema-v6 spans): per-stream breakdown for multi-tenant
  runs — spans, detections, loss, demotions and degrade levels
  grouped by the ``stream`` field (in a NAMED span the cumulative
  attribution fields are the stream's own labeled series, so each
  tenant's books balance independently); feed it one lane's journal
  or several lanes' merged.
- device (schema-v8 spans): the performance observatory's device-time
  accounting — per-segment dispatch->ready wall percentiles,
  device-time-derived Msamples/s and roofline_frac (lower bounds: the
  traffic model is the plan's audited hbm_passes floor over an
  upper-bound device wall), and the cumulative compile / plan-cache /
  AOT-cache totals.
- science observatory (schema-v9 spans): the per-segment ``quality``
  and ``canary`` extras are summarized by tools/quality_report.py;
  this report treats them like any other extra payload.
- fleet devices (schema-v11 spans): per-POOL-MEMBER breakdown for
  elastic-fleet runs — spans, streams hosted, detections, loss and
  migrations-in grouped by the ``device`` label (which switches
  exactly at a lane's migration boundary).

Mixed v1-v11 journals (rotation can leave an older-schema tail
after an upgrade) are summarized tolerantly: records simply lack the
newer fields and drop out of the sections that need them.

Usage: python -m srtb_tpu.tools.telemetry_report JOURNAL.jsonl
           [--bin SECONDS] [--format json|md]

Reads ``<path>.1`` (the rotated generation) first when present, so the
report covers everything still on disk.  Output: markdown tables (md,
default) or one JSON document (json).  Exit 0 with a note when the
journal holds no span records yet (empty / freshly rotated — an
always-on dashboard scraping a just-started run is not an error).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _open_journal(path: str):
    """Plaintext or gzip (rotation compresses generations to
    ``.jsonl.gz``) — readers must not care which."""
    if path.endswith(".gz"):
        import gzip
        return gzip.open(path, "rt")
    return open(path)


def load(path: str, include_rotated: bool = True) -> list[dict]:
    """Parse span records, oldest first, tolerating partial lines (a
    journal being written concurrently ends mid-record).  The rotated
    generation (``<path>.1.gz``, or legacy plaintext ``<path>.1``) is
    read first when present; a torn gzip tail (crash mid-rotation)
    yields its readable prefix."""
    from srtb_tpu.utils.telemetry import rotated_generation
    records = []
    paths = []
    if include_rotated:
        gen = rotated_generation(path)
        if gen:
            paths.append(gen)
    paths.append(path)
    import zlib
    for p in paths:
        try:
            with _open_journal(p) as f:
                for line in f:
                    line = line.strip()
                    if not line.startswith("{"):
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("type") == "segment_span":
                        records.append(rec)
        except (OSError, EOFError, zlib.error):
            # includes BadGzipFile, a truncated compressed tail AND a
            # corrupt deflate stream (zlib.error — e.g. zero-filled
            # blocks after power loss): keep whatever already parsed —
            # the report must not crash on the journal it was asked
            # to diagnose
            continue
    return records


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Exact linear-interpolation percentile (numpy 'linear' method)."""
    if not sorted_vals:
        return 0.0
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = q * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def stage_stats(records: list[dict]) -> dict:
    """stage -> {count, mean_ms, p50_ms, p95_ms, p99_ms, max_ms,
    total_s}, plus a synthetic 'segment' stage (sum over stages of each
    record: the per-segment host wall clock) and — for v2 records — an
    'overlap' pseudo-stage from ``overlap_hidden_ms``.  Overlap is
    concurrent with the staged wall clock, so it is *excluded* from the
    'segment' sum.  Fields are read tolerantly: a mixed v1/v2 journal
    (rotation can leave a v1 tail after an upgrade) must summarize, not
    KeyError."""
    samples: dict[str, list[float]] = {}
    for rec in records:
        stages = rec.get("stages_ms") or {}
        for name, ms in stages.items():
            samples.setdefault(name, []).append(float(ms))
        if stages:
            samples.setdefault("segment", []).append(
                float(sum(stages.values())))
        hidden = rec.get("overlap_hidden_ms")
        if hidden is not None:
            samples.setdefault("overlap", []).append(float(hidden))
    out = {}
    for name, vals in sorted(samples.items()):
        vals.sort()
        out[name] = {
            "count": len(vals),
            "mean_ms": round(sum(vals) / len(vals), 3),
            "p50_ms": round(_percentile(vals, 0.50), 3),
            "p95_ms": round(_percentile(vals, 0.95), 3),
            "p99_ms": round(_percentile(vals, 0.99), 3),
            "max_ms": round(vals[-1], 3),
            "total_s": round(sum(vals) / 1e3, 3),
        }
    return out


def timeline(records: list[dict], bin_s: float = 10.0) -> list[dict]:
    """Throughput per time bin: segments/s, Msamples/s, detections,
    dumps, and packet-loss deltas (the journal stores cumulative
    counters; consecutive-record differences localize a burst)."""
    recs = [r for r in records if "ts" in r]
    if not recs:
        return []
    recs.sort(key=lambda r: r["ts"])
    t0 = recs[0]["ts"]
    bins: dict[int, dict] = {}
    prev_lost = prev_total = None
    for r in recs:
        b = int((r["ts"] - t0) // bin_s)
        cur = bins.setdefault(b, {
            "t_start_s": round(b * bin_s, 3), "segments": 0,
            "samples": 0, "detections": 0, "dumps": 0,
            "packets_lost_delta": 0, "packets_total_delta": 0})
        cur["segments"] += 1
        cur["samples"] += int(r.get("samples", 0))
        cur["detections"] += int(r.get("detections", 0))
        cur["dumps"] += 1 if r.get("dump") else 0
        lost, total = r.get("packets_lost"), r.get("packets_total")
        if lost is not None and prev_lost is not None:
            cur["packets_lost_delta"] += max(0, lost - prev_lost)
            cur["packets_total_delta"] += max(0, total - prev_total)
        prev_lost, prev_total = lost, total
    out = []
    last_b = max(bins)
    span = recs[-1]["ts"] - t0
    # each record stands for ~one inter-arrival interval, so the mean
    # gap is the floor for the final bin's covered time: a tail record
    # landing just past a bin boundary then reports ~the true rate
    # instead of an n/epsilon spike
    mean_gap = span / (len(recs) - 1) if len(recs) > 1 else bin_s
    for b in range(last_b + 1):
        if b not in bins:
            # a stalled pipeline writes no records: the stall must show
            # as explicit 0-seg/s rows, not as silently missing bins
            out.append({"t_start_s": round(b * bin_s, 3), "segments": 0,
                        "samples": 0, "detections": 0, "dumps": 0,
                        "packets_lost_delta": 0,
                        "packets_total_delta": 0,
                        "segments_per_sec": 0.0,
                        "msamples_per_sec": 0.0})
            continue
        cur = bins[b]
        # the final bin is usually partial: divide by the time actually
        # covered, not the full width, or a steady pipeline shows a
        # phantom end-of-run slowdown
        width = bin_s if b != last_b else \
            min(bin_s, max(span - b * bin_s, mean_gap, 1e-3))
        cur["segments_per_sec"] = round(cur["segments"] / width, 3)
        cur["msamples_per_sec"] = round(cur["samples"] / width / 1e6, 3)
        out.append(cur)
    return out


def overlap_stats(records: list[dict]) -> dict:
    """Overlap efficiency of the async engine from v2 spans:
    ``overlap_hidden_ms`` is host/transfer time that ran under device
    compute, the blocking ``fetch`` stage is device wait that was NOT
    hidden — ``efficiency = hidden / (hidden + blocked fetch)`` (1.0 =
    the engine hid every device wait).  Caveat: hidden time is an
    upper bound (it includes host gap after the device finished), so
    on a source/sink-bound pipeline efficiency reads ~1.0 while the
    device idles — check the ingest/sink stage shares alongside it.
    v1 records (no overlap fields) are skipped; empty dict when none
    qualify."""
    hidden, fetch, depths = [], [], []
    for r in records:
        h = r.get("overlap_hidden_ms")
        if h is None:
            continue
        hidden.append(float(h))
        fetch.append(float((r.get("stages_ms") or {}).get("fetch", 0.0)))
        d = r.get("inflight_depth")
        if d is not None:
            depths.append(int(d))
    if not hidden:
        return {}
    tot_h, tot_f = sum(hidden), sum(fetch)
    out = {
        "records": len(hidden),
        "hidden_total_s": round(tot_h / 1e3, 3),
        "hidden_mean_ms": round(tot_h / len(hidden), 3),
        "blocked_fetch_total_s": round(tot_f / 1e3, 3),
        "efficiency": (round(tot_h / (tot_h + tot_f), 4)
                       if tot_h + tot_f > 0 else 0.0),
    }
    if depths:
        out["inflight_depth_mean"] = round(sum(depths) / len(depths), 2)
        out["inflight_depth_max"] = max(depths)
    return out


def resilience_stats(records: list[dict]) -> dict:
    """Resilience activity from v3 spans.  The counters are cumulative
    registry values (like ``segments_dropped``), so the LAST record
    carries the run totals; the per-record degradation level gives the
    time-at-degraded profile.  v1/v2 records (no resilience fields)
    are skipped; empty dict when none qualify."""
    v3 = [r for r in records if "degrade_level" in r or "retries" in r]
    if not v3:
        return {}
    last = v3[-1]
    levels = [int(r.get("degrade_level", 0)) for r in v3]
    return {
        "records": len(v3),
        "retries": int(last.get("retries", 0)),
        "requeues": int(last.get("requeues", 0)),
        "restarts": int(last.get("restarts", 0)),
        "shed_waterfalls": int(last.get("shed_waterfalls", 0)),
        "shed_baseband": int(last.get("shed_baseband", 0)),
        "degrade_level_max": max(levels),
        "segments_degraded": sum(1 for lv in levels if lv > 0),
    }


def compute_stats(records: list[dict]) -> dict:
    """Compute health from v4 spans (the self-healing ladder).  The
    counters are cumulative, so the LAST record carries run totals;
    the per-record ladder level gives time-at-demoted, and the
    ``active_plan`` change points give the plan timeline (which plan
    family each stretch of the run computed on).  v1–v3 records (no
    compute fields) are skipped; empty dict when none qualify."""
    v4 = [r for r in records if "plan_demotions" in r
          or "device_reinits" in r]
    if not v4:
        return {}
    last = v4[-1]
    levels = [int(r.get("plan_ladder_level", 0)) for r in v4]
    timeline_plans: list[dict] = []
    prev = None
    for r in v4:
        plan = r.get("active_plan")
        if plan is not None and plan != prev:
            timeline_plans.append({"segment": int(r.get("segment", -1)),
                                   "plan": plan})
            prev = plan
    return {
        "records": len(v4),
        "plan_demotions": int(last.get("plan_demotions", 0)),
        "plan_promotions": int(last.get("plan_promotions", 0)),
        "device_reinits": int(last.get("device_reinits", 0)),
        "ladder_level_max": max(levels),
        "ladder_level_last": levels[-1],
        "segments_demoted": sum(1 for lv in levels if lv > 0),
        "plan_timeline": timeline_plans,
    }


def durability_stats(records: list[dict]) -> dict:
    """Crash-recovery activity from v5 spans (the run manifest,
    io/manifest.py).  Unlike the other cumulative sections, a
    crash-recovered run spans SEVERAL processes and the counters
    reset with each one — the very runs this section describes —
    so totals are summed per process generation (a counter DECREASE
    between consecutive records marks a restart boundary).  v1-v4
    records (no durability fields) are skipped; empty dict when none
    qualify."""
    v5 = [r for r in records if "replayed_skips" in r
          or "rolled_back_intents" in r]
    if not v5:
        return {}

    def total(field: str) -> int:
        out = 0
        prev = 0
        for r in v5:
            cur = int(r.get(field, 0))
            if cur < prev:  # process restart: bank the finished life
                out += prev
            prev = cur
        return out + prev

    return {
        "records": len(v5),
        "recovered_segments": total("recovered_segments"),
        "replayed_skips": total("replayed_skips"),
        "rolled_back_intents": total("rolled_back_intents"),
    }


def fleet_stats(records: list[dict]) -> dict:
    """Per-stream breakdown from v6 spans (the multi-tenant fleet).
    Records without a ``stream`` field (v1-v5, or unnamed solo runs)
    are skipped; empty dict when none qualify.  Cumulative fields in
    a named span are the stream's OWN series (telemetry.segment_span
    v6), so the last record per stream carries that tenant's totals."""
    by_stream: dict[str, list[dict]] = {}
    for r in records:
        s = r.get("stream")
        if s is not None:
            by_stream.setdefault(str(s), []).append(r)
    if not by_stream:
        return {}
    out = {}
    for s, recs in sorted(by_stream.items()):
        last = recs[-1]
        levels = [int(r.get("degrade_level", 0)) for r in recs]
        out[s] = {
            "records": len(recs),
            "detections": sum(int(r.get("detections", 0))
                              for r in recs),
            "dumps": sum(1 for r in recs if r.get("dump")),
            "segments_dropped": int(last.get("segments_dropped", 0)),
            "shed_waterfalls": int(last.get("shed_waterfalls", 0)),
            "shed_baseband": int(last.get("shed_baseband", 0)),
            "plan_demotions": int(last.get("plan_demotions", 0)),
            "device_reinits": int(last.get("device_reinits", 0)),
            "degrade_level_max": max(levels),
            "plan_ladder_level_last":
                int(last.get("plan_ladder_level", 0)),
        }
    return out


def fleet_device_stats(records: list[dict]) -> dict:
    """Per-POOL-MEMBER breakdown from v11 spans (the elastic device
    fleet): spans executed, streams hosted, detections, loss deltas
    attributed to the device that drained them, and migrations IN
    (device-label change points per stream).  Records without a
    ``device`` label (v1-v10, or a solo run) are skipped; empty dict
    when none qualify.  Feed it one lane's journal or several lanes'
    merged — the per-stream change-point walk is order-tolerant
    because each stream's records are tracked independently."""
    by_dev: dict[str, dict] = {}
    last_dev: dict[str, str] = {}      # stream -> previous device
    last_dropped: dict[str, int] = {}  # stream -> previous cumulative
    any_v11 = False
    for r in records:
        dev = r.get("device")
        if not dev:
            continue
        any_v11 = True
        dev = str(dev)
        stream = str(r.get("stream") or "")
        cur = by_dev.setdefault(dev, {
            "spans": 0, "streams": set(), "detections": 0,
            "segments_dropped": 0, "migrations_in": 0})
        cur["spans"] += 1
        cur["streams"].add(stream)
        cur["detections"] += int(r.get("detections", 0))
        # loss is a cumulative per-stream counter (named spans carry
        # the stream's OWN series): the delta since the stream's
        # previous record belongs to the device draining NOW
        dropped = r.get("segments_dropped")
        if dropped is not None:
            prev = last_dropped.get(stream)
            if prev is not None:
                cur["segments_dropped"] += max(0, int(dropped) - prev)
            last_dropped[stream] = int(dropped)
        prev_dev = last_dev.get(stream)
        if prev_dev is not None and prev_dev != dev:
            cur["migrations_in"] += 1
        last_dev[stream] = dev
    if not any_v11:
        return {}
    return {dev: {**st, "streams": len(st["streams"])}
            for dev, st in sorted(by_dev.items())}


def device_stats(records: list[dict]) -> dict:
    """Device-time accounting from v8 spans (performance
    observatory).  ``device_ms`` is per-segment (an upper bound on
    device busy time — dispatch->drain-head-ready wall), the
    roofline/throughput fields are per-segment lower bounds, and the
    compile/cache counters are cumulative (last record = run totals).
    Older records (no device fields) are skipped; empty dict when
    none qualify."""
    v8 = [r for r in records if "device_ms" in r
          or "compile_ms" in r]
    if not v8:
        return {}
    dev = sorted(float(r["device_ms"]) for r in v8
                 if "device_ms" in r)
    fracs = [float(r["roofline_frac"]) for r in v8
             if "roofline_frac" in r]
    msamps = [float(r["achieved_msamps"]) for r in v8
              if "achieved_msamps" in r]
    last = v8[-1]
    out = {"records": len(v8)}
    if dev:
        out.update(
            device_p50_ms=round(_percentile(dev, 0.50), 3),
            device_p95_ms=round(_percentile(dev, 0.95), 3),
            device_max_ms=round(dev[-1], 3),
            device_total_s=round(sum(dev) / 1e3, 3))
    if msamps:
        out["achieved_msamps_median"] = round(
            _percentile(sorted(msamps), 0.50), 2)
    if fracs:
        out["roofline_frac_median"] = round(
            _percentile(sorted(fracs), 0.50), 4)
        out["roofline_frac_max"] = round(max(fracs), 4)
    out.update(
        compile_ms=float(last.get("compile_ms", 0.0)),
        plan_compiles=int(last.get("plan_compiles", 0)),
        aot_cache_hits=int(last.get("aot_cache_hits", 0)),
        aot_cache_misses=int(last.get("aot_cache_misses", 0)))
    return out


def report(path: str, bin_s: float = 10.0) -> dict:
    records = load(path)
    return {
        "journal": path,
        "records": len(records),
        "stages": stage_stats(records),
        "overlap": overlap_stats(records),
        "resilience": resilience_stats(records),
        "compute": compute_stats(records),
        "durability": durability_stats(records),
        "fleet": fleet_stats(records),
        "fleet_devices": fleet_device_stats(records),
        "device": device_stats(records),
        "timeline": timeline(records, bin_s),
    }


def _md(rep: dict) -> str:
    lines = [f"# Telemetry report — {rep['journal']}",
             "", f"{rep['records']} segment spans.", "",
             "## Per-stage wall clock (ms)", "",
             "| stage | count | mean | p50 | p95 | p99 | max | "
             "total s |", "|---|---|---|---|---|---|---|---|"]
    for name, s in rep["stages"].items():
        lines.append(
            f"| {name} | {s['count']} | {s['mean_ms']} | {s['p50_ms']} |"
            f" {s['p95_ms']} | {s['p99_ms']} | {s['max_ms']} |"
            f" {s['total_s']} |")
    ov = rep.get("overlap") or {}
    if ov:
        lines += ["", "## Overlap (async engine)", "",
                  f"hidden under device compute: {ov['hidden_total_s']} s"
                  f" total ({ov['hidden_mean_ms']} ms/segment mean), "
                  f"blocked fetch: {ov['blocked_fetch_total_s']} s, "
                  f"efficiency: {ov['efficiency']}"]
        if "inflight_depth_mean" in ov:
            lines.append(
                f"in-flight depth: mean {ov['inflight_depth_mean']}, "
                f"max {ov['inflight_depth_max']}")
    rs = rep.get("resilience") or {}
    if rs:
        lines += ["", "## Resilience", "",
                  f"retries: {rs['retries']}, watchdog requeues: "
                  f"{rs['requeues']}, worker restarts: "
                  f"{rs['restarts']}, shed waterfalls: "
                  f"{rs['shed_waterfalls']}, shed baseband dumps: "
                  f"{rs['shed_baseband']}",
                  f"degradation: max level {rs['degrade_level_max']}, "
                  f"{rs['segments_degraded']}/{rs['records']} segments "
                  "drained at a degraded level"]
    cs = rep.get("compute") or {}
    if cs:
        lines += ["", "## Compute health (self-healing ladder)", "",
                  f"plan demotions: {cs['plan_demotions']}, "
                  f"promotions: {cs['plan_promotions']}, "
                  f"device reinits: {cs['device_reinits']}",
                  f"ladder: max level {cs['ladder_level_max']}, final "
                  f"level {cs['ladder_level_last']}, "
                  f"{cs['segments_demoted']}/{cs['records']} segments "
                  "drained on a demoted plan"]
        if cs["plan_timeline"]:
            lines += ["", "active-plan timeline:"]
            for step in cs["plan_timeline"]:
                lines.append(f"- segment {step['segment']}: "
                             f"{step['plan']}")
    ds = rep.get("durability") or {}
    if ds:
        lines += ["", "## Durability (run manifest)", "",
                  f"recovered segments: {ds['recovered_segments']}, "
                  f"replayed skips: {ds['replayed_skips']}, "
                  f"rolled-back intents: {ds['rolled_back_intents']}"]
    fl = rep.get("fleet") or {}
    if fl:
        lines += ["", "## Fleet (per-stream)", "",
                  "| stream | spans | detections | dumps | dropped | "
                  "demotions | reinits | degrade max | ladder |",
                  "|---|---|---|---|---|---|---|---|---|"]
        for s, st in fl.items():
            lines.append(
                f"| {s} | {st['records']} | {st['detections']} | "
                f"{st['dumps']} | {st['segments_dropped']} | "
                f"{st['plan_demotions']} | {st['device_reinits']} | "
                f"{st['degrade_level_max']} | "
                f"{st['plan_ladder_level_last']} |")
    fd = rep.get("fleet_devices") or {}
    if fd:
        lines += ["", "## Fleet devices (per pool member)", "",
                  "| device | spans | streams | detections | loss | "
                  "migrations in |", "|---|---|---|---|---|---|"]
        for dev, st in fd.items():
            lines.append(
                f"| {dev} | {st['spans']} | {st['streams']} | "
                f"{st['detections']} | {st['segments_dropped']} | "
                f"{st['migrations_in']} |")
    dv = rep.get("device") or {}
    if dv:
        lines += ["", "## Device time (performance observatory)", ""]
        if "device_p50_ms" in dv:
            lines.append(
                f"dispatch->ready wall: p50 {dv['device_p50_ms']} ms, "
                f"p95 {dv['device_p95_ms']} ms, max "
                f"{dv['device_max_ms']} ms "
                f"(total {dv['device_total_s']} s; upper bound)")
        if "roofline_frac_median" in dv:
            lines.append(
                f"roofline_frac: median {dv['roofline_frac_median']}, "
                f"max {dv['roofline_frac_max']} (lower bound vs the "
                "plan's audited hbm_passes floor)"
                + (f"; achieved {dv['achieved_msamps_median']} "
                   "Msamples/s median"
                   if "achieved_msamps_median" in dv else ""))
        lines.append(
            f"compile: {dv['compile_ms']} ms cumulative over "
            f"{dv['plan_compiles']} first-dispatch compile(s); AOT "
            f"cache {dv['aot_cache_hits']} hit(s) / "
            f"{dv['aot_cache_misses']} miss(es)")
    lines += ["", "## Throughput timeline", "",
              "| t (s) | segments | seg/s | Msamples/s | detections | "
              "dumps | pkts lost |", "|---|---|---|---|---|---|---|"]
    for b in rep["timeline"]:
        lines.append(
            f"| {b['t_start_s']} | {b['segments']} | "
            f"{b['segments_per_sec']} | {b['msamples_per_sec']} | "
            f"{b['detections']} | {b['dumps']} | "
            f"{b['packets_lost_delta']} |")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("journal")
    p.add_argument("--bin", type=float, default=10.0)
    p.add_argument("--format", choices=("md", "json"), default="md")
    args = p.parse_args(argv)
    rep = report(args.journal, args.bin)
    if not rep["records"]:
        # empty or freshly rotated journal: a clear note, not a
        # failure — dashboards scrape just-started runs
        note = {"note": f"no segment spans in {args.journal} yet",
                "records": 0}
        print(json.dumps(note) if args.format == "json"
              else f"# Telemetry report\n\n{note['note']}\n")
        return 0
    if args.format == "json":
        print(json.dumps(rep, sort_keys=True))
    else:
        print(_md(rep))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
