"""UDP -> disk baseband recorder (ref: src/baseband_receiver.cpp:59-87:
composite_pipe of udp receive + cast + write, no device processing)."""

from __future__ import annotations

import sys

from srtb_tpu.config import Config
from srtb_tpu.io.udp import UdpReceiverSource
from srtb_tpu.utils.logging import log
from srtb_tpu.utils.termination import install_termination_handler


def main(argv=None) -> int:
    install_termination_handler()
    cfg = Config.from_args(argv)
    src = UdpReceiverSource(cfg)
    path = cfg.baseband_output_file_prefix + "recorded.bin"
    n = 0
    with open(path, "ab") as f:
        try:
            for seg in src:
                f.write(seg.data.tobytes())
                n += 1
                log.debug(f"[baseband_receiver] segment {n}, counter "
                          f"{seg.udp_packet_counter}")
        except KeyboardInterrupt:
            pass
        finally:
            src.close()
    log.info(f"[baseband_receiver] wrote {n} segments to {path}; "
             f"lost {src.receiver.lost_packets} packets")
    return 0


if __name__ == "__main__":
    sys.exit(main())
