"""UDP -> disk baseband recorder (ref: src/baseband_receiver.cpp:59-87:
composite_pipe of udp receive + cast + write, no device processing)."""

from __future__ import annotations

import sys

from srtb_tpu.config import Config
from srtb_tpu.io.udp import UdpReceiverSource
from srtb_tpu.utils.logging import log
from srtb_tpu.utils.termination import install_termination_handler
from srtb_tpu.utils.platform import apply_platform_env


def main(argv=None) -> int:
    apply_platform_env()
    install_termination_handler()
    cfg = Config.from_args(argv)
    src = UdpReceiverSource(cfg)
    path = cfg.baseband_output_file_prefix + "recorded.bin"
    n = 0
    # ordered async appends through the native writer pool so disk
    # latency never blocks the UDP drain loop (single thread = in-order)
    from srtb_tpu.io.native_writer import AsyncWriterPool
    with AsyncWriterPool(n_threads=1) as pool:
        try:
            for seg in src:
                pool.submit(path, seg.data, append=True)
                n += 1
                # fail fast on disk errors rather than draining UDP for
                # hours while appends silently fail
                pool.raise_new_errors(f"append to {path}")
                log.debug(f"[baseband_receiver] segment {n}, counter "
                          f"{seg.udp_packet_counter}")
        except KeyboardInterrupt:
            pass
        finally:
            src.close()
            pool.drain()
            pool.raise_new_errors(f"append to {path}")
    log.info(f"[baseband_receiver] wrote {n} segments to {path}; "
             f"lost {src.receiver.lost_packets} packets")
    return 0


if __name__ == "__main__":
    sys.exit(main())
