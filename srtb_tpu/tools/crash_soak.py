"""SIGKILL crash soak: durable exactly-once outputs under process death.

``tools/chaos_soak.py`` soaks *in-process* fault recovery; this harness
soaks the one thing no in-process mechanism can handle — the process
dying outright.  It runs the file-mode pipeline as a SUBPROCESS and
``SIGKILL``s it at seeded-random points, steered deterministically into
the nastiest crash windows:

- ``ckpt_stall@i``  — ``Config.fault_plan`` ``checkpoint:stall`` parks
  the child between segment *i*'s sink commits and its checkpoint
  update (the classic duplicate-on-resume window); the parent kills it
  mid-stall;
- ``sink_stall@i``  — ``sink_write:stall`` parks it after the fetch,
  before any artifact write (the clean-loss window);
- ``rename@N``      — the child arms ``io/writers._PRE_RENAME_HOOK``
  to park the *N*-th artifact write between its temp write and the
  atomic rename (orphan temp + uncommitted intent); the parent kills
  it mid-rename.

After each kill the child is simply restarted: ``Pipeline.__init__``
recovers the run manifest (io/manifest.py), rolls back uncommitted
artifacts, and the manifest done-set makes replayed sink pushes
idempotent.  When a child finally runs to completion the gate asserts:

- ``fsck`` (tools/fsck.py) is CLEAN — WAL CRCs, artifact
  existence/size/content-CRC, checkpoint agreement;
- the run directory's final output set (paths + bytes, SHA-256) is
  BIT-IDENTICAL to an uninterrupted golden run — zero duplicates,
  zero loss (file mode never sheds, so loss beyond accounted
  ``segments_dropped`` = any loss at all would break the equality);
- every planned SIGKILL actually landed, and no ``.srtb_tmp`` orphans
  survive.

File-mode artifact names embed the segment timestamp; subprocess runs
stamp timestamps deterministically from the stream offset
(:class:`DeterministicTimestampReader`) so names are reproducible
across golden/soak runs AND across resumes — which is also what makes
the paths+bytes equality an honest exactly-once check.

Usage::

    python -m srtb_tpu.tools.crash_soak [--seed N] [--segments N]
        [--kills N] [--log2n N] [--kill-plan "ckpt_stall@1,rename@2"]
        [--writer-threads N]

Exit 0 on a passing soak, 1 on any gate failure.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import random
import subprocess
import sys
import tempfile
import threading
import time

STALL_S = 30.0          # long enough that the parent's kill always lands
CHILD_TIMEOUT_S = 300.0
_FIRING_MARK = "[faults] firing"
_RENAME_MARK = "SOAK_RENAME_STALL"
_STATS_MARK = "SOAK_STATS "
_RECOVERY_MARK = "SOAK_RECOVERY "


class SoakFailure(AssertionError):
    """One broken exactly-once invariant (the gate)."""


# ----------------------------------------------------------------
# child side
# ----------------------------------------------------------------

def make_resumable_source(cfg):
    """The file source a resumed child needs: checkpoint-aware start
    offset (mirroring Pipeline's own source construction) plus
    offset-derived deterministic timestamps — the first-class reader
    in io/file_input.py (``DeterministicTimestampReader``, promoted
    out of this tool so the soaks and the archive replay engine share
    one implementation)."""
    from srtb_tpu.io.file_input import DeterministicTimestampReader

    start = None
    if cfg.checkpoint_path and (
            os.path.exists(cfg.checkpoint_path)
            or os.path.exists(cfg.checkpoint_path + ".bak")):
        from srtb_tpu.pipeline.checkpoint import StreamCheckpoint
        ck = StreamCheckpoint(cfg.checkpoint_path)
        if ck.segments_done:
            start = ck.file_offset_bytes
    return DeterministicTimestampReader(cfg, start_offset_bytes=start)


def _child_main(cfg_path: str, stall_rename_at: int,
                stall_s: float) -> int:
    from srtb_tpu.config import Config
    from srtb_tpu.pipeline.runtime import Pipeline
    from srtb_tpu.utils.metrics import metrics

    with open(cfg_path) as f:
        cfg = Config(**json.load(f))
    if cfg.writer_thread_count > 0:
        # pin the Python fallback pool: the native C++ pool renames in
        # C++ where the rename-stall hook cannot park, and its commit
        # granularity is the drain barrier — the py pool is the
        # deterministic per-artifact path this soak steers
        from srtb_tpu.io import native_writer
        native_writer._NATIVE = None
    if stall_rename_at > 0:
        from srtb_tpu.io import writers
        count = [0]

        def hook(path):
            count[0] += 1
            if count[0] == stall_rename_at:
                print(f"{_RENAME_MARK} {os.path.basename(path)}",
                      flush=True)
                time.sleep(stall_s)

        writers._PRE_RENAME_HOOK = hook
    src = make_resumable_source(cfg)
    with Pipeline(cfg, source=src) as pipe:
        # manifest recovery ran in the constructor; report it BEFORE
        # the run so the parent sees it even from a child it kills
        print(_RECOVERY_MARK + json.dumps({
            "recovered_segments":
                int(metrics.get("recovered_segments")),
            "rolled_back_intents":
                int(metrics.get("rolled_back_intents")),
        }), flush=True)
        stats = pipe.run()
    print(_STATS_MARK + json.dumps({
        "segments": stats.segments,
        "signals": stats.signals,
        "recovered_segments": int(metrics.get("recovered_segments")),
        "replayed_skips": int(metrics.get("replayed_skips")),
        "rolled_back_intents": int(metrics.get("rolled_back_intents")),
        "segments_dropped": int(metrics.get("segments_dropped")),
    }), flush=True)
    return 0


# ----------------------------------------------------------------
# parent side
# ----------------------------------------------------------------

def _child_cfg(tmp: str, run_dir: str, n: int, fault_plan: str = "",
               writer_threads: int = 0) -> dict:
    return dict(
        baseband_input_count=n, baseband_input_bits=8,
        baseband_freq_low=1405.0, baseband_bandwidth=64.0,
        baseband_sample_rate=128e6, dm=0.05,
        input_file_path=os.path.join(tmp, "bb.bin"),
        baseband_output_file_prefix=os.path.join(run_dir, "out_"),
        spectrum_channel_count=64,
        # zapping OFF in spirit: the soak needs every segment's pulse
        # to reach the detector so every segment writes artifacts
        mitigate_rfi_average_method_threshold=1000.0,
        mitigate_rfi_spectral_kurtosis_threshold=50.0,
        # deliberately below the noise floor: EVERY segment must write
        # artifacts (deterministically — same data, same decisions) so
        # each kill window has writes to land in and every segment
        # contributes to the exactly-once union
        signal_detect_signal_noise_threshold=1.5,
        signal_detect_max_boxcar_length=8,
        baseband_reserve_sample=True,
        writer_thread_count=writer_threads,
        fft_strategy="four_step",
        inflight_segments=2,
        checkpoint_path=os.path.join(run_dir, "ck.json"),
        run_manifest_path=os.path.join(run_dir, "manifest.jsonl"),
        fault_plan=fault_plan,
    )


def _run_child(run_dir: str, cfg: dict, kill_on: str | None = None,
               stall_rename_at: int = 0,
               timeout_s: float = CHILD_TIMEOUT_S) -> dict:
    """Spawn one pipeline child; with ``kill_on`` set, SIGKILL it as
    soon as that marker appears on its merged output.  Returns
    {rc, killed, stats, lines}."""
    cfg_path = os.path.join(run_dir, "cfg.json")
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)
    cmd = [sys.executable, "-m", "srtb_tpu.tools.crash_soak",
           "--child", cfg_path]
    if stall_rename_at > 0:
        cmd += ["--stall-rename-at", str(stall_rename_at),
                "--stall-s", f"{STALL_S:g}"]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True,
                            bufsize=1, env=env)
    # hard backstop so a wedged child can never hang the soak
    backstop = threading.Timer(timeout_s, proc.kill)
    backstop.daemon = True
    backstop.start()
    killed = False
    stats = None
    recovery = None
    lines: list[str] = []
    try:
        for line in proc.stdout:
            lines.append(line.rstrip("\n"))
            if line.startswith(_STATS_MARK):
                stats = json.loads(line[len(_STATS_MARK):])
            elif line.startswith(_RECOVERY_MARK):
                recovery = json.loads(line[len(_RECOVERY_MARK):])
            if kill_on is not None and not killed and kill_on in line:
                time.sleep(0.25)  # land the kill INSIDE the stall
                proc.kill()       # SIGKILL: no cleanup runs
                killed = True
        rc = proc.wait()
    finally:
        backstop.cancel()
        proc.stdout.close()
    replays = sum(1 for ln in lines if "skipping replay" in ln)
    return {"rc": rc, "killed": killed, "stats": stats,
            "recovery": recovery, "replayed_skips": replays,
            "lines": lines}


def _read_ck_done(run_dir: str) -> int:
    for name in ("ck.json", "ck.json.bak"):
        try:
            with open(os.path.join(run_dir, name)) as f:
                return int(json.load(f).get("segments_done", 0))
        except (OSError, ValueError):
            continue
    return 0


def snapshot_outputs(run_dir: str) -> dict:
    """relative name -> sha256 of every artifact in a run dir
    (manifest/checkpoint/config bookkeeping excluded)."""
    skip = {"manifest.jsonl", "ck.json", "ck.json.bak", "ck.json.tmp",
            "cfg.json"}
    out = {}
    for name in sorted(os.listdir(run_dir)):
        if name in skip:
            continue
        p = os.path.join(run_dir, name)
        if not os.path.isfile(p):
            continue
        h = hashlib.sha256()
        with open(p, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        out[name] = h.hexdigest()
    return out


def parse_kill_plan(text: str) -> list[tuple[str, int]]:
    """"kind@arg,..." with kinds ckpt_stall|sink_stall (arg = per-run
    segment index) and rename (arg = Nth artifact write of the run)."""
    plan = []
    for entry in (e.strip() for e in text.split(",")):
        if not entry:
            continue
        try:
            kind, arg = entry.split("@", 1)
            kind = kind.strip()
            arg_i = int(arg)
        except ValueError as e:
            raise ValueError(f"kill-plan entry {entry!r}: expected "
                             "'kind@int'") from e
        if kind not in ("ckpt_stall", "sink_stall", "rename"):
            raise ValueError(f"kill-plan entry {entry!r}: unknown kind "
                             f"{kind!r}")
        plan.append((kind, arg_i))
    return plan


def generate_kill_plan(seed: int, kills: int) -> list[tuple[str, int]]:
    """Seeded random kill points.  The first two kills always cover
    the two named crash windows (mid-checkpoint-flush, mid-rename);
    the rest draw from all three kinds.  Stall indices are RELATIVE to
    each resumed run (re-clamped to the remaining segment count at
    launch, so every planned kill lands)."""
    rng = random.Random(seed)
    plan: list[tuple[str, int]] = []
    for i in range(kills):
        if i == 0:
            kind = "ckpt_stall"
        elif i == 1:
            kind = "rename"
        else:
            kind = rng.choice(("ckpt_stall", "sink_stall", "rename"))
        # small indices: each stall-steered kill advances the resumed
        # run by ~its index, and the soak must not outrun --segments
        # before every planned kill lands
        arg = (rng.randrange(1, 3) if kind == "rename"
               else rng.randrange(0, 3))
        plan.append((kind, arg))
    return plan


def run_soak(seed: int = 0, segments: int = 10, kills: int = 5,
             log2n: int = 13, kill_plan: str | None = None,
             writer_threads: int = 0,
             tmpdir: str | None = None) -> dict:
    """One full soak (golden run, kill loop, recovery to completion,
    gate).  Returns the report dict; raises :class:`SoakFailure` on
    any broken invariant."""
    from srtb_tpu.io.synth import make_dispersed_baseband
    from srtb_tpu.tools.fsck import fsck

    tmp = tmpdir or tempfile.mkdtemp(prefix="srtb_crash_")
    n = 1 << log2n
    # one pulse per overlap-save STRIDE window, so every segment the
    # reader emits is positive and writes artifacts — the rename
    # steering then always finds a write to park, and every segment
    # contributes to the exactly-once union
    from srtb_tpu.config import Config
    from srtb_tpu.ops import dedisperse as dd
    probe_cfg = Config(**_child_cfg(tmp, tmp, n))
    reserved = int(dd.nsamps_reserved(probe_cfg))
    stride = max(1, n - reserved)
    total_bytes = n * segments
    pulses = [reserved + i * stride + stride // 2
              for i in range((total_bytes - reserved) // stride + 1)
              if reserved + i * stride + stride // 2 < total_bytes]
    make_dispersed_baseband(
        total_bytes, 1405.0, 64.0, 0.05,
        pulse_positions=pulses,
        pulse_amp=40.0, nbits=8, seed=seed,
    ).tofile(os.path.join(tmp, "bb.bin"))

    def check(cond, msg):
        if not cond:
            raise SoakFailure(msg)

    # golden: one uninterrupted run
    golden_dir = os.path.join(tmp, "golden")
    os.makedirs(golden_dir, exist_ok=True)
    res = _run_child(golden_dir,
                     _child_cfg(tmp, golden_dir, n,
                                writer_threads=writer_threads))
    check(res["rc"] == 0, f"golden run failed rc={res['rc']}:\n"
          + "\n".join(res["lines"][-20:]))
    golden_map = snapshot_outputs(golden_dir)
    total_segments = int(res["stats"]["segments"])
    check(res["stats"]["signals"] > 0 and golden_map,
          "golden run produced no artifacts — the soak would gate "
          "nothing (tune pulse_amp / detection thresholds)")

    # soak: kill, resume, repeat
    plan = (parse_kill_plan(kill_plan) if kill_plan
            else generate_kill_plan(seed, kills))
    soak_dir = os.path.join(tmp, "soak")
    os.makedirs(soak_dir, exist_ok=True)
    kills_done = 0
    resumes = 0
    all_res: list[dict] = []
    finished = False
    # whether any kill landed with a sealed-but-unchecked-pointed
    # group on disk — only then MUST a later resume replay-skip it
    # (a stalled NEGATIVE segment wrote nothing and owes no skip)
    expect_replay = False
    expect_rollback = False
    for kind, arg in plan:
        done = _read_ck_done(soak_dir)
        remaining = max(1, total_segments - done)
        if kind == "rename":
            cfg = _child_cfg(tmp, soak_dir, n,
                             writer_threads=writer_threads)
            res = _run_child(soak_dir, cfg, kill_on=_RENAME_MARK,
                             stall_rename_at=max(1, arg))
        else:
            site = ("checkpoint" if kind == "ckpt_stall"
                    else "sink_write")
            index = min(arg, remaining - 1)
            cfg = _child_cfg(
                tmp, soak_dir, n, writer_threads=writer_threads,
                fault_plan=f"{site}:stall={STALL_S:g}@{index}")
            res = _run_child(soak_dir, cfg, kill_on=_FIRING_MARK)
        resumes += 1
        all_res.append(res)
        if res["killed"]:
            kills_done += 1
            from srtb_tpu.io.manifest import (group_complete,
                                              scan_manifest)
            scan = scan_manifest(os.path.join(soak_dir,
                                              "manifest.jsonl"))
            floor = scan.checkpoint_floor()
            if any(k[1] >= floor and group_complete(g)
                   for k, g in scan.groups.items()):
                expect_replay = True
            if kind == "rename":
                expect_rollback = True
        elif res["rc"] == 0:
            # finished before the steering point was reached (e.g. a
            # rename index past the run's remaining writes)
            finished = True
            break
        else:
            raise SoakFailure(
                f"steered child died rc={res['rc']} without being "
                f"killed ({kind}@{arg}):\n"
                + "\n".join(res["lines"][-20:]))

    if not finished:
        # recovery to completion
        res = _run_child(soak_dir,
                         _child_cfg(tmp, soak_dir, n,
                                    writer_threads=writer_threads))
        check(res["rc"] == 0,
              f"final recovery run failed rc={res['rc']}:\n"
              + "\n".join(res["lines"][-20:]))
        all_res.append(res)
        resumes += 1

    check(kills_done == len(plan),
          f"only {kills_done}/{len(plan)} planned SIGKILLs landed "
          "(the run completed early — raise --segments or tighten "
          "the plan)")

    # gate 1: fsck clean
    rep = fsck(os.path.join(soak_dir, "manifest.jsonl"),
               os.path.join(soak_dir, "ck.json"))
    check(rep["clean"], f"fsck NOT clean after recovery: "
          f"errors={rep['errors']} loss={rep['loss']}")

    # gate 2: no orphan temps survive recovery
    orphans = [f for f in os.listdir(soak_dir)
               if f.endswith(".srtb_tmp")]
    check(not orphans, f"orphan temp files survive: {orphans}")

    # gate 3: the union of outputs across all lives of the run is
    # bit-identical to the golden run — no duplicates, no loss
    soak_map = snapshot_outputs(soak_dir)
    missing = sorted(set(golden_map) - set(soak_map))
    extra = sorted(set(soak_map) - set(golden_map))
    check(not missing, f"artifacts LOST across crashes: {missing}")
    check(not extra, f"duplicate/unknown artifacts after crashes: "
          f"{extra}")
    differing = sorted(k for k in golden_map
                       if golden_map[k] != soak_map[k])
    check(not differing,
          f"artifact bytes differ from the golden run: {differing}")

    # gate 4: file mode never sheds — any drop would be silent loss
    dropped = sum(int(r["stats"].get("segments_dropped", 0))
                  for r in all_res if r["stats"])
    check(dropped == 0, f"file-mode soak dropped {dropped} segment(s)")

    # recovery bookkeeping across every life of the run (recovery
    # markers print at child startup, so killed children count too)
    replayed = sum(int(r["replayed_skips"]) for r in all_res)
    recovered = sum(int(r["recovery"]["recovered_segments"])
                    for r in all_res if r["recovery"])
    rolled = sum(int(r["recovery"]["rolled_back_intents"])
                 for r in all_res if r["recovery"])

    # gate 5: the steered windows provably exercised their recovery
    # paths — a kill that left a sealed group beyond the checkpoint
    # must surface as a manifest replay-skip on resume, a mid-rename
    # kill as a rolled-back intent
    if expect_replay:
        check(replayed >= 1,
              "a kill left a committed segment beyond the checkpoint "
              "but no resumed child replay-skipped it")
    if expect_rollback:
        check(rolled >= 1,
              "a mid-rename kill landed but recovery rolled back "
              "no uncommitted intent")

    return {
        "seed": seed, "segments": total_segments,
        "artifacts": len(golden_map),
        "plan": [f"{k}@{a}" for k, a in plan],
        "sigkills": kills_done, "resumes": resumes + 1,
        "replayed_skips": replayed,
        "recovered_segments": recovered,
        "rolled_back_intents": rolled,
        "fsck_records": rep["records"],
        "ok": True,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="crash-soak",
        description="SIGKILL crash soak for durable exactly-once "
                    "outputs (see srtb_tpu/tools/crash_soak.py)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--segments", type=int, default=10)
    ap.add_argument("--kills", type=int, default=5)
    ap.add_argument("--log2n", type=int, default=13)
    ap.add_argument("--kill-plan", default=None,
                    help="explicit plan 'kind@arg,...' (kinds "
                         "ckpt_stall|sink_stall|rename); overrides "
                         "--kills generation")
    ap.add_argument("--writer-threads", type=int, default=0,
                    help="candidate-writer pool size in the children "
                         "(0 = synchronous writes)")
    # child-process plumbing (not for interactive use)
    ap.add_argument("--child", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--stall-rename-at", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--stall-s", type=float, default=STALL_S,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.child:
        return _child_main(args.child, args.stall_rename_at,
                           args.stall_s)

    try:
        report = run_soak(seed=args.seed, segments=args.segments,
                          kills=args.kills, log2n=args.log2n,
                          kill_plan=args.kill_plan,
                          writer_threads=args.writer_threads)
    except SoakFailure as e:
        print(json.dumps({"ok": False, "failure": str(e)}))
        print(f"crash-soak: GATE FAILED — {e}", file=sys.stderr)
        return 1
    print(json.dumps(report, sort_keys=True))
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
