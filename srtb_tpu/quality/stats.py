"""On-device per-segment data-quality statistics.

Everything the pipeline already knows about the signal quality of a
segment — which bins the RFI stages zapped, how the bandpass is shaped,
whether a subband died or went hot, how non-Gaussian each channel is —
lives in device buffers the segment plan is about to throw away.  This
module packs those answers into ONE small ``[S, N_SCALARS + 2*B]``
float32 vector as a cheap epilogue of the existing plans
(:meth:`SegmentProcessor._waterfall_detect` calls
:func:`quality_stats_device` right before the boundary stack), so
quality telemetry costs two extra reads of buffers already resident —
no new plan, no extra HBM pass of the big baseband buffers.

Packed layout per stream (``B = quality_coarse_bins``)::

    [0]            zap_frac        fraction of spectrum bins zeroed
                                   (RFI s1 + manual mask; the chirp is
                                   unit-modulus, so zeros survive;
                                   sampled per Config.quality_subsample)
    [1]            bandpass_mean   mean of the coarse bandpass vector
    [2]            bandpass_var    population variance of the same
    [3]            sk_mean         mean spectral-kurtosis estimate
                                   over waterfall channels (M = T)
    [4]            sk_max          max SK estimate over channels
    [5]            dead_frac       channels with mean power below
                                   quality_dead_threshold x median
    [6]            hot_frac        channels with mean power above
                                   quality_hot_threshold x median
    [7 : 7+B]      occupancy map   zero-fraction per coarse spectrum
                                   bin (the RFI occupancy heat row)
    [7+B : 7+2B]   bandpass        mean |spec|^2 per coarse bin

The host side (:class:`QualityMonitor`) unpacks the vector into
``quality_*`` gauges (flat + per-stream labeled), feeds the EWMA
bandpass-drift detector, and returns the dict the segment span
journals (telemetry schema v9).  :func:`quality_stats_oracle` is the
float64 NumPy golden model the parity tests pin every plan family
against.
"""

from __future__ import annotations

import collections
import math

import numpy as np

from srtb_tpu.utils.metrics import metrics

# scalar slots ahead of the two coarse maps (see module docstring)
IDX_ZAP_FRAC = 0
IDX_BANDPASS_MEAN = 1
IDX_BANDPASS_VAR = 2
IDX_SK_MEAN = 3
IDX_SK_MAX = 4
IDX_DEAD_FRAC = 5
IDX_HOT_FRAC = 6
N_SCALARS = 7

DEFAULT_COARSE_BINS = 64

# gauge names (the single home; metrics._HELP and the report reference
# these semantics)
SCALAR_GAUGES = (
    ("quality_zap_fraction", IDX_ZAP_FRAC),
    ("quality_bandpass_mean", IDX_BANDPASS_MEAN),
    ("quality_bandpass_var", IDX_BANDPASS_VAR),
    ("quality_sk_mean", IDX_SK_MEAN),
    ("quality_sk_max", IDX_SK_MAX),
    ("quality_dead_frac", IDX_DEAD_FRAC),
    ("quality_hot_frac", IDX_HOT_FRAC),
)


def vector_length(coarse_bins: int) -> int:
    return N_SCALARS + 2 * int(coarse_bins)


def _coarse_split(n_spec: int, coarse_bins: int) -> tuple[int, int]:
    """(B, bins_per_coarse): clamp B to the spectrum length and round
    the spectrum down to an exact tiling (the truncated remainder —
    at most B-1 bins — is outside every statistic, zap_frac
    included: all stats share the one sampled coarse grid)."""
    b = max(1, min(coarse_bins, n_spec))
    return b, n_spec // b


def quality_stats_device(spec, wf, coarse_bins: int,
                         dead_threshold: float, hot_threshold: float,
                         subsample: int = 1):
    """Pack the per-stream quality vector on device.

    ``spec [S, n_spec]`` complex: the dedispersed spectrum AFTER RFI
    stage 1 + the manual mask (zapped bins are exactly zero — the
    chirp multiply is unit-modulus and preserves them).
    ``wf [S, F, T]`` complex: the waterfall AFTER the SK zap (zapped
    channels are zero rows).  Returns ``[S, N_SCALARS + 2*B]`` f32.

    ``subsample = k`` reads every k-th bin within each coarse bin and
    every k-th time sample of each waterfall channel: the statistics
    become sampled estimators (exact at k=1).  This is the overhead
    lever — XLA computes a strided slice of an elementwise producer
    per-element, so BOTH the honest read volume and any producer
    recompute the backend chooses scale down by k.  Telemetry does
    not need every bin; the science path always reads all of them.

    Plain jnp on purpose: the inputs are already HBM-resident and tiny
    next to the segment FFT traffic, and a jnp epilogue rides inside
    every plan family (monolithic / fused / staged / ffuse / skzap)
    without new kernels.
    """
    import jax.numpy as jnp

    # coarse_bins/subsample are static Python ints (trace-time plan
    # constants sanitized by Config) — no int() coercion here, the
    # epilogue body must stay free of concretizing calls
    n_streams, n_spec = spec.shape[0], spec.shape[-1]
    b, per = _coarse_split(n_spec, coarse_bins)
    k = max(1, subsample)

    spec_s = spec[..., :b * per].reshape(n_streams, b, per)[..., ::k]
    p_spec = jnp.real(spec_s) ** 2 + jnp.imag(spec_s) ** 2  # [S, B, per/k]
    zero = (p_spec == 0).astype(jnp.float32)

    bandpass = jnp.mean(p_spec, axis=-1)                 # [S, B]
    occupancy = jnp.mean(zero, axis=-1)                  # [S, B]
    # coarse bins all hold the same sampled width, so the global zero
    # fraction is exactly the mean of the occupancy row — one big
    # reduction instead of two
    zap_frac = jnp.mean(occupancy, axis=-1)              # [S]
    bp_mean = jnp.mean(bandpass, axis=-1)                # [S]
    bp_var = jnp.mean((bandpass - bp_mean[:, None]) ** 2, axis=-1)

    # spectral kurtosis per waterfall channel, M sampled accumulations:
    # SK = ((M+1)/(M-1)) * (mean(p^2)/mean(p)^2 - 1); a zapped (zero)
    # channel reads 0 by convention, not NaN
    wf_s = wf[..., ::k]
    p_wf = jnp.real(wf_s) ** 2 + jnp.imag(wf_s) ** 2     # [S, F, T/k]
    m = wf_s.shape[-1]
    mean_p = jnp.mean(p_wf, axis=-1)                     # [S, F]
    mean_p2 = jnp.mean(p_wf * p_wf, axis=-1)
    denom = jnp.where(mean_p > 0, mean_p * mean_p, jnp.float32(1.0))
    sk = jnp.where(
        mean_p > 0,
        ((m + 1.0) / max(m - 1.0, 1.0)) * (mean_p2 / denom - 1.0),
        jnp.float32(0.0))
    sk_mean = jnp.mean(sk, axis=-1)
    sk_max = jnp.max(sk, axis=-1)

    med = jnp.median(mean_p, axis=-1, keepdims=True)     # [S, 1]
    dh = jnp.mean(jnp.stack([
        (mean_p < dead_threshold * med).astype(jnp.float32),
        (mean_p > hot_threshold * med).astype(jnp.float32)]), axis=-1)
    dead_frac, hot_frac = dh[0], dh[1]                   # [S]

    scalars = jnp.stack([zap_frac, bp_mean, bp_var, sk_mean, sk_max,
                         dead_frac, hot_frac], axis=-1)  # [S, 7]
    return jnp.concatenate(
        [scalars, occupancy, bandpass], axis=-1).astype(jnp.float32)


def quality_stats_oracle(spec: np.ndarray, wf: np.ndarray,
                         coarse_bins: int, dead_threshold: float,
                         hot_threshold: float,
                         subsample: int = 1) -> np.ndarray:
    """Float64 NumPy mirror of :func:`quality_stats_device` — the
    golden model tests/test_quality.py pins every plan family against
    (``subsample`` must match the device call's)."""
    spec = np.asarray(spec)
    wf = np.asarray(wf)
    n_streams, n_spec = spec.shape[0], spec.shape[-1]
    b, per = _coarse_split(n_spec, coarse_bins)
    k = max(1, int(subsample))

    spec_s = spec[..., :b * per].reshape(n_streams, b, per)[..., ::k]
    p_spec = np.abs(spec_s.astype(np.complex128)) ** 2
    zero = (p_spec == 0).astype(np.float64)
    bandpass = p_spec.mean(axis=-1)
    occupancy = zero.mean(axis=-1)
    zap_frac = occupancy.mean(axis=-1)
    bp_mean = bandpass.mean(axis=-1)
    bp_var = ((bandpass - bp_mean[:, None]) ** 2).mean(axis=-1)

    wf_s = wf[..., ::k]
    p_wf = np.abs(wf_s.astype(np.complex128)) ** 2
    m = wf_s.shape[-1]
    mean_p = p_wf.mean(axis=-1)
    mean_p2 = (p_wf * p_wf).mean(axis=-1)
    with np.errstate(divide="ignore", invalid="ignore"):
        sk = np.where(
            mean_p > 0,
            ((m + 1.0) / max(m - 1.0, 1.0))
            * (mean_p2 / np.where(mean_p > 0, mean_p ** 2, 1.0) - 1.0),
            0.0)
    sk_mean = sk.mean(axis=-1)
    sk_max = sk.max(axis=-1)
    med = np.median(mean_p, axis=-1, keepdims=True)
    dead_frac = (mean_p < dead_threshold * med).mean(axis=-1)
    hot_frac = (mean_p > hot_threshold * med).mean(axis=-1)

    scalars = np.stack([zap_frac, bp_mean, bp_var, sk_mean, sk_max,
                        dead_frac, hot_frac], axis=-1)
    return np.concatenate([scalars, occupancy, bandpass],
                          axis=-1).astype(np.float32)


def unpack_stats(vec: np.ndarray) -> dict:
    """Packed vector (``[S, 7+2B]`` or ``[7+2B]``) -> named arrays.
    B is recovered from the length (the layout is self-describing
    given N_SCALARS)."""
    v = np.asarray(vec)
    if v.ndim == 1:
        v = v[None, :]
    b = (v.shape[-1] - N_SCALARS) // 2
    return {
        "zap_frac": v[:, IDX_ZAP_FRAC],
        "bandpass_mean": v[:, IDX_BANDPASS_MEAN],
        "bandpass_var": v[:, IDX_BANDPASS_VAR],
        "sk_mean": v[:, IDX_SK_MEAN],
        "sk_max": v[:, IDX_SK_MAX],
        "dead_frac": v[:, IDX_DEAD_FRAC],
        "hot_frac": v[:, IDX_HOT_FRAC],
        "occupancy": v[:, N_SCALARS:N_SCALARS + b],
        "bandpass": v[:, N_SCALARS + b:N_SCALARS + 2 * b],
    }


class EWMADrift:
    """Exponentially-weighted drift detector on one scalar series.

    Tracks an EWMA mean and an EWM variance; an observation scoring
    more than ``threshold`` sigmas from the running mean is a drift
    alert.  The first ``warmup`` observations only train the
    estimates (score 0): the detector must learn THIS deployment's
    bandpass before judging it.  The estimates keep updating through
    an alert, so a persistent level shift is absorbed (and stops
    alerting) after ~1/alpha segments — the alert marks the
    *transition*, the gauges carry the new level."""

    def __init__(self, alpha: float = 0.05, threshold: float = 4.0,
                 warmup: int = 8):
        self.alpha = float(alpha)
        self.threshold = float(threshold)
        self.warmup = int(warmup)
        self.mean = 0.0
        self.var = 0.0
        self.n = 0

    def observe(self, x: float) -> tuple[float, bool]:
        """(drift score in sigmas, alert?) — then fold ``x`` in."""
        x = float(x)
        if self.n == 0:
            # seed the mean AT the first observation: starting from 0
            # would fold the series' DC level into the variance and
            # blind the detector for ~1/alpha segments
            self.mean = x
        if self.n < self.warmup:
            score, alert = 0.0, False
        else:
            # sigma floor: a perfectly constant warmup (synthetic
            # data) must not make the first real fluctuation infinite
            sigma = max(math.sqrt(max(self.var, 0.0)),
                        1e-12 + 1e-6 * abs(self.mean))
            score = abs(x - self.mean) / sigma
            alert = score > self.threshold
        d = x - self.mean
        self.mean += self.alpha * d
        self.var = (1.0 - self.alpha) * (self.var + self.alpha * d * d)
        self.n += 1
        return score, alert


TIMELINE_SPANS = 64


class QualityMonitor:
    """Host-side consumer of the packed quality vector: gauges, the
    bandpass drift detector, the journal dict, and a bounded timeline
    an incident bundle can attach (the quality context of a canary
    sensitivity regression).  ``None`` when ``Config.quality_stats``
    is off — the zero-cost-off None-hook pattern."""

    def __init__(self, drift_alpha: float = 0.05,
                 drift_threshold: float = 4.0, stream: str = ""):
        self.drift = EWMADrift(alpha=drift_alpha,
                               threshold=drift_threshold)
        self.stream = str(stream or "")
        self._timeline: collections.deque = collections.deque(
            maxlen=TIMELINE_SPANS)

    @classmethod
    def from_config(cls, cfg) -> "QualityMonitor | None":
        if not getattr(cfg, "quality_stats", False):
            return None
        return cls(
            drift_alpha=float(getattr(cfg, "quality_drift_alpha",
                                      0.05)),
            drift_threshold=float(getattr(cfg, "quality_drift_threshold",
                                          4.0)),
            stream=str(getattr(cfg, "stream_name", "") or ""))

    def observe(self, qvec, segment: int = -1) -> dict:
        """One drained segment's vector -> the journal dict.  Multi-
        datastream segments are averaged across S for the gauges and
        the drift series (per-datastream detail stays recoverable
        from the packed vector a test holds; spans carry the
        average)."""
        v = np.asarray(qvec, dtype=np.float64)
        if v.ndim == 1:
            v = v[None, :]
        mean = v.mean(axis=0)
        score, alert = self.drift.observe(mean[IDX_BANDPASS_MEAN])
        lbl = {"stream": self.stream} if self.stream else None
        for gname, idx in SCALAR_GAUGES:
            metrics.set(gname, float(mean[idx]))
            if lbl:
                metrics.set(gname, float(mean[idx]), labels=lbl)
        metrics.set("quality_drift_score", score)
        if lbl:
            metrics.set("quality_drift_score", score, labels=lbl)
        if alert:
            metrics.add("quality_drift_alerts")
            if lbl:
                metrics.add("quality_drift_alerts", labels=lbl)
        b = (mean.shape[0] - N_SCALARS) // 2
        # vectorized rounding: this runs once per drained segment in
        # the pipeline's span path, so 2*B Python-level round() calls
        # would be the most expensive part of the whole quality
        # epilogue (the device side is reduction-fused and subsampled)
        out = {
            "zap_frac": round(float(mean[IDX_ZAP_FRAC]), 5),
            "bandpass_mean": round(float(mean[IDX_BANDPASS_MEAN]), 5),
            "bandpass_var": round(float(mean[IDX_BANDPASS_VAR]), 5),
            "sk_mean": round(float(mean[IDX_SK_MEAN]), 5),
            "sk_max": round(float(mean[IDX_SK_MAX]), 5),
            "dead_frac": round(float(mean[IDX_DEAD_FRAC]), 5),
            "hot_frac": round(float(mean[IDX_HOT_FRAC]), 5),
            "drift_score": round(score, 3),
            "drift_alert": bool(alert),
            "occupancy": np.round(
                mean[N_SCALARS:N_SCALARS + b], 4).tolist(),
            "bandpass": np.round(
                mean[N_SCALARS + b:N_SCALARS + 2 * b], 5).tolist(),
        }
        self._timeline.append(dict(out, segment=int(segment)))
        return out

    def timeline(self) -> list[dict]:
        """Recent per-segment quality dicts, oldest first (bounded:
        the incident-bundle attachment)."""
        return list(self._timeline)
