"""End-to-end pulse-injection canary.

Quality statistics (stats.py) say the *data* looks healthy; only a
known signal proves the *search* still finds signals.  Every
``Config.canary_every_segments``-th segment, a deterministic synthetic
dispersed pulse of known DM / amplitude / t0 is added to the raw uint8
stream right before device staging — upstream of unpack, FFT, RFI
mitigation, dedispersion and detection, so the recovered S/N exercises
the whole science chain.  At drain the recovered S/N is checked
against the expected value; the sensitivity ratio drives the
``detection_health_state`` gauge, the /healthz detection section, the
SLO sensitivity objective, and (on a regression) an incident bundle
with the recent quality timeline attached.

Injection is quarantined by construction:

- the pulse is added to a **copy** of the segment's bytes; the pristine
  buffer is what every sink sees, so ``baseband_write_all`` output is
  bit-identical to a canary-off run;
- the delta is zeroed over the first and last ``reserved`` samples of
  the segment: the head is the overlap region (device-resident carry
  in ring mode), the tail becomes the NEXT segment's head/carry — a
  canary must never leak one byte into a neighboring science segment;
- canary segments are excluded from the ``signals`` gate and the
  science sinks by the engine (pipeline/runtime.py), and flagged in
  the journal span + run manifest so offline consumers can prove the
  quarantine.

Expected S/N is **auto-calibrated** by default
(``canary_expected_snr = 0``): the first checked canary of a run sets
the reference (journaled as ``calibrated``), and later canaries must
recover at least ``canary_min_ratio`` of it — robust across
geometries without an analytic radiometer model.  CI's smoke stage
instead measures a clean run's recovered S/N and passes it explicitly
to a degraded run to prove the gate has teeth.
"""

from __future__ import annotations

import numpy as np

from srtb_tpu.utils.logging import log
from srtb_tpu.utils.metrics import metrics

# deterministic pulse-shape seed: the SAME broadband noise burst in
# every run, every resume, every process — canary recovery must be
# bit-identical across checkpoint resume
PULSE_SEED = 1644

HEALTH_OK = 0
HEALTH_DEGRADED = 1


class CanaryController:
    """Deterministic injection schedule + sensitivity gate.  ``None``
    (from_config) when ``canary_every_segments`` is 0 — the zero-cost-
    off None-hook pattern shared with the sanitizer and faults."""

    def __init__(self, cfg, n_samples: int, reserved_samples: int = 0,
                 stream: str = ""):
        self.every = int(cfg.canary_every_segments)
        self.amp = float(getattr(cfg, "canary_amp", 25.0))
        self.width = int(getattr(cfg, "canary_width", 32))
        dm = float(getattr(cfg, "canary_dm", -1.0))
        self.dm = dm if dm >= 0 else float(cfg.dm)
        self.position = float(getattr(cfg, "canary_position", 0.5))
        self.expected = float(getattr(cfg, "canary_expected_snr", 0.0))
        self.min_ratio = float(getattr(cfg, "canary_min_ratio", 0.5))
        self.calibrated = self.expected > 0
        self.n = int(n_samples)
        self.reserved = int(reserved_samples)
        self.stream = str(stream or "")
        self._f_min = float(cfg.baseband_freq_low)
        self._bw = float(cfg.baseband_bandwidth)
        self._delta: np.ndarray | None = None
        self.t0 = 0
        metrics.set("detection_health_state", HEALTH_OK)
        if self.stream:
            metrics.set("detection_health_state", HEALTH_OK,
                        labels={"stream": self.stream})

    @classmethod
    def from_config(cls, cfg, n_samples: int | None = None,
                    reserved_samples: int = 0) -> "CanaryController | None":
        if int(getattr(cfg, "canary_every_segments", 0) or 0) <= 0:
            return None
        # injection edits raw bytes, so it must know the byte<->sample
        # map: gated to the 8-bit single-stream "simple" layout (the
        # flagship geometry); other formats get a loud skip, never a
        # silently wrong pulse
        if (cfg.baseband_input_bits != 8
                or cfg.baseband_format_type not in ("", "simple")):
            log.warning(
                "[canary] injection supports 8-bit 'simple' baseband "
                f"only (got {cfg.baseband_input_bits}-bit "
                f"{cfg.baseband_format_type!r}); canary disabled")
            return None
        return cls(cfg,
                   n_samples=int(n_samples
                                 if n_samples is not None
                                 else cfg.baseband_input_count),
                   reserved_samples=int(reserved_samples),
                   stream=str(getattr(cfg, "stream_name", "") or ""))

    # ---------------------------------------------------- injection

    def is_canary(self, abs_index: int) -> bool:
        """Absolute (resume-continuous) segment index -> scheduled?
        The first canary lands on segment ``every - 1``, never on the
        cold first segment."""
        return (int(abs_index) + 1) % self.every == 0

    def _build_delta(self) -> np.ndarray:
        """The additive int16 byte-delta of ONE canary injection:
        a width-``canary_width`` broadband noise burst of per-sample
        amplitude ``canary_amp`` digitizer counts at t0, dispersed by
        the same medium model as io/synth.make_dispersed_baseband
        (inverse of the dedispersion chirp), rounded to counts — then
        explicitly zeroed over the head and tail ``reserved`` spans
        (overlap/ring-carry quarantine, see module docstring)."""
        from srtb_tpu.ops import dedisperse as dd

        n = self.n
        rng = np.random.default_rng(PULSE_SEED)
        usable = max(n - 2 * self.reserved - self.width, 1)
        self.t0 = self.reserved + int(self.position * usable)
        pulse = np.zeros(n)
        w = min(self.width, n - self.t0)
        pulse[self.t0:self.t0 + w] = self.amp * rng.standard_normal(w)
        n_spec = n // 2
        df = self._bw / n_spec
        f_c = self._f_min + self._bw
        chirp = dd.chirp_factor_host(n_spec, self._f_min, df, f_c,
                                     self.dm)
        spec = np.fft.rfft(pulse)
        spec[:n_spec] *= np.conj(chirp)  # disperse (medium model)
        sig = np.fft.irfft(spec, n)
        delta = np.round(sig).astype(np.int16)
        if self.reserved:
            delta[:self.reserved] = 0
            delta[-self.reserved:] = 0
        return delta

    def prepare(self, abs_index: int,
                data: np.ndarray) -> tuple[np.ndarray, dict | None]:
        """Dispatch-side hook: returns ``(device_bytes, mark)``.
        Non-canary segments pass ``data`` through untouched (no copy);
        a canary segment gets the pulse added to a COPY (clipped to
        the uint8 range) — the caller keeps pushing the pristine
        ``data`` to sinks."""
        if not self.is_canary(abs_index):
            return data, None
        if self._delta is None or len(self._delta) != len(data):
            if len(data) != self.n:
                # a partial tail segment (file end) has a different
                # byte<->time map than the built delta: skip, loudly
                log.warning(f"[canary] segment {abs_index}: "
                            f"unexpected size {len(data)} != {self.n}; "
                            "skipping injection")
                return data, None
            self._delta = self._build_delta()
        out = np.clip(data.astype(np.int16) + self._delta, 0,
                      255).astype(np.uint8)
        metrics.add("canary_injected")
        if self.stream:
            metrics.add("canary_injected",
                        labels={"stream": self.stream})
        mark = {"segment": int(abs_index), "t0": int(self.t0),
                "dm": self.dm, "amp": self.amp, "width": self.width}
        return out, mark

    # --------------------------------------------------------- check

    def check(self, abs_index: int, snr_peaks) -> dict:
        """Drain-side hook for a canary segment: recovered S/N (max
        over boxcars, host values) against the expected reference.
        Returns the verdict dict the span journals; updates the
        canary gauges, the detection-health state and the SLO
        sensitivity objective."""
        recovered = float(np.max(np.asarray(snr_peaks)))
        verdict = {"injected": True, "segment": int(abs_index),
                   "snr": round(recovered, 3)}
        if not self.calibrated:
            # first checked canary of the run sets the reference —
            # journaled, so the baseline every later ratio is judged
            # against is on the record
            self.expected = max(recovered, 1e-9)
            self.calibrated = True
            verdict.update(calibrated=True, expected=round(
                self.expected, 3), ratio=1.0, ok=True)
            ratio, ok = 1.0, True
        else:
            ratio = recovered / self.expected
            ok = ratio >= self.min_ratio
            verdict.update(expected=round(self.expected, 3),
                           ratio=round(ratio, 4), ok=ok)
        lbl = {"stream": self.stream} if self.stream else None
        metrics.add("canary_checked")
        metrics.set("canary_last_snr", recovered)
        metrics.set("canary_expected_snr", self.expected)
        metrics.set("canary_sensitivity_ratio", ratio)
        state = HEALTH_OK if ok else HEALTH_DEGRADED
        metrics.set("detection_health_state", state)
        if not ok:
            metrics.add("canary_failed")
        if lbl:
            metrics.add("canary_checked", labels=lbl)
            metrics.set("canary_last_snr", recovered, labels=lbl)
            metrics.set("canary_expected_snr", self.expected,
                        labels=lbl)
            metrics.set("canary_sensitivity_ratio", ratio, labels=lbl)
            metrics.set("detection_health_state", state, labels=lbl)
            if not ok:
                metrics.add("canary_failed", labels=lbl)
        from srtb_tpu.utils import slo
        slo.note_canary(self.stream, ok)
        if not ok:
            log.warning(
                f"[canary] segment {abs_index}: sensitivity regression "
                f"— recovered S/N {recovered:.2f} is "
                f"{ratio:.2f}x the expected {self.expected:.2f} "
                f"(min ratio {self.min_ratio:g})")
        return verdict
