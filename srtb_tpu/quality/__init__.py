"""Science observatory: on-device data-quality statistics and the
end-to-end pulse-injection canary.

The observability stack (tracing, incidents, SLO burn, rooflines) says
the engine is *fast and alive*; this package says the science is
*right*:

- :mod:`srtb_tpu.quality.stats` — per-segment data-quality statistics
  (zapped fraction, coarse RFI occupancy, spectral-kurtosis summary,
  bandpass mean/variance, dead/hot channels) computed on device as a
  cheap epilogue of the existing segment plans, plus the host-side
  EWMA bandpass-drift detector and the QualityMonitor that turns the
  packed vector into gauges and journal fields.
- :mod:`srtb_tpu.quality.canary` — a deterministic synthetic dispersed
  pulse injected into the raw uint8 stream every
  ``Config.canary_every_segments`` segments, recovered S/N checked at
  the detection stage; the sensitivity ratio drives detection health
  (/healthz, SLO) and canary segments are quarantined from science
  outputs.
"""

from srtb_tpu.quality.canary import CanaryController
from srtb_tpu.quality.stats import (
    EWMADrift,
    QualityMonitor,
    quality_stats_device,
    quality_stats_oracle,
    unpack_stats,
)

__all__ = [
    "CanaryController",
    "EWMADrift",
    "QualityMonitor",
    "quality_stats_device",
    "quality_stats_oracle",
    "unpack_stats",
]
