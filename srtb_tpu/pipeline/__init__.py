from srtb_tpu.pipeline.work import SegmentWork, SegmentResultWork  # noqa: F401
from srtb_tpu.pipeline.segment import SegmentProcessor  # noqa: F401
# fleet (StreamFleet/StreamSpec) is imported lazily from
# srtb_tpu.pipeline.fleet — it pulls in the full runtime, which this
# package __init__ deliberately does not
