from srtb_tpu.pipeline.work import SegmentWork, SegmentResultWork  # noqa: F401
from srtb_tpu.pipeline.segment import SegmentProcessor  # noqa: F401
