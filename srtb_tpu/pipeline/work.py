"""Work types flowing through the streaming runtime.

The reference threads ownership of device buffers through typed POD work
structs over lock-free queues (ref: work.hpp:79-285).  Here the device
pipeline is one fused jit function, so only two host-side work types
remain: the raw input segment and the processed result.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import numpy as np

# sentinel matching work.hpp's no_udp_packet_counter (max uint64)
NO_UDP_PACKET_COUNTER = 2 ** 64 - 1


@dataclass
class SegmentWork:
    """One input segment: raw bytes plus metadata
    (ref: work.hpp copy_to_device_work:162-190)."""
    data: np.ndarray            # uint8 [segment_bytes]
    timestamp: int = 0          # nanoseconds since epoch
    udp_packet_counter: int = NO_UDP_PACKET_COUNTER
    data_stream_id: int = 0
    # per-source emission sequence (-1 = unstamped).  The ingest ring's
    # warm path is only valid between STREAM-ADJACENT segments (the new
    # segment's overlap head must be the previous dispatched segment's
    # tail): the engine goes cold whenever (data_stream_id, seq) is not
    # exactly one step past the last dispatch — a dropped segment
    # (DropOldestSegmentBuffer) or an interleaved multi-receiver stream
    # must never be warm-assembled against a foreign carry.
    seq: int = -1
    # causal trace id (utils/events.py): stamped at ingest by the
    # pipeline (0 = unstamped); every subsystem that touches this
    # segment — stage edges, retries, heal decisions, manifest
    # records — emits flight-recorder events carrying it, so one
    # segment's whole journey is reconstructable across threads.
    trace_id: int = 0


@dataclass
class SegmentResultWork:
    """Everything the host needs after one segment's device processing
    (ref: write_signal_work + draw_spectrum_work_2, work.hpp:232-284)."""
    segment: SegmentWork
    # [streams, freq_bins, time_samples] complex64 dynamic spectrum
    waterfall: Any = None
    # detection outputs (srtb_tpu.ops.detect.DetectResult, batched)
    detect: Any = None
    # optional [h, w] uint32 ARGB pixmap per stream
    pixmap: Any = None
    extras: dict = field(default_factory=dict)
