"""Placement policy for the elastic fleet device pool.

Pure functions — the fleet passes in the candidate
:class:`~srtb_tpu.pipeline.pool.PoolDevice` members and the current
per-device lane loads, and gets back a choice.  Keeping the policy
side-effect free makes it unit-testable without a fleet and keeps the
scheduler thread the only thing that mutates placement state.

Two decisions live here:

- :func:`choose_initial` — where a newly admitted stream lands.
  Honors an explicit ``StreamSpec.pin_device``, otherwise picks the
  least-loaded healthy member with soft same-tenant anti-affinity.
- :func:`choose_target` — where a migrating lane goes (device drain,
  SLO rebalance, rolling restart).  Least-loaded healthy member that
  is not the lane's current device, same soft anti-affinity.

Tenant convention: the stream-name prefix before the first ``.`` is
the tenant (``radioA.band0`` and ``radioA.band1`` are the same tenant
``radioA``).  A name with no dot is its own tenant, so anti-affinity
is a no-op for flat names.  Anti-affinity is SOFT: it breaks ties and
biases spread, but never leaves a stream unplaced — with more
same-tenant lanes than devices, co-location is accepted.

Priority (``StreamSpec.priority``) is handled upstream by admission
ordering — by the time placement runs, higher-priority streams were
admitted first and therefore grabbed the emptier devices; the policy
itself is priority-agnostic, which keeps rebalance decisions stable.
"""

from __future__ import annotations


def tenant_of(name: str) -> str:
    """Tenant key for a stream name: prefix before the first ``.``."""
    return name.split(".", 1)[0]


def _load_of(dev, loads: dict) -> int:
    return int(loads.get(dev.index, 0))


def _pick_least_loaded(candidates, loads, tenant, tenants_by_device):
    """Least-loaded candidate; soft anti-affinity = among the minimum
    load tier, prefer a device with no same-tenant lane.  Index order
    breaks the final tie for determinism."""
    if not candidates:
        return None
    lo = min(_load_of(d, loads) for d in candidates)
    tier = [d for d in candidates if _load_of(d, loads) == lo]
    clean = [d for d in tier
             if tenant not in tenants_by_device.get(d.index, ())]
    pool = clean or tier
    return min(pool, key=lambda d: d.index)


def choose_initial(spec, devices, loads, tenants_by_device=None):
    """Pick the device a newly admitted ``spec`` starts on.

    ``devices`` — healthy pool members (the fleet pre-filters).
    ``loads`` — ``{device_index: live lane count}``.
    ``tenants_by_device`` — ``{device_index: set of tenant keys}``.

    Raises ``ValueError`` for an out-of-range or unhealthy
    ``pin_device`` so the lane fails validation BEFORE any pipeline
    state is built (same contract as the fleet's other pure-config
    checks).
    """
    tenants_by_device = tenants_by_device or {}
    pin = getattr(spec, "pin_device", None)
    if pin is not None:
        by_index = {d.index: d for d in devices}
        if pin not in by_index:
            raise ValueError(
                f"stream {spec.name!r}: pin_device={pin} is not a "
                f"healthy pool member (have {sorted(by_index)})")
        return by_index[pin]
    return _pick_least_loaded(devices, loads, tenant_of(spec.name),
                              tenants_by_device)


def choose_target(lane_name, current_index, devices, loads,
                  tenants_by_device=None):
    """Pick the migration target for a lane currently on
    ``current_index``.  Candidates exclude the current device; returns
    ``None`` when no peer exists (caller falls back to fleet-wide
    reinit — today's behavior, now the last resort)."""
    tenants_by_device = tenants_by_device or {}
    candidates = [d for d in devices if d.index != current_index]
    return _pick_least_loaded(candidates, loads, tenant_of(lane_name),
                              tenants_by_device)
