"""Streaming runtime: reader -> device segment processor -> sinks.

The reference's thread-per-pipe/bounded-queue machinery
(ref: pipeline/framework/pipe.hpp, pipe_io.hpp) exists to overlap GPU
kernels of consecutive segments.  Under JAX, async dispatch provides the
device-side half for free; the host-side half is the **async in-flight
segment engine** in :meth:`Pipeline.run`:

- a bounded window of ``Config.inflight_segments`` segments is
  dispatched before the oldest result is drained, so segment k+1's
  ingest, sub-byte unpack, and H2D staging run while the device
  computes segment k (the double-buffer AstroAccelerate builds with
  CUDA streams, arXiv:2101.00941);
- fetch is non-blocking where possible: the drain loop polls device
  readiness (``jax.Array.is_ready``) and drains completed segments in
  order, blocking only when the window is full or the source is done;
- sink work (writers, lazy waterfall transfer, journal, checkpoint)
  runs on a dedicated framework Pipe, off the dispatch critical path;
- per segment, the wall clock between dispatch returning and fetch
  starting is journaled as ``overlap_hidden_ms`` (+ the ``overlap``
  stage histogram and the ``inflight_depth`` gauge), so overlap
  efficiency is measurable, not assumed;
- optional micro-batching (``Config.micro_batch_segments`` = B > 1)
  stacks B segments into ONE vmapped jit call, amortizing dispatch
  overhead and tunnel RTT over B segments.

``inflight_segments = 1`` is the fully serial reference leg (ingest ->
dispatch -> blocking fetch -> sink per segment) used by the A/B
harness.  Work accounting (ref: main.cpp:146-162
work_in_pipeline_count) and orderly shutdown
(ref: framework/exit_handler.hpp) carry over from the reference.

Fault tolerance (srtb_tpu/resilience/, PR 4): six named fault sites —
``ingest``, ``h2d``, ``dispatch``, ``fetch``, ``sink_write``,
``checkpoint`` — run under a retry policy (transient failures back off
and re-run; fatal ones escalate), an in-flight segment whose fetch
never becomes ready within ``segment_deadline_s`` is cancelled and
re-dispatched by the watchdog (``segment_watchdog_requeues``), a
crashed sink pipe is restarted with a bounded budget
(``supervisor_max_restarts``), and sustained sink backlog walks the
graceful-degradation ladder (shed waterfall dumps, then baseband
dumps, then accounted whole-segment loss).  Every recovery is a
counter and a journal field; ``Config.fault_plan`` injects
deterministic faults at any site for CI.

Self-healing compute (resilience/demote.py, PR 9): failures the
accelerator side raises — device OOM, Pallas/Mosaic compile faults,
device halts — are classified from the real jax exception strings and
recovered instead of escalating: OOM/compile faults demote the plan
down an audited ladder (micro_batch -> front_fuse -> ring -> skzap ->
fused_tail ->
staged -> monolithic) and re-dispatch the faulted segment cold from
its retained host buffer; halts reinitialize the backend (clear
caches, rebuild the processor, re-dispatch the in-flight window)
under a bounded reinit budget; ``promote_after_segments`` probes back
up after a healthy stretch.  Counters: ``plan_demotions``,
``plan_promotions``, ``device_reinits``; gauge ``plan_ladder_level``;
journal field ``active_plan`` (schema v4).
"""

from __future__ import annotations

import collections
import contextlib
import os
import threading
import time
from dataclasses import dataclass, field

import jax
import numpy as np

from srtb_tpu.config import Config
from srtb_tpu.io.file_input import BasebandFileReader
from srtb_tpu.io.writers import WriteAllSink, WriteSignalSink
from srtb_tpu.pipeline.segment import SegmentProcessor
from srtb_tpu.pipeline.work import SegmentResultWork, SegmentWork
from srtb_tpu.resilience.errors import DEVICE_HALT, WatchdogEscalation
from srtb_tpu.resilience.faults import FaultInjector
from srtb_tpu.resilience.retry import RetryPolicy, retry_call
from srtb_tpu.utils import events, slo, telemetry
from srtb_tpu.utils.logging import log
from srtb_tpu.utils.metrics import metrics
from srtb_tpu.utils.tracing import StageTimer, trace_annotation


@dataclass
class PipelineStats:
    segments: int = 0
    samples: int = 0
    signals: int = 0
    elapsed_s: float = 0.0
    extras: dict = field(default_factory=dict)

    @property
    def msamples_per_sec(self) -> float:
        return self.samples / self.elapsed_s / 1e6 if self.elapsed_s else 0.0


def has_signal(cfg: Config, detect_result, stream: int | None = None,
               frequency_bin_count: int | None = None) -> bool:
    """The reference's gating: skip when too many channels are zapped
    (ref: signal_detect_pipe.hpp:343-345), else positive when any boxcar
    fired.

    ``frequency_bin_count`` is the *actual* row count of the waterfall the
    detection ran on (the reference reads it off the work item,
    signal_detect_pipe.hpp:343-345); callers that have the waterfall should
    pass its shape so a trimmed or alternate-path spectrum doesn't silently
    mis-scale the gate.  Falls back to the configured channel count.
    """
    zero_count = np.asarray(detect_result.zero_count)
    counts = np.asarray(detect_result.signal_counts)
    if zero_count.ndim == 0:
        zero_count = zero_count[None]
        counts = counts[None]
    freq_bins = (frequency_bin_count if frequency_bin_count is not None
                 else cfg.spectrum_channel_count)
    ok = zero_count < cfg.signal_detect_channel_threshold * freq_bins
    fired = counts.sum(axis=-1) > 0
    # registered-mode hook (pipeline/registry.py contract): a result
    # type carrying its own positive rule (e.g. the periodicity
    # mode's trials-corrected candidate gate) extends the verdict —
    # the engine stays mode-blind, the mode owns its statistics
    gate = getattr(detect_result, "positive_gate", None)
    if gate is not None:
        # the hook runs drain-side on fetched host
        # data  # srtb-lint: disable=sync-hot-path
        fired = fired | np.asarray(gate(cfg)).reshape(fired.shape)
    per_stream = ok & fired
    if stream is not None:
        return bool(per_stream[stream])
    return bool(per_stream.any())


def _abort_on_deadline(deadline_s: float) -> None:  # pragma: no cover
    import os
    import signal

    log.error(
        f"[pipeline] device sync exceeded segment_deadline_s={deadline_s}: "
        "accelerator runtime wedged; aborting")
    os.kill(os.getpid(), signal.SIGABRT)


def sync_with_deadline(deadline_s: float, fn, on_deadline=None):
    """Run a blocking device fetch under a fail-fast deadline (seconds,
    <= 0 disables).  A wedged accelerator runtime otherwise hangs the
    observation silently (observed on a v5e after a remote-compiler
    crash); on expiry the default handler aborts through the installed
    termination handlers for a loud stacktrace."""
    if not deadline_s or deadline_s <= 0:
        return fn()
    import threading

    timer = threading.Timer(deadline_s,
                            on_deadline or
                            (lambda: _abort_on_deadline(deadline_s)))
    timer.daemon = True
    timer.start()
    try:
        return fn()
    finally:
        timer.cancel()


class _DeadlineArray:
    """Lazy device-array handle whose host fetch runs under the pipeline's
    fail-fast deadline, however late a consumer triggers it.  Sinks fetch
    the waterfall via ``np.asarray`` and only for segments they actually
    write, so eagerly transferring the (multi-GB) waterfall per segment
    in drain would tax every segment; this keeps the fetch lazy while
    still arming the watchdog around the device transfer."""

    __slots__ = ("_arr", "_sync", "_fetched")

    def __init__(self, dev, sync_with_deadline):
        self._arr = dev
        self._sync = sync_with_deadline
        self._fetched = False

    @property
    def shape(self):
        return self._arr.shape

    @property
    def ndim(self):
        return self._arr.ndim

    @property
    def dtype(self):
        return self._arr.dtype

    @property
    def nbytes(self):
        return self._arr.nbytes

    def __len__(self):
        return len(self._arr)

    def __getitem__(self, idx):
        return self.__array__()[idx]

    def __array__(self, dtype=None, copy=None):
        if not self._fetched:
            dev = self._arr
            # explicit device_get, not np.asarray: the lazy waterfall
            # transfer is a *sanctioned* D2H (sink side), and the
            # sanitizer's transfer tripwire only exempts the explicit
            # spelling (srtb-lint sync-hot-path true positive, PR 3)
            self._arr = self._sync(lambda: jax.device_get(dev))
            self._fetched = True  # drop the device handle; memoize host
        a = self._arr
        if dtype is not None and np.dtype(dtype) != a.dtype:
            a = a.astype(dtype)
        elif copy:
            a = a.copy()
        return a


class Pipeline:
    """File (or any SegmentWork iterator) to sinks."""

    def __init__(self, cfg: Config, source=None, sinks=None,
                 keep_waterfall: bool = True, processor=None):
        self.cfg = cfg
        if processor is None:
            # donate the per-segment input buffer on accelerators: the
            # engine stages a fresh device array per segment and never
            # reuses it, so XLA may recycle its HBM as program scratch
            # (steady state does no net fresh device allocation).  Kept
            # off on CPU where donation is a no-op.  Built through the
            # plan registry so Config.search_mode selects the
            # registered mode's processor class.
            from srtb_tpu.pipeline import registry
            from srtb_tpu.utils.platform import on_accelerator
            processor = registry.build_processor(
                cfg, donate_input=on_accelerator())
        self.processor = processor
        self._owned_writer_pool = None
        # causal tracing + flight recorder (utils/events.py): arm the
        # process-global hub from this config and hold the None-hook
        # handle — every hot-path emit below is one attribute read +
        # None check when disabled.  Incident bundles + SLO burn-rate
        # tracking follow the same zero-cost-off contract.
        events.configure(
            enabled=bool(getattr(cfg, "events_enable", True)),
            ring_size=int(getattr(cfg, "events_ring_size", 0)
                          or events.DEFAULT_RING_SIZE))
        self._events_enabled = bool(getattr(cfg, "events_enable",
                                            True))
        from srtb_tpu.utils.incidents import IncidentRecorder
        self.incidents = IncidentRecorder.from_config(cfg)
        self._slo_armed = slo.configure(cfg) is not None
        # durable exactly-once outputs (io/manifest.py): opening the
        # manifest RUNS RECOVERY — torn WAL tail truncated,
        # uncommitted artifact groups rolled back, the done-set of
        # committed (stream, segment, sink) groups rebuilt so the
        # replay below skips them.  Must happen before sinks open the
        # prefix and before the checkpoint loads (recovery may
        # truncate files the sinks are about to append to).
        self.manifest = None
        if getattr(cfg, "run_manifest_path", ""):
            from srtb_tpu.io.manifest import RunManifest
            from srtb_tpu.pipeline.checkpoint import StreamCheckpoint
            # peek the checkpoint FILE (the resume authority) before
            # recovery: a WAL that lost its ckpt records to corruption
            # must not roll back artifacts in segments the checkpoint
            # says are done — the resume would never regenerate them
            hint = 0
            if cfg.checkpoint_path:
                state = (StreamCheckpoint._load(cfg.checkpoint_path)
                         or StreamCheckpoint._load(
                             cfg.checkpoint_path + ".bak") or {})
                hint = int(state.get("segments_done", 0))
            loss0 = metrics.get("manifest_loss_flags")
            self.manifest = RunManifest.open(
                cfg.run_manifest_path,
                fsync=bool(getattr(cfg, "manifest_fsync", True)),
                hash_content=bool(getattr(cfg, "manifest_hash", True)),
                checkpoint_floor_hint=hint)
            if self.incidents is not None and \
                    metrics.get("manifest_loss_flags") > loss0:
                # fsck-grade LOSS surfaced during startup recovery:
                # bundle the evidence before the run overwrites the
                # recent past (the recovery events are on the ring)
                self.incidents.dump(
                    "manifest_loss",
                    reason="manifest recovery flagged unrecoverable "
                           "data loss (see events.jsonl)",
                    stream=str(getattr(cfg, "stream_name", "") or ""),
                    cfg=cfg, processor=self.processor,
                    journal_path=getattr(cfg, "telemetry_journal_path",
                                         ""))
        self.checkpoint = None
        if cfg.checkpoint_path:
            from srtb_tpu.pipeline.checkpoint import StreamCheckpoint
            self.checkpoint = StreamCheckpoint(cfg.checkpoint_path,
                                               manifest=self.manifest)
        if source is None:
            if not cfg.input_file_path:
                raise ValueError("no input_file_path and no source given")
            start = None
            if self.checkpoint and self.checkpoint.segments_done:
                start = self.checkpoint.file_offset_bytes
            # make_file_source honors Config.deterministic_timestamps
            # (offset-derived stamps -> reproducible artifact names)
            from srtb_tpu.io.file_input import make_file_source
            source = make_file_source(cfg, start_offset_bytes=start)
        self.source = source
        if sinks is None:
            if cfg.baseband_write_all:
                from srtb_tpu.ops import dedisperse as dd
                reserved_bytes = int(
                    dd.nsamps_reserved(cfg) * cfg.bytes_per_sample
                    * self.processor.data_stream_count)
                sinks = [WriteAllSink(cfg, reserved_bytes)]
            else:
                if cfg.writer_thread_count > 0:
                    from srtb_tpu.io.native_writer import AsyncWriterPool
                    self._owned_writer_pool = AsyncWriterPool(
                        cfg.writer_thread_count)
                sinks = [WriteSignalSink(
                    cfg, writer_pool=self._owned_writer_pool)]
        self.sinks = sinks
        # manifest sink names must be stable across process restarts
        # (the done-set keys on them): position + class, both
        # config-determined
        self._sink_names = [f"{i}:{type(s).__name__}"
                            for i, s in enumerate(sinks)]
        if self.manifest is not None:
            for s in sinks:
                bind = getattr(s, "bind_manifest", None)
                if bind is not None:
                    bind(self.manifest)
        self.keep_waterfall = keep_waterfall
        self.stats = PipelineStats()
        # set when a bounded shutdown gave up on a wedged sink: close()
        # must then abandon the owned writer pool instead of draining
        # it (the drain would block on the very writes that are stuck)
        self._sink_wedged = False
        # opt-in runtime sanitizer: None when off, so every hook site
        # below is a single `is not None` check (zero-cost disabled)
        self.sanitizer = None
        if getattr(cfg, "sanitize", False):
            from srtb_tpu.analysis.sanitizer import Sanitizer
            self.sanitizer = Sanitizer()
        # multi-tenant stream identity (pipeline/fleet.py): the fleet
        # names each lane's config; solo runs are unnamed and every
        # labeled-twin bump below is a single None check
        self.stream = str(getattr(cfg, "stream_name", "") or "")
        self._stream_labels = ({"stream": self.stream}
                               if self.stream else None)
        # resilience hooks, each None when off (same zero-cost-disabled
        # contract as the sanitizer): deterministic fault injection,
        # the retry policy for the six guarded sites, and the
        # graceful-degradation ladder.  Fault-plan entries carrying a
        # stream selector arm only in the matching lane.
        self.faults = FaultInjector.from_plan(
            getattr(cfg, "fault_plan", ""), stream=self.stream)
        self.retry = RetryPolicy.from_config(cfg)
        # self-healing compute (resilience/demote.py): plan demotion
        # for device OOM/compile faults, bounded backend reinit for
        # halts.  None when both are configured off; when armed it is
        # consulted only from the dispatch/fetch exception handlers
        # plus one counter bump per drained segment — a healthy run
        # pays nothing measurable (PERF.md round 13 A/B).
        from srtb_tpu.resilience.demote import ComputeHealer
        self.healer = ComputeHealer.from_config(cfg, self._plan_factory)
        if self.healer is not None:
            self.healer.bind_base(getattr(self.processor, "staged",
                                          None))
        # sink-side liveness heartbeat: bumped after every completed
        # per-sink push (not per drained item), so the engine's wedge
        # detectors see progress through a slow multi-sink flush
        self._sink_heartbeat = 0
        # device-resident carry of the ingest ring (None = cold): the
        # reserved tail of the last dispatched segment, threaded from
        # one dispatch into the next (pipeline/segment.py ring plans),
        # plus the (data_stream_id, seq) of that segment — warm
        # assembly is only valid against the stream-adjacent successor
        self._ring_carry = None
        self._ring_prev = None
        # serializes the accounted/abandoned handoff between a wedged
        # sink worker and the bounded shutdown: _drain_body's
        # "abandoned? else account" decision and the shutdown's
        # "unaccounted? then abandon" decision must be atomic with
        # respect to each other, or a worker unwedging at exactly the
        # join expiry gets the segment BOTH drained and dropped
        self._handoff_lock = threading.Lock()
        self._ladder = None
        if getattr(cfg, "degrade_enable", False):
            from srtb_tpu.resilience.degrade import DegradationLadder
            self._ladder = DegradationLadder.from_config(cfg)
        # startup recovery sweep (crash consistency): a run that died
        # between a writer's temp write and its atomic rename leaves
        # orphaned <name>.srtb_tmp files; remove them before sinks
        # re-open the prefix, then resume from the checkpoint (above)
        if cfg.baseband_output_file_prefix:
            from srtb_tpu.io.writers import recover_orphan_temps
            recover_orphan_temps(cfg.baseband_output_file_prefix)
        # every completed host-stage timing also lands in a bounded
        # histogram, so /metrics carries live p50/p95/p99 per stage
        self.stage_timer = StageTimer(
            on_stage=lambda name, dt: metrics.histogram(
                "stage_seconds", labels={"stage": name}).observe(dt))
        # ---- performance observatory (always-on) ----
        # pre-register the compile/cache families so /metrics exposes
        # them from the first scrape (a counter that was never bumped
        # is still an answer: zero compiles so far), and the labeled
        # twins for a named fleet lane
        for fam in ("compile_seconds", "plan_compiles",
                    "aot_cache_hits", "aot_cache_misses"):
            metrics.add(fam, 0.0)
            if self._stream_labels is not None:
                metrics.add(fam, 0.0, labels=self._stream_labels)
        # on-demand jax.profiler capture of the first N segments
        # (Config.profile_capture_segments; None = off, zero-cost)
        from srtb_tpu.utils.tracing import ProfileCapture
        self.profile_capture = ProfileCapture.from_config(cfg)
        self.journal = None
        jpath = getattr(cfg, "telemetry_journal_path", "")
        if jpath:
            from srtb_tpu.utils.telemetry import SpanJournal
            self.journal = SpanJournal(
                jpath, max_bytes=getattr(
                    cfg, "telemetry_journal_max_bytes", 64 << 20),
                compress=bool(getattr(cfg,
                                      "telemetry_journal_compress",
                                      True)))
        # ---- science observatory (srtb_tpu/quality/) ----
        # data-quality monitor (gauges + drift detector + journal
        # payload for the plans' quality epilogue) and the pulse-
        # injection canary; both are the zero-cost-off None hook
        from srtb_tpu.quality import QualityMonitor
        self.quality = QualityMonitor.from_config(cfg)
        self.canary = None
        if int(getattr(cfg, "canary_every_segments", 0) or 0) > 0:
            from srtb_tpu.ops import dedisperse as dd
            from srtb_tpu.quality import CanaryController
            self.canary = CanaryController.from_config(
                cfg, n_samples=cfg.baseband_input_count,
                reserved_samples=dd.nsamps_reserved(cfg))
        # canary schedule base: the engines set this to the
        # checkpoint's resume-continuous drain count at run start, so
        # "every N-th segment" means the same segments across resumes
        self._canary_base = 0

    @contextlib.contextmanager
    def _stage(self, name: str):
        """One named host stage: StageTimer accumulation + per-segment
        ``last`` capture + an xprof TraceAnnotation so device traces and
        the span journal correlate by stage name."""
        with trace_annotation(f"srtb:{name}"), \
                self.stage_timer.stage(name):
            yield

    def _op(self, site: str, index: int, fn):
        """One guarded pipeline operation: the fault-injection hook
        fires first (a scheduled raise/stall/corrupt at exactly
        (site, index)), then the retry policy re-runs transient
        failures with backoff.  With faults unarmed and retries off
        this is a plain call — the hot path pays two attribute reads.
        Retried operations must be idempotent at their site: an ingest
        retry re-runs a read that never happened, a fetch retry
        re-fetches the same device arrays, a sink retry may re-push
        (sinks are at-least-once under recovery, like the reference's
        piggybacked rewrites)."""
        faults = self.faults
        if faults is not None and faults.armed(site):
            inner = fn

            def fn():
                faults.fire(site, index)
                return inner()
        if self.retry is None:
            return fn()
        return retry_call(fn, self.retry, site)

    def _timed_ingest(self, it, index: int = 0):
        """One source read as the "ingest" stage; the terminal failed
        read (source exhausted — for a UDP source, a receive blocked
        until shutdown) is NOT recorded, so the ingest histogram holds
        exactly one sample per segment like every other stage.  The
        read runs under the "ingest" fault site: transient receiver
        errors (interrupted syscalls, connection churn) retry with
        backoff instead of killing the run."""
        t0 = time.perf_counter()
        with trace_annotation("srtb:ingest"):
            seg = self._op("ingest", index, lambda: next(it, None))
        if seg is not None:
            dt = time.perf_counter() - t0
            self.stage_timer.record("ingest", dt)
            if self.events is not None:
                # stamp the causal trace id at the segment's birth (a
                # source that pre-stamped its own keeps it) and bind
                # the ambient context so retry/fault events attribute
                tid = getattr(seg, "trace_id", 0)
                if not tid:
                    tid = events.next_trace_id()
                    try:
                        seg.trace_id = tid
                    except AttributeError:  # read-only stub segments
                        pass
                events.set_current(tid, self.stream)
                self.events.emit("stage.ingest", trace=tid,
                                 stream=self.stream, seg=index, dur=dt)
        return seg

    def _device_time_account(self, device_s: float,
                             n_samples: int) -> tuple:
        """Always-on device-time accounting for one drained segment:
        the ``device_seconds`` histogram plus the LIVE roofline gauges
        — achieved Msamples/s and modeled-HBM GB/s over this segment's
        device wall, and ``roofline_frac`` against the configured HBM
        peak (``Config.hbm_peak_gbps``).  The traffic model is the
        active plan's audited ``hbm_passes`` floor (the quantity the
        HLO plan auditor pins in plan_cards.json), so the gauges are
        per-plan LOWER bounds: device_s is an upper bound on device
        busy time and hbm_passes a floor on traffic.  Returns
        (achieved_msamps, roofline_frac) for the journal span (None
        when the active processor has no plan model — duck-typed
        stubs)."""
        metrics.histogram("device_seconds").observe(device_s)
        if self._stream_labels is not None:
            metrics.histogram(
                "device_seconds",
                labels=self._stream_labels).observe(device_s)
        proc = self.processor
        passes = getattr(proc, "hbm_passes", None)
        n_spec = getattr(proc, "n_spectrum", None)
        if passes is None or n_spec is None or device_s <= 0:
            return None, None
        seg_bytes = getattr(proc, "_segment_bytes",
                            self.cfg.segment_bytes(1))
        model_bytes = seg_bytes + 8.0 * n_spec * passes
        gbps = model_bytes / device_s / 1e9
        msamps = n_samples / device_s / 1e6
        peak = float(getattr(self.cfg, "hbm_peak_gbps", 819.0) or 819.0)
        frac = gbps / peak
        for name, val in (("achieved_msamps", msamps),
                          ("achieved_gbps", gbps),
                          ("roofline_frac", frac)):
            metrics.set(name, val)
            if self._stream_labels is not None:
                metrics.set(name, val, labels=self._stream_labels)
        return msamps, frac

    def _record_segment(self, index: int, seg, det_res, positive: bool,
                        span: dict, queue_depth: int,
                        n_samples: int,
                        overlap_hidden_s: float | None = None,
                        inflight_depth: int | None = None,
                        device_s: float | None = None) -> None:
        """Per-drained-segment telemetry: lifetime counters, sliding
        window rates (segments/s and samples/s over the last 10 s — a
        stall is visible immediately, unlike the lifetime average), the
        /healthz liveness stamp, device-time/roofline accounting, and
        one journal span record."""
        metrics.add("segments")
        metrics.add("samples", n_samples)
        if positive:
            metrics.add("signals")
        metrics.window("segments").add(1)
        metrics.window("samples").add(n_samples)
        if self._stream_labels is not None:
            metrics.add("segments", labels=self._stream_labels)
            metrics.add("samples", n_samples,
                        labels=self._stream_labels)
        telemetry.mark_segment(self.stream or None)
        msamps = frac = None
        if device_s is not None:
            msamps, frac = self._device_time_account(device_s,
                                                     n_samples)
        if self.profile_capture is not None:
            # counts drained segments and auto-stops after N; the
            # sidecar records the covered trace_ids so the device
            # trace joins the causal-event timeline
            self.profile_capture.note_segment(
                index, getattr(seg, "trace_id", 0))
        if self.slo is not None:
            # the latency objective scores the segment's HOST wall
            # clock (the span's summed stages — what the journal's
            # synthetic 'segment' stage reports); overlap-hidden time
            # is concurrent and deliberately excluded
            self.slo.note_segment(self.stream, sum(span.values()))
        det_count = 0
        counts = getattr(det_res, "signal_counts", None)
        if counts is not None:
            det_count = int(np.asarray(counts).sum())
        # quality epilogue -> gauges + drift detector (journal or not:
        # /metrics must carry the quality state of a journal-less run)
        quality_extra = None
        if self.quality is not None:
            qvec = getattr(det_res, "quality", None)
            if qvec is not None:
                # drain-side on a fetched result: the blocking fetch
                # already materialized every det_res leaf
                host_q = np.asarray(qvec)  # srtb-lint: disable=sync-hot-path
                quality_extra = self.quality.observe(
                    host_q, segment=index)
        if self.journal is not None:
            # registered-mode hook: a result type with its own span
            # payload (e.g. the periodicity candidate table) journals
            # it on every segment — search outcomes survive even when
            # the positive gate withholds the file dumps
            span_extra = getattr(det_res, "span_extra", None)
            extra = span_extra() if span_extra is not None else None
            if quality_extra is not None:
                extra = dict(extra or {}, quality=quality_extra)
            # canary flag: the full verdict when the drain scored one
            # this life; the bare injection mark on a replayed drain
            # (exactly-once check already done by a previous life)
            verdict = getattr(seg, "canary_verdict", None)
            if verdict is None and getattr(seg, "canary",
                                           None) is not None:
                verdict = {"injected": True,
                           "segment": seg.canary["segment"]}
            if verdict is not None:
                extra = dict(extra or {}, canary=verdict)
            self.journal.write(telemetry.segment_span(
                index, span, queue_depth, det_count, positive, n_samples,
                timestamp_ns=getattr(seg, "timestamp", 0),
                extra=extra,
                overlap_hidden_s=overlap_hidden_s,
                inflight_depth=inflight_depth,
                active_plan=getattr(self.processor, "plan_name", None),
                stream=self.stream or None,
                trace_id=getattr(seg, "trace_id", 0) or None,
                device_s=device_s,
                achieved_msamps=msamps,
                roofline_frac=frac,
                # v10: stamped by the fleet's cross-stream batch
                # former (pipeline/fleet._BatchFormer); absent on
                # every solo dispatch — the span omits them
                batch_size=getattr(seg, "batch_size", None),
                batch_wait_ms=(
                    None if getattr(seg, "batch_wait_s", None) is None
                    else seg.batch_wait_s * 1e3),
                # v11: the pool member this lane dispatches through
                # (stamped by the fleet at placement and re-stamped
                # by a live migration); absent outside a fleet
                device=getattr(self, "device_label", None)))

    # ---------------------------------------------- async segment engine

    @staticmethod
    def _result_ready(det_res) -> bool:
        """True when every device array in the detect result has
        materialized (``jax.Array.is_ready``) — the non-blocking fetch
        probe.  Objects without a readiness probe (host arrays, test
        stubs that choose not to implement one) count as ready.  A
        *failing* probe also counts as ready — the blocking fetch path
        surfaces the real error with full context — but is logged so a
        flaky probe never degrades the engine to serial silently."""
        try:
            leaves = jax.tree_util.tree_leaves(det_res)
        except Exception as e:
            log.debug(f"[pipeline] readiness probe: tree_leaves failed "
                      f"({e!r}); treating result as ready")
            return True
        for leaf in leaves:
            probe = getattr(leaf, "is_ready", None)
            if probe is None:
                continue
            try:
                if not probe():
                    return False
            except Exception as e:
                log.debug(f"[pipeline] is_ready probe failed ({e!r}); "
                          "deferring to the blocking fetch")
                return True
        return True

    # ------------------------------------------- self-healing compute

    def _plan_factory(self, cfg, staged):
        """Build a replacement segment plan for the self-healing
        ladder (a demotion rung, the promotion probe, or a device
        reinit).  Mirrors the constructor-relevant state of the
        CURRENT processor — donation policy and window — so the only
        thing that changes is the plan itself; the rung's config
        changes trace-relevant knobs, so ``plan_signature()`` differs
        and any AOT cache (``cfg.aot_plan_path``, re-enabled by the
        constructor) misses cleanly and re-lowers.  Built through the
        plan registry: the search_mode rung demotes by CHANGING the
        mode, so the replacement may be a different processor class."""
        from srtb_tpu.ops import window as W
        from srtb_tpu.pipeline import registry
        return registry.build_processor(
            cfg,
            window_name=getattr(self.processor, "_window_name",
                                W.DEFAULT_WINDOW),
            staged=staged,
            donate_input=bool(getattr(self.processor, "_donate_input",
                                      False)))

    def _swap_processor(self, newp) -> None:
        """Install a replacement plan (demotion / promotion / reinit).
        The warm ingest-ring carry belongs to the OLD plan's programs
        and carry-aval contract, so it is invalidated — the next
        dispatch goes cold from its retained host buffer — and the
        old processor is retired: its compiled handles (including any
        in-memory AOT executables bound to a dead backend after a
        reinit) raise loudly on any stray dispatch instead of running
        stale."""
        old, self.processor = self.processor, newp
        self._ring_invalidate()
        retire = getattr(old, "retire", None)
        if retire is not None and old is not newp:
            # a fleet-SHARED processor no-ops its retire (other
            # tenants still dispatch through it; segment.py guards)
            retire()

    def _account_dropped(self, n: int = 1,
                         trace: int | None = None) -> None:
        """Account ``n`` whole shed segments: the process-wide counter
        + loss window, plus the per-stream labeled twin when this
        pipeline is a named fleet lane (loss must be attributable to
        its tenant).  ``trace`` is the SHED segment's own causal id —
        callers that hold the work item pass it; the ambient context
        belongs to the most recently dispatched segment and would
        blame the wrong one."""
        metrics.add("segments_dropped", n)
        metrics.window("segments_dropped").add(n)
        if self._stream_labels is not None:
            metrics.add("segments_dropped", n,
                        labels=self._stream_labels)
        if self.slo is not None:
            self.slo.note_dropped(self.stream, n)
        ev = self.events
        if ev is not None:
            ev.emit("shed.segment",
                    trace=(trace if trace is not None
                           else events.current()[0]),
                    stream=self.stream, info=f"n={n}")

    @property
    def events(self):
        """The LIVE process-global hub (or None).  Deliberately not
        cached at construction: a later pipeline may reconfigure the
        global hub (different ring size), and a stale handle would
        silently split one process's causal story across two
        recorders — half in this pipeline's orphaned hub, half (the
        module-level emits) in the new one.  The disabled path stays
        one property call + global read + None check."""
        return events.hub if self._events_enabled else None

    @property
    def slo(self):
        """The LIVE process-global SLO tracker (or None) — same
        no-stale-handle rule as :attr:`events`: a later pipeline
        reconfiguring the global tracker must not leave this one
        feeding an orphan that /healthz and /metrics never read."""
        return slo.tracker if self._slo_armed else None

    def _incident(self, kind: str, reason: str = "",
                  trace: int | None = None,
                  extra: dict | None = None) -> None:
        """Dump an incident bundle (None-hook off; best-effort,
        rate-limited and bounded by the recorder).  ``extra`` is an
        arbitrary JSON-able payload landing as ``extra.json`` in the
        bundle — e.g. the canary verdict + quality timeline."""
        if self.incidents is not None:
            self.incidents.dump(
                kind, reason=reason, trace=trace, stream=self.stream,
                cfg=self.cfg, processor=self.processor,
                journal_path=getattr(self.cfg,
                                     "telemetry_journal_path", ""),
                extra=extra)

    # ------------------------------------------------- ingest ring state

    @property
    def _ring_live(self) -> bool:
        """Whether the device-resident carry ring is active for this
        run: the processor resolved Config.ingest_ring on AND it speaks
        the staging protocol (duck-typed stub processors don't)."""
        return bool(getattr(self.processor, "ring", False)) \
            and getattr(self.processor, "stage_input", None) is not None

    def _ring_invalidate(self) -> None:
        """Drop the device carry: the NEXT dispatch goes cold (full
        upload from its retained host buffer).  Called whenever carry
        continuity breaks — watchdog requeue, shed segment — and at
        run start/end (a checkpoint resume is a fresh run, so resume
        re-dispatch is cold by construction)."""
        if self._ring_carry is not None and self.events is not None:
            # a live carry is being dropped: the warm chain breaks
            # here and the next dispatch pays a full upload
            self.events.emit("ring.invalidate",
                             trace=events.current()[0],
                             stream=self.stream)
        self._ring_carry = None
        self._ring_prev = None

    def _ring_adjacent(self, seg) -> bool:
        """Whether ``seg`` is the stream-adjacent successor of the last
        dispatched segment — the precondition for warm assembly: its
        overlap head must BE the carry.  Unstamped segments (seq < 0,
        e.g. hand-built SegmentWork) are never warm; a seq gap (a
        dropped segment upstream) or a different data_stream_id (an
        interleaved multi-receiver stream) goes cold rather than
        assembling against a foreign tail."""
        prev = self._ring_prev
        return (prev is not None
                and getattr(seg, "seq", -1) >= 0
                and seg.seq == prev[1] + 1
                and getattr(seg, "data_stream_id", 0) == prev[0])

    def _dispatch_ring(self, seg, index: int, requeue: bool) -> tuple:
        """Ring-mode device dispatch of one segment.  Warm when a
        carry is live: upload stride bytes only and run the two-input
        assemble plan.  Cold (no carry / requeue): full upload through
        the carry-emitting cold plan, so the ring re-arms with no
        extra H2D bytes.  A dispatch RETRY always re-stages cold from
        the retained host buffer — the first attempt donated both the
        carry and the staged stride bytes — and stays bit-identical.
        ``requeue`` isolates the dispatch from the ring: the live
        carry belongs to a LATER segment (the caller invalidated it),
        and the requeued segment's own carry is already history."""
        proc = self.processor
        stage_in = proc.stage_input
        # canary-injected copy when attached (the delta is zero over
        # the head/tail reserved spans, so the warm stride slice and
        # the adopted carry stay consistent with a cold dispatch)
        data = self._device_bytes(seg)
        # a requeue that lands on a FULLY invalidated ring (processor
        # swap, device reinit, live migration) is the stream's new
        # frontier: its cold full upload emits a valid carry, and
        # adopting it re-arms the ring in the same dispatch — the
        # follow-up segment warm-assembles instead of paying a second
        # full upload.  A requeue with ring state still live (watchdog
        # cancel of a mid-window segment) must NOT anchor: the ring
        # has moved past it, and adjacency would lie.
        ring_down = self._ring_prev is None and self._ring_carry is None
        carry = None if requeue or not self._ring_adjacent(seg) \
            else self._ring_carry
        if carry is not None:
            self._ring_carry = None  # consumed below (donated)
            staged = self._op("h2d", index,
                              lambda: stage_in(data,
                                               stride_only=True))
            attempt = [0]

            def run_it():
                attempt[0] += 1
                if attempt[0] == 1:
                    return proc.run_device_ring(carry, staged)
                # the failed warm attempt consumed the carry: go cold
                return proc.run_device_cold(stage_in(data))

            out, next_carry = self._op("dispatch", index, run_it)
        else:
            if self.events is not None:
                self.events.emit("ring.cold",
                                 trace=getattr(seg, "trace_id", 0),
                                 stream=self.stream, seg=index,
                                 info="requeue" if requeue else "")
            staged = self._op("h2d", index, lambda: stage_in(data))
            first = [True]

            def run_it():
                if first[0]:
                    first[0] = False
                    return proc.run_device_cold(staged)
                return proc.run_device_cold(stage_in(data))

            out, next_carry = self._op("dispatch", index, run_it)
        if not requeue or ring_down:
            # adopt the carry for the next dispatch; a requeued
            # segment's carry is stale (the ring has moved past it)
            # UNLESS the ring was down at entry — then this requeue
            # IS the re-arm (see ring_down above)
            self._ring_carry = next_carry
            seq = getattr(seg, "seq", -1)
            # an unstamped segment cannot anchor adjacency: the next
            # dispatch stays cold
            self._ring_prev = ((getattr(seg, "data_stream_id", 0), seq)
                               if seq >= 0 else None)
        return out

    # ------------------------------------------- pulse-injection canary

    def _canary_prepare(self, seg, index: int) -> None:
        """Dispatch-side canary hook: on a scheduled segment, attach
        the injected COPY (``seg.canary_data``) and the injection mark
        (``seg.canary``).  Device staging reads the copy through
        :meth:`_device_bytes`; every sink keeps seeing the pristine
        ``seg.data``, so science outputs stay bit-identical to a
        canary-off run.  Idempotent: a watchdog requeue or healed
        re-dispatch reuses the already-attached copy (same bytes —
        the delta is deterministic — and the injected counter stays
        exactly-once)."""
        c = self.canary
        if c is None or getattr(seg, "canary", None) is not None:
            return
        data, mark = c.prepare(self._canary_base + index, seg.data)
        if mark is None:
            return
        try:
            seg.canary = mark
            seg.canary_data = data
        except AttributeError:  # read-only stub segments: no canary
            log.warning("[canary] segment cannot carry the injection "
                        "mark; skipping")

    def _device_bytes(self, seg):
        """The host bytes the DEVICE stages: the canary-injected copy
        when one is attached, else the segment's pristine buffer.
        Also the staging-release key — the staging registry keys on
        ``id()`` of whatever buffer was staged."""
        d = getattr(seg, "canary_data", None)
        return seg.data if d is None else d

    def _canary_drain(self, seg, mark: dict, det_res,
                      sinks_done: set, drain_index: int) -> bool:
        """Drain-side canary handling: score the recovered S/N
        against the expected reference, flag the segment in the run
        manifest, and escalate a sensitivity regression as an
        incident bundle with the recent quality timeline attached.
        Exactly-once under sink retry / supervisor replay via the
        "canary" marker in ``sinks_done`` (sink entries are ints, no
        collision).  Returns the QUARANTINED positive verdict —
        always False: a synthetic pulse must never count as science
        (no ``signals`` bump, no candidate dumps)."""
        if "canary" in sinks_done:
            return False
        sinks_done.add("canary")
        verdict = None
        if self.canary is not None:
            # drain-side on a fetched result (same sanction as the
            # quality observe in _record_segment)
            peaks = np.asarray(  # srtb-lint: disable=sync-hot-path
                getattr(det_res, "snr_peaks", 0.0))
            verdict = self.canary.check(mark["segment"], peaks)
        try:
            seg.canary_verdict = verdict  # journaled by _record_segment
        except AttributeError:
            pass
        if self.manifest is not None:
            self.manifest.canary(
                getattr(seg, "data_stream_id", 0), drain_index,
                mark["segment"],
                ok=bool(verdict.get("ok", True)) if verdict else True)
        if verdict is not None and not verdict.get("ok", True):
            if self.events is not None:
                self.events.emit(
                    "canary.regression",
                    trace=getattr(seg, "trace_id", 0),
                    stream=self.stream, seg=mark["segment"],
                    info=f"ratio={verdict.get('ratio')}")
            self._incident(
                "canary_sensitivity",
                reason=(f"canary segment {mark['segment']}: recovered "
                        f"S/N {verdict.get('snr')} is "
                        f"{verdict.get('ratio')}x the expected "
                        f"{verdict.get('expected')}"),
                trace=getattr(seg, "trace_id", 0),
                extra={"canary": dict(mark, **verdict),
                       "quality_timeline":
                           (self.quality.timeline()
                            if self.quality is not None else [])})
        return False

    def _dispatch_segment(self, seg, ingest_s: float,
                          offset_after: int, index: int = 0,
                          requeue: bool = False) -> tuple:
        """Stage one segment's bytes to the device (async H2D) and
        enqueue its program; both run under the "dispatch" stage, and
        under the "h2d" / "dispatch" fault sites respectively.
        ``offset_after`` is the source's logical offset captured right
        after THIS segment's ingest (not at dispatch time — with
        batching, later ingests have already advanced the source).
        Returns the in-flight record (the trailing ``index`` is the
        dispatch-order segment index, which the watchdog uses to bound
        requeues and the fault injector to schedule)."""
        tid = getattr(seg, "trace_id", 0)
        if self.events is not None:
            events.set_current(tid, self.stream)
        self._canary_prepare(seg, index)
        data = self._device_bytes(seg)
        with self._stage("dispatch"):
            stage_in = getattr(self.processor, "stage_input", None)
            if self._ring_live:
                wf, det_res = self._dispatch_ring(seg, index, requeue)
            elif stage_in is not None:
                staged = self._op("h2d", index,
                                  lambda: stage_in(data))
                first = [True]

                def run_it():
                    # a donated plan consumes the staged buffer the
                    # moment the first attempt dispatches, so a RETRY
                    # must re-stage from the retained host bytes —
                    # reusing the donated handle would fail "deleted"
                    if first[0]:
                        first[0] = False
                        return self.processor.run_device(staged)
                    return self.processor.run_device(
                        stage_in(data))

                wf, det_res = self._op("dispatch", index, run_it)
            else:  # duck-typed stub processors (tests)
                wf, det_res = self._op(
                    "dispatch", index,
                    lambda: self.processor.process(data))
        span = {"ingest": ingest_s,
                "dispatch": self.stage_timer.last["dispatch"]}
        if self.events is not None:
            self.events.emit("stage.dispatch", trace=tid,
                             stream=self.stream, seg=index,
                             dur=span["dispatch"],
                             info="requeue" if requeue else "")
        return (seg, wf, det_res, offset_after, span,
                time.perf_counter(), index)

    def _dispatch_micro_batch(self, segs: list, ingests: list,
                              offsets: list, first_index: int = 0) \
            -> list:
        """Stack B ingested segments into ONE vmapped jit call; each
        segment's results are lazy device slices of the batch outputs.
        The batch dispatch cost is amortized evenly across the spans;
        each item keeps its OWN post-ingest source offset so a
        checkpoint written after a partially drained batch resumes at
        the first undrained segment, not past the whole batch.  The
        whole batch dispatch runs under the first segment's "dispatch"
        fault site (one jit call = one failure domain)."""
        t0 = time.perf_counter()
        for i, s in enumerate(segs):
            self._canary_prepare(s, first_index + i)
        with trace_annotation("srtb:dispatch"):
            if self._ring_live:
                wf_b, det_b = self._dispatch_batch_ring(segs, first_index)
            else:
                stack = getattr(self.processor, "stack_batch", None)
                # host byte buffers, never device arrays: the
                # contiguous wrap is a no-op for the sources' ndarrays
                datas = [self._device_bytes(s) for s in segs]
                stacked = (stack(datas)
                           if stack is not None else
                           np.stack([np.ascontiguousarray(d)
                                     for d in datas]))
                wf_b, det_b = self._op(
                    "dispatch", first_index,
                    lambda: self.processor.process_batch(stacked))
        per_seg = (time.perf_counter() - t0) / len(segs)
        items = []
        for i, seg in enumerate(segs):
            self.stage_timer.record("dispatch", per_seg)
            det_i = jax.tree_util.tree_map(
                lambda x, j=i: x[j], det_b)
            span = {"ingest": ingests[i], "dispatch": per_seg}
            if self.events is not None:
                self.events.emit("stage.dispatch",
                                 trace=getattr(seg, "trace_id", 0),
                                 stream=self.stream,
                                 seg=first_index + i, dur=per_seg,
                                 info=f"batch={len(segs)}")
            items.append((seg, wf_b[i], det_i, offsets[i], span,
                          time.perf_counter(), first_index + i))
        return items

    def _dispatch_batch_ring(self, segs: list, first_index: int):
        """Ring-mode micro-batch dispatch: warm batches upload B stride
        slices (pooled stack) against the live carry; cold batches
        upload B full segments through the carry-emitting cold batch
        plan.  Retries go cold from the retained host buffers, exactly
        like the single-segment path."""
        proc = self.processor
        # warm needs the whole batch stream-adjacent: segs[0] continues
        # the carry, and each member continues its predecessor
        chain_ok = self._ring_adjacent(segs[0]) and all(
            getattr(b, "seq", -1) == getattr(a, "seq", -2) + 1
            and getattr(b, "data_stream_id", 0)
            == getattr(a, "data_stream_id", 0)
            for a, b in zip(segs, segs[1:]))
        carry = self._ring_carry if chain_ok else None
        datas = [self._device_bytes(s) for s in segs]
        if carry is not None:
            self._ring_carry = None  # consumed below (donated)
            attempt = [0]

            def run_it():
                attempt[0] += 1
                if attempt[0] == 1:
                    return proc.process_batch_ring(
                        carry, proc.stack_batch(datas, stride_only=True))
                return proc.process_batch_cold(proc.stack_batch(datas))

            out, next_carry = self._op("dispatch", first_index, run_it)
        else:
            out, next_carry = self._op(
                "dispatch", first_index,
                lambda: proc.process_batch_cold(proc.stack_batch(datas)))
        self._ring_carry = next_carry
        seq = getattr(segs[-1], "seq", -1)
        self._ring_prev = ((getattr(segs[-1], "data_stream_id", 0), seq)
                           if seq >= 0 else None)
        return out

    def _fetch_inflight(self, item: tuple, depth: int,
                        live_depth: int) -> tuple:
        """Resolve one in-flight record to host data.  The gap between
        dispatch returning and this fetch starting is host time the
        engine hid under device compute — journaled as
        ``overlap_hidden_ms`` and observed into the ``overlap`` stage
        histogram."""
        seg, wf, det_res, offset_after, span, t_dispatched, index = item
        hidden = max(0.0, time.perf_counter() - t_dispatched)
        self.stage_timer.record("overlap", hidden)
        seg, wf, det_res, offset_after, span = self._fetch_device(
            (seg, wf, det_res, offset_after, span), index)
        # device-time accounting (always-on): dispatch-return ->
        # fetch-complete wall for THIS segment.  The blocking fetch
        # proves device completion, so this is an UPPER bound on the
        # segment's device busy time — exact in serial mode, inflated
        # by drain-queue wait when the window runs deep — which makes
        # every gauge derived from it (achieved Msamp/s, roofline
        # fraction) an honest LOWER bound.
        device_s = max(0.0, time.perf_counter() - t_dispatched)
        # the dispatch-order index rides along so the sink-side fault
        # sites (sink_write, checkpoint) address segments in the SAME
        # index space as ingest/h2d/dispatch/fetch — the drain counter
        # starts at the checkpoint on resume and skips shed segments,
        # so one fault_plan index would otherwise mean different
        # segments at different sites
        return (seg, wf, det_res, offset_after, span, hidden, device_s,
                depth, live_depth, index)

    def _drain_body(self, item: tuple, drained: list) -> None:
        """Sink-side half of one segment: detection gate, sink pushes,
        buffer-pool release, journal record, checkpoint.  Runs on the
        sink pipe thread in overlapped mode (off the dispatch critical
        path), inline in serial mode."""
        cfg = self.cfg
        (seg, wf, det_res, offset_after, span, hidden, device_s, depth,
         live, index, degrade_level, sinks_done) = item
        if self.events is not None:
            # bind the causal context on the SINK thread: manifest
            # intent/commit/done records and sink-side retries emitted
            # below attribute to this segment's trace
            events.set_current(getattr(seg, "trace_id", 0),
                               self.stream)
        san = self.sanitizer
        if san is not None:
            # the sink side is single-owner too: either the sink pipe
            # thread (overlapped) or the main thread (serial), never
            # both within one run
            san.assert_owner("sink_drain")
            self._sanitize_check(wf, det_res)
        positive = has_signal(
            cfg, det_res,
            frequency_bin_count=(wf.shape[-2] if wf is not None
                                 else None))
        cmark = getattr(seg, "canary", None)
        if cmark is not None:
            # quarantine: the canary's recovered S/N is scored and
            # journaled, then the segment is forced NEGATIVE — the
            # synthetic pulse never counts as science
            positive = self._canary_drain(seg, cmark, det_res,
                                          sinks_done, drained[0])
        # the "stats" marker rides in sinks_done (sink entries are
        # ints, no collision): a supervisor replay of a crashed drain
        # re-enters this body, and the first attempt may already have
        # counted the signal — stats must stay exactly-once too
        if positive and "stats" not in sinks_done:
            sinks_done.add("stats")
            self.stats.signals += 1
            # drained[0] is the index this segment journals as; the
            # dispatch counter runs ahead of the drain in overlapped
            # mode and would name the wrong segment
            log.info("[pipeline] signal detected in segment "
                     f"{drained[0]}")
        # fault/retry sites address segments by dispatch-order index
        # (the space ingest/h2d/dispatch/fetch already use); the
        # JOURNAL keeps the drain counter below, which is resume-
        # continuous across checkpointed runs
        seg_index = index
        # durable exactly-once key: the RESUME-CONTINUOUS drain index
        # (what the checkpoint counts), not the per-run dispatch
        # index — a replayed segment after a crash+resume must land on
        # the same manifest key its first life used
        mkey = (None if self.manifest is None
                else (getattr(seg, "data_stream_id", 0), drained[0]))
        with self._stage("sink"):
            # ``sinks_done`` rides with the item: a retry (or a
            # supervisor replay) re-enters _push_sinks but skips the
            # sinks that already succeeded — exactly-once per sink,
            # which in-place appenders (WriteAllSink) require
            self._op("sink_write", seg_index,
                     lambda: self._push_sinks(seg, wf, det_res,
                                              positive, degrade_level,
                                              done=sinks_done,
                                              seg_key=mkey))
        span["sink"] = self.stage_timer.last["sink"]
        if self.events is not None:
            self.events.emit("stage.sink",
                             trace=getattr(seg, "trace_id", 0),
                             stream=self.stream, seg=index,
                             dur=span["sink"],
                             info="dump" if positive else "")
        # host staging-buffer pool: copies staged for this segment
        # (micro-batch stacks, non-contiguous inputs) are reusable once
        # the segment drained — the device program that consumed the
        # transfer has completed.  MUST run BEFORE the reader-pool
        # release below: the registry keys on id(seg.data), and once
        # the reader can reacquire that exact buffer object a fresh
        # registration under the same id could be popped here instead,
        # returning a staging buffer whose transfer is still in flight
        rel = getattr(self.processor, "release_staging", None)
        if rel is not None:
            # the staging registry keys on id() of the STAGED buffer
            # — the canary-injected copy when one was attached
            rel(self._device_bytes(seg))
        # file mode: sinks never retain segments (no piggybank deque),
        # so the host buffer can go back to the pool for the reader
        pool = getattr(self.source, "pool", None)
        if pool is not None and cfg.input_file_path:
            pool.release(seg.data)
        with self._handoff_lock:
            if "abandoned" in sinks_done:
                # the bounded shutdown accounted this segment as
                # dropped while this thread was wedged mid-push; a
                # late completion must not also journal/count it
                return
            # claiming the drain count INSIDE the lock is what makes
            # the handoff race-free: once drained advances, the
            # shutdown's drained == progress check can no longer
            # abandon this item
            drained[0] += 1
        self._record_segment(drained[0] - 1, seg, det_res, positive,
                             span, queue_depth=depth,
                             n_samples=cfg.baseband_input_count,
                             overlap_hidden_s=hidden,
                             inflight_depth=live,
                             device_s=device_s)
        if self.checkpoint is not None:
            # a checkpointed segment must be durable: flush queued
            # async candidate writes before recording it as done.
            # Both run under the "checkpoint" fault site: the flush
            # and the atomic state rewrite are idempotent.
            self._op("checkpoint", seg_index,
                     lambda: (self._drain_sinks(),
                              self.checkpoint.update(drained[0],
                                                     offset_after)))

    def run(self, max_segments: int | None = None) -> PipelineStats:
        """The async in-flight engine (see module docstring).  With
        ``inflight_segments = 1`` this degenerates to the fully serial
        reference loop; the default window of 2 reproduces the
        reference's queue-capacity-2 pipe graph with sink work off the
        critical path.

        With ``Config.sanitize`` the whole run executes inside the
        sanitizer scope: implicit-transfer tripwire armed, thread
        owners tracked, and a leaked-thread check after the sink pipe
        joins."""
        if self.sanitizer is None:
            return self._run_engine(max_segments)
        with self.sanitizer.run_scope():
            return self._run_engine(max_segments)

    def _run_engine(self, max_segments: int | None = None) \
            -> PipelineStats:
        from srtb_tpu.pipeline import framework as fw

        cfg = self.cfg
        window = max(1, int(getattr(cfg, "inflight_segments", 2) or 1))
        batch = max(1, int(getattr(cfg, "micro_batch_segments", 1) or 1))
        if batch > window:
            raise ValueError(
                f"micro_batch_segments={batch} exceeds "
                f"inflight_segments={window}: a batch dispatch must fit "
                "the in-flight window")
        if batch > 1 and getattr(self.processor, "staged", False):
            # fail before any ingest/compile happens: process_batch
            # would reject this anyway, but only after B multi-GB
            # segments were read and stacked
            raise ValueError(
                "micro_batch_segments > 1 requires the fused plan "
                "(staged segments are already dispatch-amortized)")
        start = time.perf_counter()
        if self.profile_capture is not None:
            # arm the on-demand XLA trace BEFORE the first dispatch so
            # the capture covers compile + the first N segments
            self.profile_capture.start()
        n_samples_per_seg = cfg.baseband_input_count
        drained = [self.checkpoint.segments_done if self.checkpoint else 0]
        # resume-continuous canary schedule: dispatch indices restart
        # at 0 every run, so the absolute index is base + index
        self._canary_base = drained[0]
        # ring carry starts cold every run: a checkpoint-resumed (or
        # simply restarted) process has no device-resident tail, so the
        # first dispatch is a full upload that re-arms the ring
        self._ring_invalidate()

        # sink work runs on a framework Pipe in overlapped mode so
        # writers + the lazy waterfall transfer cannot serialize into
        # the next segment's ingest/dispatch; serial mode keeps it
        # inline (the honest A/B reference leg)
        use_sink_pipe = window > 1
        stop = fw.StopToken()
        q_sink = fw.WorkQueue(capacity=window)
        # a segment is "in flight" from dispatch until its SINK
        # completes: the admission gate below bounds this count by the
        # window, so at most W waterfalls are device-resident at once.
        # Without sink accounting, fetched-but-unsunk items in the
        # queue would stack up to ~2W waterfalls — an HBM regression
        # at multi-GB waterfall sizes the old 2-deep loop never risked.
        live_lock = threading.Lock()
        live = [0]

        def live_count() -> int:
            with live_lock:
                return live[0]

        def live_add(n: int) -> None:
            with live_lock:
                live[0] += n
                metrics.set("inflight_depth", live[0])
                if self._stream_labels is not None:
                    metrics.set("inflight_depth", live[0],
                                labels=self._stream_labels)

        # bounded-restart supervision of the sink pipe: a transient
        # crash restarts the worker (the failed item is replayed
        # inline first, preserving journal order); fatal crashes and
        # exhausted budgets escalate exactly like today.  Disabled
        # under the sanitizer (its claim-on-first-use thread-ownership
        # guard is incompatible with a replacement sink thread).
        supervisor = None
        if use_sink_pipe and self.sanitizer is None \
                and int(getattr(cfg, "supervisor_max_restarts", 0)) > 0:
            from srtb_tpu.resilience.supervisor import Supervisor
            supervisor = Supervisor(
                "sink_drain",
                max_restarts=cfg.supervisor_max_restarts,
                window_s=getattr(cfg, "supervisor_window_s", 60.0))
        current = [None]   # item the sink worker is processing
        progress = [0]     # drained[0] when that item started

        def sink_f(_stop, item):
            current[0] = item
            progress[0] = drained[0]
            try:
                self._drain_body(item, drained)
            finally:
                # an item abandoned by the bounded shutdown had its
                # live slot released (and the drop counted) there
                if "abandoned" not in item[-1]:
                    live_add(-1)
            current[0] = None

        sink_pipe = None
        if use_sink_pipe:
            sink_pipe = fw.start_pipe(sink_f, q_sink, None, stop,
                                      "sink_drain")

        def sink_alive() -> bool:
            """True while the sink side can make progress; restarts a
            supervised crashed pipe as a side effect."""
            nonlocal sink_pipe
            if sink_pipe is None or sink_pipe.exception is None:
                return True
            if supervisor is None or \
                    not supervisor.should_restart(sink_pipe.exception):
                return False
            failed, current[0] = current[0], None
            if failed is not None and failed is not fw.SENTINEL:
                if drained[0] == progress[0]:
                    # the crash hit BEFORE the item was accounted:
                    # replay it inline BEFORE the new pipe starts
                    # popping, preserving journal order (its live slot
                    # was already released by sink_f's finally; sink
                    # pushes are at-least-once under recovery); a
                    # second failure here propagates = escalation
                    self._drain_body(failed, drained)
                else:
                    # the crash hit AFTER accounting (e.g. in the
                    # checkpoint flush): the segment is already
                    # counted, journaled and pushed — replaying
                    # _drain_body would double-count it.  A missed
                    # checkpoint update self-heals: update() writes
                    # absolute state, so the next segment's
                    # checkpoint covers this one.
                    log.warning(
                        "[supervisor] sink_drain crashed after its "
                        "segment was accounted; skipping replay (the "
                        "next checkpoint covers it)")
            sink_pipe = fw.start_pipe(sink_f, q_sink, None, stop,
                                      "sink_drain")
            return True

        watchdog_max = int(getattr(cfg, "segment_watchdog_requeues",
                                   0) or 0)
        deadline_s = float(cfg.segment_deadline_s or 0.0)
        watchdog = watchdog_max > 0 and deadline_s > 0
        # ladder pressure flag: the engine waited on the sink since
        # the last emit (set by push_sink and the parked-window wait)
        sink_wait = [False]

        # shedding (watchdog shed + degradation ladder) is a LIVENESS
        # mechanism: it only applies to a real-time source (UDP), where
        # a stalled engine turns into receiver loss.  A file-mode run
        # throttles losslessly by design — backpressure on the reader
        # is the correct outcome, not a reason to drop science output —
        # so there a slow or even wedged sink stalls (bounded by
        # shutdown_join_timeout_s / the fetch deadline), never sheds.
        real_time = not cfg.input_file_path

        def shed_segment(seg, in_flight: bool) -> None:
            """Account one shed segment as explicit loss (counter +
            loss window) and return its host buffer to the reader pool
            (file mode — sinks never retained it); ``in_flight`` frees
            the window slot the sink will never release.  A shed also
            breaks ring-carry continuity: the next dispatched
            segment's overlap head is no longer the tail of the last
            DISPATCHED segment, so the carry is invalidated and the
            next dispatch re-arms cold (an undispatched shed breaks
            the source-adjacency chain; an in-flight shed is just
            conservative hygiene, at one full upload's cost)."""
            self._account_dropped(trace=getattr(seg, "trace_id", 0))
            self._ring_invalidate()
            if in_flight:
                live_add(-1)
            # staging release first, reader pool second — same id-reuse
            # ordering rule as _drain_body.  Releasing is safe on every
            # shed path: an undispatched shed never staged (no-op), and
            # every in-flight shed (wedged-sink / bounded-shutdown)
            # sheds a FETCHED item, so the program that consumed the
            # staged transfer has provably completed.
            rel = getattr(self.processor, "release_staging", None)
            if rel is not None:
                rel(self._device_bytes(seg))
            pool = getattr(self.source, "pool", None)
            if pool is not None and cfg.input_file_path:
                pool.release(seg.data)

        def push_sink(item) -> bool:
            """Bounded push to the sink pipe: blocks while the queue is
            full (the engine's backpressure point — sinks falling
            behind transitively stalls ingest, which a lossy source
            surfaces as accounted loss), but bails out if the sink
            thread crashed while the queue was full — WorkQueue.push's
            stop-token loop cannot see a dead consumer.  With the
            watchdog armed, a sink pipe *wedged* (alive but stuck, ZERO
            drain progress) past the segment deadline sheds this
            segment as accounted loss instead of stalling the engine
            forever (the ladder's whole-segment rung).  Drain progress
            resets the clock, at per-sink-push granularity (the
            heartbeat), not per drained item: a slow-but-healthy
            multi-sink flush keeps showing progress, and only a SINGLE
            write stalled past the deadline reads as a wedge — size
            ``segment_deadline_s`` above the largest expected single
            flush.  Same rule as the parked-window wait below."""
            t0 = time.perf_counter()
            progress0 = (drained[0], self._sink_heartbeat)
            while not q_sink.push_lossy(item):
                sink_wait[0] = True
                if not sink_alive() or stop.stop_requested:
                    return False
                if watchdog and real_time and item is not fw.SENTINEL:
                    cur = (drained[0], self._sink_heartbeat)
                    if cur != progress0:
                        t0, progress0 = time.perf_counter(), cur
                    elif time.perf_counter() - t0 > deadline_s:
                        log.error(
                            "[watchdog] sink pipe wedged past "
                            f"{deadline_s:g}s with no drain progress: "
                            "shedding segment as accounted loss")
                        self._incident(
                            "sink_wedge",
                            trace=getattr(item[0], "trace_id", 0),
                            reason=f"sink pipe wedged > {deadline_s:g}s"
                                   " with no drain progress")
                        # sink_f will never see this item
                        shed_segment(item[0], in_flight=True)
                        return True
                time.sleep(0.002)
            return True

        def emit(fetched) -> bool:
            # graceful degradation: one ladder observation per emitted
            # segment, on the ENGINE side.  The pressure signal is
            # "the engine had to wait on the sink since the last emit"
            # (a full queue at push, or the whole window parked in the
            # sink backlog) — queue size alone reads 0 the instant the
            # sink pops, hiding a sink-bound pipeline — plus whether
            # accounted segment loss is currently happening.  The
            # level rides with the item so the sink side sheds
            # consistently with what was observed.
            level = 0
            if self._ladder is not None:
                if not real_time:
                    occupancy = 0.0
                elif sink_wait[0]:
                    occupancy = 1.0
                else:
                    occupancy = (q_sink.qsize() / window
                                 if sink_pipe is not None else 0.0)
                sink_wait[0] = False
                level = self._ladder.observe(
                    occupancy,
                    metrics.window("segments_dropped").sum() > 0)
            # level + the per-item sinks-done set (see _drain_body)
            fetched = fetched + (level, set())
            if sink_pipe is None:
                try:
                    self._drain_body(fetched, drained)
                finally:
                    live_add(-1)
                return True
            return push_sink(fetched)

        pending: collections.deque = collections.deque()
        it = iter(self.source)
        dispatched = [0]
        exhausted = [False]

        def want_more() -> bool:
            return (not exhausted[0]
                    and (max_segments is None
                         or dispatched[0] < max_segments))

        def ingest_one(index: int):
            """One source read; returns (seg, ingest_seconds,
            offset_after_this_segment) or None when exhausted."""
            seg = self._timed_ingest(it, index)
            if seg is None:
                exhausted[0] = True
                return None
            return (seg, self.stage_timer.last["ingest"],
                    getattr(self.source, "logical_offset", 0))

        # dispatch granularity: a micro-batch lands B segments at once,
        # so admission is gated on the whole unit fitting the window —
        # in-flight depth never exceeds inflight_segments.  The unit is
        # DYNAMIC: the self-healing ladder's first rung drops the
        # micro-batch, and the engine's admission/dispatch unit must
        # follow the active plan (the demoted processor has no batch
        # programs).
        def cur_unit() -> int:
            if self.healer is not None:
                return min(window, self.healer.micro_batch)
            return batch

        san = self.sanitizer

        # ---- self-healing compute: the dispatch/fetch fault handlers.
        # heal() is called ONLY from exception handlers — a healthy run
        # never reaches any of this.

        def reinit_and_redispatch(exc) -> bool:
            """Device-halt recovery: every in-flight device buffer and
            compiled handle on the halted backend is suspect.  Budget-
            checked by the healer's device_reinit supervisor; on
            approval: drop the jit/compile caches bound to the old
            backend handle (jax.clear_caches), swap in a freshly built
            processor at the current rung (no loaded AOT executables,
            no warm state; the swap also invalidates the warm
            ingest-ring carry, so the next warm-eligible dispatch goes
            COLD instead of assembling against a dead device buffer),
            then re-dispatch every in-flight segment cold from its
            retained host buffer, in dispatch order — journal order
            and checkpoint resume offsets are unchanged, exactly like
            a watchdog requeue."""
            h = self.healer
            newp = h.reinit(exc)
            if newp is None:
                return False  # budget spent: escalate
            try:
                jax.clear_caches()
            except Exception as e:  # version drift must not block
                log.warning(f"[selfheal] jax.clear_caches failed "
                            f"({e!r}); proceeding with the rebuild")
            self._swap_processor(newp)
            for i in range(len(pending)):
                seg, _wf, _det, offset_after, span, _t0, idx = \
                    pending[i]
                pending[i] = dispatch_one(seg, span["ingest"],
                                          offset_after, idx,
                                          requeue=True)
            return True

        def heal(exc) -> bool:
            """True when a device-classified fault was recovered (the
            active processor may have been swapped).  False propagates
            the ORIGINAL failure (not a device fault / healing off).
            A spent budget raises the typed FATAL escalation instead —
            the escaped exception must classify FATAL, not DEVICE, or
            an outer supervisor would keep restarting a permanently
            OOMing run."""
            from srtb_tpu.resilience.errors import (LadderExhausted,
                                                    ReinitBudgetExceeded)
            h = self.healer
            if h is None:
                return False
            kind = h.classify(exc)
            if kind is None:
                return False
            events.emit("fault.device",
                        info=f"{kind}:{type(exc).__name__}")
            if kind == DEVICE_HALT:
                if reinit_and_redispatch(exc):
                    return True
                self._incident(
                    "reinit_budget_exceeded",
                    reason=f"device halt beyond reinit budget: {exc}")
                raise ReinitBudgetExceeded(
                    "device halt beyond reinit recovery "
                    "(device_reinit_max budget spent or disabled): "
                    f"{exc}") from exc
            newp = h.demote(exc, kind)
            if newp is None:
                self._incident(
                    "ladder_exhausted",
                    reason=f"device fault survived every rung: {exc}")
                raise LadderExhausted(
                    f"device fault survived every demotion rung: "
                    f"{exc}") from exc
            self._swap_processor(newp)
            return True

        def dispatch_one(seg, ingest_s, offset_after, index,
                         requeue=False):
            """One segment dispatch with self-healing: a device-
            classified failure demotes/reinits and re-dispatches the
            SAME segment from its retained host buffer; anything else
            propagates.  The replacement dispatch is carry-isolated
            (``requeue=True``): the swap invalidated the ring, and a
            re-dispatched segment must never warm-assemble."""
            while True:
                try:
                    return self._dispatch_segment(seg, ingest_s,
                                                  offset_after, index,
                                                  requeue=requeue)
                except BaseException as e:  # noqa: BLE001 — classified
                    if not heal(e):
                        raise
                    requeue = True

        def maybe_promote() -> None:
            """Promotion probe: after promote_after_segments healthy
            drains on a demoted plan, step one rung back up before
            admitting the next segment — the next dispatch probes the
            richer plan; a recurring fault demotes again via heal()."""
            h = self.healer
            if h is not None and h.promote_due():
                newp = h.promote()
                if newp is not None:
                    self._swap_processor(newp)

        def fill_window() -> None:
            if san is not None:
                # dispatch-window state (pending deque, dispatch
                # counters) is owned by the run() thread
                san.assert_owner("inflight_window")
            while live_count() + cur_unit() <= window and want_more() \
                    and sink_alive():
                maybe_promote()
                b = cur_unit()
                if live_count() + b > window:
                    # the promotion probe restored the micro-batch and
                    # the bigger unit no longer fits: drain first (the
                    # in-flight depth bound holds across promotions)
                    return
                if b > 1:
                    budget = b if max_segments is None else \
                        min(b, max_segments - dispatched[0])
                    got = []
                    while len(got) < budget:
                        one = ingest_one(dispatched[0] + len(got))
                        if one is None:
                            break
                        got.append(one)
                    if not got:
                        return
                    segs, ingests, offsets = map(list, zip(*got))
                    if len(segs) == b:
                        try:
                            items = self._dispatch_micro_batch(
                                segs, ingests, offsets, dispatched[0])
                        except BaseException as e:  # noqa: BLE001
                            if not heal(e):
                                raise
                            # the healed plan may no longer micro-
                            # batch: finish these segments as single
                            # cold dispatches (the tail path below
                            # proves the single-segment plan is
                            # result-compatible)
                            items = [dispatch_one(s, dt, off,
                                                  dispatched[0] + i,
                                                  requeue=True)
                                     for i, (s, dt, off)
                                     in enumerate(got)]
                    else:  # tail shorter than B: single-segment plan
                        items = [dispatch_one(s, dt, off,
                                              dispatched[0] + i)
                                 for i, (s, dt, off) in enumerate(got)]
                    pending.extend(items)
                    live_add(len(segs))
                    dispatched[0] += len(segs)
                    self.stats.segments += len(segs)
                    self.stats.samples += n_samples_per_seg * len(segs)
                else:
                    one = ingest_one(dispatched[0])
                    if one is None:
                        return
                    seg, dt, off = one
                    pending.append(
                        dispatch_one(seg, dt, off, dispatched[0]))
                    live_add(1)
                    dispatched[0] += 1
                    self.stats.segments += 1
                    self.stats.samples += n_samples_per_seg

        requeue_counts: dict[int, int] = {}

        def watchdog_wait() -> bool:
            """Segment watchdog: poll the oldest in-flight segment's
            readiness up to the deadline, measured from when the
            engine starts WAITING on it here (becoming the drain
            head) — not from its dispatch: with a deep window or a
            micro-batch, a segment healthily queues behind earlier
            in-flight work for several compute times, and charging
            that queue wait against the deadline would fire spurious
            requeues (and eventually escalate) on a perfectly healthy
            device.  On expiry, cancel it (drop the device handles —
            JAX cannot abort an enqueued program, but the results are
            never read) and re-dispatch from the retained host
            buffer, up to ``segment_watchdog_requeues`` times, then
            escalate.  Every requeue is accounted
            (``watchdog_requeues``).  Returns False when the sink
            died while waiting."""
            item = pending[0]
            waited_since = time.perf_counter()
            while not self._result_ready(item[2]):
                if not sink_alive() or stop.stop_requested:
                    return False
                if time.perf_counter() - waited_since >= deadline_s:
                    index = item[6]
                    used = requeue_counts.get(index, 0)
                    tid = getattr(item[0], "trace_id", 0)
                    if used >= watchdog_max:
                        events.emit("watchdog.escalate", trace=tid,
                                    stream=self.stream, seg=index,
                                    info=f"requeues={used}")
                        self._incident(
                            "watchdog_escalation", trace=tid,
                            reason=f"segment {index} wedged through "
                                   f"{used} requeue(s)")
                        raise WatchdogEscalation(
                            f"segment {index} fetch still not ready "
                            f"after {deadline_s:g}s at the drain head "
                            f"and {used} requeue(s): device wedged")
                    requeue_counts[index] = used + 1
                    metrics.add("watchdog_requeues")
                    events.emit("watchdog.requeue", trace=tid,
                                stream=self.stream, seg=index,
                                info=f"attempt={used + 1}")
                    log.warning(
                        f"[watchdog] segment {index} in-flight past "
                        f"{deadline_s:g}s (fetch never ready): "
                        f"cancelling and re-dispatching "
                        f"({used + 1}/{watchdog_max})")
                    seg, _wf, _det, offset_after, span, _t0, _i = item
                    # ring: the wedged device may never materialize the
                    # in-flight carry chain — invalidate so the next
                    # FRESH dispatch goes cold too, and re-dispatch
                    # this segment cold + carry-isolated from its
                    # retained full host buffer (bit-identical)
                    self._ring_invalidate()
                    # healed re-dispatch: a requeue onto a faulty plan
                    # (the wedge WAS an OOM in disguise, or the probe
                    # plan broke) demotes and retries instead of
                    # re-wedging through the whole requeue budget
                    item = dispatch_one(seg, span["ingest"],
                                        offset_after, index,
                                        requeue=True)
                    pending[0] = item
                    waited_since = time.perf_counter()
                else:
                    time.sleep(min(0.005, deadline_s / 20))
            return True

        def drain_oldest() -> bool:
            if san is not None:
                san.assert_owner("inflight_window")
            if watchdog and not watchdog_wait():
                return False
            # journaled depths, both captured AT drain time including
            # the item being drained (a full window journals as W, not
            # a perpetual W-1): queue_depth = dispatched-not-yet-
            # fetched, inflight_depth = dispatched-through-sink (the
            # gauge's definition — fetched-but-unsunk items on the
            # sink pipe still hold device waterfalls)
            depth = len(pending)
            live_now = live_count()
            item = pending.popleft()
            while True:
                try:
                    fetched = self._fetch_inflight(item, depth,
                                                   live_now)
                    break
                except BaseException as e:  # noqa: BLE001 — classified
                    if not heal(e):
                        raise
                    # the faulted segment's device results died with
                    # the fault: re-dispatch it cold from the retained
                    # host buffer under the (possibly demoted /
                    # reinitialized) plan, then fetch again
                    seg, _wf, _det, offset_after, span, _t0, idx = item
                    item = dispatch_one(seg, span["ingest"],
                                        offset_after, idx,
                                        requeue=True)
            h = self.healer
            if h is not None:
                h.note_healthy()
            return emit(fetched)

        # watchdog state for a fully-parked window: [since, progress
        # marker] — same per-sink-push progress rule as push_sink
        parked = [None, (drained[0], self._sink_heartbeat)]

        def shed_ingest() -> bool:
            """Wedged sink with the whole window parked: keep draining
            the source (the never-stall-on-loss property) and account
            each undispatched segment as loss.  False = source done.

            The shed segment still consumes its dispatch index: a
            ``max_segments``-bounded run (soak harness, tests) must
            terminate even while shedding, and an indexed fault plan
            must keep addressing later segments — only the window
            slot and the stats/samples counters (it was never
            processed) are skipped."""
            one = ingest_one(dispatched[0])
            if one is None:
                return False
            dispatched[0] += 1
            log.error("[watchdog] sink wedged with a full in-flight "
                      "window: shedding ingested segment as accounted "
                      "loss")
            self._incident(
                "sink_wedge",
                trace=getattr(one[0], "trace_id", 0),
                reason="whole window parked behind a wedged sink; "
                       "shedding ingest as accounted loss")
            events.emit("shed.ingest",
                        trace=getattr(one[0], "trace_id", 0),
                        stream=self.stream, seg=dispatched[0] - 1)
            # never dispatched, so it holds no window slot
            shed_segment(one[0], in_flight=False)
            return True

        sink_wedged = False
        try:
            while sink_alive():
                fill_window()
                if not pending:
                    if want_more() and live_count() > 0 and sink_alive():
                        # the whole window is parked in the sink
                        # backlog: wait for the sink to free a slot —
                        # bounded by the watchdog (when armed): zero
                        # drain progress past the deadline means a
                        # wedged sink, and the source must keep
                        # draining with accounted loss, never stall
                        sink_wait[0] = True
                        if watchdog and real_time:
                            now = time.perf_counter()
                            cur = (drained[0], self._sink_heartbeat)
                            if parked[0] is None or cur != parked[1]:
                                parked[0], parked[1] = now, cur
                            elif now - parked[0] > deadline_s:
                                if not shed_ingest():
                                    break
                                continue
                        time.sleep(0.002)
                        continue
                    break
                parked[0] = None
                # non-blocking drain: everything already materialized
                # goes straight to the sink side, in order
                while pending and sink_alive() \
                        and self._result_ready(pending[0][2]):
                    if not drain_oldest():
                        break
                if not pending:
                    continue
                # window too full to admit the next dispatch unit (or
                # source done): block on the oldest — the in-order
                # point where overlap is actually earned
                if live_count() + cur_unit() > window \
                        or not want_more():
                    if not drain_oldest():
                        break
            while pending and sink_alive():
                if not drain_oldest():
                    break
        finally:
            if sink_pipe is not None:
                # bounded sentinel push: a sink wedged with a full
                # queue can never accept the sentinel — give up after
                # the join budget instead of hanging shutdown on it
                join_s = float(getattr(cfg, "shutdown_join_timeout_s",
                                       0) or 0)
                t_sent = time.perf_counter()
                while not q_sink.push_lossy(fw.SENTINEL):
                    if not sink_alive() or stop.stop_requested:
                        break
                    if join_s > 0 and \
                            time.perf_counter() - t_sent > join_s:
                        break
                    time.sleep(0.002)
                # bounded join: the sink may legitimately be flushing
                # a multi-GB waterfall (hence a generous default), but
                # a *wedged* pipe must not hang shutdown forever — on
                # expiry the thread is reported (name + stack) via
                # utils.termination and shutdown proceeds (it is a
                # daemon thread).  A *crashed* sink thread has already
                # exited, so this returns immediately in every failure
                # path.  0 keeps the legacy wait-forever behavior.
                sink_pipe.join(join_s if join_s > 0 else None)
                if sink_pipe.thread.is_alive():
                    sink_wedged = True
                    self._incident(
                        "sink_wedge_shutdown",
                        reason=f"sink pipe still alive after the "
                               f"{join_s:g}s shutdown join budget")
                    # flagged HERE, inside the finally: an exception
                    # escaping run() (fatal fault, watchdog
                    # escalation) still reaches close(), which must
                    # skip the wedged pool's drain or shutdown hangs
                    # on the very writes the bounded join gave up on
                    self._sink_wedged = True
                    from srtb_tpu.utils import termination
                    termination.report_wedged(
                        [sink_pipe.thread],
                        f"pipeline shutdown ({join_s:g}s join timeout)")
                    # items still parked on the sink queue will never
                    # reach a sink: account them as dropped (not
                    # silent loss) and return their host buffers
                    while True:
                        leftover = q_sink.try_pop()
                        if leftover is None:
                            break
                        if leftover is fw.SENTINEL:
                            continue
                        shed_segment(leftover[0], in_flight=True)
                    # the item the wedged worker holds mid-drain is
                    # loss too if it never reached accounting
                    # (sink_f's finally never runs): count it, or it
                    # vanishes — dispatched but neither journaled nor
                    # dropped.  Same already-accounted rule as the
                    # supervisor replay; its host buffer stays with
                    # the wedged thread, never back to the pool.  The
                    # "abandoned" marker in its sinks-done set hands
                    # the accounting over: should the worker unwedge
                    # during teardown and finish the drain, it must
                    # not ALSO journal/count the segment (and sink_f's
                    # finally must not re-release the live slot).
                    held = current[0]
                    if held is not None and held is not fw.SENTINEL:
                        # atomic with _drain_body's accounted/abandoned
                        # decision (self._handoff_lock): a worker
                        # unwedging at exactly this moment either
                        # claims the drain count first (drained moves
                        # past progress — no abandonment here) or sees
                        # the marker and skips its own accounting —
                        # never both
                        with self._handoff_lock:
                            if drained[0] == progress[0]:
                                held[-1].add("abandoned")
                                self._account_dropped(
                                    trace=getattr(held[0], "trace_id",
                                                  0))
                                live_add(-1)
                    log.error("[pipeline] wedged sink: still-queued "
                              "segments accounted as segments_dropped")
                stop.request_stop()
            metrics.set("inflight_depth", 0)
            # drop the carry's device buffer at run end (a retained
            # reserved-tail array would pin HBM between runs)
            self._ring_invalidate()
            if self.profile_capture is not None:
                # a run shorter than N segments (or one that raised)
                # still flushes a valid trace + sidecar
                self.profile_capture.stop()
        if sink_pipe is not None and sink_pipe.exception is not None:
            raise sink_pipe.exception
        if sink_wedged:
            # the bounded join already gave up on the wedged sink —
            # draining its writer pools would block on the very writes
            # that are stuck, hanging shutdown after promising not to
            # (self._sink_wedged was flagged in the finally above)
            log.error("[pipeline] skipping sink drain: sink pipe "
                      "wedged (queued async writes were NOT flushed)")
        else:
            self._drain_sinks()
        self.stats.elapsed_s = time.perf_counter() - start
        self.stats.extras["stages"] = self.stage_timer.summary()
        self._perf_ledger_record()
        log.info(f"[pipeline] {self.stats.segments} segments, "
                 f"{self.stats.msamples_per_sec:.1f} Msamples/s")
        return self.stats

    def _perf_ledger_record(self) -> None:
        """One "steady" perf-ledger record per finished run
        (Config.perf_ledger_path; off by default) — steady-state runs
        feed the same queryable trajectory bench rounds do."""
        if getattr(self.cfg, "perf_ledger_path", ""):
            from srtb_tpu.utils import perf_ledger as PL
            PL.record_steady_state(self.cfg, self.stats,
                                   self.processor)

    def _sanitize_check(self, wf, det_res) -> None:
        """Per-segment sanitizer checks at the drain boundary: NaN/Inf
        tripwires plus the stacked-(re, im) waterfall contract."""
        from srtb_tpu.analysis import sanitizer as S
        S.check_finite("detect result", det_res)
        if wf is not None:
            S.check_contract("drained waterfall", wf, ndim=4, lead=2,
                             dtype=np.float32)
            S.check_finite("drained waterfall", wf)

    # overridable for tests; the default aborts through the installed
    # signal/termination handlers for a loud stacktrace (the reference's
    # fail-fast philosophy, ref: util/termination_handler.hpp:38-113)
    def _push_sinks(self, seg, wf, det_res, positive,
                    degrade_level: int = 0,
                    done: set | None = None,
                    seg_key: tuple | None = None) -> None:
        """Push to every sink, handing the waterfall only to sinks
        entitled to it: all of them under ``keep_waterfall``, else only
        sinks declaring ``wants_waterfall`` (a lossy GUI tap must not
        make every OTHER sink — e.g. the candidate writer, which dumps
        a multi-GB .npy per positive segment — start seeing
        waterfalls the plan chose not to keep).

        Degradation ladder: at level >= 1 the waterfall is withheld
        from every sink (the multi-GB dumps and GUI frames go first);
        at level >= 2 sinks marked ``sheddable`` (the candidate /
        baseband writers) are skipped entirely.  Both sheds are
        counted — degraded output must be visible on /metrics, never
        silent.

        ``done`` (when given) records the indices of sinks that
        already received this segment, and completed ones are skipped
        on re-entry: a retried or replayed push is exactly-once per
        sink, never a duplicate — an in-place appender
        (``WriteAllSink``) would otherwise corrupt its stream.

        ``seg_key`` is the durable half of the same guarantee: the
        ``(data_stream_id, drain index)`` the run manifest keys on.
        A sink whose group the manifest recovered as committed is
        skipped entirely (``replayed_skips`` — the in-memory done-set
        died with the crashed process, the manifest did not); every
        completed push seals a durable ``done`` record, and the sink
        logs intent/commit per artifact in between (io/manifest.py)."""
        if degrade_level >= 1 and wf is not None:
            wf = None
            # the "wf" marker in ``done`` (sink entries are ints, no
            # collision) keeps the counter exactly-once when a retried
            # or replayed push re-enters with the original waterfall
            if done is None or "wf" not in done:
                metrics.add("shed_waterfalls")
                if self._stream_labels is not None:
                    metrics.add("shed_waterfalls",
                                labels=self._stream_labels)
                if done is not None:
                    done.add("wf")
        full = SegmentResultWork(segment=seg, waterfall=wf,
                                 detect=det_res)
        light = full if self.keep_waterfall else SegmentResultWork(
            segment=seg, waterfall=None, detect=det_res)
        m = self.manifest
        canary = getattr(seg, "canary", None) is not None
        for i, sink in enumerate(self.sinks):
            if done is not None and i in done:
                continue
            if canary and not getattr(sink, "canary_exempt", False):
                # quarantine: results derived from the injected bytes
                # (the waterfall, the detect series) must never become
                # science artifacts — not even through the candidate
                # writer's negative piggybank.  Only sinks declaring
                # ``canary_exempt`` still receive the segment: the
                # contiguous baseband appender (WriteAllSink) sees the
                # PRISTINE seg.data and must keep its byte-stream
                # continuity (skipping it would corrupt the output,
                # not protect it).
                if done is not None:
                    done.add(i)
                continue
            key = None
            if m is not None and seg_key is not None:
                key = (seg_key[0], seg_key[1], self._sink_names[i])
                if m.is_done(key):
                    # committed by a previous life of this run: the
                    # crash landed between this sink's commit and the
                    # covering checkpoint, and replaying the push
                    # would duplicate the artifacts under fresh names
                    metrics.add("replayed_skips")
                    log.info(f"[manifest] segment {seg_key[1]} sink "
                             f"{self._sink_names[i]}: already "
                             "committed, skipping replay")
                    if done is not None:
                        done.add(i)
                    continue
            if degrade_level >= 2 and getattr(sink, "sheddable", False):
                metrics.add("shed_baseband")
                if self._stream_labels is not None:
                    metrics.add("shed_baseband",
                                labels=self._stream_labels)
                if done is not None:
                    done.add(i)
                continue
            if key is not None:
                setk = getattr(sink, "set_manifest_key", None)
                if setk is not None:
                    setk(key)
            give = self.keep_waterfall or getattr(
                sink, "wants_waterfall", False)
            sink.push(full if give else light, positive)
            if key is not None and getattr(sink, "last_push_wrote",
                                           True):
                # empty pushes skip the durable done record: a
                # replayed negative segment recomputes the same
                # decision and writes nothing — nothing to protect,
                # and the common all-negative observation keeps its
                # WAL to one record per segment
                m.sink_done(key)
            self._sink_heartbeat += 1
            if done is not None:
                done.add(i)

    def _on_segment_deadline(self) -> None:  # pragma: no cover - aborts
        _abort_on_deadline(self.cfg.segment_deadline_s)

    def _sync_with_deadline(self, fn):
        """Run a blocking device fetch under cfg.segment_deadline_s."""
        return sync_with_deadline(self.cfg.segment_deadline_s, fn,
                                  self._on_segment_deadline)

    def _fetch_device(self, item, index: int = 0):
        """Resolve one (seg, wf, det_res, offset) drain item's device
        handles to host data, with the fail-fast deadline scoped to the
        *device fetches only*: those are what a wedged accelerator tunnel
        blocks.  Sink pushes and checkpoint flushes are host disk I/O —
        a slow-but-healthy disk flush of a multi-GB waterfall must not
        SIGABRT the observation — so they run with no timer armed.

        The detect results (a few KB) are fetched eagerly.  The waterfall
        can be multi-GB and most sinks never read it (WriteSignalSink only
        touches it for written segments), so it is wrapped in a lazy proxy
        whose eventual ``np.asarray`` still runs under the deadline.

        The timed "fetch" stage therefore covers the blocking detect
        fetch (= device completion of the whole segment program); a lazy
        waterfall transfer lands in the consuming sink's time."""
        seg, wf, det_res, offset_after, span = item
        if self.events is not None:
            events.set_current(getattr(seg, "trace_id", 0),
                               self.stream)
        with self._stage("fetch"):
            # explicit D2H (device_get) — this is the engine's one
            # sanctioned blocking fetch; implicit np.asarray here
            # would trip the sanitizer's transfer guard.  Under the
            # "fetch" fault site: device_get of the same handles is
            # idempotent, so a transient failure simply re-fetches.
            det_res = self._op(
                "fetch", index,
                lambda: self._sync_with_deadline(
                    lambda: jax.device_get(det_res)))
        span["fetch"] = self.stage_timer.last["fetch"]
        if self.events is not None:
            self.events.emit("stage.fetch",
                             trace=getattr(seg, "trace_id", 0),
                             stream=self.stream, seg=index,
                             dur=span["fetch"])
        if wf is not None and self.cfg.segment_deadline_s > 0:
            wf = _DeadlineArray(wf, self._sync_with_deadline)
        return seg, wf, det_res, offset_after, span

    def _drain_sinks(self) -> None:
        for sink in self.sinks:
            if hasattr(sink, "drain"):
                sink.drain()  # async writer pool: wait for disk

    def close(self) -> None:
        """Release runtime resources (the owned writer-pool threads).
        The pool also self-finalizes at GC, so forgetting this leaks
        nothing — but explicit close gives deterministic shutdown.
        After a bounded shutdown gave up on a wedged sink, the pool is
        abandoned instead of drained (same bounded-exit contract)."""
        if self.profile_capture is not None:
            # idempotent: a crashed threaded run may not have reached
            # its engine-side stop
            self.profile_capture.stop()
        if self._owned_writer_pool is not None:
            self._owned_writer_pool.close(drain=not self._sink_wedged)
            self._owned_writer_pool = None
        if self.manifest is not None:
            self.manifest.close()
            self.manifest = None
        if self.journal is not None:
            self.journal.close()
            self.journal = None
        dump_path = getattr(self.cfg, "events_dump_path", "")
        if dump_path and self.events is not None:
            # persist the flight recorder's view of this run (ring-
            # bounded: the LAST events_ring_size events per thread) —
            # the input of `python -m srtb_tpu.tools.trace_export`
            try:
                n = self.events.dump_jsonl(dump_path)
                log.info(f"[events] {n} flight-recorder events -> "
                         f"{dump_path}")
            except OSError as e:
                log.warning(f"[events] dump to {dump_path} failed: "
                            f"{e}")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class DMSearchPipeline:
    """Streaming DM search: every segment runs the full multi-chip
    (dm x seq)-sharded step (parallel.segment_dist) over a DM trial grid;
    per-trial summaries are appended to ``<prefix>dm_trials.jsonl`` and the
    best trial per segment is logged.  This is the capability the
    reference leaves as a TODO ("DM search list for unknown source",
    ref: config.hpp:129-132), made practical by chip-parallel trials.
    """

    def __init__(self, cfg: Config, source=None, mesh=None):
        import jax as _jax

        from srtb_tpu.parallel import mesh as M
        from srtb_tpu.parallel.segment_dist import DistSegmentProcessor

        self.cfg = cfg
        self.dm_list = list(cfg.dm_list) or [cfg.dm]
        if mesh is None:
            n_dev = len(_jax.devices()) if cfg.n_devices == 0 \
                else cfg.n_devices
            # largest dm-axis size that divides both trials and devices
            n_dm = 1
            for d in range(min(n_dev, len(self.dm_list)), 0, -1):
                if len(self.dm_list) % d == 0 and n_dev % d == 0:
                    n_dm = d
                    break
            mesh = M.make_mesh(n_dm=n_dm, n_seq=1)
        self.mesh = mesh
        self.processor = DistSegmentProcessor(cfg, mesh, self.dm_list)
        if source is None:
            source = BasebandFileReader(cfg)
        self.source = source
        self.trials_path = cfg.baseband_output_file_prefix + \
            "dm_trials.jsonl"
        self.stats = PipelineStats()

    def run(self, max_segments: int | None = None) -> PipelineStats:
        import json

        cfg = self.cfg
        start = time.perf_counter()
        # multi-controller runs: summaries are replicated, so only the
        # first process records them (all write identical content)
        write_records = jax.process_index() == 0
        with open(self.trials_path if write_records else os.devnull,
                  "a") as trials_file:
            for i, seg in enumerate(self.source):
                if max_segments is not None and i >= max_segments:
                    break
                res = self.processor.process(seg.data)
                n_dm = len(self.dm_list)
                # reduce over (stream, boxcar) axes -> per-dm quantities;
                # every device transfer runs under the fail-fast deadline
                # (a wedged tunnel blocks transfers, not just compute)
                peaks, counts, zero = sync_with_deadline(
                    cfg.segment_deadline_s,
                    lambda: (jax.device_get(res.snr_peaks),
                             jax.device_get(res.signal_counts),
                             jax.device_get(res.zero_count)))
                peaks = peaks.reshape(n_dm, -1)
                counts = counts.reshape(n_dm, -1)
                zero = zero.reshape(n_dm, -1).max(axis=-1)
                ok = zero < (cfg.signal_detect_channel_threshold
                             * cfg.spectrum_channel_count)
                fired = counts.sum(axis=-1) > 0
                # rank trials by raw peak SNR: a matched trial concentrates
                # the pulse and may trip the SK zap gate, which only means
                # "be cautious", not "not the best DM"
                best = int(np.argmax(peaks.max(axis=-1)))
                record = {
                    "segment": i,
                    "timestamp": seg.timestamp,
                    "best_dm": self.dm_list[best],
                    "best_snr": float(peaks[best].max()),
                    "dm_list": self.dm_list,
                    "peak_snr": peaks.max(axis=-1).tolist(),
                    "signal_counts": counts.sum(axis=-1).tolist(),
                    "zero_counts": zero.tolist(),
                }
                trials_file.write(json.dumps(record) + "\n")
                trials_file.flush()
                if bool((ok & fired).any()):
                    self.stats.signals += 1
                    log.info(f"[dm_search] segment {i}: best dm "
                             f"{record['best_dm']} "
                             f"snr {record['best_snr']:.1f}")
                self.stats.segments += 1
                self.stats.samples += cfg.baseband_input_count
                metrics.add("segments")
                metrics.add("samples", cfg.baseband_input_count)
                metrics.window("segments").add(1)
                metrics.window("samples").add(cfg.baseband_input_count)
                telemetry.mark_segment()  # /healthz liveness
        self.stats.elapsed_s = time.perf_counter() - start
        return self.stats


class ThreadedPipeline(Pipeline):
    """Thread-per-host-stage variant using the framework module: ingest,
    device dispatch and result draining run concurrently over bounded
    queues — the closest analog of the reference's full pipe graph, useful
    when ingest (UDP parsing, disk reads) must overlap drain (writers).
    """

    def run(self, max_segments: int | None = None) -> PipelineStats:
        # Config.sanitize arms the same run scope as Pipeline.run
        # (transfer tripwire + leaked-thread check); the per-stage
        # thread-ownership guards don't apply to this engine — every
        # stage owning its own thread IS the design here
        if self.sanitizer is None:
            return self._run_threaded(max_segments)
        with self.sanitizer.run_scope():
            return self._run_threaded(max_segments)

    def _run_threaded(self, max_segments: int | None = None) \
            -> PipelineStats:
        from srtb_tpu.pipeline import framework as fw

        cfg = self.cfg
        start_t = time.perf_counter()
        if self.profile_capture is not None:
            self.profile_capture.start()
        it = iter(self.source)
        count = [0]
        drained = [self.checkpoint.segments_done if self.checkpoint else 0]
        # same resume-continuous canary schedule as the async engine
        self._canary_base = drained[0]

        def source_f(stop_token, _):
            if max_segments is not None and count[0] >= max_segments:
                raise StopIteration
            seg = self._timed_ingest(it, count[0])
            if seg is None:
                raise StopIteration
            count[0] += 1
            # carry the ingest time AND the ingest-order index with
            # the work item: the span is assembled across three
            # threads, and every fault/retry site downstream must
            # address this segment by the same index ingest used
            return (seg, self.stage_timer.last["ingest"], count[0] - 1)

        def device_f(stop_token, item):
            from srtb_tpu.resilience.errors import LadderExhausted
            seg, ingest_dt, index = item
            h = self.healer
            if h is not None and h.promote_due():
                # promotion probe, same pacing as the async engine
                # (note_healthy is bumped by the drain thread; an
                # off-by-one-segment probe is acceptable pacing slack)
                newp = h.promote()
                if newp is not None:
                    self._swap_processor(newp)
            if self.events is not None:
                events.set_current(getattr(seg, "trace_id", 0),
                                   self.stream)
            self._canary_prepare(seg, index)
            data = self._device_bytes(seg)
            with self._stage("dispatch"):
                while True:
                    try:
                        wf, det_res = self._op(
                            "dispatch", index,
                            lambda: self.processor.process(data))
                        break
                    except BaseException as e:  # noqa: BLE001
                        # plan demotion works here exactly like the
                        # async engine: rebuild cheaper, re-dispatch
                        # the retained segment.  Device-HALT recovery
                        # does not — results already queued on q_res
                        # belong to the dead backend and this engine
                        # has no retained in-flight window to
                        # re-dispatch them from — so halts escalate
                        # (use the async engine for reinit coverage).
                        kind = h.classify(e) if h is not None else None
                        if kind is None or kind == DEVICE_HALT:
                            raise
                        newp = h.demote(e, kind)
                        if newp is None:
                            raise LadderExhausted(
                                "device fault survived every demotion "
                                f"rung: {e}") from e
                        self._swap_processor(newp)
            span = {"ingest": ingest_dt,
                    "dispatch": self.stage_timer.last["dispatch"]}
            if self.events is not None:
                self.events.emit("stage.dispatch",
                                 trace=getattr(seg, "trace_id", 0),
                                 stream=self.stream, seg=index,
                                 dur=span["dispatch"])
            self.stats.segments += 1
            self.stats.samples += cfg.baseband_input_count
            return (seg, wf, det_res,
                    getattr(self.source, "logical_offset", 0), span,
                    index)

        drain_busy = [False]

        def drain_f(stop_token, item):
            drain_busy[0] = True
            index = item[-1]
            try:
                fetched = self._fetch_device(item[:-1], index)
                if self.healer is not None:
                    # healthy-segment pacing for the promotion probe
                    # (consumed by device_f; an int bump under the GIL)
                    self.healer.note_healthy()
                return _drain_body(stop_token, fetched, index)
            finally:
                drain_busy[0] = False

        def _drain_body(stop_token, item, index):
            seg, wf, det_res, offset_after, span = item
            if self.events is not None:
                events.set_current(getattr(seg, "trace_id", 0),
                                   self.stream)
            if self.sanitizer is not None:
                self._sanitize_check(wf, det_res)
            positive = has_signal(
                cfg, det_res,
                frequency_bin_count=(wf.shape[-2] if wf is not None
                                     else None))
            done = set()  # retries stay exactly-once per sink
            cmark = getattr(seg, "canary", None)
            if cmark is not None:
                # same quarantine as the async engine's _drain_body
                positive = self._canary_drain(seg, cmark, det_res,
                                              done, drained[0])
            if positive:
                self.stats.signals += 1
            # ingest-order index for the fault/retry sites (the drain
            # counter below stays the journal's resume-continuous
            # numbering, same split as the async engine)
            seg_index = index
            mkey = (None if self.manifest is None
                    else (getattr(seg, "data_stream_id", 0),
                          drained[0]))
            with self._stage("sink"):
                self._op("sink_write", seg_index,
                         lambda: self._push_sinks(seg, wf, det_res,
                                                  positive, done=done,
                                                  seg_key=mkey))
            span["sink"] = self.stage_timer.last["sink"]
            if self.events is not None:
                self.events.emit("stage.sink",
                                 trace=getattr(seg, "trace_id", 0),
                                 stream=self.stream, seg=index,
                                 dur=span["sink"],
                                 info="dump" if positive else "")
            pool = getattr(self.source, "pool", None)
            if pool is not None and cfg.input_file_path:
                pool.release(seg.data)
            drained[0] += 1
            # +1: the item being drained was already popped from q_res,
            # so qsize() alone would understate the in-flight depth
            self._record_segment(drained[0] - 1, seg, det_res, positive,
                                 span, queue_depth=q_res.qsize() + 1,
                                 n_samples=cfg.baseband_input_count)
            if self.checkpoint is not None:
                self._op("checkpoint", seg_index,
                         lambda: (self._drain_sinks(),
                                  self.checkpoint.update(drained[0],
                                                         offset_after)))
            return None

        stop = fw.StopToken()
        q_seg = fw.WorkQueue()
        q_res = fw.WorkQueue()
        pipes = [
            fw.start_pipe(source_f, None, q_seg, stop, "source"),
            fw.start_pipe(device_f, q_seg, q_res, stop, "device"),
            fw.start_pipe(drain_f, q_res, None, stop, "drain"),
        ]
        # wait for the drain pipe to see the sentinel.  This is the
        # COMPLETION wait — it lasts the whole observation, so it must
        # not itself be bounded by shutdown_join_timeout_s (that would
        # silently truncate any healthy run longer than the timeout).
        # The bound applies only to a WEDGE: the drain worker busy on
        # one item with zero per-sink-push progress (the heartbeat,
        # same rule as the async engine) for the whole budget.  An
        # idle drain waiting on a quiet source is healthy and waits
        # forever; a crashed source/device pipe propagates a sentinel
        # from its finally, so the drain still exits.
        join_s = float(getattr(cfg, "shutdown_join_timeout_s", 0) or 0)
        if join_s <= 0:
            pipes[2].join(None)
        else:
            last = (drained[0], self._sink_heartbeat)
            t0 = time.perf_counter()
            while not pipes[2].join(min(0.1, join_s / 10)):
                cur = (drained[0], self._sink_heartbeat)
                if not drain_busy[0] or cur != last:
                    last, t0 = cur, time.perf_counter()
                elif time.perf_counter() - t0 > join_s:
                    break
        wedged = fw.on_exit(stop, pipes)
        if pipes[2] in wedged:
            # same contract as the async engine: the wedged DRAIN
            # pipe's writer pools would block the final drain on the
            # stuck writes.  Only the drain pipe owns sink/writer
            # work — a wedged source or device (on_exit reported it)
            # must not cost the healthy sink side its final flush.
            # Flagged BEFORE the exception re-raise below so close()
            # still skips the wedged pool's drain when another pipe
            # crashed the run.
            self._sink_wedged = True
            log.error("[pipeline threaded] skipping sink drain: "
                      f"{[p.name for p in wedged]} wedged (queued "
                      "async writes were NOT flushed)")
        for p in pipes:
            if p.exception is not None:
                raise p.exception
        if not self._sink_wedged:
            self._drain_sinks()
        if self.profile_capture is not None:
            self.profile_capture.stop()
        self.stats.elapsed_s = time.perf_counter() - start_t
        self.stats.extras["stages"] = self.stage_timer.summary()
        self._perf_ledger_record()
        log.info(f"[pipeline threaded] {self.stats.segments} segments, "
                 f"{self.stats.msamples_per_sec:.1f} Msamples/s")
        return self.stats
