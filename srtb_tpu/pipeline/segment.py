"""The fused segment processor.

The reference runs one OS thread per pipeline stage with bounded queues so
GPU kernels of consecutive segments overlap (ref: pipeline/framework/
pipe.hpp, src/main.cpp:125-272).  On TPU the idiomatic equivalent is a
**single jitted function for the whole device chain** — XLA fuses the
elementwise stages into the FFTs' epilogues and overlaps host transfers
with compute via async dispatch; the host-side stage structure survives
only around the device (reader -> processor -> writers).

Device chain (ref call stack: SURVEY.md §3.2):

  unpack (+window) -> R2C FFT (drop Nyquist) -> RFI s1 (avg-zap +
  normalize + manual zap) -> chirp multiply -> waterfall backward C2C ->
  RFI s2 (spectral kurtosis) -> signal detect (boxcar cascade)

Everything is batched over data streams (polarizations): shape [S, ...].
"""

from __future__ import annotations

import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from srtb_tpu.config import Config
from srtb_tpu.io import formats
from srtb_tpu.ops import dedisperse as dd
from srtb_tpu.ops import detect as det
from srtb_tpu.ops import fft as F
from srtb_tpu.ops import rfi
from srtb_tpu.ops import unpack as U
from srtb_tpu.ops import window as W
from srtb_tpu.utils.logging import log


def unpack_streams(raw: jnp.ndarray, variant: str, nbits: int,
                   window: jnp.ndarray | None) -> jnp.ndarray:
    """Dispatch to the right unpack kernel and stack the resulting data
    streams into [S, n] (ref dispatch: unpack_pipe.hpp:46-136, 392-413)."""
    if variant == "simple":
        return U.unpack(raw, nbits, window)[None, :]
    if variant == "interleaved_samples_2":
        return jnp.stack(U.unpack_interleaved_2pol(raw, nbits, window))
    if variant == "naocpsr_snap1":
        return jnp.stack(U.unpack_naocpsr_snap1(raw, nbits, window))
    if variant == "gznupsr_a1":
        return jnp.stack(U.unpack_gznupsr_a1(raw, window))
    if variant == "gznupsr_a1_v2_1":
        return jnp.stack(U.unpack_gznupsr_a1_v2_1(raw, window))
    raise ValueError(f"unknown unpack variant {variant!r}")


# Segments at or above this sample count execute as three XLA programs
# instead of one fused program: a 2^30-sample segment's fused graph needs
# > 16 GB of HBM scratch on a v5e even with the four-step FFT (the two
# transposes + batched FFTs + Hermitian combine all overlap in one
# program's lifetime), while the staged plan frees each program's
# temporaries before the next starts and never materializes a chirp bank.
STAGED_MIN_N = 1 << 30

# Largest n_spectrum at which fused_tail="auto" turns fusion on for the
# BANKLESS plans (staged / use_pallas), whose epilogue generates the
# df64 chirp in-trace.  The anchored-Taylor evaluation is per-anchor
# cheap, but its per-element update still runs through ops/df64's
# EFT optimization_barriers, which block XLA fusion — a handful of
# spectrum-sized f32 intermediates materialize (~2 GB each at
# n_spectrum = 2^29).  Harmless through 2^27 (n = 2^28), an unproven
# peak-HBM risk at the 2^30 staged scale until a real-chip run retires
# it (tools_tpu_r6_queue.sh staged_fused_on_30 forces it with
# fused_tail="on", which overrides this gate).  Bank plans are exempt:
# their chirp rides the precombined (c, cw) banks, no in-trace df64.
FUSED_TAIL_DF64_MAX_SPECTRUM = 1 << 27


# ---- pure-config plan-resolution predicates.  Single home shared by
# the SegmentProcessor resolvers below AND the demotion ladder's
# no-op-rung detection (resilience/demote.py): the ladder must skip a
# rung exactly when the feature would not resolve ON, and a hand-
# maintained mirror of these rules would silently drift.


def staged_resolves(cfg, staged: bool | None = None) -> bool:
    """Resolution of the staged-plan flag from config alone (the
    constructor's default when no explicit override is given) — the
    single home of the size rule, shared by the demotion ladder's
    rung predicates (pipeline/registry.py) and the fleet's pre-build
    lane validation."""
    if staged is not None:
        return staged
    return int(getattr(cfg, "baseband_input_count", 0) or 0) \
        >= STAGED_MIN_N


def ring_usable(cfg) -> bool:
    """Whether overlap-save reserves a non-empty, byte-aligned tail
    strictly smaller than the segment — the structural precondition of
    the ingest ring, independent of the ``ingest_ring`` mode knob."""
    from srtb_tpu.io import formats as _formats
    fmt = _formats.resolve(cfg.baseband_format_type)
    bits = abs(int(cfg.baseband_input_bits))
    nres = int(dd.nsamps_reserved(cfg))
    reserved = nres * bits // 8 * fmt.data_stream_count
    seg = cfg.segment_bytes(fmt.data_stream_count)
    return nres > 0 and (nres * bits) % 8 == 0 and 0 < reserved < seg


def fused_tail_resolves(cfg, staged: bool) -> bool:
    """Resolution of ``Config.fused_tail`` ("auto"/"on"/"off") for a
    plan with the given resolved ``staged`` flag (see
    SegmentProcessor._resolve_fused_tail for the rationale of each
    branch).  Raises on "on" with a monolithic, non-staged plan."""
    mode = str(getattr(cfg, "fused_tail", "auto")).lower()
    if mode not in ("auto", "on", "off"):
        raise ValueError(f"fused_tail must be auto/on/off, got {mode!r}")
    if mode == "off":
        return False
    n = int(cfg.baseband_input_count)
    hostable = staged or F.resolve_strategy(
        n, cfg.fft_strategy) != "monolithic"
    if mode == "on":
        if not hostable:
            raise ValueError(
                "fused_tail=on requires a non-monolithic "
                "fft_strategy (the XLA R2C custom call cannot host "
                "the RFI/chirp epilogue)")
        return True
    if not hostable:
        return False
    bankless = staged or getattr(cfg, "use_pallas", False)
    return not (bankless and n // 2 > FUSED_TAIL_DF64_MAX_SPECTRUM)


def _front_fuse_structural(cfg, staged: bool) -> bool:
    """Whether the front-fused staged megakernel (``staged_ffuse``,
    ops/pallas_fft2 pass1_front/pass2_spectrum) is structurally
    possible for this config: the staged plan with pallas2 rows, a
    fusable tail, an unpack variant the kernel spells in-register, and
    a factorizable transform length.  Platform/probe gating lives in
    :func:`front_fuse_resolves`."""
    if not staged:
        return False
    impl = os.environ.get("SRTB_STAGED_ROWS_IMPL", "xla")
    if impl not in ("pallas2", "pallas2_interpret"):
        return False
    if int(os.environ.get("SRTB_STAGED_BLOCKED", "0")):
        # the blocked-plane staged pack is a different front entirely
        return False
    from srtb_tpu.io import formats as _formats
    from srtb_tpu.ops import pallas_fft2 as pf2
    fmt = _formats.resolve(cfg.baseband_format_type)
    bits = int(cfg.baseband_input_bits)
    if bits not in pf2.FFUSE_VARIANT_BITS.get(fmt.unpack_variant, ()):
        return False
    if not fused_tail_resolves(cfg, staged):
        # the pass-2 epilogue IS the fused tail; without it there is
        # nothing to emit the dedispersed spectrum from
        return False
    return pf2.ffuse_factor(int(cfg.baseband_input_count) // 2) \
        is not None


def front_fuse_resolves(cfg, staged: bool) -> bool:
    """Resolution of ``Config.front_fuse`` ("auto"/"on"/"off") for a
    plan with the given resolved ``staged`` flag — the single home
    shared by the SegmentProcessor resolver and the demotion ladder's
    front_fuse rung (pipeline/registry.py).  "auto" additionally gates
    on the kernels being trusted (the FFUSE_MOSAIC_OK probe flag or
    SRTB_PALLAS_FFUSE=1 — never implicitly, so existing pallas2
    configs keep their plan); "on" forces past that gate (the
    ffuse family / hardware-probe spelling) but raises when the
    fusion is structurally impossible."""
    mode = str(getattr(cfg, "front_fuse", "auto")).lower()
    if mode not in ("auto", "on", "off"):
        raise ValueError(f"front_fuse must be auto/on/off, got {mode!r}")
    if mode == "off":
        return False
    ok = _front_fuse_structural(cfg, staged)
    if mode == "on":
        if not ok:
            raise ValueError(
                "front_fuse=on requires the staged plan with "
                "SRTB_STAGED_ROWS_IMPL=pallas2, a fusable tail "
                "(fused_tail != off, non-monolithic), a simple "
                "1/2/4/8-bit or 2-pol byte-interleaved format, and a "
                "pallas2-factorizable length")
        return True
    if not ok:
        return False
    from srtb_tpu.ops import pallas_fft2 as pf2
    return pf2.ffuse_enabled()


class SegmentProcessor:
    """Builds and owns the jitted per-segment device function plus its
    precomputed constants (chirp, window, RFI mask, normalization).

    Execution plans:
    - **fused** (default): the whole device chain is one jitted program.
    - **staged** (n >= STAGED_MIN_N, or ``staged=True``): three jitted
      programs — (a) unpack + pack + four-step first half, (b) four-step
      second half + Hermitian post-process, (c) RFI + in-step df64 chirp
      + waterfall + detect.  Boundaries are stacked (re, im) float32 in
      the CANONICAL shape [2, S, channel_count, watfft_len]: XLA only
      honors ``donate_argnums`` when an output aval exactly matches the
      donated input's aval, so every stage boundary (and the waterfall
      output) shares one aval — stage (b) and (c) genuinely alias their
      donated inputs instead of silently dropping the donation (the
      pre-canonical shapes [2, S, n2, n1] -> [2, S, m] never matched
      and XLA warned "donated buffers were not usable" on every staged
      dispatch).  The reshapes ride the producing/consuming kernels.
      ``python -m srtb_tpu.tools.plan_audit`` proves the aliasing
      statically per plan.
    """

    # registered search mode this class implements
    # (pipeline/registry.py): subclasses adding a search capability
    # override it, and it stamps plan_signature/plan_cache_key so
    # plans of different modes can never share an AOT entry or a
    # fleet plan-cache slot
    MODE = "single_pulse"

    def __init__(self, cfg: Config, window_name: str = W.DEFAULT_WINDOW,
                 compute_chirp_on_device: bool | None = None,
                 staged: bool | None = None,
                 donate_input: bool = False):
        self.cfg = cfg
        self.fmt = formats.resolve(cfg.baseband_format_type)
        n = cfg.baseband_input_count
        if n & (n - 1):
            raise ValueError("baseband_input_count must be a power of 2")
        self.n = n
        self.n_spectrum = n // 2  # after R2C + drop-Nyquist
        self.channel_count = min(cfg.spectrum_channel_count, self.n_spectrum)
        self.watfft_len = self.n_spectrum // self.channel_count

        # ---- precomputed constants ----
        self._window_name = window_name  # enters plan_signature: the
        # window is a captured constant of the traced programs
        win = W.window_coefficients(window_name, n)
        self.window = None if win is None else jnp.asarray(win)
        # Simple-format sub-byte segments take the fused blocked-plane
        # R2C (ops/fft.rfft_subbyte) on the non-monolithic strategies:
        # unpack + pack + FFT with no sample-order interleave anywhere —
        # the sample-order composition materializes a [bytes, count]
        # layout that pads 32x on TPU.  Independent of use_pallas: the
        # Pallas unpack kernel (sample order) only serves the monolithic
        # route, which fuses it away.
        self._blocked_subbyte = (
            self.fmt.unpack_variant == "simple"
            and cfg.baseband_input_bits in (1, 2, 4))
        self.window_planes = None
        if self._blocked_subbyte and win is not None:
            self.window_planes = jnp.asarray(F.subbyte_window_planes(
                win, cfg.baseband_input_bits))
        # watfft-length window to divide out of the dynamic spectrum after
        # the backward C2C (ref: fft_pipe.hpp:346-359); zero edges already
        # sanitized to 1 by dewindow_coefficients
        wat_win = W.dewindow_coefficients(window_name, self.watfft_len)
        self.watfft_dewindow = None if wat_win is None \
            else jnp.asarray(wat_win)

        f_min, f_c, df = dd.spectrum_frequencies(cfg, self.n_spectrum)
        self.f_min, self.f_c, self.df = f_min, f_c, df
        self.staged = (self.n >= STAGED_MIN_N) if staged is None else staged
        # fused spectrum tail (Config.fused_tail): RFI s1 + chirp fold
        # into the forward FFT's final pass; resolved once so the plan,
        # its signature, and the hbm_passes model can never disagree
        self.fused_tail = self._resolve_fused_tail()
        # front-fused staged megakernel (Config.front_fuse, the
        # staged_ffuse family): unpack + window + even/odd pack +
        # FFT pass 1 fold into the pallas2 pass-1 kernel (raw bytes
        # in, blocked intermediate out) and the Hermitian + RFI-s1 +
        # chirp tail into pass 2's epilogue
        self.front_fuse = front_fuse_resolves(cfg, self.staged)
        # the chirp crosses the host->device boundary as stacked (re, im)
        # float32 [2, n]: some TPU runtimes can't transfer complex buffers,
        # and split re/im is the natural VPU layout anyway; complex exists
        # only inside jit.  The staged plan never materializes a bank —
        # at n = 2^30 it would occupy 4 GB of HBM for the segment's whole
        # lifetime — and instead computes the df64 chirp inside stage (c).
        self.chirp_w = None  # chirp·twiddle precombined bank (fused tail)
        if self.staged or cfg.use_pallas:
            # staged and Pallas plans compute the chirp in-step; a
            # precomputed bank would sit dead in HBM (2 GB at n = 2^29)
            self.chirp = None
        else:
            if compute_chirp_on_device is None:
                compute_chirp_on_device = cfg.use_emulated_fp64
            if compute_chirp_on_device:
                self.chirp = jax.jit(
                    lambda: dd.chirp_factor_df64_ri(
                        self.n_spectrum, f_min, df, f_c, cfg.dm,
                        exact=getattr(cfg, "chirp_exact", False)))()
            else:
                self.chirp = jnp.asarray(dd.chirp_factor_host_ri(
                    self.n_spectrum, f_min, df, f_c, cfg.dm))
            if self.fused_tail:
                # chirp·twiddle precombination: cw = chirp · w folds the
                # Hermitian twiddle into the bank once, so the fused
                # final pass costs one complex mul per bin and zero
                # in-trace trig (explicit arg, not a closure capture —
                # a captured 2 GB bank would bake into the program)
                self.chirp_w = jax.jit(self._premul_bank)(self.chirp)

        mask = rfi.rfi_ranges_to_mask(
            rfi.eval_rfi_ranges(cfg.mitigate_rfi_freq_list), self.n_spectrum,
            cfg.baseband_freq_low, cfg.baseband_bandwidth)
        self.rfi_mask = None if mask is None else jnp.asarray(mask)

        self.norm_coeff = rfi.normalization_coefficient(
            self.n_spectrum, self.channel_count)

        self.nsamps_reserved = dd.nsamps_reserved(cfg)
        # trim of the waterfall time axis (ref: signal_detect_pipe.hpp:289-299)
        self.time_reserved_count = self.nsamps_reserved // self.channel_count

        # ---- incremental H2D overlap-save ring (Config.ingest_ring) ----
        # Overlap-save re-processes the reserved tail of every segment,
        # so a full-segment upload re-transmits bytes that are already
        # device-resident from one segment ago.  The ring keeps that
        # tail on the device as a raw-byte CARRY: each warm dispatch
        # uploads only the stride's new bytes and a jitted assemble step
        # concatenates carry ++ new into the full segment while emitting
        # the next carry with an IDENTICAL aval (uint8[reserved_bytes]
        # in -> uint8[reserved_bytes] out) — XLA only honors donation on
        # an exact aval match (the PR 7 lesson), so the carry donation
        # is a *proven* input->output alias, checked per plan by the
        # plan-audit gate (analysis/hlo_audit.py ring families).
        self._segment_bytes = cfg.segment_bytes(self.fmt.data_stream_count)
        self.reserved_bytes = int(
            self.nsamps_reserved * abs(cfg.baseband_input_bits) // 8
            * self.fmt.data_stream_count)
        self.stride_bytes = self._segment_bytes - self.reserved_bytes
        self.ring = self._resolve_ring()

        # Pallas kernels need interpret mode off-TPU (CPU CI)
        from srtb_tpu.utils.platform import on_accelerator
        self._pallas_interpret = not on_accelerator()
        # fully-fused waterfall tail (pf.fft_rows_skzap_ri): C2C +
        # de-window + SK decision + zap + time series in ONE kernel —
        # requires the fused tail, both Pallas knobs, and rows that fit
        # the VMEM row-FFT window
        from srtb_tpu.ops import pallas_fft as _pf
        self._skzap = bool(
            self.fused_tail and cfg.use_pallas and cfg.use_pallas_sk
            and _pf.supported(self.watfft_len, self.channel_count))
        # modeled spectrum-sized HBM sweeps of this plan — the quantity
        # bench.py's roofline model multiplies by (PERF.md "Roofline").
        # A FLOOR in units of one spectrum-sized transfer (read or
        # write), per stage group:
        #   R2C read+write (2)
        # + RFI s1 + chirp read+write (2, folded away by the fused tail)
        # + waterfall FFT read+write (2)
        # + SK + detect re-read floor (1, folded away by the skzap
        #   kernel, whose stats/zap/time-series ride the watfft write)
        # Which kernels execute a group changes real traffic only
        # UPWARD from this floor (e.g. the unfused pallas_sk pair's zap
        # rewrite makes the SK group 2 where the floor says 1), so
        # achieved_gbps stays a lower bound for every plan; only the
        # fusions above lower the floor itself.
        self.hbm_passes = (2 + (0 if self.fused_tail else 2) + 2
                           + (0 if self._skzap else 1))
        if self.front_fuse:
            # Front-fused floor (the ISSUE-15 model): the two megakernel
            # sweeps a segment's front half cannot avoid — pass 1's
            # blocked-intermediate write (its raw-byte + window reads
            # are sub-spectrum-sized) and pass 2's intermediate re-read,
            # whose dedispersed-spectrum emission hands straight to the
            # waterfall tail.  Deliberately the most conservative floor
            # on the board: the waterfall tail's traffic rides ABOVE it
            # (like every kernel-choice cost does for the other plans),
            # so achieved_gbps / roofline_frac stay honest lower
            # bounds, and the audited per-program counts in
            # plan_cards.json pin the true structural traffic.
            self.hbm_passes = 2
        # XLA FFT row-length cap override (Config.fft_len_cap; None =
        # the ops/fft default), threaded through every FFT entry point
        self._len_cap = cfg.fft_len_cap or None
        # Input donation (async engine): every segment's raw byte array
        # is a fresh device_put the caller never reuses, so donating it
        # lets XLA recycle that HBM as program scratch — steady-state
        # streaming does no net fresh device allocation per segment.
        # Off by default: external callers (bench.py, A/B tests) legally
        # reuse one device-resident input across calls, which donation
        # would invalidate.
        self._donate_input = bool(donate_input)
        # runtime sanitizer (Config.sanitize): per-stage NaN tripwires
        # + boundary contracts + explicit expiry of donated inputs.
        # Not part of plan_signature: it changes call sequencing only,
        # never the traced programs.
        self._sanitize = bool(getattr(cfg, "sanitize", False))
        in_donate = (0,) if self._donate_input else ()
        self._jit_process = jax.jit(self._process, donate_argnums=in_donate)
        self._jit_process_batch = None  # built lazily (micro-batch mode)
        if self.staged and not self.front_fuse:
            # natural (pre-canonicalization) shape of the stage (a)
            # intermediate, recovered inside stage (b) by a fused
            # metadata reshape (abstract trace only — no compile, no run)
            expected = cfg.segment_bytes(self.fmt.data_stream_count)
            self._a_nat_shape = jax.eval_shape(
                self._stage_a_nat,
                jax.ShapeDtypeStruct((expected,), jnp.uint8)).shape
        if self.front_fuse:
            self._init_front_fuse()
        self._jit_stage_a = jax.jit(self._stage_a, donate_argnums=in_donate)
        # the staged intermediates are consumed exactly once, so stages
        # donate their inputs — and because every boundary shares the
        # canonical aval (see class docstring) the donation is a REAL
        # input->output alias, not a dropped request: the 4 GB boundary
        # array of a 2^30 segment is reused in place instead of staying
        # live across the next program's entire temp footprint (the
        # chain ResourceExhausted at runtime without it even though each
        # program compiled within budget)
        self._jit_stage_b = jax.jit(self._stage_b, donate_argnums=(0,))
        self._jit_stage_c = jax.jit(self._stage_c, donate_argnums=(0,))
        # ring plan variants.  The carry (arg 0) is ALWAYS donated: it
        # is a ring-owned intermediate consumed exactly once per step
        # (callers receive the next carry in exchange), and its output
        # twin shares the exact aval so the donation is a real alias —
        # the reserved-bytes buffer is rewritten in place every segment
        # instead of accreting one fresh HBM allocation per dispatch.
        # The stride input rides the caller's donate_input policy (it
        # can never alias an output — recorded as no_candidate).
        self._jit_ring = None
        self._jit_cold = None
        self._jit_stage_a_ring = None
        self._jit_stage_a_cold = None
        self._jit_batch_ring = None
        self._jit_batch_cold = None
        if self.ring:
            ring_donate = (0,) + ((1,) if self._donate_input else ())
            if self.staged:
                self._jit_stage_a_ring = jax.jit(
                    self._stage_a_ring, donate_argnums=ring_donate)
                self._jit_stage_a_cold = jax.jit(
                    self._stage_a_cold, donate_argnums=in_donate)
            else:
                self._jit_ring = jax.jit(self._process_ring,
                                         donate_argnums=ring_donate)
                self._jit_cold = jax.jit(self._process_cold,
                                         donate_argnums=in_donate)
        # host staging-buffer pool: when stage_input/stack_batch must
        # materialize a contiguous uint8 copy (non-contiguous or
        # non-uint8 input, micro-batch stacking), the bytes land in a
        # pooled buffer sized by the plan's segment/stride byte counts
        # instead of a fresh allocation per segment.  Buffers register
        # against the owning segment's host buffer and return to the
        # pool when the segment drains (Pipeline calls release_staging);
        # the FIFO cap self-heals callers that never release.
        from srtb_tpu.utils.bufferpool import BufferPool
        self._staging_pool = BufferPool("staging")
        self._staging_out: "dict[int, tuple]" = {}
        self._staging_cap = 2 * max(
            1, int(getattr(cfg, "inflight_segments", 2) or 1)) + 4
        # performance-observatory compile accounting (always-on): the
        # lazy-jit protocol traces+compiles inside the FIRST dispatch
        # of each program, so that call's wall clock is the live
        # compile measurement (an upper bound — it includes the first
        # execution's dispatch; the AOT protocol measures exactly in
        # aot_cache.get_or_compile instead).  Per-stream labeled twins
        # when this processor serves a named fleet lane.
        self._dispatched_programs: set[str] = set()
        self._metric_labels = ({"stream": cfg.stream_name}
                               if getattr(cfg, "stream_name", "")
                               else None)
        self.aot_active = False
        if cfg.aot_plan_path:
            if not self.enable_aot(cfg.aot_plan_path):
                # visible, not debug: the config requested warm-restart
                # protection and it did NOT activate
                log.warning(
                    "[segment] aot_plan_path set but the AOT cache is "
                    "inactive (CPU backend without SRTB_AOT_ALLOW_CPU=1)"
                    " — restarts will recompile")
        log.debug(f"[segment] n={n} spectrum={self.n_spectrum} "
                  f"channels={self.channel_count} watfft={self.watfft_len} "
                  f"reserved={self.nsamps_reserved} plan={self.plan_name} "
                  f"hbm_passes={self.hbm_passes}")

    # ------------------------------------------------------------------
    # fused spectrum tail: plan resolution + the epilogue itself

    def _resolve_fused_tail(self) -> bool:
        """Resolve Config.fused_tail ("auto"/"on"/"off") against the
        plan: the staged plan and every non-monolithic strategy end in
        the Hermitian post-process, which can host the RFI-s1 + chirp
        epilogue; the monolithic XLA R2C custom call cannot and stays
        the unfused fallback under "auto".  Under "auto", bankless
        plans (staged / use_pallas, in-trace df64 chirp) additionally
        gate on the proven size range
        (FUSED_TAIL_DF64_MAX_SPECTRUM); "on" overrides for the
        hardware experiments.  The rule itself lives in the module-
        level :func:`fused_tail_resolves` (shared with the demotion
        ladder)."""
        return fused_tail_resolves(self.cfg, self.staged)

    def _resolve_ring(self) -> bool:
        """Resolve Config.ingest_ring ("auto"/"on"/"off") against the
        plan: the ring needs a non-empty, byte-aligned reserved tail
        strictly smaller than the segment.  "auto" turns it on whenever
        overlap-save is active; "on" forces it (and errors when the
        config has nothing to carry); "off" restores full re-uploads."""
        mode = str(getattr(self.cfg, "ingest_ring", "auto")).lower()
        if mode not in ("auto", "on", "off"):
            raise ValueError(
                f"ingest_ring must be auto/on/off, got {mode!r}")
        if mode == "off":
            return False
        # the structural test is the shared module-level predicate
        # (the demotion ladder consults the same rule)
        usable = ring_usable(self.cfg)
        if mode == "on" and not usable:
            raise ValueError(
                "ingest_ring=on requires overlap-save with a byte-"
                "aligned reserved tail (baseband_reserve_sample with "
                f"0 < reserved_bytes < segment_bytes; got reserved="
                f"{self.reserved_bytes} of {self._segment_bytes})")
        return usable

    # ---- ring plan variants: carry ++ new assemble + carry emission.
    # The warm variants take (carry uint8[R], new uint8[stride]) and
    # return the plan outputs PLUS the next carry uint8[R] — the last
    # reserved_bytes of the assembled segment, emitted with the exact
    # aval of the donated carry input so XLA aliases the two buffers.
    # The cold variants take the full uint8[segment_bytes] upload and
    # also emit the carry, so a cold dispatch needs no extra H2D bytes
    # and no separate slice program to re-arm the ring.

    def _process_ring(self, carry: jnp.ndarray, new: jnp.ndarray,
                      chirp_ri: jnp.ndarray, chirp_w_ri=None):
        raw = jnp.concatenate([carry, new])
        out = self._process(raw, chirp_ri, chirp_w_ri)
        return out, raw[self.stride_bytes:]

    def _process_cold(self, raw: jnp.ndarray, chirp_ri: jnp.ndarray,
                      chirp_w_ri=None):
        return (self._process(raw, chirp_ri, chirp_w_ri),
                raw[self.stride_bytes:])

    def _stage_a_with_carry(self, raw: jnp.ndarray):
        """Shared body of the staged ring variants: stage (a) — in
        whichever spelling the plan resolved, classic or front-fused —
        plus the next carry sliced from the same assembled raw view.
        One home, so the warm/cold twins (and any future variant)
        cannot drift apart."""
        return self._stage_a(raw), raw[self.stride_bytes:]

    def _stage_a_ring(self, carry: jnp.ndarray, new: jnp.ndarray):
        return self._stage_a_with_carry(jnp.concatenate([carry, new]))

    def _stage_a_cold(self, raw: jnp.ndarray):
        return self._stage_a_with_carry(raw)

    def _process_batch_ring(self, carry: jnp.ndarray, new_b: jnp.ndarray,
                            chirp_ri: jnp.ndarray, chirp_w_ri=None):
        """Micro-batch warm step: ONE carry plus B stride uploads
        reassemble B overlapped segments (raw_i starts at i*stride of
        carry ++ new_0 ++ ... ++ new_{B-1}); the next carry is the tail
        of the whole window, aliased onto the donated carry."""
        b = new_b.shape[0]
        full = jnp.concatenate([carry, new_b.reshape(-1)])
        seg = self._segment_bytes
        raws = jnp.stack([full[i * self.stride_bytes:
                               i * self.stride_bytes + seg]
                          for i in range(b)])
        out = jax.vmap(self._process, in_axes=(0, None, None))(
            raws, chirp_ri, chirp_w_ri)
        return out, full[full.shape[0] - self.reserved_bytes:]

    def _process_batch_cold(self, raws: jnp.ndarray,
                            chirp_ri: jnp.ndarray, chirp_w_ri=None):
        out = jax.vmap(self._process, in_axes=(0, None, None))(
            raws, chirp_ri, chirp_w_ri)
        return out, raws[-1, self.stride_bytes:]

    @property
    def plan_name(self) -> str:
        """Human/bench-readable plan id: base plan + resolved strategy
        + which fusions are live (bench.py emits this per JSON line)."""
        strategy = F.resolve_strategy(self.n, self.cfg.fft_strategy)
        name = ("staged" if self.staged else "fused") + f":{strategy}"
        if self.fused_tail:
            name += "+ftail"
        if self.front_fuse:
            name += "+ffuse"
        if self._skzap:
            name += "+skzap"
        if self.ring:
            name += "+ring"
        return name

    @staticmethod
    def _premul_bank(c_ri: jnp.ndarray) -> jnp.ndarray:
        """cw = chirp · w with w the drop-Nyquist Hermitian twiddle
        exp(-2πik/n) — the chirp·twiddle precombination consumed by
        ops.fft.hermitian_rfft_post(premul=...)."""
        m = c_ri.shape[-1]
        c = jax.lax.complex(c_ri[0], c_ri[1])
        cw = c * F._iota_phase(m, 2 * m, -1.0)
        return jnp.stack([jnp.real(cw), jnp.imag(cw)])

    def _tail_epilogue(self, chirp_ri):
        """The elementwise epilogue folded into the forward FFT's final
        pass: RFI stage-1 zap (mean power via the Parseval identity over
        the FFT's own input, rfi.mean_power_packed — no spectrum
        re-read) + normalize + manual mask, then the chirp.  With a bank
        (``chirp_ri`` given) the chirp was already applied through the
        precombined (c, cw) pair inside the Hermitian assembly — the
        zap/normalize commute with the unit-modulus multiply — so only
        the zap runs here; without one the df64 chirp (anchored-Taylor
        unless Config.chirp_exact) is generated in-trace and fuses into
        the same write."""
        cfg = self.cfg

        def epilogue(zf, spec):
            mean_power = rfi.mean_power_packed(zf)
            spec = rfi.mitigate_rfi_s1_given_mean(
                spec, mean_power,
                cfg.mitigate_rfi_average_method_threshold,
                self.norm_coeff)
            spec = rfi.mitigate_rfi_manual(spec, self.rfi_mask)
            if chirp_ri is None:
                c_ri = dd.chirp_factor_df64_ri(
                    spec.shape[-1], self.f_min, self.df, self.f_c,
                    cfg.dm, exact=getattr(cfg, "chirp_exact", False))
                spec = spec * jax.lax.complex(c_ri[0], c_ri[1])
            return spec
        return epilogue

    # ------------------------------------------------------------------

    def _unpack(self, raw: jnp.ndarray) -> jnp.ndarray:
        """raw bytes -> windowed float32 samples [S, n]."""
        cfg = self.cfg
        interp = getattr(self, "_pallas_interpret", False)
        from srtb_tpu.ops import pallas_kernels as pk
        if (cfg.use_pallas and cfg.baseband_input_bits in (1, 2, 4)
                and self.fmt.unpack_variant == "simple"
                and (interp or pk.UNPACK_MOSAIC_OK)):
            return pk.unpack_subbyte_window(raw, cfg.baseband_input_bits,
                                            self.window,
                                            interpret=interp)[None, :]
        return unpack_streams(raw, self.fmt.unpack_variant,
                              cfg.baseband_input_bits, self.window)

    def _resolve_rows_impl(self, impl: str) -> str:
        """Single home of the off-TPU downgrade rule: 'pallas' runs the
        kernels in interpret mode on CPU backends.  Unknown names raise —
        a typo in SRTB_STAGED_ROWS_IMPL must not silently fall back to
        XLA while the probe log claims a Pallas result."""
        if impl not in ("xla", "four_step", "mxu", "monolithic", "auto",
                        "pallas", "pallas_interpret",
                        "pallas2", "pallas2_interpret"):
            raise ValueError(f"unknown rows impl / fft strategy {impl!r}")
        if impl in ("pallas", "pallas2") \
                and getattr(self, "_pallas_interpret", False):
            return impl + "_interpret"
        return impl

    def _process(self, raw: jnp.ndarray, chirp_ri: jnp.ndarray,
                 chirp_w_ri: jnp.ndarray = None):
        strategy = self._resolve_rows_impl(
            F.resolve_strategy(self.n, self.cfg.fft_strategy))
        epilogue = premul = None
        if self.fused_tail:
            epilogue = self._tail_epilogue(chirp_ri)
            if chirp_ri is not None:
                # bank plan: chirp·twiddle precombination inside the
                # Hermitian assembly (see _premul_bank)
                premul = (jax.lax.complex(chirp_ri[0], chirp_ri[1]),
                          jax.lax.complex(chirp_w_ri[0], chirp_w_ri[1]))
        if self._blocked_subbyte and strategy in ("four_step", "mxu",
                                                  "pallas",
                                                  "pallas_interpret",
                                                  "pallas2",
                                                  "pallas2_interpret"):
            from srtb_tpu.ops import pallas_kernels as pk
            interp = getattr(self, "_pallas_interpret", False)
            planes = None
            if self.cfg.use_pallas and pk.planes_unpack_enabled(interp) \
                    and pk.planes_tiling_ok(raw.shape[-1]):
                # fused unpack + blocked-window multiply in one HBM pass
                # (the Mosaic-lowerable blocked-plane spelling)
                planes = pk.unpack_subbyte_planes_window(
                    raw, self.cfg.baseband_input_bits,
                    self.window_planes, interpret=interp)
            spec = F.rfft_subbyte(raw, self.cfg.baseband_input_bits,
                                  strategy, self.window_planes,
                                  planes=planes,
                                  len_cap=self._len_cap,
                                  epilogue=epilogue,
                                  premul=premul)[None, :]
        else:
            x = self._unpack(raw)
            spec = F.segment_rfft(x, strategy,
                                  len_cap=self._len_cap,
                                  epilogue=epilogue,
                                  premul=premul)   # [S, n/2]
        if self.fused_tail:
            # the spectrum left the FFT already zapped/normalized/
            # masked/chirped — straight to the waterfall tail
            return self._waterfall_detect(spec)
        return self._spectrum_tail(spec, chirp_ri)

    # ---- staged plan: three programs with (re, im) f32 boundaries ----

    # The blocked-plane form inside the *staged* plan reproducibly
    # SIGSEGVs the XLA TPU compiler at the 2^30 production shape (the
    # fused blocked form through 2^28 and the classic staged form are
    # both fine) — keep the staged plan on the proven unpack+pack path
    # until that compiler crash is root-caused.  Flip for experiments
    # with SRTB_STAGED_BLOCKED=1.
    @property
    def _staged_blocked(self) -> bool:
        return self._blocked_subbyte and bool(
            int(os.environ.get("SRTB_STAGED_BLOCKED", "0")))

    @property
    def _staged_rows_impl(self) -> str:
        """Who runs the staged plan's batched leg FFTs.  Default XLA;
        SRTB_STAGED_ROWS_IMPL=pallas moves the legs to the VMEM row-FFT
        kernel — both a perf experiment and a workaround candidate for
        the XLA TPU compiler SIGSEGV on the 2^30 blocked stage_a shape
        (the crash is in XLA's handling of that batched FFT; Pallas legs
        never hand XLA an FFT op at all)."""
        return self._resolve_rows_impl(
            os.environ.get("SRTB_STAGED_ROWS_IMPL", "xla"))

    def _staged_impl(self) -> str:
        """The staged plan's leg implementation after the pallas2 window
        check: the fused two-pass form only covers leg lengths in
        [2^24, 2^29], so tiny forced-staged test configs downgrade to
        the pallas-legs four-step (same numeric contract)."""
        impl = self._staged_rows_impl
        if impl in ("pallas2", "pallas2_interpret"):
            from srtb_tpu.ops import pallas_fft2 as pf2
            count = (8 // self.cfg.baseband_input_bits
                     if self._staged_blocked else 2)
            if not pf2.supported(self.n // count):
                # loud if an explicit SRTB_PALLAS2_N1 pin caused this
                pf2.require_pin_fit(self.n // count)
                return ("pallas_interpret" if impl.endswith("interpret")
                        else "pallas")
        return impl

    def _staged_pack(self, raw: jnp.ndarray) -> jnp.ndarray:
        """unpack + pack for the staged plan: blocked field-plane pairs
        [S, p, M] (sub-byte, lane-dense by construction) or even/odd
        packed [S, m]."""
        if self._staged_blocked:
            planes = U.unpack_subbyte_planes(
                raw, self.cfg.baseband_input_bits)
            if self.window_planes is not None:
                planes = planes * self.window_planes
            return F.subbyte_planes_to_packed(planes)[None]
        return F.pack_even_odd(self._unpack(raw))

    # The staged boundary CANONICAL aval: [2, S, channel_count,
    # watfft_len] float32.  Every stage consumes and produces this exact
    # shape so XLA's aval-matching donation rule can alias each donated
    # boundary to the stage's output (see the class docstring); the
    # reshapes to/from the stages' natural working shapes are metadata
    # remappings fused into the adjacent kernels' reads/writes — the
    # plan auditor's entry-level copy count is the regression tripwire
    # should a relayout ever materialize one as a real pass.

    def _boundary_canon(self, x: jnp.ndarray) -> jnp.ndarray:
        if self.channel_count * self.watfft_len == self.n_spectrum:
            return x.reshape(2, -1, self.channel_count, self.watfft_len)
        # non-dividing channel count: the waterfall row view truncates
        # the spectrum tail (spec[..., :F*T]), so [2, S, F, T] cannot
        # hold the full boundary — fall back to the flat canonical
        # [2, S, m].  stage (b) in==out still aliases; stage (c)'s
        # donation becomes a structural no_candidate (wf is smaller),
        # which the plan card records honestly.
        return x.reshape(2, -1, self.n_spectrum)

    def _stage_a(self, raw: jnp.ndarray):
        if self.front_fuse:
            return self._stage_a_front(raw)
        return self._boundary_canon(self._stage_a_nat(raw))

    def _stage_b(self, a_ri, aux=None):
        if self.front_fuse:
            return self._stage_b_front(a_ri, aux)
        return self._boundary_canon(
            self._stage_b_nat(a_ri.reshape(self._a_nat_shape)))

    def _run_stage_b(self, a):
        """Dispatch the stage-(a) boundary into the jitted stage (b).
        The front-fused boundary is (canonical, accumulators) passed
        as TWO program arguments so only the canonical leaf is donated
        — donating the [S, 3, 128] aux (which has no output aval to
        alias) would be a dropped-donation warning on every compile."""
        if self.front_fuse:
            return self._jit_stage_b(*a)
        return self._jit_stage_b(a)

    def _stage_c(self, spec_ri: jnp.ndarray):
        x = spec_ri.reshape(2, spec_ri.shape[1], -1)
        if self.front_fuse:
            # the front-fused stage (b) emits the dedispersed spectrum
            # in pass-2's k1-major blocked order; unblock here so the
            # XLA transpose fuses into this program's first read (the
            # waterfall row view / complex assembly)
            n1, n2 = self._ffuse_fac
            x = jnp.swapaxes(x.reshape(2, x.shape[1], n1, n2),
                             -1, -2).reshape(2, x.shape[1], -1)
        return self._stage_c_nat(x)

    # ---- front-fused staged stages (the staged_ffuse plan family) ----

    def _init_front_fuse(self) -> None:
        """Precompute the front-fuse plan constants: the factorization,
        the even/odd-split blocked window view, the blocked RFI keep
        mask, and the chirp parameters of pass 2's epilogue."""
        from srtb_tpu.ops import pallas_fft2 as pf2
        self._ffuse_fac = pf2.ffuse_factor(self.n_spectrum)
        n1, n2 = self._ffuse_fac
        self._ffuse_window = None
        if self.window is not None:
            w = np.asarray(self.window)
            self._ffuse_window = (
                jnp.asarray(np.ascontiguousarray(
                    w[0::2].reshape(n1, n2))),
                jnp.asarray(np.ascontiguousarray(
                    w[1::2].reshape(n1, n2))))
        self._ffuse_mask = None
        if self.rfi_mask is not None:
            # natural [m] zap mask -> blocked [n1, n2] KEEP multiplier
            # (bin k = k2*n1 + k1 lives at [k1, k2])
            keep = 1.0 - np.asarray(self.rfi_mask, np.float32)
            self._ffuse_mask = jnp.asarray(np.ascontiguousarray(
                keep.reshape(n2, n1).T))
        self._ffuse_chirp = dict(
            f_min=float(self.f_min), df=float(self.df),
            f_c=float(self.f_c), dm=float(self.cfg.dm))

    def _stage_a_front(self, raw: jnp.ndarray):
        """Front-fused stage (a): the raw uint8 segment goes straight
        into the pass-1 megakernel (in-kernel unpack + window +
        even/odd pack + column FFT + four-step twiddle) — HBM pass 1
        is one raw-byte read + one blocked-intermediate write.  The
        boundary is (canonical intermediate, [S, 3, 128] RFI-s1
        mean-power accumulators)."""
        from srtb_tpu.ops import pallas_fft2 as pf2
        br, bi, aux = pf2.pass1_front(
            raw, m=self.n_spectrum, streams=self.fmt.data_stream_count,
            variant=self.fmt.unpack_variant,
            nbits=int(self.cfg.baseband_input_bits),
            window_eo=self._ffuse_window,
            interpret=self._pallas_interpret)
        return self._boundary_canon(jnp.stack([br, bi])), aux

    def _stage_b_front(self, a_ri, aux):
        """Front-fused stage (b): pass 2 emits the dedispersed
        spectrum directly — row FFT + in-kernel Hermitian post +
        RFI-s1 zap/normalize/mask (threshold from the pass-1
        accumulators, no spectrum-sized re-read) + the in-register
        df64 chirp, all in pass 2's epilogue.  The chirp is always the
        bankless spelling here because staged plans never materialize
        a chirp bank (see __init__: at 2^30 it would hold 4 GB of HBM
        for the segment's lifetime) and front fusion requires the
        staged plan — pass2_spectrum's premul operands exist for the
        kernel's own generality (tests, future non-staged callers).
        Output is the canonical boundary holding the blocked spectrum
        (stage (c) unblocks with a fused metadata transpose)."""
        from srtb_tpu.ops import pallas_fft2 as pf2
        n1, n2 = self._ffuse_fac
        b = a_ri.reshape(2, -1, n1, n2)
        thr = jnp.float32(
            self.cfg.mitigate_rfi_average_method_threshold) \
            * pf2.front_mean_power(aux, n2, self.n_spectrum)
        outs = []
        for s in range(b.shape[1]):
            sr, si = pf2.pass2_spectrum(
                b[0, s], b[1, s], thr=thr[s], norm=self.norm_coeff,
                mask_blocked=self._ffuse_mask,
                chirp=self._ffuse_chirp,
                interpret=self._pallas_interpret)
            outs.append((sr, si))
        spec_ri = jnp.stack([
            jnp.stack([o[0] for o in outs]),
            jnp.stack([o[1] for o in outs])])  # [2, S, n1, n2] blocked
        return self._boundary_canon(spec_ri)

    def _stage_a_nat(self, raw: jnp.ndarray):
        """unpack + even/odd pack + segment-FFT first half."""
        impl = self._staged_impl()
        z = self._staged_pack(raw)
        if impl in ("pallas2", "pallas2_interpret"):
            # fused pass 1: transpose + leg FFT + four-step twiddle in
            # ONE kernel; boundary is the [.., n1, n2] intermediate
            from srtb_tpu.ops import pallas_fft2 as pf2
            br, bi = pf2.pass1_ri(jnp.real(z), jnp.imag(z),
                                  interpret=impl.endswith("interpret"))
            return jnp.stack([br, bi])
        a = F.four_step_stage1(z, rows_impl=impl,
                               len_cap=self._len_cap)  # [..., n2, n1]
        return jnp.stack([jnp.real(a), jnp.imag(a)])

    def _stage_b_nat(self, a_ri: jnp.ndarray):
        """segment-FFT second half + Hermitian post -> spectrum [S, n/2].
        With the fused tail the RFI-s1 + df64-chirp epilogue folds into
        the Hermitian post's single write here, so stage (c) starts from
        an already-dedispersed spectrum."""
        impl = self._staged_impl()
        if impl in ("pallas2", "pallas2_interpret"):
            from srtb_tpu.ops import pallas_fft2 as pf2
            yr, yi = pf2.pass2_ri(a_ri[0], a_ri[1],
                                  interpret=impl.endswith("interpret"))
            zf = jax.lax.complex(yr, yi)
        else:
            zf = F.four_step_stage2(jax.lax.complex(a_ri[0], a_ri[1]),
                                    rows_impl=impl,
                                    len_cap=self._len_cap)
        epilogue = self._tail_epilogue(None) if self.fused_tail else None
        if self._staged_blocked:
            spec = F.finish_rfft_subbyte(zf[0], epilogue=epilogue)[None, :]
        else:
            spec = F.hermitian_rfft_post(zf, drop_nyquist=True,
                                         epilogue=epilogue)
        return jnp.stack([jnp.real(spec), jnp.imag(spec)])

    def _stage_c_nat(self, spec_ri: jnp.ndarray):
        """RFI s1 + in-step chirp + waterfall + RFI s2 + detect (the s1
        + chirp front half lives in stage (b) when the tail is fused)."""
        spec = jax.lax.complex(spec_ri[0], spec_ri[1])
        if self.fused_tail:
            return self._waterfall_detect(spec)
        return self._spectrum_tail(spec, None)

    def _spectrum_tail(self, spec: jnp.ndarray, chirp_ri):
        """Legacy (unfused-tail) device chain from the raw spectrum
        onward: RFI s1 + chirp as their own sweeps, then the waterfall
        tail.  With ``chirp_ri=None`` the df64 chirp is generated inside
        the trace (fuses into the multiply; nothing bank-sized is
        materialized)."""
        chirped, qtap = self._apply_s1_chirp(spec, chirp_ri)
        return self._waterfall_detect(chirped, qspec=qtap)

    def _apply_s1_chirp(self, spec: jnp.ndarray, chirp_ri):
        """RFI stage 1 + manual mask + chirp multiply as standalone
        spectrum sweeps (the passes the fused tail folds into the FFT's
        final write).  Returns ``(chirped, qtap)`` where ``qtap`` is
        the spectrum the quality epilogue should read bin powers from:
        the chirp is unit-modulus, so the PRE-chirp zapped/normalized
        spectrum has bin-identical power and zeros — and reading it
        keeps the (expensive, error-free-transform) df64 chirp chain
        out of the epilogue's fusion producers."""
        cfg = self.cfg
        interp = getattr(self, "_pallas_interpret", False)
        from srtb_tpu.ops import pallas_kernels as pk
        n_streams = spec.shape[0]
        if cfg.use_pallas:
            # Fully fused front half: RFI s1 zap + normalize + manual
            # mask + df64 in-register chirp in ONE HBM pass per stream
            # (the mean-power reduce stays a jnp pass).  Phase computed
            # in-register; no chirp bank exists.
            outs = []
            for s in range(n_streams):
                spec_ri = jnp.stack([jnp.real(spec[s]), jnp.imag(spec[s])])
                out_ri = pk.rfi_s1_dedisperse_df64(
                    spec_ri, cfg.mitigate_rfi_average_method_threshold,
                    self.norm_coeff, self.f_min, self.df, self.f_c,
                    cfg.dm, mask=self.rfi_mask, interpret=interp,
                    exact=getattr(cfg, "chirp_exact", False))
                outs.append(jax.lax.complex(out_ri[0], out_ri[1]))
            out = jnp.stack(outs)
            # the Pallas kernel materializes its output: reading it
            # again is one cheap pass, no producer duplication
            return out, out
        spec = rfi.mitigate_rfi_average_and_normalize(
            spec, cfg.mitigate_rfi_average_method_threshold,
            self.norm_coeff)
        spec = rfi.mitigate_rfi_manual(spec, self.rfi_mask)
        qtap = spec  # pre-chirp: bin powers/zeros identical post-chirp
        if chirp_ri is None:
            # In-step df64 chirp without Pallas (staged plan on the
            # jnp path).  The XLA df64 chirp's optimization_barriers
            # block fusion, so its ~12 error-free-transform
            # intermediates each materialize a plane (24 GB peak at
            # 2^30) — the Pallas kernel is the form that scales;
            # this branch serves CPU tests and small segments.
            outs = []
            for s in range(n_streams):
                spec_ri = jnp.stack([jnp.real(spec[s]),
                                     jnp.imag(spec[s])])
                out_ri = pk.dedisperse_df64(
                    spec_ri, self.f_min, self.df, self.f_c,
                    cfg.dm, interpret=interp,
                    exact=getattr(cfg, "chirp_exact", False))
                outs.append(jax.lax.complex(out_ri[0], out_ri[1]))
            return jnp.stack(outs), qtap
        chirp = jax.lax.complex(chirp_ri[0], chirp_ri[1])
        return dd.dedisperse(spec, chirp), qtap

    def _waterfall_detect(self, spec: jnp.ndarray, qspec=None):
        """Waterfall backward C2C + RFI stage 2 + detection from an
        already-dedispersed spectrum.  With the fully-fused skzap plan
        (fused tail + use_pallas + use_pallas_sk + VMEM-resident rows)
        the whole tail is ONE kernel per stream — the detect stage never
        re-reads the waterfall from HBM.

        ``qspec`` is the spectrum the quality epilogue reads bin powers
        from when it differs from ``spec`` (the unfused jnp path hands
        the PRE-chirp zapped/normalized spectrum — power-identical,
        and it keeps the df64 chirp chain out of the epilogue's XLA
        fusion producers, which otherwise duplicates it at ~40%
        per-segment cost on the CPU path)."""
        cfg = self.cfg
        if qspec is None:
            qspec = spec
        use_pallas = cfg.use_pallas
        interp = getattr(self, "_pallas_interpret", False)
        from srtb_tpu.ops import pallas_kernels as pk
        n_streams = spec.shape[0]
        if self._skzap:
            from srtb_tpu.ops import pallas_fft as pf
            t_len = self.watfft_len
            x = spec[..., :self.channel_count * t_len].reshape(
                n_streams, self.channel_count, t_len)
            zapped, zero_counts, ts_rows = [], [], []
            for s in range(n_streams):
                wr, wi, zapf, fs0, ts = pf.fft_rows_skzap_ri(
                    jnp.real(x[s]), jnp.imag(x[s]),
                    cfg.mitigate_rfi_spectral_kurtosis_threshold,
                    inverse=True, dewindow=self.watfft_dewindow,
                    interpret=interp)
                zapped.append(jax.lax.complex(wr, wi))
                zero_counts.append(jnp.sum(
                    ((zapf[:, 0] != 0) | (fs0[:, 0] == 0))
                    .astype(jnp.int32)))
                ts_rows.append(ts)
            wf = jnp.stack(zapped)
            t = det.trimmed_length(wf.shape[-1], self.time_reserved_count)
            result = det.detect_from_time_series(
                jnp.stack(ts_rows)[:, :t], jnp.stack(zero_counts),
                cfg.signal_detect_signal_noise_threshold,
                cfg.signal_detect_max_boxcar_length)
            result = self._quality_epilogue(qspec, wf, result)
            wf_ri = jnp.stack([jnp.real(wf), jnp.imag(wf)])
            return wf_ri, result
        from srtb_tpu.ops import pallas_fft as pf
        pallas_wf = use_pallas and pf.supported(
            self.watfft_len, spec.shape[0] * self.channel_count)
        pallas_sk = cfg.use_pallas_sk and pk.sk_tiling_ok(
            self.channel_count, self.watfft_len)
        if pallas_sk and pallas_wf:
            # Fully fused waterfall post-chain: ONE batched VMEM row-FFT
            # kernel computes the backward C2C for all streams,
            # de-applies the window and collects the SK power moments
            # while each row is still in VMEM
            # (ops/pallas_fft.fft_rows_stats_ri) — the waterfall is never
            # re-read for statistics; the zap verdict + time series then
            # cost exactly one more read+write (pk.sk_apply_timeseries).
            # 2 HBM round trips total where the jnp chain takes ~5.
            t_len = self.watfft_len
            x = spec[..., :self.channel_count * t_len].reshape(
                n_streams, self.channel_count, t_len)
            wr, wi, s2p, s4p = pf.fft_rows_stats_ri(
                jnp.real(x), jnp.imag(x), inverse=True,
                dewindow=self.watfft_dewindow, interpret=interp)
            zap_all = pk.sk_zap_decision(            # [S, F]
                s2p.sum(-1), s4p.sum(-1), t_len,
                cfg.mitigate_rfi_spectral_kurtosis_threshold)
            fs0 = wr[..., 0] ** 2 + wi[..., 0] ** 2
            zc_all = jnp.sum((zap_all | (fs0 == 0)).astype(jnp.int32),
                             axis=-1)
            zapped, zero_counts, ts_rows = [], [], []
            for s in range(n_streams):
                wf_ri1, ts = pk.sk_apply_timeseries(
                    jnp.stack([wr[s], wi[s]]), zap_all[s],
                    interpret=interp)
                zapped.append(jax.lax.complex(wf_ri1[0], wf_ri1[1]))
                zero_counts.append(zc_all[s])
                ts_rows.append(ts)
        elif pallas_sk:
            wf = F.waterfall_c2c(spec, self.channel_count,
                                 self.watfft_dewindow,
                                 len_cap=self._len_cap)  # [S, F, T]
            zapped, zero_counts, ts_rows = [], [], []
            for s in range(n_streams):
                wf_ri1 = jnp.stack([jnp.real(wf[s]), jnp.imag(wf[s])])
                wf_ri1, zc, ts = pk.sk_zap_timeseries(
                    wf_ri1, cfg.mitigate_rfi_spectral_kurtosis_threshold,
                    interpret=interp)
                zapped.append(jax.lax.complex(wf_ri1[0], wf_ri1[1]))
                zero_counts.append(zc)
                ts_rows.append(ts)
        if pallas_sk:
            wf = jnp.stack(zapped)
            t = det.trimmed_length(wf.shape[-1], self.time_reserved_count)
            result = det.detect_from_time_series(
                jnp.stack(ts_rows)[:, :t], jnp.stack(zero_counts),
                cfg.signal_detect_signal_noise_threshold,
                cfg.signal_detect_max_boxcar_length)
        else:
            if pallas_wf:
                # one-HBM-pass Pallas waterfall C2C (ops/pallas_fft):
                # rows in VMEM, DFT-matmul stages on the MXU
                x = spec[..., :self.channel_count
                         * self.watfft_len].reshape(
                    *spec.shape[:-1], self.channel_count, self.watfft_len)
                wr, wi = pf.fft_rows_ri(jnp.real(x), jnp.imag(x),
                                        inverse=True, interpret=interp)
                wf = jax.lax.complex(wr, wi)
                if self.watfft_dewindow is not None:
                    wf = wf / self.watfft_dewindow
            else:
                wf = F.waterfall_c2c(spec, self.channel_count,
                                     self.watfft_dewindow,
                                     len_cap=self._len_cap)  # [S, F, T]
            wf = rfi.mitigate_rfi_spectral_kurtosis(
                wf, cfg.mitigate_rfi_spectral_kurtosis_threshold)
            result = det.detect(wf, self.time_reserved_count,
                                cfg.signal_detect_signal_noise_threshold,
                                cfg.signal_detect_max_boxcar_length)
        result = self._quality_epilogue(qspec, wf, result)
        # boundary representation: waterfall leaves jit as stacked (re, im)
        wf_ri = jnp.stack([jnp.real(wf), jnp.imag(wf)])  # [2, S, F, T]
        return wf_ri, result

    def _quality_epilogue(self, spec: jnp.ndarray, wf: jnp.ndarray,
                          result):
        """Data-quality statistics rider (srtb_tpu/quality/stats.py):
        with ``Config.quality_stats`` armed, pack the per-stream
        quality vector from the spectrum and waterfall ALREADY
        resident in this trace and attach it to the detect result —
        two cheap extra reads inside every plan family, no new plan.
        Off (the default) this is an exact no-op: existing plans trace
        byte-identically."""
        cfg = self.cfg
        if not getattr(cfg, "quality_stats", False):
            return result
        from srtb_tpu.quality import stats as Q
        qvec = Q.quality_stats_device(
            spec, wf,
            int(getattr(cfg, "quality_coarse_bins", 64) or 64),
            float(getattr(cfg, "quality_dead_threshold", 0.1)),
            float(getattr(cfg, "quality_hot_threshold", 10.0)),
            subsample=int(getattr(cfg, "quality_subsample", 1) or 1))
        return result._replace(quality=qvec)

    # ------------------------------------------------------------------
    # AOT warm restart (utils/aot_cache.py): replace the jit wrappers
    # with persisted compiled executables so a restarted observation
    # skips the (minutes-long at 2^30) XLA compile entirely.

    # Config fields that enter the traced programs.  An ALLOWLIST, not a
    # denylist: IO/GUI/paths knobs added later can't silently start
    # keying the AOT cache and turning a deployment-local tweak (e.g.
    # udp_receiver_rcvbuf_bytes) into an 11-minute 2^30 recompile.
    _TRACE_CFG_KEYS = (
        "baseband_input_count", "baseband_input_bits",
        "baseband_format_type", "baseband_freq_low",
        "baseband_bandwidth", "baseband_sample_rate", "dm", "dm_list",
        "spectrum_channel_count", "signal_detect_signal_noise_threshold",
        "signal_detect_max_boxcar_length", "signal_detect_channel_threshold",
        "mitigate_rfi_average_method_threshold",
        "mitigate_rfi_spectral_kurtosis_threshold",
        "mitigate_rfi_freq_list", "baseband_reserve_sample",
        "fft_strategy", "fft_len_cap", "use_pallas", "use_pallas_sk",
        "use_emulated_fp64", "fused_tail", "front_fuse", "chirp_exact",
        # overlap-engine trace shapers: micro_batch_segments changes the
        # traced program (vmapped batch plan) outright;
        # inflight_segments shapes the runtime's donation/aliasing
        # pattern around the executables — a restarted process with
        # different overlap settings must miss the cache cleanly, not
        # load a stale executable
        "inflight_segments", "micro_batch_segments",
        # the ingest ring adds the two-input assemble programs and
        # changes which program the engine dispatches per segment
        "ingest_ring",
        # quality epilogue: armed/off changes the traced program (the
        # detect result grows the packed stats output), and the bin
        # count / channel thresholds are trace-time constants shaping
        # it — host-side quality knobs (drift detector) and the
        # canary (raw-byte injection upstream of the trace) are
        # deliberately NOT here
        "quality_stats", "quality_coarse_bins",
        "quality_dead_threshold", "quality_hot_threshold",
        "quality_subsample",
    )

    @classmethod
    def _trace_projection(cls, cfg) -> tuple[dict, dict]:
        """The (config fields, env knobs) that shape the traced
        programs — the ONE projection both :meth:`plan_signature` and
        :meth:`plan_cache_key` are built from, so the fleet's shared-
        plan safety claim ("equal cache keys imply equal signatures")
        can never drift apart by a one-sided edit.  Only SRTB_* env
        prefixes that shape traces are swept: keying on run-local
        paths (SRTB_BENCH_*, SRTB_WATCH_LOG, the cache dir itself)
        would silently miss on every deployment-environment
        difference — the exact outage the AOT cache exists to
        prevent."""
        cfg_d = {k: getattr(cfg, k) for k in cls._TRACE_CFG_KEYS
                 if hasattr(cfg, k)}
        trace_prefixes = ("SRTB_STAGED", "SRTB_PALLAS", "SRTB_DIST",
                          "SRTB_MXU")
        knobs = {k: v for k, v in os.environ.items()
                 if k.startswith(trace_prefixes)}
        return cfg_d, knobs

    @classmethod
    def plan_cache_key(cls, cfg, window_name: str = W.DEFAULT_WINDOW,
                       donate_input: bool = False) -> str:
        """Conservative shared-plan cache key WITHOUT constructing a
        processor: the trace projection + the constructor inputs.
        Equal keys imply equal :meth:`plan_signature` — every derived
        plan flag (staged, fused_tail, ring, skzap, hbm_passes)
        resolves as a pure function of exactly these inputs and the
        local platform — so the fleet's SharedPlanCache
        (pipeline/fleet.py) can serve one compiled plan family to
        every stream whose config projects identically, probing
        nothing.  (The key is *finer* than the family only in the
        degenerate sense that two DIFFERENT projections could resolve
        to the same plan; those compile twice — correct, merely
        unshared.)  Per-stream identity (stream_name, priority,
        paths) is deliberately outside the projection: tenancy must
        never split the plan cache."""
        import json

        cfg_d, knobs = cls._trace_projection(cfg)
        return json.dumps(
            {"cfg": cfg_d, "env": knobs, "window": window_name,
             "mode": cls.MODE,
             "donate_input": bool(donate_input)},
            sort_keys=True, default=str)

    def plan_signature(self) -> str:
        """Stable string identifying everything that shapes the compiled
        programs: the trace-relevant config fields, the trace-shaping
        SRTB_* env knobs, and the plan flags.  Any drift misses the AOT
        cache cleanly and recompiles."""
        import json

        cfg_d, knobs = self._trace_projection(self.cfg)
        return json.dumps(
            {"cfg": cfg_d, "env": knobs, "mode": self.MODE,
             "staged": self.staged,
             "interp": self._pallas_interpret,
             "window": self._window_name,
             "has_chirp": self.chirp is not None,
             "donate_input": self._donate_input,
             # resolved fusion state, not just the "auto" request: a
             # restarted process whose plan resolves differently (e.g.
             # strategy flips monolithic <-> four_step across the
             # threshold) must miss the AOT cache cleanly
             "fused_tail": self.fused_tail,
             # resolved front fusion: the staged_ffuse programs have
             # different boundary pytrees (canonical + accumulators)
             # and a blocked stage-(b) spectrum — an AOT cache written
             # by either spelling must miss cleanly for the other
             "front_fuse": self.front_fuse,
             "skzap": self._skzap,
             "hbm_passes": self.hbm_passes,
             # resolved ingest plan: the ring's two-input assemble
             # programs (and their carry avals) exist only when it is
             # live, so a restart that resolves differently (e.g. a
             # dm change flips reserved_bytes to 0) must miss cleanly
             "ingest": "ring-v1" if self.ring else "direct",
             # staged-boundary schema version: the canonical
             # donation-aliasable [2, S, F, T] boundary changed the
             # staged programs' avals — a warm AOT cache written before
             # it must miss cleanly, not feed the new chain executables
             # with the old boundary shapes
             "boundary": "canonical-v2"},
            sort_keys=True, default=str)

    def lowerables(self):
        """Every jitted program of this plan as ``(name, jit_fn,
        abstract_args, donated_argnums)`` — lowerable via
        ``jit_fn.lower(*abstract_args)`` without touching a device or
        running anything.  The plan-enumeration hook the compile-time
        HLO plan auditor (``srtb_tpu/analysis/hlo_audit.py``) and the
        AOT cache both build on: abstract avals only, boundary shapes
        chained by ``jax.eval_shape`` exactly as ``enable_aot`` chains
        them, so the audited artifacts ARE the executed artifacts."""
        expected = self.cfg.segment_bytes(self.fmt.data_stream_count)
        raw_s = jax.ShapeDtypeStruct((expected,), jnp.uint8)
        in_donate = (0,) if self._donate_input else ()
        ring_donate = (0,) + ((1,) if self._donate_input else ())
        carry_s = jax.ShapeDtypeStruct((self.reserved_bytes,), jnp.uint8)
        new_s = jax.ShapeDtypeStruct((self.stride_bytes,), jnp.uint8)
        # Fresh jit wrappers of the underlying plan functions, NOT the
        # self._jit_* attributes: enable_aot swaps those for loaded
        # Compiled executables, which cannot .lower() again — the
        # audit must stay lowerable on an AOT-active processor (e.g.
        # SRTB_BENCH_AOT_DIR together with SRTB_BENCH_AUDIT).  The
        # per-call wrappers are sanctioned here: this is the audit-only
        # cold path (never the per-segment dispatch), and a cached
        # wrapper would defeat the AOT independence above.
        if self.staged:
            a_out = jax.eval_shape(self._stage_a, raw_s)
            # the front-fused stage-(a) boundary is (canonical, aux)
            # passed as two program args so only the canonical leaf is
            # donated (see _run_stage_b)
            b_args = tuple(a_out) if self.front_fuse else (a_out,)
            b_out = jax.eval_shape(self._stage_b, *b_args)
            progs = [
                ("stage_a",
                 # srtb-lint: disable=recompile-hazard
                 jax.jit(self._stage_a, donate_argnums=in_donate),
                 (raw_s,), in_donate),
                # srtb-lint: disable=recompile-hazard
                ("stage_b", jax.jit(self._stage_b, donate_argnums=(0,)),
                 b_args, (0,)),
                # srtb-lint: disable=recompile-hazard
                ("stage_c", jax.jit(self._stage_c, donate_argnums=(0,)),
                 (b_out,), (0,)),
            ]
            if self.ring:
                progs += [
                    ("stage_a_ring",
                     # srtb-lint: disable=recompile-hazard
                     jax.jit(self._stage_a_ring,
                             donate_argnums=ring_donate),
                     (carry_s, new_s), ring_donate),
                    ("stage_a_cold",
                     # srtb-lint: disable=recompile-hazard
                     jax.jit(self._stage_a_cold,
                             donate_argnums=in_donate),
                     (raw_s,), in_donate),
                ]
            return progs

        def aval(x):
            return None if x is None else jax.ShapeDtypeStruct(
                x.shape, x.dtype)

        chirps = (aval(self.chirp), aval(self.chirp_w))
        progs = [("fused",
                  # srtb-lint: disable=recompile-hazard
                  jax.jit(self._process, donate_argnums=in_donate),
                  (raw_s,) + chirps, in_donate)]
        if self.ring:
            progs += [
                ("ring",
                 # srtb-lint: disable=recompile-hazard
                 jax.jit(self._process_ring, donate_argnums=ring_donate),
                 (carry_s, new_s) + chirps, ring_donate),
                ("ring_cold",
                 # srtb-lint: disable=recompile-hazard
                 jax.jit(self._process_cold, donate_argnums=in_donate),
                 (raw_s,) + chirps, in_donate),
            ]
        mb = int(getattr(self.cfg, "micro_batch_segments", 1) or 1)
        if mb > 1:
            batch_s = jax.ShapeDtypeStruct((mb, expected), jnp.uint8)
            progs.append(("batch",
                          jax.jit(jax.vmap(self._process,
                                           in_axes=(0, None, None)),
                                  donate_argnums=in_donate),
                          (batch_s,) + chirps, in_donate))
            if self.ring:
                news_s = jax.ShapeDtypeStruct((mb, self.stride_bytes),
                                              jnp.uint8)
                progs += [
                    ("batch_ring",
                     # srtb-lint: disable=recompile-hazard
                     jax.jit(self._process_batch_ring,
                             donate_argnums=ring_donate),
                     (carry_s, news_s) + chirps, ring_donate),
                    ("batch_cold",
                     # srtb-lint: disable=recompile-hazard
                     jax.jit(self._process_batch_cold,
                             donate_argnums=in_donate),
                     (batch_s,) + chirps, in_donate),
                ]
        return progs

    def enable_aot(self, path: str, allow_cpu: bool = False) -> bool:
        """Swap the jitted plan programs for cached compiled executables
        (compiling + persisting on miss).  Returns False when the cache
        is unavailable (CPU backend without the opt-in) — the jit
        wrappers stay in place and behavior is unchanged."""
        from srtb_tpu.utils.aot_cache import AotPlanCache

        cache = AotPlanCache(path, allow_cpu=allow_cpu,
                             labels=self._metric_labels)
        if not cache.enabled():
            return False
        sig = self.plan_signature()
        expected = self.cfg.segment_bytes(self.fmt.data_stream_count)
        raw_s = jax.ShapeDtypeStruct((expected,), jnp.uint8)
        carry_s = jax.ShapeDtypeStruct((self.reserved_bytes,), jnp.uint8)
        new_s = jax.ShapeDtypeStruct((self.stride_bytes,), jnp.uint8)
        if not self.staged:
            self._jit_process = cache.get_or_compile(
                "fused", sig, self._jit_process, raw_s, self.chirp,
                self.chirp_w)
            if self.ring:
                self._jit_ring = cache.get_or_compile(
                    "ring", sig, self._jit_ring, carry_s, new_s,
                    self.chirp, self.chirp_w)
                self._jit_cold = cache.get_or_compile(
                    "ring_cold", sig, self._jit_cold, raw_s,
                    self.chirp, self.chirp_w)
        else:
            # chain the boundary avals by abstract evaluation (free:
            # trace only, no compile)
            a_out = jax.eval_shape(self._stage_a, raw_s)
            b_args = tuple(a_out) if self.front_fuse else (a_out,)
            b_out = jax.eval_shape(self._stage_b, *b_args)
            self._jit_stage_a = cache.get_or_compile(
                "stage_a", sig, self._jit_stage_a, raw_s)
            self._jit_stage_b = cache.get_or_compile(
                "stage_b", sig, self._jit_stage_b, *b_args)
            self._jit_stage_c = cache.get_or_compile(
                "stage_c", sig, self._jit_stage_c, b_out)
            if self.ring:
                self._jit_stage_a_ring = cache.get_or_compile(
                    "stage_a_ring", sig, self._jit_stage_a_ring,
                    carry_s, new_s)
                self._jit_stage_a_cold = cache.get_or_compile(
                    "stage_a_cold", sig, self._jit_stage_a_cold, raw_s)
        self.aot_active = True
        return True

    @staticmethod
    def _count_h2d(nbytes: int) -> None:
        """Account one host->device transfer (the ring's falsifiable
        payoff: warm dispatches move exactly stride_bytes, cold ones
        exactly segment_bytes — tests and the ci smoke assert the
        counter against that stride model)."""
        from srtb_tpu.utils.metrics import metrics
        metrics.add("h2d_bytes", nbytes)

    def _as_device_bytes(self, raw) -> jnp.ndarray:
        """Host bytes -> device uint8 via *explicit* ``device_put``
        (``jnp.asarray`` on host data is an implicit H2D transfer; the
        explicit spelling keeps every pipeline transfer visible to
        ``jax.transfer_guard`` and the runtime sanitizer)."""
        if isinstance(raw, jax.Array):
            return raw if raw.dtype == jnp.uint8 \
                else jnp.asarray(raw, dtype=jnp.uint8)
        arr = np.ascontiguousarray(np.asarray(raw), dtype=np.uint8)
        self._count_h2d(arr.nbytes)
        return jax.device_put(arr)

    # ---------------------------- host staging buffers (pooled copies)

    def _staged_host(self, raw, owner=None) -> np.ndarray:
        """A contiguous uint8 host view of ``raw``, copying into a
        pooled staging buffer only when a copy is unavoidable (wrong
        dtype / non-contiguous input).  ``owner`` keys the buffer's
        lifetime: it returns to the pool at release_staging(owner)
        (the pipeline calls that when the segment drains), or via the
        FIFO overflow cap for callers that never release."""
        arr = raw if isinstance(raw, np.ndarray) \
            else np.ascontiguousarray(raw)  # host data, never a device fetch
        if arr.dtype == np.uint8 and arr.flags["C_CONTIGUOUS"]:
            return arr
        buf = self._staging_pool.acquire(arr.size, zero=False)
        np.copyto(buf, arr.reshape(-1), casting="unsafe")
        self._register_staging(owner if owner is not None else raw, buf)
        return buf

    def _register_staging(self, owner, buf: np.ndarray) -> None:
        entry = self._staging_out.get(id(owner))
        if entry is None:
            # the owner rides in the entry so its id stays pinned
            # until release (no reuse-after-GC key collisions)
            self._staging_out[id(owner)] = (owner, [buf])
        else:
            entry[1].append(buf)
        while len(self._staging_out) > self._staging_cap:
            # overflow: the oldest registration's transfer completed
            # long ago (the in-flight window bounds concurrency), so
            # reclaiming it is safe even for a caller that never
            # releases explicitly
            _, (_owner, bufs) = next(iter(self._staging_out.items()))
            self._staging_out.pop(id(_owner))
            for b in bufs:
                self._staging_pool.release(b)

    def release_staging(self, owner) -> None:
        """Return the staging buffers registered against ``owner``
        (one segment's host byte buffer) to the pool.  Called by the
        pipeline when the segment drains; a no-op for segments that
        never needed a staging copy."""
        entry = self._staging_out.pop(id(owner), None)
        if entry is not None:
            for b in entry[1]:
                self._staging_pool.release(b)

    def stack_batch(self, datas, stride_only: bool = False) -> np.ndarray:
        """Stack B segments' host bytes into one pooled, contiguous
        [B, segment_bytes] (or [B, stride_bytes] with ``stride_only``)
        uint8 array for a micro-batch dispatch — reusing a staging
        buffer instead of a fresh ``np.stack`` allocation per batch.
        Registered against the FIRST segment's buffer: the batch is one
        device program, so its first drain implies the whole transfer
        completed."""
        width = self.stride_bytes if stride_only else self._segment_bytes
        buf = self._staging_pool.acquire(len(datas) * width, zero=False)
        out = buf.reshape(len(datas), width)
        for i, d in enumerate(datas):
            src = d if isinstance(d, np.ndarray) \
                else np.ascontiguousarray(d)
            out[i] = src[src.shape[0] - width:] if stride_only else src
        self._register_staging(datas[0], buf)
        return out

    # ------------------------------------------------- H2D staging

    def stage_input(self, raw, stride_only: bool = False) -> jnp.ndarray:
        """Start the async host->device transfer of one segment's raw
        bytes and return the device handle immediately (H2D staging).
        The overlap engine calls this right after ingest, so the
        transfer runs under the *previous* segment's device compute
        instead of serializing into the next dispatch.

        With ``stride_only`` (the live ring's warm path) only the
        stride's NEW bytes — ``raw[reserved_bytes:]`` — cross the PCIe/
        tunnel link; the reserved head is already device-resident as
        the carry.  ``raw`` stays the FULL segment either way: the
        retained host buffer is what watchdog requeues and dispatch
        retries re-stage cold, bit-identically."""
        expected = self.cfg.segment_bytes(self.fmt.data_stream_count)
        if raw.shape != (expected,):
            raise ValueError(
                f"segment must be {expected} bytes, got {raw.shape}")
        staged = self._staged_host(raw, owner=raw)
        if stride_only:
            if not self.ring:
                raise ValueError("stride_only staging requires the "
                                 "ingest ring (Config.ingest_ring)")
            staged = staged[self.reserved_bytes:]
        elif self.ring:
            # counted HERE, not in the engine, so the count stays one-
            # per-full-upload under retries (a retried dispatch
            # re-stages and re-counts) — the invariant telemetry
            # consumers rely on: h2d_bytes == ring_cold_dispatches *
            # segment_bytes + warm_count * stride_bytes
            from srtb_tpu.utils.metrics import metrics
            metrics.add("ring_cold_dispatches")
        self._count_h2d(staged.nbytes)
        return jax.device_put(staged)

    def _batch_jit(self):
        """The lazily-built micro-batch program: the fused plan vmapped
        over the leading batch axis (one jit object, shared by
        :meth:`process_batch` and :meth:`lowerables`)."""
        if self._jit_process_batch is None:
            in_donate = (0,) if self._donate_input else ()
            self._jit_process_batch = jax.jit(
                jax.vmap(self._process, in_axes=(0, None, None)),
                donate_argnums=in_donate)
        return self._jit_process_batch

    def process_batch(self, raws) -> tuple[jnp.ndarray, det.DetectResult]:
        """Micro-batch mode: run B stacked segments ``raws`` [B, bytes]
        in ONE jit call (the fused plan vmapped over the batch axis),
        amortizing per-dispatch host overhead and tunnel RTT over B
        segments.  Returns ``(waterfall_ri, detect)`` with a leading
        batch axis on every array; slice per segment with
        ``jax.tree_util.tree_map(lambda x: x[i], ...)``."""
        if self.staged:
            raise ValueError(
                "micro_batch_segments > 1 requires the fused plan "
                "(staged segments are already dispatch-amortized)")
        raw = self._as_device_bytes(raws)
        expected = self.cfg.segment_bytes(self.fmt.data_stream_count)
        if raw.ndim != 2 or raw.shape[1] != expected:
            raise ValueError(
                f"batch must be [B, {expected}] bytes, got {raw.shape}")
        out = self._timed_first(
            "batch",
            lambda: self._batch_jit()(raw, self.chirp, self.chirp_w))
        if self._sanitize and self._donate_input:
            from srtb_tpu.analysis import sanitizer as S
            # the sanitizer is the sanctioned holder of the donated
            # buffer (it deletes it)  # srtb-lint: disable=use-after-donate
            S.expire_donated(raw, out)
        return out

    def process(self, raw) -> tuple[jnp.ndarray, det.DetectResult]:
        """Run one segment. ``raw`` is the uint8 byte array of the segment
        (all streams interleaved, as read from file or UDP).

        Returns ``(waterfall_ri, detect_result)`` where waterfall_ri is
        [2, S, F, T] float32 (re, im); use :func:`waterfall_to_numpy` to
        assemble a complex host array.
        """
        raw = self._as_device_bytes(raw)
        expected = self.cfg.segment_bytes(self.fmt.data_stream_count)
        if raw.shape != (expected,):
            raise ValueError(
                f"segment must be {expected} bytes, got {raw.shape}")
        return self.run_device(raw)

    def _timed_first(self, name: str, fn):
        """Dispatch ``fn`` with first-call compile accounting: the
        first dispatch of program family ``name`` on this processor is
        where lazy jit traces+compiles, so its wall clock feeds the
        ``compile_seconds`` / ``plan_compiles`` / ``last_compile_ms``
        metrics (per-stream twins when labeled).  An AOT-active
        processor compiled in ``enable_aot`` (counted exactly there by
        the cache), so its first dispatch is marked but not counted.
        Steady-state dispatches pay one set-membership check."""
        if name in self._dispatched_programs:
            return fn()
        if self.aot_active:
            self._dispatched_programs.add(name)
            return fn()
        from srtb_tpu.utils.metrics import metrics
        t0 = time.perf_counter()
        out = fn()
        dt = time.perf_counter() - t0
        # marked only AFTER fn() returned: a transient failure inside
        # the first dispatch leaves the family unmarked, so the retry
        # (where the trace+compile actually completes) is the timed
        # compile event instead of slipping past the books
        self._dispatched_programs.add(name)
        metrics.add("plan_compiles")
        metrics.add("compile_seconds", dt)
        metrics.set("last_compile_ms", dt * 1e3)
        if self._metric_labels is not None:
            metrics.add("plan_compiles", labels=self._metric_labels)
            metrics.add("compile_seconds", dt,
                        labels=self._metric_labels)
        return out

    def run_device(self, raw: jnp.ndarray):
        """Run one segment on an already-device-resident byte array,
        dispatching between the fused and staged execution plans.

        Under ``Config.sanitize`` every plan boundary gets a NaN/Inf
        tripwire + a stacked-(re, im) float32 contract assert, and the
        donated input buffer is explicitly expired once consumed so a
        use-after-donate raises on CPU CI too (donation there is a
        no-op and the bug would otherwise only corrupt on the TPU).
        This serializes dispatch — sanitize is a debugging mode."""
        if not self.staged:
            out = self._timed_first(
                "fused",
                lambda: self._jit_process(raw, self.chirp,
                                          self.chirp_w))
            if self._sanitize and self._donate_input:
                from srtb_tpu.analysis import sanitizer as S
                # sanctioned holder: expiry deletes the donated
                # buffer  # srtb-lint: disable=use-after-donate
                S.expire_donated(raw, out)
            return out
        if not self._sanitize:
            def _run_staged():
                # the fused branch above returned, so its donation
                # can never reach this chain's read
                a = self._jit_stage_a(
                    raw)  # srtb-lint: disable=use-after-donate
                return self._jit_stage_c(self._run_stage_b(a))

            return self._timed_first("staged", _run_staged)

        def _run_checked():
            # the sanitizer is the sanctioned holder of the donated
            # input (it expires it); the fused branch above returned,
            # so its lambda-wrapped donation never reaches this read
            a = self._staged_a_checks(
                self._jit_stage_a(raw),
                raw)  # srtb-lint: disable=use-after-donate
            return self._staged_tail(a)

        # the WHOLE three-stage chain under one first-dispatch timer:
        # stage_b/stage_c compile on the first call too, and counting
        # only stage_a would report a third of the staged plan's cost
        # (the fused branch times its entire program — uniform books)
        return self._timed_first("staged", _run_checked)

    def _staged_a_checks(self, a, consumed, donated: bool | None = None):
        """Sanitizer hooks at the stage (a) boundary: contract + NaN
        tripwires, and explicit expiry of the consumed (donated)
        input so a use-after-donate raises on CPU CI too.  ``donated``
        overrides the donate_input default — the ring carry is ALWAYS
        donated regardless of the raw-input policy, so its expiry must
        not be gated on ``self._donate_input``."""
        from srtb_tpu.analysis import sanitizer as S
        # the front-fused boundary is (canonical, accumulators); the
        # contract applies to the canonical leaf, the NaN tripwire to
        # the whole pytree
        canon = a[0] if isinstance(a, tuple) else a
        S.check_contract("stage_a boundary", canon, lead=2,
                         dtype=jnp.float32)
        S.check_finite("stage_a boundary", a)
        if self._donate_input if donated is None else donated:
            # sanctioned holder: expiry deletes the donated
            # buffer  # srtb-lint: disable=use-after-donate
            S.expire_donated(consumed, a)
        return a

    def _staged_tail(self, a):
        """Stages (b) + (c) under the sanitizer (the shared back half
        of run_device and the ring variants)."""
        from srtb_tpu.analysis import sanitizer as S
        b = self._run_stage_b(a)  # donates a (checked above, by value)
        S.check_contract("stage_b boundary", b, lead=2,
                         dtype=jnp.float32)
        S.check_finite("stage_b boundary", b)
        return self._jit_stage_c(b)

    # ------------------------------------------- ring execution paths

    def run_device_ring(self, carry: jnp.ndarray, new: jnp.ndarray):
        """Warm ring step: run one segment from the device-resident
        ``carry`` (the previous segment's reserved tail) plus the
        stride's freshly uploaded ``new`` bytes.  Returns
        ``((waterfall_ri, detect), next_carry)``.

        The carry is DONATED (a proven alias — see the ring comment in
        ``__init__``): callers must treat it as consumed and thread the
        returned next_carry into the following step instead."""
        if not self.ring:
            raise ValueError("ingest ring disabled for this plan "
                             "(Config.ingest_ring / no reserved tail)")
        if self.staged:
            def _run_ring():
                # whole chain under one timer (see run_device): the
                # b/c stages compile on first dispatch too
                a, nc = self._jit_stage_a_ring(carry, new)
                if not self._sanitize:
                    return self._jit_stage_c(self._run_stage_b(a)), nc
                # sanctioned holder: _staged_a_checks expires the
                # carry, which is donated UNCONDITIONALLY (unlike the
                # raw input)
                return self._staged_tail(self._staged_a_checks(
                    a, carry,  # srtb-lint: disable=use-after-donate
                    donated=True)), nc

            out, next_carry = self._timed_first("staged_ring",
                                                _run_ring)
        else:
            out, next_carry = self._timed_first(
                "ring",
                lambda: self._jit_ring(carry, new, self.chirp,
                                       self.chirp_w))
            if self._sanitize:
                from srtb_tpu.analysis import sanitizer as S
                # sanctioned holder: the donated carry is expired
                # here  # srtb-lint: disable=use-after-donate
                S.expire_donated(carry, out)
        return out, next_carry

    def run_device_cold(self, raw: jnp.ndarray):
        """Cold ring step: run one segment from a FULL device-resident
        upload and (re-)arm the ring — the carry is emitted by the same
        program, so a cold dispatch costs exactly segment_bytes of H2D
        and no extra slice pass.  Used for the first segment and after
        any event that breaks carry continuity (watchdog requeue,
        dispatch retry, shed segment, checkpoint resume)."""
        if not self.ring:
            raise ValueError("ingest ring disabled for this plan "
                             "(Config.ingest_ring / no reserved tail)")
        if self.staged:
            def _run_cold():
                # whole chain under one timer (see run_device)
                a, nc = self._jit_stage_a_cold(raw)
                if not self._sanitize:
                    return self._jit_stage_c(self._run_stage_b(a)), nc
                # sanctioned holder: _staged_a_checks expires the
                # donated input
                return self._staged_tail(self._staged_a_checks(
                    a,
                    raw)), nc  # srtb-lint: disable=use-after-donate

            out, next_carry = self._timed_first("staged_ring_cold",
                                                _run_cold)
        else:
            out, next_carry = self._timed_first(
                "ring_cold",
                lambda: self._jit_cold(raw, self.chirp, self.chirp_w))
            if self._sanitize and self._donate_input:
                from srtb_tpu.analysis import sanitizer as S
                # sanctioned holder  # srtb-lint: disable=use-after-donate
                S.expire_donated(raw, out)
        return out, next_carry

    def _batch_ring_jit(self):
        if self._jit_batch_ring is None:
            donate = (0,) + ((1,) if self._donate_input else ())
            self._jit_batch_ring = jax.jit(self._process_batch_ring,
                                           donate_argnums=donate)
        return self._jit_batch_ring

    def _batch_cold_jit(self):
        if self._jit_batch_cold is None:
            in_donate = (0,) if self._donate_input else ()
            self._jit_batch_cold = jax.jit(self._process_batch_cold,
                                           donate_argnums=in_donate)
        return self._jit_batch_cold

    def _check_batch(self, raw, width: int):
        if self.staged:
            raise ValueError(
                "micro_batch_segments > 1 requires the fused plan "
                "(staged segments are already dispatch-amortized)")
        if raw.ndim != 2 or raw.shape[1] != width:
            raise ValueError(
                f"batch must be [B, {width}] bytes, got {raw.shape}")

    def process_batch_ring(self, carry, news):
        """Micro-batch warm ring step: B stride uploads ``news``
        [B, stride_bytes] plus the device carry run B overlapped
        segments in ONE vmapped jit call.  Returns
        ``((waterfall_ri, detect), next_carry)`` batched like
        :meth:`process_batch`; the carry is donated (consumed)."""
        if not self.ring:
            raise ValueError("ingest ring disabled for this plan "
                             "(Config.ingest_ring / no reserved tail)")
        news = self._as_device_bytes(news)
        self._check_batch(news, self.stride_bytes)
        out, next_carry = self._timed_first(
            "batch_ring",
            lambda: self._batch_ring_jit()(carry, news, self.chirp,
                                           self.chirp_w))
        if self._sanitize:
            from srtb_tpu.analysis import sanitizer as S
            # sanctioned holder  # srtb-lint: disable=use-after-donate
            S.expire_donated(carry, out)
        return out, next_carry

    def process_batch_cold(self, raws):
        """Micro-batch cold ring step: B full-segment uploads, plan
        outputs plus the re-armed carry in one jit call."""
        if not self.ring:
            raise ValueError("ingest ring disabled for this plan "
                             "(Config.ingest_ring / no reserved tail)")
        from srtb_tpu.utils.metrics import metrics
        metrics.add("ring_cold_dispatches")  # one per full-batch upload
        raws = self._as_device_bytes(raws)
        self._check_batch(raws, self._segment_bytes)
        out, next_carry = self._timed_first(
            "batch_cold",
            lambda: self._batch_cold_jit()(raws, self.chirp,
                                           self.chirp_w))
        if self._sanitize and self._donate_input:
            from srtb_tpu.analysis import sanitizer as S
            # sanctioned holder  # srtb-lint: disable=use-after-donate
            S.expire_donated(raws, out)
        return out, next_carry

    # ---------------------------------------- self-healing retirement

    _RETIRED_PROGRAMS = (
        "_jit_process", "_jit_process_batch", "_jit_stage_a",
        "_jit_stage_b", "_jit_stage_c", "_jit_ring", "_jit_cold",
        "_jit_stage_a_ring", "_jit_stage_a_cold", "_jit_batch_ring",
        "_jit_batch_cold")

    # set by SharedPlanCache.mark_shared(): this processor serves
    # SEVERAL fleet streams at once, so one stream's plan demotion
    # must not retire the programs its neighbors are still
    # dispatching through (the bulkhead contract).  A fleet-wide
    # device reinit retires shared processors too, via force=True.
    _fleet_shared = False

    def mark_shared(self) -> "SegmentProcessor":
        """Flag this processor as fleet-shared (see retire)."""
        self._fleet_shared = True
        return self

    def retire(self, force: bool = False) -> None:
        """Disarm a processor the pipeline has replaced (plan demotion,
        promotion probe, or device reinit — resilience/demote.py).

        Every compiled-program handle is swapped for a loud failure:
        after a device reinit the old handles (in-memory AOT
        executables, jit caches) are bound to the dead backend, and a
        stray dispatch through a stale reference must raise instead of
        feeding a dead handle — or silently racing the replacement
        plan.  Host-side state (the staging pool, retained buffers) is
        left to the garbage collector: in-flight transfers may still
        reference those buffers, and a fresh processor owns fresh
        pools.

        A fleet-SHARED processor (mark_shared) is a no-op here unless
        ``force=True``: one stream swapping it out (demotion) leaves
        the other tenants' dispatch path alive; only the fleet itself
        retires the shared plan (device reinit, fleet close)."""
        if self._fleet_shared and not force:
            return
        def _dead(*_args, **_kwargs):
            raise RuntimeError(
                "SegmentProcessor retired (plan demotion / device "
                "reinit replaced it) — dispatch through the "
                "pipeline's active processor")
        for name in self._RETIRED_PROGRAMS:
            if getattr(self, name, None) is not None:
                setattr(self, name, _dead)
        self.aot_active = False

    @property
    def data_stream_count(self) -> int:
        return self.fmt.data_stream_count


def waterfall_to_numpy(wf_ri) -> np.ndarray:
    """[2, S, F, T] float32 (re, im) -> [S, F, T] complex64 on host.

    Uses the explicit D2H spelling (utils/platform.to_host) so sinks
    fetching a still-device waterfall stay visible to the transfer
    guard / sanitizer."""
    from srtb_tpu.utils.platform import to_host
    a = to_host(wf_ri)
    return (a[0] + 1j * a[1]).astype(np.complex64)
