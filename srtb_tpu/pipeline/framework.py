"""Host-side pipeline framework: threads + bounded queues + stop tokens.

On TPU the *device* stages live in one fused jit (see segment.py), but the
host stages around it — ingest from N UDP receivers, device feeding,
result draining, writers — still benefit from the reference's
thread-per-stage structure (ref: pipeline/framework/pipe.hpp:108-175,
pipe_io.hpp:27-152):

- ``WorkQueue``: bounded queue, capacity 2 by default
  (ref: work.hpp:30-72 + config.hpp:40-43), blocking push/pop with a stop
  token, and a lossy push for visualization taps
  (ref: loose_queue_out_functor, pipe_io.hpp:79-94);
- ``Pipe``/``start_pipe``: a worker thread running
  in -> functor -> out until stopped (thread named after the functor);
- ``on_exit``: request stop + join all (ref: framework/exit_handler.hpp).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable

from srtb_tpu.utils import termination
from srtb_tpu.utils.logging import log

WORK_QUEUE_CAPACITY = 2  # ref: config.hpp:40


class StopToken:
    def __init__(self):
        self._evt = threading.Event()

    def request_stop(self):
        self._evt.set()

    @property
    def stop_requested(self) -> bool:
        return self._evt.is_set()


class WorkQueue:
    """Bounded blocking queue with stop-token-aware operations."""

    def __init__(self, capacity: int = WORK_QUEUE_CAPACITY):
        self._q = queue.Queue(maxsize=capacity)

    def push(self, item, stop_token: StopToken | None = None) -> bool:
        while True:
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                if stop_token is not None and stop_token.stop_requested:
                    return False

    def push_lossy(self, item) -> bool:
        """Drop-if-full push for lossy visualization taps
        (ref: pipe_io.hpp:79-94)."""
        try:
            self._q.put_nowait(item)
            return True
        except queue.Full:
            return False

    def pop(self, stop_token: StopToken | None = None):
        """Blocking pop; returns None once stopped and drained."""
        while True:
            try:
                return self._q.get(timeout=0.05)
            except queue.Empty:
                if stop_token is not None and stop_token.stop_requested:
                    return None

    def try_pop(self):
        """Non-blocking pop; None when empty (shutdown accounting of
        items a dead/wedged consumer will never take)."""
        try:
            return self._q.get_nowait()
        except queue.Empty:
            return None

    def qsize(self) -> int:
        return self._q.qsize()


# end-of-stream marker: a producer that is not a Pipe (e.g. the async
# engine's main loop feeding its sink pipe) pushes this to terminate the
# consumer cleanly; Pipe._run forwards it downstream automatically
SENTINEL = object()
_SENTINEL = SENTINEL  # historical private alias


class Pipe:
    """One worker thread: pop from in_queue, apply functor, push to
    out_queue.  A functor returning None drops the work item; raising
    StopIteration ends the pipe (and forwards the sentinel downstream)."""

    def __init__(self, functor: Callable, in_queue: WorkQueue | None,
                 out_queue: WorkQueue | None, stop_token: StopToken,
                 name: str | None = None,
                 on_done: Callable | None = None):
        self.functor = functor
        self.in_queue = in_queue
        self.out_queue = out_queue
        self.stop_token = stop_token
        self.name = name or getattr(functor, "__name__", type(functor).__name__)
        # completion hook, called on the worker thread as it exits
        # (normally or crashed): an event-driven consumer of this
        # pipe's lifecycle (e.g. the fleet scheduler's idle wakeup)
        # needs a push signal, not a join-poll
        self.on_done = on_done
        self.thread = threading.Thread(target=self._run, name=self.name,
                                       daemon=True)
        # attribution for leak/wedge reports: which caller spawned
        # this pipe (utils/termination.tag_thread walks past this file)
        termination.tag_thread(self.thread)
        self.exception: BaseException | None = None

    def _run(self):
        log.debug(f"[pipe {self.name}] started")
        try:
            while not self.stop_token.stop_requested:
                if self.in_queue is not None:
                    work = self.in_queue.pop(self.stop_token)
                    if work is None:
                        break
                    if work is _SENTINEL:
                        break
                else:
                    work = None
                try:
                    out = self.functor(self.stop_token, work)
                except StopIteration:
                    break
                if out is not None and self.out_queue is not None:
                    if not self.out_queue.push(out, self.stop_token):
                        break
        except BaseException as e:  # noqa: BLE001 - report, don't die silent
            self.exception = e
            log.error(f"[pipe {self.name}] crashed: {e!r}")
        finally:
            if self.out_queue is not None:
                # blocking push: a lossy sentinel could be dropped on a full
                # queue and deadlock the consumer
                self.out_queue.push(_SENTINEL, self.stop_token)
            if self.on_done is not None:
                try:
                    self.on_done()
                except Exception as e:  # noqa: BLE001 - exit path
                    log.debug(f"[pipe {self.name}] on_done hook "
                              f"failed: {e!r}")
            log.debug(f"[pipe {self.name}] exiting")

    def start(self):
        self.thread.start()
        return self

    def join(self, timeout=None) -> bool:
        """Join the worker thread; returns True when it actually
        stopped (False = still alive after ``timeout``)."""
        self.thread.join(timeout)
        return not self.thread.is_alive()


def start_pipe(functor: Callable, in_queue: WorkQueue | None,
               out_queue: WorkQueue | None, stop_token: StopToken,
               name: str | None = None,
               on_done: Callable | None = None) -> Pipe:
    """Spawn a pipe thread (ref: start_pipe, framework/pipe.hpp:148-175)."""
    return Pipe(functor, in_queue, out_queue, stop_token, name,
                on_done=on_done).start()


def on_exit(stop_token: StopToken, pipes: list[Pipe],
            timeout: float = 5.0) -> list[Pipe]:
    """Orderly shutdown: request stop, join everything within ONE
    shared ``timeout`` budget (ref: framework/exit_handler.hpp:28-39).
    A pipe that does not stop in time must not hang shutdown behind it
    — the remaining pipes are still joined with whatever budget is
    left (each guaranteed at least an equal share, so one slow join
    cannot starve its neighbors into false wedged reports; worst case
    < 2x ``timeout`` total), and every wedged pipe is reported loudly
    (name + current stack, via utils.termination) and returned to the
    caller."""
    stop_token.request_stop()
    deadline = time.monotonic() + timeout
    share = timeout / max(1, len(pipes))
    wedged = []
    for p in pipes:
        p.join(max(share, deadline - time.monotonic()))
        if p.thread.is_alive():
            wedged.append(p)
    # grace re-sweep: a later pipe starved of budget by an earlier
    # slow join may only need an instant to notice the stop token —
    # don't stack-dump a healthy pipe for its neighbor's sins
    wedged = [p for p in wedged if not p.join(0.1)]
    if wedged:
        from srtb_tpu.utils import termination
        termination.report_wedged([p.thread for p in wedged],
                                  f"on_exit ({timeout:g}s timeout)")
    return wedged


def composite(*functors: Callable) -> Callable:
    """Sequential fusion of pipe functors into one thread
    (ref: framework/composite_pipe.hpp:28-51)."""

    def fused(stop_token, work):
        for f in functors:
            work = f(stop_token, work)
            if work is None:
                return None
        return work

    fused.__name__ = "+".join(
        getattr(f, "__name__", type(f).__name__) for f in functors)
    return fused
