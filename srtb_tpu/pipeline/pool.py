"""Elastic device pool: the fleet's placement substrate.

ROADMAP item 4 generalizes :class:`~srtb_tpu.pipeline.fleet.StreamFleet`
from "N streams on ONE device" to "N streams on a POOL of devices" —
the compose-modules direction of the FPGA pulsar-search stacks
(PAPERS.md): treat accelerators as interchangeable pool members and
MOVE work between them, instead of healing a sick device in place.

A :class:`DevicePool` holds one :class:`PoolDevice` per member.  Each
member owns:

- its OWN :class:`~srtb_tpu.pipeline.fleet.SharedPlanCache` — plan
  families are shared *within* a device, never across devices, so a
  member's compiled handles die with the member and a halt can only
  force-retire ITS cache, never a neighbor's (the per-device HALT
  domain);
- its health state (``ok`` / ``draining`` / ``halted``) published as
  the ``fleet_device_state`` gauge (per-device ``/healthz`` +
  Prometheus twins);
- a dispatch counter, which doubles as the deterministic fault
  injection point for CPU CI: :meth:`schedule_halt` arms a virtual
  halt that raises a :class:`~srtb_tpu.resilience.errors.DeviceHalt`
  (the exact class the fault injector's ``device_halt`` action
  raises) on the first dispatch at or past the scheduled count — no
  wall clock, no RNG, bit-reproducible across runs.

On an accelerator host with ``fleet_devices >= 2`` the pool labels map
onto real ``jax.devices()`` members; on CPU (CI) the pool is VIRTUAL:
N logical devices share one physical device but keep fully distinct
plan caches, batch-former families and halt domains — the control
plane (placement, migration, drain, scoped invalidation) is identical,
which is what the migration soak gates.

``fleet_devices`` <= 1 builds a single-member pool: every fleet code
path goes through the pool, and the one-device fleet is bit-identical
to the pre-pool engine (PERF round 23 pins the A/B within noise).
"""

from __future__ import annotations

from srtb_tpu.utils.logging import log
from srtb_tpu.utils.metrics import metrics

# fleet_device_state gauge codes (per-device label)
STATE_OK = "ok"
STATE_DRAINING = "draining"
STATE_HALTED = "halted"
_STATE_CODE = {STATE_OK: 0, STATE_DRAINING: 1, STATE_HALTED: 2}


class PoolDevice:
    """One pool member: identity + its own plan cache + health state
    + the deterministic dispatch counter."""

    def __init__(self, index: int, label: str | None = None,
                 jax_device=None):
        from srtb_tpu.pipeline.fleet import SharedPlanCache
        self.index = int(index)
        self.label = label or f"dev{index}"
        # the per-device plan-family cache (the per-device HALT
        # domain: invalidating THIS cache never touches a neighbor's)
        self.plans = SharedPlanCache(device=self.label)
        self.state = STATE_OK
        # the real jax.Device when the pool maps onto hardware; None
        # for a virtual (CPU CI) member
        self.jax_device = jax_device
        self.dispatches = 0
        self._halt_at: int | None = None
        self._halt_fired = False
        self._publish()

    # ----------------------------------------------------- health state

    def set_state(self, state: str) -> None:
        if state not in _STATE_CODE:
            raise ValueError(f"unknown device state {state!r}")
        self.state = state
        self._publish()

    def _publish(self) -> None:
        metrics.set("fleet_device_state", _STATE_CODE[self.state],
                    labels={"device": self.label})

    # ------------------------------------------ deterministic injection

    def schedule_halt(self, after_dispatches: int) -> None:
        """Arm a VIRTUAL halt: the first :meth:`note_dispatch` at or
        past ``after_dispatches`` total dispatches on this member
        raises :class:`DeviceHalt` — the deterministic pool-scoped
        twin of the fault injector's ``device_halt`` action, for CPU
        CI where no real device can die."""
        self._halt_at = max(0, int(after_dispatches))
        self._halt_fired = False

    def note_dispatch(self, check: bool = True) -> None:
        """Count one device dispatch; fires the scheduled virtual
        halt exactly once.  Called by the fleet on every lane solo
        dispatch and once per formed batch (``check=False`` there —
        scheduled halts fire at SOLO dispatch boundaries, where the
        lane's healer classifies them; a halt raised mid-batch would
        be absorbed by the former's solo fallback)."""
        self.dispatches += 1
        if (check and self._halt_at is not None and not self._halt_fired
                and self.state == STATE_OK
                and self.dispatches > self._halt_at):
            self._halt_fired = True
            from srtb_tpu.resilience.errors import DeviceHalt
            raise DeviceHalt(
                f"virtual pool device {self.label} halted "
                f"(scheduled at dispatch {self._halt_at})")

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"PoolDevice({self.label}, state={self.state}, "
                f"dispatches={self.dispatches})")


class DevicePool:
    """The fleet's device membership: real ``jax.devices()`` members
    on accelerator hosts, a deterministic virtual pool on CPU CI."""

    def __init__(self, count: int = 1, jax_devices=None):
        count = max(1, int(count))
        devs = list(jax_devices or [])
        self.devices = [
            PoolDevice(i, jax_device=devs[i] if i < len(devs) else None)
            for i in range(count)]
        metrics.set("fleet_pool_size", len(self.devices))

    @classmethod
    def from_config(cls, cfg) -> "DevicePool":
        """Build the pool from ``Config.fleet_devices`` (the FLEET
        config).  0/1 = the legacy single-device fleet (everything
        still routes through a one-member pool).  >= 2 on an
        accelerator host binds real ``jax.devices()`` members (capped
        at the hardware count); on CPU the pool is virtual — N
        logical members, one physical device, distinct plan caches."""
        want = int(getattr(cfg, "fleet_devices", 0) or 0)
        if want <= 1:
            return cls(1)
        from srtb_tpu.utils.platform import on_accelerator
        if on_accelerator():
            import jax
            have = jax.devices()
            if want > len(have):
                log.warning(
                    f"[pool] fleet_devices={want} exceeds the "
                    f"{len(have)} visible devices; capping")
                want = len(have)
            return cls(want, jax_devices=have[:want])
        log.info(f"[pool] virtual {want}-device pool (CPU): distinct "
                 "plan caches / halt domains on one physical device")
        return cls(want)

    def __len__(self) -> int:
        return len(self.devices)

    def __iter__(self):
        return iter(self.devices)

    def healthy(self) -> list[PoolDevice]:
        """Members accepting placements (not draining, not halted)."""
        return [d for d in self.devices if d.state == STATE_OK]

    @property
    def total_dispatches(self) -> int:
        return sum(d.dispatches for d in self.devices)

    @property
    def compiles(self) -> int:
        """Pool-wide plan-family compiles (sum of member caches)."""
        return sum(d.plans.compiles for d in self.devices)

    @property
    def hits(self) -> int:
        return sum(d.plans.hits for d in self.devices)

    def schedule_halt(self, index: int, after_dispatches: int) -> None:
        self.devices[index].schedule_halt(after_dispatches)

    def invalidate_all(self) -> None:
        """Fleet-wide reinit (the no-peer last resort): every member's
        cache force-retired and every member re-armed — the backend
        under the whole pool was reinitialized, so halted members are
        healthy again."""
        for d in self.devices:
            d.plans.invalidate()
            if d.state != STATE_OK:
                d.set_state(STATE_OK)
