"""Streaming checkpoint/resume.

The reference is a streaming system with no checkpointing; its closest
analogs are FFTW wisdom, the piggybank capture, and
``input_file_offset_bytes`` for resuming file reads (SURVEY.md §5.4).
Here resume is first-class: a small JSON state file tracks the logical
file offset and segment counter so a crashed/restarted file-mode run
continues where it stopped, and the persistent XLA compile cache
(utils.compile_cache) removes the recompilation cost on restart.

Durability (ISSUE 10):

- the state file carries a CRC32 of its canonical JSON (shared
  encoding with the run manifest, io/manifest.py), so a torn or
  bit-rotted checkpoint is DETECTED instead of silently parsed;
- every update keeps the previous generation as ``<path>.bak``; a
  corrupt/unreadable/missing primary falls back to it with a loud
  warning — at worst one segment of progress is repeated, and the run
  manifest's done-set makes that repeat idempotent.  Only when BOTH
  generations are dead does the run restart from segment 0, as an
  ERROR, never silently;
- with a run manifest bound, ``update`` logs the manifest's ``ckpt``
  consistency-point record BEFORE rewriting the state file: the
  checkpoint can never claim progress the manifest has not sealed
  ("checkpoint ahead of manifest" is always corruption — fsck flags
  it);
- the renames are followed by a parent-directory fsync
  (io/writers.fsync_dir) so a published checkpoint survives power
  loss, not just process death.
"""

from __future__ import annotations

import json
import os

from srtb_tpu.utils.logging import log


class StreamCheckpoint:
    def __init__(self, path: str, manifest=None):
        self.path = path
        self.manifest = manifest
        self.state = {"segments_done": 0, "file_offset_bytes": 0}
        # recovery sweep: a crash between the temp write and the
        # atomic rename in update() leaves a stale <path>.tmp; the
        # durable state is whatever the rename last published, so the
        # orphan is simply removed before resuming from it
        tmp = path + ".tmp"
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
                log.warning(f"[checkpoint] removed orphan temp {tmp} "
                            "from an interrupted update")
            except OSError as e:
                log.warning(f"[checkpoint] cannot remove {tmp}: {e}")
        loaded = self._load(path)
        if loaded is None and (os.path.exists(path)
                               or os.path.exists(path + ".bak")):
            loaded = self._load(path + ".bak")
            if loaded is not None:
                log.warning(
                    f"[checkpoint] primary {path} corrupt or missing: "
                    f"resuming from previous generation {path}.bak "
                    f"(at worst one segment of progress is repeated)")
            else:
                log.error(
                    f"[checkpoint] BOTH {path} and {path}.bak are "
                    "unreadable/corrupt: restarting from segment 0 — "
                    "expect the run manifest (if armed) to skip "
                    "already-committed artifacts")
        if loaded is not None:
            self.state.update(loaded)
            log.info(f"[checkpoint] resuming from {path}: "
                     f"{self.state}")

    @staticmethod
    def _load(path: str) -> dict | None:
        """Parse + CRC-verify one checkpoint generation; None when
        missing, unparseable, or failing its integrity check.
        Pre-CRC-era files (no ``crc`` key) are accepted as legacy."""
        try:
            with open(path) as f:
                data = json.load(f)
        except FileNotFoundError:
            return None
        except (json.JSONDecodeError, OSError, ValueError) as e:
            log.warning(f"[checkpoint] unreadable {path}: {e}")
            return None
        if not isinstance(data, dict):
            log.warning(f"[checkpoint] malformed {path}: not an object")
            return None
        crc = data.pop("crc", None)
        if crc is not None:
            from srtb_tpu.io.manifest import record_crc
            if record_crc(data) != crc:
                log.warning(f"[checkpoint] CRC mismatch in {path}: "
                            "corrupt state rejected")
                return None
        return data

    @property
    def segments_done(self) -> int:
        return self.state["segments_done"]

    @property
    def file_offset_bytes(self) -> int:
        return self.state["file_offset_bytes"]

    def update(self, segments_done: int, file_offset_bytes: int) -> None:
        from srtb_tpu.io.manifest import record_crc
        from srtb_tpu.io.writers import fsync_dir
        self.state["segments_done"] = segments_done
        self.state["file_offset_bytes"] = file_offset_bytes
        if self.manifest is not None:
            # consistency point FIRST: a crash between here and the
            # file rename leaves the checkpoint file one generation
            # behind the manifest — safe (the resume re-drains one
            # segment and the manifest done-set skips its sinks).
            # The reverse order could leave a checkpoint claiming
            # progress the manifest never sealed.
            self.manifest.checkpoint(segments_done, file_offset_bytes)
        body = dict(self.state)
        body["crc"] = record_crc(self.state)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(body, f)
            f.flush()
            os.fsync(f.fileno())
        if os.path.exists(self.path):
            # keep the previous generation: a crash between these two
            # renames leaves no primary but a valid .bak (the loader's
            # fallback) plus the fsync'd tmp — never zero generations
            os.replace(self.path, self.path + ".bak")
        os.replace(tmp, self.path)  # atomic, like the fdatasync'd writers
        fsync_dir(self.path)

    def clear(self) -> None:
        for p in (self.path, self.path + ".bak"):
            if os.path.exists(p):
                os.unlink(p)
