"""Streaming checkpoint/resume.

The reference is a streaming system with no checkpointing; its closest
analogs are FFTW wisdom, the piggybank capture, and
``input_file_offset_bytes`` for resuming file reads (SURVEY.md §5.4).
Here resume is first-class: a small JSON state file tracks the logical
file offset and segment counter so a crashed/restarted file-mode run
continues where it stopped, and the persistent XLA compile cache
(utils.compile_cache) removes the recompilation cost on restart.
"""

from __future__ import annotations

import json
import os

from srtb_tpu.utils.logging import log


class StreamCheckpoint:
    def __init__(self, path: str):
        self.path = path
        self.state = {"segments_done": 0, "file_offset_bytes": 0}
        # recovery sweep: a crash between the temp write and the
        # atomic rename in update() leaves a stale <path>.tmp; the
        # durable state is whatever the rename last published, so the
        # orphan is simply removed before resuming from it
        tmp = path + ".tmp"
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
                log.warning(f"[checkpoint] removed orphan temp {tmp} "
                            "from an interrupted update")
            except OSError as e:
                log.warning(f"[checkpoint] cannot remove {tmp}: {e}")
        if os.path.exists(path):
            try:
                with open(path) as f:
                    self.state.update(json.load(f))
                log.info(f"[checkpoint] resuming from {path}: "
                         f"{self.state}")
            except (json.JSONDecodeError, OSError) as e:
                log.warning(f"[checkpoint] unreadable {path}: {e}")

    @property
    def segments_done(self) -> int:
        return self.state["segments_done"]

    @property
    def file_offset_bytes(self) -> int:
        return self.state["file_offset_bytes"]

    def update(self, segments_done: int, file_offset_bytes: int) -> None:
        self.state["segments_done"] = segments_done
        self.state["file_offset_bytes"] = file_offset_bytes
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.state, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)  # atomic, like the fdatasync'd writers

    def clear(self) -> None:
        if os.path.exists(self.path):
            os.unlink(self.path)
