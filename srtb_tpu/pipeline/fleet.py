"""Multi-tenant stream fleet: N concurrent streams on one device.

The reference backend serves one stream per process; the production
target (ROADMAP item 1) is one engine serving many concurrent beams
and replay jobs from one device — the concurrent-streams architecture
of *Implementing CUDA Streams into AstroAccelerate* (arXiv:2101.00941),
where independent streams hide each other's transfer/compute gaps.
This module makes that multi-tenancy SAFE before it is fast:

- **Round-robin scheduler**: one scheduler thread multiplexes every
  admitted stream's in-flight window onto the shared device dispatch
  queue — each :class:`_StreamLane` is a cooperative state machine
  (``step()``) over the same Pipeline building blocks the solo engine
  uses (``_dispatch_segment`` / ``_fetch_inflight`` / ``_drain_body``),
  so lane outputs are bit-identical to solo runs by construction.

- **Shared AOT plan cache** (:class:`SharedPlanCache`): streams whose
  trace-relevant config projects identically
  (``SegmentProcessor.plan_cache_key``) share ONE ``SegmentProcessor``
  — one jit cache, one set of compiled programs; the second stream of
  a plan family compiles nothing.  Shared processors are
  ``mark_shared()``-ed so a single lane's plan demotion can never
  retire the programs its neighbors are dispatching through.

- **Per-stream bulkheads**: every lane owns its OWN Pipeline instance
  and with it its own ComputeHealer ladder position, degradation
  ladder, retry policy, fault injector (stream-selector scoped),
  supervisor restart budget, ring carry, checkpoint, telemetry
  journal and RunManifest namespace — a DEVICE fault, sink wedge or
  manifest rollback on stream A demotes/sheds/rolls back A only.  The
  one deliberately SHARED failure domain is a true device halt: the
  device under every lane died, so the fleet makes one budgeted
  reinit decision and cold-restarts every lane from its retained host
  buffers (journal order and exactly-once outputs preserved per
  stream, like the solo engine's reinit).

- **Admission control + priority shedding**: the
  :class:`~srtb_tpu.resilience.admission.AdmissionController` gates
  stream starts (``fleet_max_streams`` / ``fleet_queue_limit``,
  priority-ordered), and under fleet-wide sink pressure the
  :class:`~srtb_tpu.resilience.degrade.FleetShedPolicy` force-sheds
  the lowest-priority REAL-TIME stream first (hysteretic, loss
  accounted per stream) instead of letting the overload land on an
  arbitrary tenant.

Every per-stream quantity is labeled: loss counters, degrade /
ladder levels, in-flight depth (``{stream="..."}`` series on
/metrics), the v6 journal's ``stream`` field, and /healthz per-stream
staleness.  The fleet chaos gate is ``tools/fleet_soak.py``.

Limits (documented, enforced loudly): REAL-TIME lanes are
single-segment dispatch units (``micro_batch_segments`` must be 1
there — batching ingest on a live stream trades bounded latency for
throughput silently; use the solo engine).  FILE-MODE lanes may
micro-batch: replaying recorded baseband has no latency contract, so
the archive replay engine (pipeline/archive.py) batches B segments
into one vmapped dispatch per lane for full device occupancy.
``Config.sanitize`` is unsupported inside a fleet (the sanitizer's
thread-ownership guards assume one engine per process).
"""

from __future__ import annotations

import collections
import time
from dataclasses import dataclass, field
from typing import Any

from srtb_tpu.config import Config
from srtb_tpu.pipeline import framework as fw
from srtb_tpu.pipeline.runtime import Pipeline, PipelineStats
from srtb_tpu.pipeline.segment import SegmentProcessor
from srtb_tpu.resilience.admission import (ADMIT, QUEUE,
                                           AdmissionController)
from srtb_tpu.resilience.degrade import FleetShedPolicy
from srtb_tpu.resilience.errors import (DEVICE_HALT, LadderExhausted,
                                        ReinitBudgetExceeded)
from srtb_tpu.resilience.supervisor import Supervisor
from srtb_tpu.utils import events, telemetry
from srtb_tpu.utils.logging import log
from srtb_tpu.utils.metrics import metrics


@dataclass
class StreamSpec:
    """One stream's identity + wiring handed to the fleet.  ``cfg``
    is the stream's OWN config: per-stream paths (output prefix,
    checkpoint, manifest, journal) are its bulkhead namespace;
    trace-relevant fields shared with other streams let them share a
    compiled plan."""
    name: str
    cfg: Config
    source: Any = None
    sinks: Any = None
    keep_waterfall: bool = True
    max_segments: int | None = None

    @property
    def priority(self) -> int:
        return int(getattr(self.cfg, "stream_priority", 0) or 0)


@dataclass
class StreamResult:
    """Per-stream outcome of a fleet run."""
    name: str
    status: str                  # done | failed | rejected
    stats: PipelineStats | None = None
    error: BaseException | None = None
    drained: int = 0
    dropped: int = 0
    extras: dict = field(default_factory=dict)


class SharedPlanCache:
    """One ``SegmentProcessor`` per plan family, shared across every
    stream whose trace-relevant config projects identically
    (``SegmentProcessor.plan_cache_key``).  ``compiles`` counts
    processor builds (one per family — the proof the fleet soak
    gates on), ``hits`` counts streams served an existing plan."""

    def __init__(self):
        self._by_key: dict[str, SegmentProcessor] = {}
        self.compiles = 0
        self.hits = 0

    def get(self, cfg: Config,
            donate_input: bool = False) -> SegmentProcessor:
        # keyed AND built through the plan registry: a registered
        # search mode's processor class serves its lanes, and plans of
        # different modes can never share a cache slot (the key
        # carries the mode)
        from srtb_tpu.pipeline import registry
        key = registry.plan_cache_key(cfg, donate_input=donate_input)
        # per-stream labeled twins (performance observatory): which
        # tenant paid a compile and which rode a shared plan for free
        # must be scrapeable, not just the fleet totals
        lbl = ({"stream": cfg.stream_name}
               if getattr(cfg, "stream_name", "") else None)
        proc = self._by_key.get(key)
        if proc is None:
            proc = registry.build_processor(
                cfg, donate_input=donate_input).mark_shared()
            self._by_key[key] = proc
            self.compiles += 1
            metrics.add("fleet_plan_compiles")
            if lbl is not None:
                metrics.add("fleet_plan_compiles", labels=lbl)
            log.info(f"[fleet] plan cache MISS: built shared plan "
                     f"{proc.plan_name} ({self.compiles} families)")
        else:
            self.hits += 1
            metrics.add("fleet_plan_cache_hits")
            if lbl is not None:
                metrics.add("fleet_plan_cache_hits", labels=lbl)
        return proc

    def invalidate(self) -> None:
        """Retire every shared plan (force past the shared guard) and
        forget it: after a device reinit the compiled handles are
        bound to the dead backend, and the next ``get`` rebuilds."""
        for proc in self._by_key.values():
            proc.retire(force=True)
        self._by_key.clear()


class _StreamLane:
    """One admitted stream's cooperative engine: a step()-driven
    in-flight window over the lane's own Pipeline, with sink work on
    a per-lane pipe thread (the bulkhead: a wedged or crashed sink
    stalls/sheds THIS lane only)."""

    def __init__(self, fleet: "StreamFleet", spec: StreamSpec):
        cfg = spec.cfg
        real_time = not cfg.input_file_path
        mb = int(getattr(cfg, "micro_batch_segments", 1) or 1)
        if mb > 1 and real_time:
            # file-mode (archive replay) lanes may batch — replaying
            # recorded baseband has no latency contract; a LIVE
            # stream batching ingest would silently trade bounded
            # latency for throughput, so real-time lanes reject loudly
            raise ValueError(
                f"stream {spec.name!r}: micro_batch_segments > 1 is "
                "only supported on file-mode (non-real-time) fleet "
                "lanes (use the solo engine for a batched live "
                "stream)")
        if getattr(cfg, "sanitize", False):
            raise ValueError(
                f"stream {spec.name!r}: Config.sanitize is "
                "incompatible with fleet scheduling (single-engine "
                "thread-ownership guards)")
        # every validation that can fail is pure-config-decidable and
        # sits BEFORE Pipeline construction: a lane rejected here must
        # not leak an opened Pipeline (input file, checkpoint,
        # manifest WAL fd, telemetry registration) into a failed
        # StreamResult that nothing ever closes
        self.window = max(1, int(getattr(cfg, "inflight_segments", 2)
                                 or 1))
        self.micro_batch = mb
        if mb > self.window:
            raise ValueError(
                f"stream {spec.name!r}: micro_batch_segments={mb} "
                f"exceeds inflight_segments={self.window}: a batch "
                "dispatch must fit the lane's in-flight window")
        if mb > 1:
            from srtb_tpu.pipeline.segment import staged_resolves
            if staged_resolves(cfg):
                raise ValueError(
                    f"stream {spec.name!r}: micro_batch_segments > 1 "
                    "requires the fused plan (staged segments are "
                    "already dispatch-amortized)")
        self.fleet = fleet
        self.spec = spec
        self.name = spec.name
        self.priority = spec.priority
        from srtb_tpu.utils.platform import on_accelerator
        self.pipe = Pipeline(
            cfg, source=spec.source, sinks=spec.sinks,
            keep_waterfall=spec.keep_waterfall,
            processor=fleet.plans.get(
                cfg, donate_input=on_accelerator()))
        self.real_time = real_time
        self.max_segments = spec.max_segments
        self.deadline_s = float(cfg.segment_deadline_s or 0.0)
        self.join_s = float(getattr(cfg, "shutdown_join_timeout_s", 0)
                            or 0)
        self.pending: collections.deque = collections.deque()
        self._it = iter(self.pipe.source)
        self.dispatched = 0
        self.exhausted = False
        self.drained = [self.pipe.checkpoint.segments_done
                        if self.pipe.checkpoint else 0]
        self._drained0 = self.drained[0]
        self.done = False
        self.status = "running"
        self.error: BaseException | None = None
        # fleet fairness: force-shed (ingest-and-account, no dispatch)
        self.forced_shed = False
        # "this lane waited on its sink since the fleet's last
        # fairness observation" — the pressure signal
        self.sink_wait = False
        self._emitted_since_obs = 0
        # fetched item awaiting sink-queue space (the lane's emit
        # backpressure point)
        self._staged_emit = None
        self._wedge_t0 = None
        self._wedge_mark = None
        # parked-window watchdog (whole window stuck behind the sink)
        self._park_t0 = None
        self._park_mark = None
        # lane-local loss recency (the engine's 10 s loss window,
        # scoped to THIS stream's labeled counter): when this lane
        # last saw its own accounted loss grow
        self._loss_seen = 0.0
        self._loss_t = None
        # bounded sentinel push at close
        self._sentinel_t0 = None
        self._t_start = time.perf_counter()
        self._t_close = None
        # dispatched-through-sink count (the lane's live window);
        # written by the scheduler thread and the lane's sink thread
        import threading
        self._live_lock = threading.Lock()
        self._live = 0
        # per-lane sink pipe + bounded-restart supervision (each
        # stream its own restart budget)
        self._stop = fw.StopToken()
        self._q_sink = fw.WorkQueue(capacity=self.window)
        self._current = [None]
        self._progress = [self.drained[0]]
        self._supervisor = None
        if int(getattr(cfg, "supervisor_max_restarts", 0)) > 0:
            self._supervisor = Supervisor(
                f"sink_drain_{self.name}",
                max_restarts=cfg.supervisor_max_restarts,
                window_s=getattr(cfg, "supervisor_window_s", 60.0))
        self._sink_pipe = fw.start_pipe(
            self._sink_f, self._q_sink, None, self._stop,
            f"sink_drain:{self.name}")
        telemetry.register_stream(self.name)

    # ------------------------------------------------------ accounting

    def _live_add(self, n: int) -> None:
        with self._live_lock:
            self._live += n
            metrics.set("inflight_depth", self._live,
                        labels={"stream": self.name})

    def _live_count(self) -> int:
        with self._live_lock:
            return self._live

    # ------------------------------------------------------- sink side

    def _sink_f(self, _stop, item):
        self._current[0] = item
        self._progress[0] = self.drained[0]
        try:
            self.pipe._drain_body(item, self.drained)
        finally:
            if "abandoned" not in item[-1]:
                self._live_add(-1)
        self._current[0] = None

    def _sink_alive(self) -> bool:
        """True while this lane's sink side can make progress;
        restarts a supervised crashed pipe (replaying the unaccounted
        item inline first — journal order kept, same contract as the
        solo engine)."""
        if self._sink_pipe.exception is None:
            return True
        if self._supervisor is None or \
                not self._supervisor.should_restart(
                    self._sink_pipe.exception):
            return False
        failed, self._current[0] = self._current[0], None
        if failed is not None and failed is not fw.SENTINEL:
            if self.drained[0] == self._progress[0]:
                self.pipe._drain_body(failed, self.drained)
            else:
                log.warning(
                    f"[fleet:{self.name}] sink crashed after its "
                    "segment was accounted; skipping replay")
        self._sink_pipe = fw.start_pipe(
            self._sink_f, self._q_sink, None, self._stop,
            f"sink_drain:{self.name}")
        return True

    # ------------------------------------------------------ heal hooks

    def _heal(self, exc: BaseException) -> bool:
        """Device-fault recovery with the fleet's blast-radius rules:
        OOM/compile faults demote THIS lane's plan only (the shared
        processor is swapped out for an unshared demoted one — and
        never retired under the neighbors); a device HALT is the one
        shared failure domain and goes to the fleet's single budgeted
        reinit."""
        h = self.pipe.healer
        if h is None:
            return False
        kind = h.classify(exc)
        if kind is None:
            return False
        if kind == DEVICE_HALT:
            if self.fleet._reinit_all(exc, faulting=self.name):
                return True
            raise ReinitBudgetExceeded(
                "device halt beyond fleet reinit recovery "
                f"(budget spent or disabled): {exc}") from exc
        newp = h.demote(exc, kind)
        if newp is None:
            raise LadderExhausted(
                f"stream {self.name!r}: device fault survived every "
                f"demotion rung: {exc}") from exc
        self.pipe._swap_processor(newp)
        return True

    def _dispatch(self, seg, ingest_s, offset_after, index,
                  requeue=False):
        while True:
            try:
                return self.pipe._dispatch_segment(
                    seg, ingest_s, offset_after, index,
                    requeue=requeue)
            except BaseException as e:  # noqa: BLE001 — classified
                if not self._heal(e):
                    raise
                requeue = True

    def _unit(self) -> int:
        """The lane's dispatch unit: the active plan's micro-batch
        (dynamic — the self-healing ladder's micro_batch rung drops
        it to 1, and the lane must follow the demoted plan exactly
        like the solo engine's cur_unit)."""
        h = self.pipe.healer
        if h is not None:
            return min(self.window, h.micro_batch)
        return self.micro_batch

    def _dispatch_batch(self, got: list, b: int) -> list:
        """Dispatch up to B ingested segments as ONE vmapped jit call
        (file-mode archive lanes).  Unit 1, a short tail, or a healed
        plan that no longer micro-batches all finish as plain single
        dispatches (the vmapped B=1 program is a DIFFERENT trace —
        the single path keeps lane outputs bit-identical to solo
        runs), result-compatible by the solo engine's proof."""
        segs, ingests, offsets = map(list, zip(*got))
        first = self.dispatched
        if b > 1 and len(segs) == b:
            try:
                return self.pipe._dispatch_micro_batch(
                    segs, ingests, offsets, first)
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 — classified
                if not self._heal(e):
                    raise
                return [self._dispatch(s, dt, off, first + i,
                                       requeue=True)
                        for i, (s, dt, off) in enumerate(got)]
        return [self._dispatch(s, dt, off, first + i)
                for i, (s, dt, off) in enumerate(got)]

    def reinit_cold(self) -> None:
        """Fleet-wide device reinit, this lane's share: swap in a
        fresh processor at the lane's current ladder rung and
        re-dispatch every in-flight segment cold from its retained
        host buffer, in dispatch order."""
        h = self.pipe.healer
        if h is not None:
            newp = h.rebuild()
        else:
            from srtb_tpu.utils.platform import on_accelerator
            newp = self.fleet.plans.get(
                self.pipe.cfg, donate_input=on_accelerator())
        self.pipe._swap_processor(newp)
        for i in range(len(self.pending)):
            seg, _wf, _det, offset_after, span, _t0, idx = \
                self.pending[i]
            self.pending[i] = self.pipe._dispatch_segment(
                seg, span["ingest"], offset_after, idx, requeue=True)

    # ----------------------------------------------------- engine step

    def _want_more(self) -> bool:
        return (not self.exhausted
                and (self.max_segments is None
                     or self.dispatched < self.max_segments))

    def _ingest_one(self, index: int):
        seg = self.pipe._timed_ingest(self._it, index)
        if seg is None:
            self.exhausted = True
            return None
        return (seg, self.pipe.stage_timer.last["ingest"],
                getattr(self.pipe.source, "logical_offset", 0))

    def _observe_level(self) -> int:
        """Per-lane degradation observation at emit (the solo engine's
        emit() signal, lane-scoped): occupancy 1.0 when this lane
        waited on its sink since the last emit, plus the lane's own
        recent accounted loss."""
        ladder = self.pipe._ladder
        if ladder is None:
            return 0
        if not self.real_time:
            occupancy = 0.0
        elif self.sink_wait:
            occupancy = 1.0
        else:
            occupancy = self._q_sink.qsize() / self.window
        # loss signal scoped to THIS stream: the process-wide window
        # would let a noisy neighbor's drops degrade a healthy lane —
        # exactly the blast radius the bulkheads exist to prevent
        cur = metrics.get("segments_dropped",
                          labels={"stream": self.name})
        if cur > self._loss_seen:
            self._loss_seen = cur
            self._loss_t = time.perf_counter()
        loss = (self._loss_t is not None
                and time.perf_counter() - self._loss_t < 10.0)
        return ladder.observe(occupancy, loss)

    def _shed_item(self, item) -> None:
        """Account one fetched-but-unsunk item as this stream's loss
        and release its buffers (the solo engine's shed_segment,
        lane-scoped)."""
        pipe = self.pipe
        pipe._account_dropped(trace=getattr(item[0], "trace_id", 0))
        pipe._ring_invalidate()
        self._live_add(-1)
        rel = getattr(pipe.processor, "release_staging", None)
        if rel is not None:
            rel(item[0].data)
        pool = getattr(pipe.source, "pool", None)
        if pool is not None and pipe.cfg.input_file_path:
            pool.release(item[0].data)

    def _try_emit(self) -> bool:
        """Push the staged fetched item to this lane's sink pipe.
        Queue full = lane-local backpressure (flagged for the fleet's
        fairness observation); a sink wedged past the deadline with
        zero per-push progress sheds the item as accounted per-stream
        loss (real-time lanes only — a file-mode lane throttles
        losslessly, exactly like the solo engine)."""
        item = self._staged_emit
        if self._q_sink.push_lossy(item):
            self._staged_emit = None
            self._wedge_t0 = None
            self._emitted_since_obs += 1
            return True
        self.sink_wait = True
        if self.deadline_s > 0 and self.real_time:
            cur = (self.drained[0], self.pipe._sink_heartbeat)
            if self._wedge_t0 is None or cur != self._wedge_mark:
                self._wedge_t0 = time.perf_counter()
                self._wedge_mark = cur
            elif time.perf_counter() - self._wedge_t0 \
                    > self.deadline_s:
                log.error(
                    f"[fleet:{self.name}] sink wedged past "
                    f"{self.deadline_s:g}s with no drain progress: "
                    "shedding segment as accounted loss")
                self._shed_item(item)
                self._staged_emit = None
                self._wedge_t0 = None
                return True
        return False

    def _drain_head(self, block: bool) -> bool:
        """Fetch the oldest in-flight segment (device-fault healed)
        and stage it for emit.  ``block`` allows a blocking fetch;
        otherwise only a device-ready head is fetched."""
        if not block and not Pipeline._result_ready(self.pending[0][2]):
            return False
        depth = len(self.pending)
        live_now = self._live_count()
        item = self.pending.popleft()
        while True:
            try:
                fetched = self.pipe._fetch_inflight(item, depth,
                                                    live_now)
                break
            except BaseException as e:  # noqa: BLE001 — classified
                if not self._heal(e):
                    raise
                seg, _wf, _det, offset_after, span, _t0, idx = item
                item = self._dispatch(seg, span["ingest"],
                                      offset_after, idx, requeue=True)
        h = self.pipe.healer
        if h is not None:
            h.note_healthy()
        level = self._observe_level()
        self.sink_wait = False
        self._staged_emit = fetched + (level, set())
        self._try_emit()
        return True

    def step(self, allow_block: bool = False) -> bool:
        """One cooperative scheduler slice; returns True when the lane
        made progress.  Any escaping failure is contained to this
        lane (the fleet's bulkhead): the lane fails, accounts its
        in-flight segments as per-stream loss, and its neighbors
        never observe it."""
        if self.done:
            return False
        try:
            return self._step_inner(allow_block)
        except (KeyboardInterrupt, SystemExit):
            # operator interrupts are NOT lane faults: containing one
            # would shed a tenant's data and leave the fleet running
            # un-interruptibly — propagate to stop the whole run
            raise
        except BaseException as e:  # noqa: BLE001 — bulkhead boundary
            self._fail(e)
            return True

    def _step_inner(self, allow_block: bool) -> bool:
        if self.status == "closing":
            return self._step_close()
        if not self._sink_alive():
            raise self._sink_pipe.exception
        # 0) a fetched item waiting for sink-queue space blocks the
        #    lane's drain (in-order) but nothing else
        if self._staged_emit is not None:
            if not self._try_emit():
                return False
        # 1) fleet fairness force-shed: keep draining the source,
        #    account every undispatched segment as this tenant's loss
        if self.forced_shed and self._want_more():
            one = self._ingest_one(self.dispatched)
            if one is not None:
                self.dispatched += 1
                log.warning(f"[fleet:{self.name}] force-shed: "
                            "dropping ingested segment (accounted)")
                self.pipe._account_dropped(
                    trace=getattr(one[0], "trace_id", 0))
                self.pipe._ring_invalidate()
                pool = getattr(self.pipe.source, "pool", None)
                if pool is not None and self.pipe.cfg.input_file_path:
                    pool.release(one[0].data)
                return True
        # 2) drain whatever is device-ready, in order
        if self.pending and self._drain_head(block=False):
            return True
        # 3) admit + dispatch the next unit while the window has room
        #    (file-mode lanes may micro-batch: B segments, one jit
        #    call — admission gates on the WHOLE unit fitting, so the
        #    lane's in-flight depth never exceeds its window; the
        #    b = 1 case is the same path with a budget of one, routed
        #    to a plain single dispatch inside _dispatch_batch)
        if self._live_count() + self._unit() <= self.window \
                and self._want_more() and not self.forced_shed:
            self._maybe_promote()
            b = self._unit()
            if self._live_count() + b <= self.window:
                # (a promotion probe may have restored a bigger unit
                # that no longer fits: drain first, dispatch later)
                budget = b if self.max_segments is None else \
                    min(b, self.max_segments - self.dispatched)
                got = []
                while len(got) < budget:
                    one = self._ingest_one(self.dispatched + len(got))
                    if one is None:
                        break
                    got.append(one)
                if got:
                    self.pending.extend(self._dispatch_batch(got, b))
                    self._live_add(len(got))
                    self.dispatched += len(got)
                    self.pipe.stats.segments += len(got)
                    self.pipe.stats.samples += \
                        self.pipe.cfg.baseband_input_count * len(got)
                    self._park_t0 = None
                    return True
        # 3b) whole window parked behind the sink: a real-time lane
        #    must never stall on a wedged sink — past the deadline
        #    with zero per-push progress, keep draining the source
        #    and account each undispatched segment as this stream's
        #    loss (the solo engine's shed_ingest, lane-scoped)
        if self.real_time and self.deadline_s > 0 \
                and self._want_more() and not self.pending \
                and self._staged_emit is None \
                and self._live_count() >= self.window:
            self.sink_wait = True
            cur = (self.drained[0], self.pipe._sink_heartbeat)
            if self._park_t0 is None or cur != self._park_mark:
                self._park_t0 = time.perf_counter()
                self._park_mark = cur
            elif time.perf_counter() - self._park_t0 \
                    > self.deadline_s:
                one = self._ingest_one(self.dispatched)
                if one is not None:
                    self.dispatched += 1
                    log.error(
                        f"[fleet:{self.name}] sink wedged with a "
                        "full window: shedding ingested segment as "
                        "accounted loss")
                    self.pipe._account_dropped(
                        trace=getattr(one[0], "trace_id", 0))
                    self.pipe._ring_invalidate()
                    pool = getattr(self.pipe.source, "pool", None)
                    if pool is not None \
                            and self.pipe.cfg.input_file_path:
                        pool.release(one[0].data)
                    return True
            return False
        # 4) window full (or source done) with an unready head: only a
        #    blocking fetch makes progress — the fleet grants that to
        #    one lane per idle round
        if self.pending and allow_block:
            return self._drain_head(block=True)
        # 5) complete: everything dispatched, drained and handed to
        #    the sink — close the lane (sentinel + bounded join).  A
        #    wedged sink can hold the queue full forever; the
        #    sentinel push is bounded by shutdown_join_timeout_s like
        #    the solo engine's
        if not self.pending and self._staged_emit is None \
                and not self._want_more():
            if self._q_sink.push_lossy(fw.SENTINEL):
                self.status = "closing"
                self._t_close = time.perf_counter()
                self._sentinel_t0 = None
                return True
            if self._sentinel_t0 is None:
                self._sentinel_t0 = time.perf_counter()
            elif self.join_s > 0 and \
                    time.perf_counter() - self._sentinel_t0 \
                    > self.join_s:
                self._wedge_teardown()
                return True
        return False

    def _maybe_promote(self) -> None:
        h = self.pipe.healer
        if h is not None and h.promote_due():
            newp = h.promote()
            if newp is not None:
                self.pipe._swap_processor(newp)

    def _step_close(self) -> bool:
        """Closing: wait for the lane's sink pipe to drain + exit,
        bounded by shutdown_join_timeout_s (0 = wait as long as it
        takes — but never blocking the scheduler more than a poll)."""
        if self._sink_pipe.exception is not None:
            if not self._sink_alive():
                raise self._sink_pipe.exception
            # supervised restart mid-close: the sentinel is still on
            # the queue unless the crash consumed past it; repost
            # (lossy — a duplicate sentinel is harmless, the pipe
            # exits on the first)
            self._q_sink.push_lossy(fw.SENTINEL)
            return True
        if self._sink_pipe.join(0.002):
            self._finish()
            return True
        if self.join_s > 0 and \
                time.perf_counter() - self._t_close > self.join_s:
            self._wedge_teardown()
            return True
        return False

    def _wedge_teardown(self) -> None:
        """Bounded-shutdown giveup on a wedged sink: report the
        thread, account still-queued segments as this stream's loss,
        and finish with the pool abandoned (never drained)."""
        from srtb_tpu.utils import termination
        self.pipe._sink_wedged = True
        self.pipe._incident(
            "sink_wedge_shutdown",
            reason=f"fleet lane {self.name}: sink pipe still alive "
                   f"after the {self.join_s:g}s join budget")
        termination.report_wedged(
            [self._sink_pipe.thread],
            f"fleet lane {self.name} shutdown "
            f"({self.join_s:g}s join timeout)")
        while True:
            leftover = self._q_sink.try_pop()
            if leftover is None:
                break
            if leftover is fw.SENTINEL:
                continue
            self._shed_item(leftover)
        held = self._current[0]
        if held is not None and held is not fw.SENTINEL:
            with self.pipe._handoff_lock:
                if self.drained[0] == self._progress[0]:
                    held[-1].add("abandoned")
                    self.pipe._account_dropped()
                    self._live_add(-1)
        self._stop.request_stop()
        log.error(f"[fleet:{self.name}] wedged sink: queued segments "
                  "accounted as segments_dropped")
        self._finish()

    def _finish(self) -> None:
        if not self.pipe._sink_wedged:
            self.pipe._drain_sinks()
        self.pipe.stats.elapsed_s = time.perf_counter() - self._t_start
        self.pipe.stats.extras["stages"] = \
            self.pipe.stage_timer.summary()
        self.status = "done"
        self.done = True
        metrics.set("inflight_depth", 0, labels={"stream": self.name})
        telemetry.release_stream(self.name)
        log.info(f"[fleet:{self.name}] done: "
                 f"{self.pipe.stats.segments} segments, "
                 f"{self.drained[0] - self._drained0} drained")

    def _fail(self, exc: BaseException) -> None:
        """Bulkhead containment of a lane failure: every in-flight /
        queued segment becomes accounted per-stream loss, resources
        are released, neighbors never see the exception."""
        self.error = exc
        self.status = "failed"
        events.emit("fleet.lane_failed", trace=0, stream=self.name,
                    info=type(exc).__name__)
        self.pipe._incident("lane_failed",
                            reason=f"contained lane failure: {exc!r}")
        log.error(f"[fleet:{self.name}] stream failed (contained): "
                  f"{exc!r}")
        self._stop.request_stop()
        while True:
            leftover = self._q_sink.try_pop()
            if leftover is None:
                break
            if leftover is fw.SENTINEL:
                continue
            self._shed_item(leftover)
        if self._staged_emit is not None:
            self._shed_item(self._staged_emit)
            self._staged_emit = None
        while self.pending:
            item = self.pending.popleft()
            self.pipe._account_dropped(
                trace=getattr(item[0], "trace_id", 0))
            self._live_add(-1)
            rel = getattr(self.pipe.processor, "release_staging", None)
            if rel is not None:
                try:
                    rel(item[0].data)
                except Exception as e:  # noqa: BLE001 - teardown
                    log.debug(f"[fleet:{self.name}] staging release "
                              f"during teardown failed: {e!r}")
        self.pipe._ring_invalidate()
        self._q_sink.push_lossy(fw.SENTINEL)
        self._sink_pipe.join(1.0)
        self.done = True
        metrics.set("inflight_depth", 0, labels={"stream": self.name})
        telemetry.release_stream(self.name)

    def close(self) -> None:
        self.pipe.close()


class StreamFleet:
    """Serve N streams from one device (see module docstring).

    ``run()`` drives every admitted lane round-robin to completion and
    returns ``{name: StreamResult}`` — including rejected streams
    (status "rejected") and contained failures (status "failed" with
    the error attached); it raises only for fleet-level failures (an
    exhausted shared reinit budget escalating through a lane is
    contained to that lane's result).
    """

    def __init__(self, specs: list[StreamSpec],
                 fleet_cfg: Config | None = None):
        if not specs:
            raise ValueError("StreamFleet needs at least one stream")
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate stream names: {names}")
        for s in specs:
            # the lane label must reach the lane's telemetry/faults:
            # stamp the spec's config with its fleet name
            if getattr(s.cfg, "stream_name", "") not in ("", s.name):
                raise ValueError(
                    f"stream {s.name!r}: cfg.stream_name "
                    f"{s.cfg.stream_name!r} disagrees with the spec")
            s.cfg.stream_name = s.name
        self.specs = {s.name: s for s in specs}
        cfg0 = fleet_cfg if fleet_cfg is not None else specs[0].cfg
        self.plans = SharedPlanCache()
        self.admission = AdmissionController.from_config(cfg0)
        self.fairness = FleetShedPolicy.from_config(cfg0)
        # the SHARED device-halt reinit budget (one device, one
        # budget): per-lane healers keep demotion only
        self._reinit_sup = None
        reinit_max = int(getattr(cfg0, "device_reinit_max", 0) or 0)
        if reinit_max > 0:
            self._reinit_sup = Supervisor(
                "fleet_device_reinit", max_restarts=reinit_max,
                window_s=float(getattr(cfg0, "device_reinit_window_s",
                                       300.0)),
                counter=None)
        self.lanes: dict[str, _StreamLane] = {}
        self.results: dict[str, StreamResult] = {}
        self._waitlist: dict[str, StreamSpec] = {}

    # ---------------------------------------------------- lane control

    def _start(self, name: str) -> bool:
        spec = self.specs[name]
        try:
            self.lanes[name] = _StreamLane(self, spec)
            return True
        except (KeyboardInterrupt, SystemExit):
            self.admission.release(name)
            raise
        except BaseException as e:  # noqa: BLE001 — contained
            log.error(f"[fleet] stream {name!r} failed to start: "
                      f"{e!r}")
            self.admission.release(name)
            self.results[name] = StreamResult(name, "failed", error=e)
            return False

    def _start_queued(self) -> None:
        """Start queued streams into freed capacity.  Loops PAST
        start failures: a lane whose constructor raises released its
        slot, and the next queued stream must get it — otherwise a
        failed start with a non-empty waitlist would leave run()
        spinning forever with no active lanes."""
        while True:
            nxt = self.admission.pop_ready()
            if nxt is None:
                return
            spec = self._waitlist.pop(nxt, None)
            if spec is None:
                # popped a stream the waitlist no longer holds (e.g.
                # recorded rejected after an eviction race): give the
                # slot back and try the next one
                self.admission.release(nxt)
                continue
            # a start failure released its slot; keep popping until
            # capacity is genuinely full or the queue is drained
            self._start(nxt)

    def _reinit_all(self, exc: BaseException, faulting: str) -> bool:
        """The one shared failure domain: a device halt.  One budgeted
        decision (the fleet supervisor), then: drop the jax caches,
        retire + forget every shared plan, rebuild each lane's
        processor at its own ladder rung and re-dispatch each lane's
        in-flight window cold — journal order and checkpoint offsets
        unchanged per stream."""
        if self._reinit_sup is None or \
                not self._reinit_sup.should_restart(exc):
            return False
        metrics.add("device_reinits")
        metrics.add("device_reinits", labels={"stream": faulting})
        events.emit("fleet.reinit", trace=0, stream=faulting,
                    info=type(exc).__name__)
        log.warning(f"[fleet] device halt (stream {faulting!r}): "
                    "shared reinit — rebuilding every lane's plan "
                    f"({exc!r})")
        import jax
        try:
            jax.clear_caches()
        except Exception as e:  # pragma: no cover - version drift
            log.warning(f"[fleet] jax.clear_caches failed ({e!r}); "
                        "proceeding with the rebuild")
        self.plans.invalidate()
        for lane in self.lanes.values():
            if not lane.done:
                lane.reinit_cold()
        return True

    def _on_lane_done(self, lane: _StreamLane) -> None:
        self.admission.release(lane.name)
        dropped = int(metrics.get("segments_dropped",
                                  labels={"stream": lane.name}))
        self.results[lane.name] = StreamResult(
            lane.name,
            lane.status if lane.status in ("done", "failed")
            else "failed",
            stats=lane.pipe.stats, error=lane.error,
            drained=lane.drained[0] - lane._drained0,
            dropped=dropped,
            extras={"plan": getattr(lane.pipe.processor, "plan_name",
                                    None)})
        # capacity freed: start queued streams in priority order
        self._start_queued()

    def _observe_fairness(self) -> None:
        """One fleet-wide fairness observation, paced on emits (not
        scheduler rounds — an idle spin must not walk the hysteresis):
        pressure = fraction of running lanes that waited on their sink
        since the last observation."""
        running = [ln for ln in self.lanes.values() if not ln.done]
        emits = sum(ln._emitted_since_obs for ln in running)
        waits = sum(1 for ln in running if ln.sink_wait)
        if not running or (emits == 0 and waits == 0):
            return
        pressure = waits / len(running)
        loss = metrics.window("segments_dropped").sum() > 0
        shed = self.fairness.observe(
            pressure, loss,
            [(ln.name, ln.priority, ln.real_time) for ln in running])
        for ln in running:
            ln.forced_shed = ln.name in shed
            ln._emitted_since_obs = 0

    # ------------------------------------------------------------ run

    def run(self) -> dict[str, StreamResult]:
        metrics.set("fleet_streams_total", len(self.specs))
        for spec in self.specs.values():
            decision = self.admission.request(spec.name, spec.priority)
            if decision == ADMIT:
                self._start(spec.name)
            elif decision == QUEUE:
                self._waitlist[spec.name] = spec
        # queue evictions recorded by the controller surface as
        # rejected results too
        for name in self.admission.rejected:
            self._waitlist.pop(name, None)
            self.results.setdefault(
                name, StreamResult(name, "rejected"))
        # a start failure in the admission pass freed capacity: give
        # it to queued streams before the loop (otherwise nothing
        # active + a populated waitlist = an immediate idle spin)
        self._start_queued()
        try:
            while True:
                active = [ln for ln in self.lanes.values()
                          if not ln.done]
                if not active and not self._waitlist:
                    break
                if not active and self._waitlist:
                    # every running lane is gone but streams still
                    # wait: start them now; if none can start (all
                    # fail / inconsistent queue state), fail the
                    # remainder loudly instead of spinning forever
                    self._start_queued()
                    if not any(not ln.done
                               for ln in self.lanes.values()):
                        for name, spec in list(self._waitlist.items()):
                            del self._waitlist[name]
                            self.results.setdefault(name, StreamResult(
                                name, "failed",
                                error=RuntimeError(
                                    "queued stream never became "
                                    "startable")))
                        break
                    continue
                progressed = False
                for lane in active:
                    if lane.step():
                        progressed = True
                    if lane.done:
                        self._on_lane_done(lane)
                self._observe_fairness()
                for name in self.admission.rejected:
                    if name in self._waitlist:
                        del self._waitlist[name]
                        self.results.setdefault(
                            name, StreamResult(name, "rejected"))
                if not progressed:
                    blocker = next(
                        (ln for ln in self.lanes.values()
                         if not ln.done and ln.pending), None)
                    if blocker is not None:
                        blocker.step(allow_block=True)
                        if blocker.done:
                            self._on_lane_done(blocker)
                    else:
                        time.sleep(0.002)
        finally:
            metrics.set("fleet_running", 0)
        return self.results

    def close(self) -> None:
        for lane in self.lanes.values():
            lane.close()
        self.plans.invalidate()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
