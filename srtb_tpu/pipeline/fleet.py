"""Multi-tenant stream fleet: N concurrent streams on a device pool.

The reference backend serves one stream per process; the production
target (ROADMAP item 1) is one engine serving many concurrent beams
and replay jobs from one device — the concurrent-streams architecture
of *Implementing CUDA Streams into AstroAccelerate* (arXiv:2101.00941),
where independent streams hide each other's transfer/compute gaps.
This module makes that multi-tenancy SAFE before it is fast:

- **Round-robin scheduler**: one scheduler thread multiplexes every
  admitted stream's in-flight window onto the shared device dispatch
  queue — each :class:`_StreamLane` is a cooperative state machine
  (``step()``) over the same Pipeline building blocks the solo engine
  uses (``_dispatch_segment`` / ``_fetch_inflight`` / ``_drain_body``),
  so lane outputs are bit-identical to solo runs by construction.

- **Shared AOT plan cache** (:class:`SharedPlanCache`): streams whose
  trace-relevant config projects identically
  (``SegmentProcessor.plan_cache_key``) share ONE ``SegmentProcessor``
  — one jit cache, one set of compiled programs; the second stream of
  a plan family compiles nothing.  Shared processors are
  ``mark_shared()``-ed so a single lane's plan demotion can never
  retire the programs its neighbors are dispatching through.

- **Cross-tenant continuous batching** (:class:`_BatchFormer`, armed
  by ``Config.fleet_batch_max >= 2`` on the fleet config): ready
  segments from lanes sharing a plan family are folded into ONE
  vmapped device dispatch (``SegmentProcessor.process_batch`` /
  ``process_batch_cold``), with per-tenant results scattered back to
  each lane's in-flight window — the unit of dispatch inverts from "a
  lane's segment" to "a formed batch".  Batch size follows load up to
  ``fleet_batch_max``; a partial batch flushes after
  ``fleet_batch_linger_ms`` (a lone tenant never waits) or when the
  scheduler goes idle; fill is priority-ordered.  A ragged tail of
  one rides the lane's plain solo dispatch (the already-compiled
  program — never a fresh B=1 vmap trace).  Off by default: solo
  lanes stay bit-identical to the pre-batching fleet; batched lanes
  trade float bit-exactness for dispatch amortization (``.bin``
  candidates stay bitwise equal, float artifacts match within the
  documented vmap tolerance).

- **Per-stream bulkheads**: every lane owns its OWN Pipeline instance
  and with it its own ComputeHealer ladder position, degradation
  ladder, retry policy, fault injector (stream-selector scoped),
  supervisor restart budget, ring carry, checkpoint, telemetry
  journal and RunManifest namespace — a DEVICE fault, sink wedge or
  manifest rollback on stream A demotes/sheds/rolls back A only.  The
  one deliberately SHARED failure domain is a true device halt: the
  device under every lane died, so the fleet makes one budgeted
  reinit decision and cold-restarts every lane from its retained host
  buffers (journal order and exactly-once outputs preserved per
  stream, like the solo engine's reinit).

- **Admission control + priority shedding**: the
  :class:`~srtb_tpu.resilience.admission.AdmissionController` gates
  stream starts (``fleet_max_streams`` / ``fleet_queue_limit``,
  priority-ordered), and under fleet-wide sink pressure the
  :class:`~srtb_tpu.resilience.degrade.FleetShedPolicy` force-sheds
  the lowest-priority REAL-TIME stream first (hysteretic, loss
  accounted per stream) instead of letting the overload land on an
  arbitrary tenant.

- **Elastic device pool + live migration** (ROADMAP item 4,
  ``Config.fleet_devices``): lanes are placed across a
  :class:`~srtb_tpu.pipeline.pool.DevicePool` (real ``jax.devices()``
  members, or a deterministic virtual pool on CPU CI) by the
  ``pipeline/placement.py`` policy — least-loaded first, soft
  same-tenant anti-affinity, explicit ``StreamSpec.pin_device``
  honored.  Each member owns its OWN plan cache, batch-former
  families and HALT domain.  A lane **live-migrates** between members
  (``_StreamLane.migrate_to``): quiesce → drain the in-flight window
  (trusted sources only) → checkpoint + manifest consistency point →
  re-admit on the target's plan cache → cold ring re-arm → resume,
  bit-identical to an unmigrated run (the cold re-dispatch recovers
  from retained host buffers — the solo engine's reinit proof).
  Three drivers: (a) a HALTED member drains its lanes onto survivors
  and only ITS plan cache is retired (fleet-wide reinit is the last
  resort when no peer exists), (b) ``migrate_on_burn`` rebalances a
  burning-SLO stream onto the least-loaded member before its error
  budget is spent, (c) ``rolling_restart()`` drains members one at a
  time for operator maintenance.  ``fleet_devices <= 1`` keeps the
  single-device fleet bit-identical to the pre-pool engine.

Every per-stream quantity is labeled: loss counters, degrade /
ladder levels, in-flight depth (``{stream="..."}`` series on
/metrics), the v6 journal's ``stream`` field (``device`` since v11),
and /healthz per-stream staleness.  The fleet chaos gate is
``tools/fleet_soak.py`` (``--migrate`` for the pool gates).

Limits (documented, enforced loudly): REAL-TIME lanes are
single-segment dispatch units (``micro_batch_segments`` must be 1
there — batching ingest on a live stream trades bounded latency for
throughput silently; use the solo engine).  FILE-MODE lanes may
micro-batch: replaying recorded baseband has no latency contract, so
the archive replay engine (pipeline/archive.py) batches B segments
into one vmapped dispatch per lane for full device occupancy.
``Config.sanitize`` is unsupported inside a fleet (the sanitizer's
thread-ownership guards assume one engine per process).
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field
from typing import Any

from srtb_tpu.config import Config
from srtb_tpu.pipeline import framework as fw
from srtb_tpu.pipeline import placement
from srtb_tpu.pipeline.pool import (STATE_DRAINING, STATE_HALTED,
                                    STATE_OK, DevicePool, PoolDevice)
from srtb_tpu.pipeline.runtime import Pipeline, PipelineStats
from srtb_tpu.pipeline.segment import SegmentProcessor
from srtb_tpu.resilience.admission import (ADMIT, QUEUE,
                                           AdmissionController)
from srtb_tpu.resilience.degrade import FleetShedPolicy
from srtb_tpu.resilience.errors import (DEVICE_HALT, LadderExhausted,
                                        ReinitBudgetExceeded)
from srtb_tpu.resilience.supervisor import Supervisor
from srtb_tpu.utils import events, telemetry
from srtb_tpu.utils.logging import log
from srtb_tpu.utils.metrics import metrics


@dataclass
class StreamSpec:
    """One stream's identity + wiring handed to the fleet.  ``cfg``
    is the stream's OWN config: per-stream paths (output prefix,
    checkpoint, manifest, journal) are its bulkhead namespace;
    trace-relevant fields shared with other streams let them share a
    compiled plan."""
    name: str
    cfg: Config
    source: Any = None
    sinks: Any = None
    keep_waterfall: bool = True
    max_segments: int | None = None
    # explicit pool placement (None = the placement policy decides):
    # validated against the healthy pool before any pipeline state is
    # built, so a bad pin fails like any other pure-config error
    pin_device: int | None = None

    @property
    def priority(self) -> int:
        return int(getattr(self.cfg, "stream_priority", 0) or 0)


@dataclass
class StreamResult:
    """Per-stream outcome of a fleet run."""
    name: str
    status: str                  # done | failed | rejected
    stats: PipelineStats | None = None
    error: BaseException | None = None
    drained: int = 0
    dropped: int = 0
    extras: dict = field(default_factory=dict)


class SharedPlanCache:
    """One ``SegmentProcessor`` per plan family, shared across every
    stream whose trace-relevant config projects identically
    (``SegmentProcessor.plan_cache_key``).  ``compiles`` counts
    processor builds (one per family — the proof the fleet soak
    gates on), ``hits`` counts streams served an existing plan.

    Plan families are shared WITHIN a pool device, never across
    devices: each :class:`~srtb_tpu.pipeline.pool.PoolDevice` owns
    one cache (``device`` labels its metric twins), so compiled
    handles die with their member and a scoped halt retires exactly
    one cache."""

    def __init__(self, device: str | None = None):
        self._by_key: dict[str, SegmentProcessor] = {}
        self.compiles = 0
        self.hits = 0
        self.device = device

    def get(self, cfg: Config,
            donate_input: bool = False) -> SegmentProcessor:
        # keyed AND built through the plan registry: a registered
        # search mode's processor class serves its lanes, and plans of
        # different modes can never share a cache slot (the key
        # carries the mode)
        from srtb_tpu.pipeline import registry
        key = registry.plan_cache_key(cfg, donate_input=donate_input)
        # per-stream labeled twins (performance observatory): which
        # tenant paid a compile and which rode a shared plan for free
        # must be scrapeable, not just the fleet totals
        lbl = ({"stream": cfg.stream_name}
               if getattr(cfg, "stream_name", "") else None)
        proc = self._by_key.get(key)
        if proc is None:
            proc = registry.build_processor(
                cfg, donate_input=donate_input).mark_shared()
            self._by_key[key] = proc
            self.compiles += 1
            metrics.add("fleet_plan_compiles")
            if lbl is not None:
                metrics.add("fleet_plan_compiles", labels=lbl)
            if self.device is not None:
                metrics.add("fleet_plan_compiles",
                            labels={"device": self.device})
            log.info(f"[fleet] plan cache MISS: built shared plan "
                     f"{proc.plan_name} ({self.compiles} families"
                     + (f" on {self.device}" if self.device else "")
                     + ")")
        else:
            self.hits += 1
            metrics.add("fleet_plan_cache_hits")
            if lbl is not None:
                metrics.add("fleet_plan_cache_hits", labels=lbl)
            if self.device is not None:
                metrics.add("fleet_plan_cache_hits",
                            labels={"device": self.device})
        return proc

    def invalidate(self) -> None:
        """Retire every shared plan (force past the shared guard) and
        forget it: after a device reinit the compiled handles are
        bound to the dead backend, and the next ``get`` rebuilds."""
        for proc in self._by_key.values():
            proc.retire(force=True)
        self._by_key.clear()


class _BatchSlot:
    """One lane's reservation in a forming cross-stream batch.  The
    slot sits in the lane's ``pending`` deque at its dispatch-order
    position, holding the ingested segment host-side until the former
    dispatches it, then the standard 7-tuple in-flight record
    (``item``) — so drain order, checkpoint offsets and journal order
    are exactly what a solo dispatch would have produced.  A dispatch
    failure lands on ``error`` and raises inside the OWNING lane's
    step (the bulkhead: the lane that happened to trigger a flush
    never observes a neighbor's exception)."""

    __slots__ = ("lane", "seg", "ingest_s", "offset_after", "index",
                 "t_offer", "item", "error", "cancelled")

    def __init__(self, lane: "_StreamLane", seg, ingest_s: float,
                 offset_after: int, index: int):
        self.lane = lane
        self.seg = seg
        self.ingest_s = ingest_s
        self.offset_after = offset_after
        self.index = index
        self.t_offer = time.perf_counter()
        self.item: tuple | None = None
        self.error: BaseException | None = None
        # lane withdrew the offer (fleet reinit, lane teardown): the
        # former must skip it at flush
        self.cancelled = False


class _BatchFormer:
    """Cross-tenant continuous batching: collect ready segments from
    lanes sharing a plan family (the SAME :class:`SharedPlanCache`
    processor — equal ``plan_cache_key`` by construction, so one
    compiled program serves every member) and dispatch them as ONE
    vmapped device call, scattering per-tenant results back to each
    lane's in-flight window.

    Formation policy: a family flushes the moment it holds
    ``fleet_batch_max`` live offers; a partial family flushes when its
    oldest offer has lingered past ``fleet_batch_linger_ms`` (the
    lone-tenant latency bound, pumped by the fleet scheduler) or when
    the scheduler goes idle (nothing else can progress — dispatch
    now).  When one flush holds more offers than a batch takes, fill
    is priority-ordered (``stream_priority`` desc, offer age asc): the
    important tenants ride the first dispatch.  A batch must span at
    least TWO distinct lanes (cross-tenant, the name of the game): a
    ragged tail of one, or a chunk drawn entirely from a lone
    tenant's own in-flight window, goes through the lane's plain
    solo-dispatch path instead — the lone tenant keeps its warm ring
    carry and pays no batching overhead.

    Bulkheads: eligibility is re-checked per offer against the lane's
    CURRENT processor, so a healed/demoted lane (whose swap installed
    an unshared processor) drops out of its batch group automatically
    and its neighbors' shared program is never retired; a member whose
    own scheduled dispatch fault fires during formation heals with
    lane-local blast radius and falls back to its solo dispatch."""

    def __init__(self, fleet: "StreamFleet", batch_max: int,
                 linger_s: float):
        self.fleet = fleet
        self.batch_max = max(2, int(batch_max))
        self.linger_s = max(0.0, float(linger_s))
        # plan family -> (shared processor, pending offers); keyed on
        # the shared processor's identity (one object per family) with
        # the processor ref alongside so id() can never be recycled
        # under a live group
        self._groups: dict[int, tuple] = {}

    # ------------------------------------------------------ membership

    def eligible(self, lane: "_StreamLane") -> bool:
        """May this lane's next segment join a cross-stream batch?
        Demotion swaps in an unshared processor, so a victim exits its
        group here — the bulkhead's membership rule.  Staged plans
        reject ``process_batch`` (their dispatch is already
        amortized), and lanes micro-batching internally (archive
        replay units > 1) already fill the device.

        Re-validated after any migration/heal by construction: a
        migrated lane's swap installed a processor from the TARGET
        device's cache (a different object, so a different group
        key), and a draining/halted member's lanes stop offering —
        a lane can never batch into its former device's family."""
        proc = lane.pipe.processor
        return (getattr(proc, "_fleet_shared", False)
                and not getattr(proc, "staged", False)
                and lane._unit() == 1
                and lane.device.state == STATE_OK)

    def offer(self, lane: "_StreamLane", one: tuple,
              index: int) -> _BatchSlot:
        """Park one ingested segment in its plan family's forming
        batch; returns the slot the lane must append to ``pending``.
        Reaching ``batch_max`` flushes the family immediately (the
        slot comes back already filled)."""
        ts = getattr(self.fleet, "_tsan", None)
        if ts is not None:
            # group slots are scheduler-owned (offer/pump/flush all
            # run on the scheduler thread): claim-on-first-use
            ts.assert_owner("former.groups")
        seg, ingest_s, offset_after = one
        slot = _BatchSlot(lane, seg, ingest_s, offset_after, index)
        proc = lane.pipe.processor
        key = id(proc)
        group = self._groups.get(key)
        if group is None:
            group = self._groups[key] = (proc, [])
        group[1].append(slot)
        if sum(1 for s in group[1] if not s.cancelled) \
                >= self.batch_max:
            self._flush(key)
        return slot

    def pump(self) -> bool:
        """Scheduler-paced linger check: flush every family whose
        oldest live offer has waited past the deadline.  True when
        anything dispatched."""
        ts = getattr(self.fleet, "_tsan", None)
        if ts is not None:
            ts.assert_owner("former.groups")
        now = time.perf_counter()
        flushed = False
        for key in list(self._groups):
            slots = [s for s in self._groups[key][1]
                     if not s.cancelled]
            if not slots:
                del self._groups[key]
                continue
            if now - min(s.t_offer for s in slots) >= self.linger_s:
                self._flush(key)
                flushed = True
        return flushed

    def flush_all(self) -> bool:
        """Idle-scheduler flush: nothing else can make progress, so
        every pending offer dispatches now (partial batches included —
        waiting out the linger would only add latency)."""
        ts = getattr(self.fleet, "_tsan", None)
        if ts is not None:
            ts.assert_owner("former.groups")
        flushed = False
        for key in list(self._groups):
            if any(not s.cancelled for s in self._groups[key][1]):
                self._flush(key)
                flushed = True
            else:
                del self._groups[key]
        return flushed

    def flush_lane(self, lane: "_StreamLane") -> None:
        """Flush the family holding this lane's offers (the blocking
        drain granted to a lane whose head still sits in the former)."""
        for key, (_proc, slots) in list(self._groups.items()):
            if any(s.lane is lane and not s.cancelled for s in slots):
                self._flush(key)

    def drop_lane(self, lane: "_StreamLane") -> None:
        """Withdraw a failing lane's offers (its teardown accounts the
        parked segments as per-stream loss)."""
        for key in list(self._groups):
            _proc, slots = self._groups[key]
            for s in slots:
                if s.lane is lane:
                    s.cancelled = True
            if all(s.cancelled for s in slots):
                del self._groups[key]

    def reset(self) -> None:
        """Fleet-wide device reinit: every unfilled offer was
        re-dispatched cold by its lane's ``reinit_cold`` (and
        cancelled), so the forming state is garbage — forget it."""
        self._groups.clear()

    # -------------------------------------------------------- dispatch

    def _flush(self, key: int) -> None:
        ts = getattr(self.fleet, "_tsan", None)
        if ts is not None:
            ts.assert_owner("former.groups")
        proc, slots = self._groups.pop(key)
        live = [s for s in slots if not s.cancelled]
        # priority fill: higher-priority streams ride the first
        # (immediately dispatched) batch, oldest offer first within a
        # band — deterministic under the scheduler's round-robin
        live.sort(key=lambda s: (-s.lane.priority, s.t_offer,
                                 s.lane.name))
        while live:
            take, live = live[:self.batch_max], live[self.batch_max:]
            if len({id(s.lane) for s in take}) >= 2:
                self._dispatch_shared(proc, take)
            else:
                # CROSS-tenant batching only: a chunk drawn from one
                # lane (a lone tenant's own in-flight window, or a
                # ragged tail of one) goes through the lane's plain
                # solo path — its ring carry stays warm and no B=1
                # vmap is ever traced.  Slots a mid-flush reinit
                # already re-dispatched (cancelled) are skipped.
                for s in take:
                    if not s.cancelled and s.item is None \
                            and s.error is None:
                        self._single_fallback(s)

    @staticmethod
    def _single_fallback(slot: _BatchSlot,
                         requeue: bool = False) -> None:
        """Dispatch one member through its lane's own solo path (full
        fault/retry/heal machinery); a post-heal failure lands on the
        slot for the owning lane's step to raise."""
        try:
            slot.item = slot.lane._dispatch(
                slot.seg, slot.ingest_s, slot.offset_after,
                slot.index, requeue=requeue)
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:  # noqa: BLE001 — member-contained
            slot.error = e

    def _member_fault(self, slot: _BatchSlot,
                      exc: BaseException) -> None:
        """A member's own scheduled dispatch fault fired during
        formation: heal with the lane's blast-radius rules (a device
        fault demotes THIS lane — the processor swap drops it out of
        the batch group), then dispatch its segment solo.  Heal
        failures (ladder exhausted, reinit budget spent) land on the
        slot for the owning lane to raise."""
        try:
            healed = slot.lane._heal(exc)
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e2:  # noqa: BLE001 — member-contained
            slot.error = e2
            return
        if slot.cancelled:
            # the heal went through the fleet-wide reinit, which
            # already re-dispatched this slot's segment cold
            return
        if healed:
            self._single_fallback(slot, requeue=True)
            return
        # not a device fault: transient/data-loss classes get the
        # solo path's retry semantics — the one-shot injected fault is
        # consumed, so the solo re-dispatch IS the retry; anything
        # else fails the owning lane exactly like a solo dispatch
        from srtb_tpu.resilience.errors import (DATA_LOSS, TRANSIENT,
                                                classify)
        if slot.lane.pipe.retry is not None and \
                classify(exc) in (TRANSIENT, DATA_LOSS):
            self._single_fallback(slot)
        else:
            slot.error = exc

    def _dispatch_shared(self, proc, slots: list) -> None:
        """One vmapped device call for B members from (possibly) B
        different lanes, per-tenant results scattered back as lazy
        batch-output slices — the cross-stream twin of the solo
        engine's ``_dispatch_micro_batch``, with per-member fault
        fidelity and member-contained failure."""
        t0 = time.perf_counter()
        live = []
        for slot in slots:
            lane = slot.lane
            lane.pipe._canary_prepare(slot.seg, slot.index)
            faults = lane.pipe.faults
            if faults is not None and faults.armed("dispatch"):
                # per-member fault fidelity: the member's scheduled
                # "dispatch" fault fires against ITS index before the
                # shared call, and its consequences stay on that
                # member — neighbors keep batching
                try:
                    faults.fire("dispatch", slot.index)
                except (KeyboardInterrupt, SystemExit):
                    raise
                except BaseException as e:  # noqa: BLE001 — classified
                    self._member_fault(slot, e)
                    continue
            live.append(slot)
        # a mid-formation heal may have re-dispatched members (solo
        # fallback) or cancelled them (fleet reinit); only untouched
        # members still on the shared program proceed.  A member
        # whose lane migrated (processor now from another device's
        # cache) or whose device left the OK state between offer and
        # flush must NEVER ride this family's dispatch: route it to
        # its own solo path instead of dropping it silently (the
        # post-migration membership guard; migration normally
        # cancels parked offers, so this counter staying 0 is the
        # regression signal)
        stale = [s for s in live
                 if not s.cancelled and s.item is None
                 and s.error is None
                 and (s.lane.pipe.processor is not proc
                      or s.lane.device.state != STATE_OK)]
        live = [s for s in live
                if not s.cancelled and s.item is None
                and s.error is None and s.lane.pipe.processor is proc
                and s.lane.device.state == STATE_OK]
        for s in stale:
            metrics.add("fleet_batch_device_guard")
            log.warning(f"[fleet:{s.lane.name}] batch offer left "
                        "behind by a migration/heal: dispatching solo")
            self._single_fallback(s, requeue=True)
        if not live:
            return
        if len({id(s.lane) for s in live}) < 2:
            # member faults thinned the chunk below two tenants: the
            # cross-tenant contract no longer holds, dispatch solo
            for s in live:
                self._single_fallback(s)
            return
        datas = [s.lane.pipe._device_bytes(s.seg) for s in live]
        # one formed batch = one device dispatch on the family's pool
        # member (every live member shares it: same cache, same
        # device).  check=False — a scheduled virtual halt firing
        # inside a formed batch would be absorbed by the solo
        # fallback below; halts fire at solo dispatch boundaries.
        live[0].lane.device.note_dispatch(check=False)
        try:
            if any(s.lane.pipe._ring_live for s in live):
                # a ring carry belongs to ONE lane's consecutive-seq
                # chain, which a cross-stream batch never is: the
                # carry-emitting cold batch plan uploads full
                # segments, and members' live carries are invalidated
                # so their next solo dispatch goes (correctly) cold
                for s in live:
                    s.lane.pipe._ring_invalidate()
                (wf_b, det_b), _carry = proc.process_batch_cold(
                    proc.stack_batch(datas))
            else:
                stack = getattr(proc, "stack_batch", None)
                if stack is not None:
                    stacked = stack(datas)
                else:  # duck-typed stub processors (tests)
                    import numpy as np
                    stacked = np.stack(
                        [np.ascontiguousarray(d) for d in datas])
                wf_b, det_b = proc.process_batch(stacked)
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:  # noqa: BLE001 — classified per lane
            # whole-batch failure: every member falls back to its own
            # solo path, where its own healer/retry classifies the
            # fault with lane-local blast radius
            log.warning(f"[fleet] batched dispatch of {len(live)} "
                        f"segments failed ({type(e).__name__}); "
                        f"falling back to solo dispatches: {e!r}")
            for s in live:
                if not s.cancelled and s.item is None:
                    self._single_fallback(s, requeue=True)
            return
        import jax
        b = len(live)
        per_seg = (time.perf_counter() - t0) / b
        now = time.perf_counter()
        metrics.add("batched_dispatches")
        metrics.histogram("batch_size",
                          buckets=(1.0, 2.0, 4.0, 8.0, 16.0)).observe(b)
        for i, slot in enumerate(live):
            lane = slot.lane
            seg = slot.seg
            det_i = jax.tree_util.tree_map(lambda x, j=i: x[j], det_b)
            wf_i = wf_b[i] if wf_b is not None else None
            span = {"ingest": slot.ingest_s, "dispatch": per_seg}
            lane.pipe.stage_timer.record("dispatch", per_seg)
            metrics.add("batched_segments")
            metrics.add("batched_segments",
                        labels={"stream": lane.name})
            try:
                # journaled by _record_segment (span schema v10);
                # omitted — never faked — on solo dispatches
                seg.batch_size = b
                seg.batch_wait_s = max(0.0, t0 - slot.t_offer)
            except AttributeError:  # read-only stub segments
                pass
            if lane.pipe.events is not None:
                lane.pipe.events.emit(
                    "stage.dispatch",
                    trace=getattr(seg, "trace_id", 0),
                    stream=lane.name, seg=slot.index, dur=per_seg,
                    info=f"fleet_batch={b}")
            slot.item = (seg, wf_i, det_i, slot.offset_after, span,
                         now, slot.index)


class _StreamLane:
    """One admitted stream's cooperative engine: a step()-driven
    in-flight window over the lane's own Pipeline, with sink work on
    a per-lane pipe thread (the bulkhead: a wedged or crashed sink
    stalls/sheds THIS lane only)."""

    def __init__(self, fleet: "StreamFleet", spec: StreamSpec):
        cfg = spec.cfg
        real_time = not cfg.input_file_path
        mb = int(getattr(cfg, "micro_batch_segments", 1) or 1)
        if mb > 1 and real_time:
            # file-mode (archive replay) lanes may batch — replaying
            # recorded baseband has no latency contract; a LIVE
            # stream batching ingest would silently trade bounded
            # latency for throughput, so real-time lanes reject loudly
            raise ValueError(
                f"stream {spec.name!r}: micro_batch_segments > 1 is "
                "only supported on file-mode (non-real-time) fleet "
                "lanes (use the solo engine for a batched live "
                "stream)")
        if getattr(cfg, "sanitize", False):
            raise ValueError(
                f"stream {spec.name!r}: Config.sanitize is "
                "incompatible with fleet scheduling (single-engine "
                "thread-ownership guards)")
        # every validation that can fail is pure-config-decidable and
        # sits BEFORE Pipeline construction: a lane rejected here must
        # not leak an opened Pipeline (input file, checkpoint,
        # manifest WAL fd, telemetry registration) into a failed
        # StreamResult that nothing ever closes
        self.window = max(1, int(getattr(cfg, "inflight_segments", 2)
                                 or 1))
        self.micro_batch = mb
        if mb > self.window:
            raise ValueError(
                f"stream {spec.name!r}: micro_batch_segments={mb} "
                f"exceeds inflight_segments={self.window}: a batch "
                "dispatch must fit the lane's in-flight window")
        if mb > 1:
            from srtb_tpu.pipeline.segment import staged_resolves
            if staged_resolves(cfg):
                raise ValueError(
                    f"stream {spec.name!r}: micro_batch_segments > 1 "
                    "requires the fused plan (staged segments are "
                    "already dispatch-amortized)")
        self.fleet = fleet
        self.spec = spec
        self.name = spec.name
        self.priority = spec.priority
        # placement: pick this lane's pool member BEFORE the Pipeline
        # is built (an invalid pin_device fails the pure-config way,
        # leaking nothing), and draw the shared plan from THAT
        # member's cache — the per-device plan family
        self.device: PoolDevice = fleet._place(spec)
        self.migrations = 0
        self._migrated_t = 0.0
        # False between a migration and the lane's first dispatch on
        # its NEW member: the rolling-restart pacer waits for every
        # migrant to actually resume before draining the next device
        self._resumed = True
        from srtb_tpu.utils.platform import on_accelerator
        self.pipe = Pipeline(
            cfg, source=spec.source, sinks=spec.sinks,
            keep_waterfall=spec.keep_waterfall,
            processor=self.device.plans.get(
                cfg, donate_input=on_accelerator()))
        # journal attribution (span schema v11 ``device`` field)
        self.pipe.device_label = self.device.label
        self.real_time = real_time
        self.max_segments = spec.max_segments
        self.deadline_s = float(cfg.segment_deadline_s or 0.0)
        self.join_s = float(getattr(cfg, "shutdown_join_timeout_s", 0)
                            or 0)
        self.pending: collections.deque = collections.deque()
        self._it = iter(self.pipe.source)
        self.dispatched = 0
        self.exhausted = False
        self.drained = [self.pipe.checkpoint.segments_done
                        if self.pipe.checkpoint else 0]
        self._drained0 = self.drained[0]
        self.done = False
        self.status = "running"
        self.error: BaseException | None = None
        # fleet fairness: force-shed (ingest-and-account, no dispatch)
        self.forced_shed = False
        # "this lane waited on its sink since the fleet's last
        # fairness observation" — the pressure signal
        self.sink_wait = False
        self._emitted_since_obs = 0
        # fetched item awaiting sink-queue space (the lane's emit
        # backpressure point)
        self._staged_emit = None
        self._wedge_t0 = None
        self._wedge_mark = None
        # parked-window watchdog (whole window stuck behind the sink)
        self._park_t0 = None
        self._park_mark = None
        # lane-local loss recency (the engine's 10 s loss window,
        # scoped to THIS stream's labeled counter): when this lane
        # last saw its own accounted loss grow
        self._loss_seen = 0.0
        self._loss_t = None
        # bounded sentinel push at close
        self._sentinel_t0 = None
        self._t_start = time.perf_counter()
        self._t_close = None
        # dispatched-through-sink count (the lane's live window);
        # written by the scheduler thread and the lane's sink thread
        if fleet._tsan is not None:
            self._live_lock = fleet._tsan.lock(
                f"lane.{spec.name}._live_lock")
        else:
            import threading
            self._live_lock = threading.Lock()
        self._live = 0
        # per-lane sink pipe + bounded-restart supervision (each
        # stream its own restart budget)
        self._stop = fw.StopToken()
        self._q_sink = fw.WorkQueue(capacity=self.window)
        self._current = [None]
        self._progress = [self.drained[0]]
        self._supervisor = None
        if int(getattr(cfg, "supervisor_max_restarts", 0)) > 0:
            self._supervisor = Supervisor(
                f"sink_drain_{self.name}",
                max_restarts=cfg.supervisor_max_restarts,
                window_s=getattr(cfg, "supervisor_window_s", 60.0))
        self._sink_pipe = fw.start_pipe(
            self._sink_f, self._q_sink, None, self._stop,
            f"sink_drain:{self.name}", on_done=fleet._notify)
        telemetry.register_stream(self.name)

    # ------------------------------------------------------ accounting

    def _live_add(self, n: int) -> None:
        with self._live_lock:
            self._live += n
            metrics.set("inflight_depth", self._live,
                        labels={"stream": self.name})

    def _live_count(self) -> int:
        with self._live_lock:
            return self._live

    # ------------------------------------------------------- sink side

    def _sink_f(self, _stop, item):
        ts = getattr(self.fleet, "_tsan", None)
        if ts is not None:
            # per-lane sink state is sink-thread-owned
            ts.assert_owner(f"lane.{self.name}.sink")
        self._current[0] = item
        self._progress[0] = self.drained[0]
        try:
            self.pipe._drain_body(item, self.drained)
        finally:
            if "abandoned" not in item[-1]:
                self._live_add(-1)
            # the drain freed window/queue space the scheduler may be
            # idle-waiting on (event-driven wakeup, no 2 ms poll)
            self.fleet._notify()
        self._current[0] = None

    def _sink_alive(self) -> bool:
        """True while this lane's sink side can make progress;
        restarts a supervised crashed pipe (replaying the unaccounted
        item inline first — journal order kept, same contract as the
        solo engine)."""
        if self._sink_pipe.exception is None:
            return True
        if self._supervisor is None or \
                not self._supervisor.should_restart(
                    self._sink_pipe.exception):
            return False
        failed, self._current[0] = self._current[0], None
        if failed is not None and failed is not fw.SENTINEL:
            if self.drained[0] == self._progress[0]:
                self.pipe._drain_body(failed, self.drained)
            else:
                log.warning(
                    f"[fleet:{self.name}] sink crashed after its "
                    "segment was accounted; skipping replay")
        if self.fleet._tsan is not None:
            # the restarted pipe is a NEW thread: drop the crashed
            # thread's ownership claim so the successor can re-claim
            self.fleet._tsan.release_owners(f"lane.{self.name}.sink")
        self._sink_pipe = fw.start_pipe(
            self._sink_f, self._q_sink, None, self._stop,
            f"sink_drain:{self.name}", on_done=self.fleet._notify)
        return True

    # ------------------------------------------------------ heal hooks

    def _heal(self, exc: BaseException) -> bool:
        """Device-fault recovery with the fleet's blast-radius rules:
        OOM/compile faults demote THIS lane's plan only (the shared
        processor is swapped out for an unshared demoted one — and
        never retired under the neighbors); a device HALT is shared
        by the lanes of ONE pool member: with a healthy peer its
        lanes drain-migrate onto survivors (scoped HALT domain), and
        only with no peer does the fleet fall back to its single
        budgeted fleet-wide reinit."""
        h = self.pipe.healer
        if h is None:
            return False
        kind = h.classify(exc)
        if kind is None:
            return False
        if kind == DEVICE_HALT:
            if self.fleet._device_halt(exc, lane=self):
                return True
            raise ReinitBudgetExceeded(
                "device halt beyond fleet reinit recovery "
                f"(budget spent or disabled): {exc}") from exc
        newp = h.demote(exc, kind)
        if newp is None:
            raise LadderExhausted(
                f"stream {self.name!r}: device fault survived every "
                f"demotion rung: {exc}") from exc
        self.pipe._swap_processor(newp)
        return True

    def _dispatch(self, seg, ingest_s, offset_after, index,
                  requeue=False):
        while True:
            try:
                # the pool's dispatch clock: counts this member's
                # device work and fires any SCHEDULED virtual halt
                # here, where the healer classifies it (a migrated
                # lane re-dispatches through its NEW device's clock)
                self.device.note_dispatch()
                self._resumed = True
                return self.pipe._dispatch_segment(
                    seg, ingest_s, offset_after, index,
                    requeue=requeue)
            except BaseException as e:  # noqa: BLE001 — classified
                if not self._heal(e):
                    raise
                requeue = True

    def _unit(self) -> int:
        """The lane's dispatch unit: the active plan's micro-batch
        (dynamic — the self-healing ladder's micro_batch rung drops
        it to 1, and the lane must follow the demoted plan exactly
        like the solo engine's cur_unit)."""
        h = self.pipe.healer
        if h is not None:
            return min(self.window, h.micro_batch)
        return self.micro_batch

    def _dispatch_batch(self, got: list, b: int) -> list:
        """Dispatch up to B ingested segments as ONE vmapped jit call
        (file-mode archive lanes).  Unit 1, a short tail, or a healed
        plan that no longer micro-batches all finish as plain single
        dispatches (the vmapped B=1 program is a DIFFERENT trace —
        the single path keeps lane outputs bit-identical to solo
        runs), result-compatible by the solo engine's proof."""
        segs, ingests, offsets = map(list, zip(*got))
        first = self.dispatched
        if b > 1 and len(segs) == b:
            try:
                self.device.note_dispatch()
                self._resumed = True
                return self.pipe._dispatch_micro_batch(
                    segs, ingests, offsets, first)
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 — classified
                if not self._heal(e):
                    raise
                return [self._dispatch(s, dt, off, first + i,
                                       requeue=True)
                        for i, (s, dt, off) in enumerate(got)]
        return [self._dispatch(s, dt, off, first + i)
                for i, (s, dt, off) in enumerate(got)]

    def _shared_factory(self) -> SegmentProcessor:
        """Build/fetch this lane's processor from its CURRENT pool
        member's plan cache — the shared path for rung-0 rebuilds."""
        from srtb_tpu.utils.platform import on_accelerator
        return self.device.plans.get(
            self.pipe.cfg, donate_input=on_accelerator())

    def _redispatch_pending_cold(self) -> None:
        """Re-dispatch every in-flight segment cold from its retained
        host buffer, in dispatch order (journal order and checkpoint
        offsets unchanged — the solo engine's reinit proof).  Offers
        still parked in the batch former are withdrawn first: the
        retained host buffer is the recovery source either way."""
        for i in range(len(self.pending)):
            item = self.pending[i]
            if isinstance(item, _BatchSlot):
                if item.item is None:
                    item.cancelled = True
                    self.pending[i] = self.pipe._dispatch_segment(
                        item.seg, item.ingest_s, item.offset_after,
                        item.index, requeue=True)
                    continue
                item = item.item
            seg, _wf, _det, offset_after, span, _t0, idx = item
            self.pending[i] = self.pipe._dispatch_segment(
                seg, span["ingest"], offset_after, idx, requeue=True)

    def reinit_cold(self) -> None:
        """Fleet-wide device reinit, this lane's share: swap in a
        fresh processor at the lane's current ladder rung and
        re-dispatch every in-flight segment cold from its retained
        host buffer, in dispatch order."""
        h = self.pipe.healer
        if h is not None:
            newp = h.rebuild()
        else:
            newp = self._shared_factory()
        self.pipe._swap_processor(newp)
        self._redispatch_pending_cold()

    def migrate_to(self, device: PoolDevice, trusted: bool,
                   deadline_s: float = 0.0) -> None:
        """LIVE migration onto another pool member: quiesce → drain
        the in-flight window (trusted sources only — a HALTED
        device's in-flight results died with it) → checkpoint +
        manifest consistency point → re-admit on the target's plan
        cache → cold ring re-arm → resume.  Bit-identical to an
        unmigrated run: drained segments were already exactly-once
        accounted, and everything undrained re-dispatches cold from
        its retained host buffer on the target (the same proof as
        the solo engine's reinit).  Runs on the scheduler thread
        while the lane is quiescent (or re-entrantly from the
        faulting lane's own ``_heal``, whose current segment is not
        yet in ``pending``)."""
        src = self.device
        if trusted:
            # drain whatever the (healthy) source device already
            # computed: fewer cold re-dispatches on the target.
            # Bounded by the drain deadline and by sink backpressure
            # — breaking early is always safe, the cold path below
            # is lossless.
            deadline = time.monotonic() + max(0.0, deadline_s)
            try:
                while self.pending:
                    if self._staged_emit is not None \
                            and not self._try_emit():
                        break
                    if time.monotonic() > deadline:
                        log.warning(
                            f"[fleet:{self.name}] migration drain "
                            f"deadline ({deadline_s:g}s) hit; moving "
                            "the remaining window cold")
                        break
                    if not self._drain_head(block=True):
                        break
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 — cold path covers
                log.warning(
                    f"[fleet:{self.name}] migration drain failed "
                    f"({e!r}); the remaining window moves cold")
        # consistency point: the checkpoint is already durable per
        # drained segment (atomic replace + fsync); sync the manifest
        # WAL so the target-side resume sees every record the drain
        # produced
        man = getattr(self.pipe, "manifest", None)
        if man is not None:
            try:
                man.sync()
            except Exception as e:  # noqa: BLE001 — advisory
                log.warning(f"[fleet:{self.name}] manifest sync at "
                            f"migration consistency point: {e!r}")
        self.device = device
        h = self.pipe.healer
        newp = (h.rebuild(shared=self._shared_factory)
                if h is not None else self._shared_factory())
        self.pipe._swap_processor(newp)
        self.pipe.device_label = device.label
        self._redispatch_pending_cold()
        # a re-dispatched window already resumed on the target; an
        # empty one resumes at the lane's next fresh dispatch
        self._resumed = bool(self.pending)
        self.migrations += 1
        self._migrated_t = time.monotonic()
        self.fleet.admission.note_migration(
            self.name, src.label, device.label)
        metrics.add("migrations")
        metrics.add("migrations", labels={"stream": self.name})
        # per-device twin (arrival side): feeds the control tower's
        # per-member breakdown and the _pool_sum/_pool_max aggregates
        metrics.add("migrations", labels={"device": device.label})
        events.emit("fleet.migrate", trace=0, stream=self.name,
                    info=f"{src.label}->{device.label}")
        log.warning(f"[fleet:{self.name}] migrated {src.label} -> "
                    f"{device.label}"
                    f" ({len(self.pending)} segment(s) re-dispatched "
                    "cold)")
        self.fleet._publish_lanes()

    # ----------------------------------------------------- engine step

    def _want_more(self) -> bool:
        return (not self.exhausted
                and (self.max_segments is None
                     or self.dispatched < self.max_segments))

    def _ingest_one(self, index: int):
        seg = self.pipe._timed_ingest(self._it, index)
        if seg is None:
            self.exhausted = True
            return None
        return (seg, self.pipe.stage_timer.last["ingest"],
                getattr(self.pipe.source, "logical_offset", 0))

    def _observe_level(self) -> int:
        """Per-lane degradation observation at emit (the solo engine's
        emit() signal, lane-scoped): occupancy 1.0 when this lane
        waited on its sink since the last emit, plus the lane's own
        recent accounted loss."""
        ladder = self.pipe._ladder
        if ladder is None:
            return 0
        if not self.real_time:
            occupancy = 0.0
        elif self.sink_wait:
            occupancy = 1.0
        else:
            occupancy = self._q_sink.qsize() / self.window
        # loss signal scoped to THIS stream: the process-wide window
        # would let a noisy neighbor's drops degrade a healthy lane —
        # exactly the blast radius the bulkheads exist to prevent
        cur = metrics.get("segments_dropped",
                          labels={"stream": self.name})
        if cur > self._loss_seen:
            self._loss_seen = cur
            self._loss_t = time.perf_counter()
        loss = (self._loss_t is not None
                and time.perf_counter() - self._loss_t < 10.0)
        return ladder.observe(occupancy, loss)

    def _shed_item(self, item) -> None:
        """Account one fetched-but-unsunk item as this stream's loss
        and release its buffers (the solo engine's shed_segment,
        lane-scoped)."""
        pipe = self.pipe
        pipe._account_dropped(trace=getattr(item[0], "trace_id", 0))
        pipe._ring_invalidate()
        self._live_add(-1)
        rel = getattr(pipe.processor, "release_staging", None)
        if rel is not None:
            rel(item[0].data)
        pool = getattr(pipe.source, "pool", None)
        if pool is not None and pipe.cfg.input_file_path:
            pool.release(item[0].data)

    def _try_emit(self) -> bool:
        """Push the staged fetched item to this lane's sink pipe.
        Queue full = lane-local backpressure (flagged for the fleet's
        fairness observation); a sink wedged past the deadline with
        zero per-push progress sheds the item as accounted per-stream
        loss (real-time lanes only — a file-mode lane throttles
        losslessly, exactly like the solo engine)."""
        item = self._staged_emit
        if self._q_sink.push_lossy(item):
            self._staged_emit = None
            self._wedge_t0 = None
            self._emitted_since_obs += 1
            return True
        self.sink_wait = True
        if self.deadline_s > 0 and self.real_time:
            cur = (self.drained[0], self.pipe._sink_heartbeat)
            if self._wedge_t0 is None or cur != self._wedge_mark:
                self._wedge_t0 = time.perf_counter()
                self._wedge_mark = cur
            elif time.perf_counter() - self._wedge_t0 \
                    > self.deadline_s:
                log.error(
                    f"[fleet:{self.name}] sink wedged past "
                    f"{self.deadline_s:g}s with no drain progress: "
                    "shedding segment as accounted loss")
                self._shed_item(item)
                self._staged_emit = None
                self._wedge_t0 = None
                return True
        return False

    def _drain_head(self, block: bool) -> bool:
        """Fetch the oldest in-flight segment (device-fault healed)
        and stage it for emit.  ``block`` allows a blocking fetch;
        otherwise only a device-ready head is fetched."""
        head = self.pending[0]
        if isinstance(head, _BatchSlot):
            if head.error is not None:
                # a batched-formation dispatch failed for THIS member:
                # raise inside the owning lane's own step (the
                # bulkhead boundary; _fail accounts the parked slot)
                raise head.error
            if head.item is None:
                if not block:
                    return False
                # a blocking drain granted to a lane whose head still
                # sits in the former: flush its family now (the
                # lone-tenant path when the linger pump has not fired)
                former = self.fleet._former
                if former is not None:
                    former.flush_lane(self)
                if head.error is not None:
                    raise head.error
                if head.item is None:
                    return False
            self.pending[0] = head.item
        if not block and not Pipeline._result_ready(self.pending[0][2]):
            return False
        depth = len(self.pending)
        live_now = self._live_count()
        item = self.pending.popleft()
        while True:
            try:
                fetched = self.pipe._fetch_inflight(item, depth,
                                                    live_now)
                break
            except BaseException as e:  # noqa: BLE001 — classified
                if not self._heal(e):
                    raise
                seg, _wf, _det, offset_after, span, _t0, idx = item
                item = self._dispatch(seg, span["ingest"],
                                      offset_after, idx, requeue=True)
        h = self.pipe.healer
        if h is not None:
            h.note_healthy()
        level = self._observe_level()
        self.sink_wait = False
        self._staged_emit = fetched + (level, set())
        self._try_emit()
        return True

    def step(self, allow_block: bool = False) -> bool:
        """One cooperative scheduler slice; returns True when the lane
        made progress.  Any escaping failure is contained to this
        lane (the fleet's bulkhead): the lane fails, accounts its
        in-flight segments as per-stream loss, and its neighbors
        never observe it."""
        if self.done:
            return False
        ts = getattr(self.fleet, "_tsan", None)
        if ts is not None:
            # lane step state is scheduler-owned: claim-on-first-use
            ts.assert_owner(f"lane.{self.name}.step")
        try:
            return self._step_inner(allow_block)
        except (KeyboardInterrupt, SystemExit):
            # operator interrupts are NOT lane faults: containing one
            # would shed a tenant's data and leave the fleet running
            # un-interruptibly — propagate to stop the whole run
            raise
        except BaseException as e:  # noqa: BLE001 — bulkhead boundary
            self._fail(e)
            return True

    def _step_inner(self, allow_block: bool) -> bool:
        if self.status == "closing":
            return self._step_close()
        if not self._sink_alive():
            raise self._sink_pipe.exception
        # 0) a fetched item waiting for sink-queue space blocks the
        #    lane's drain (in-order) but nothing else
        if self._staged_emit is not None:
            if not self._try_emit():
                return False
        # 1) fleet fairness force-shed: keep draining the source,
        #    account every undispatched segment as this tenant's loss
        if self.forced_shed and self._want_more():
            one = self._ingest_one(self.dispatched)
            if one is not None:
                self.dispatched += 1
                log.warning(f"[fleet:{self.name}] force-shed: "
                            "dropping ingested segment (accounted)")
                self.pipe._account_dropped(
                    trace=getattr(one[0], "trace_id", 0))
                self.pipe._ring_invalidate()
                pool = getattr(self.pipe.source, "pool", None)
                if pool is not None and self.pipe.cfg.input_file_path:
                    pool.release(one[0].data)
                return True
        # 2) drain whatever is device-ready, in order
        if self.pending and self._drain_head(block=False):
            return True
        # 3) admit + dispatch the next unit while the window has room
        #    (file-mode lanes may micro-batch: B segments, one jit
        #    call — admission gates on the WHOLE unit fitting, so the
        #    lane's in-flight depth never exceeds its window; the
        #    b = 1 case is the same path with a budget of one, routed
        #    to a plain single dispatch inside _dispatch_batch)
        if self._live_count() + self._unit() <= self.window \
                and self._want_more() and not self.forced_shed:
            self._maybe_promote()
            b = self._unit()
            if self._live_count() + b <= self.window:
                # (a promotion probe may have restored a bigger unit
                # that no longer fits: drain first, dispatch later)
                budget = b if self.max_segments is None else \
                    min(b, self.max_segments - self.dispatched)
                got = []
                while len(got) < budget:
                    one = self._ingest_one(self.dispatched + len(got))
                    if one is None:
                        break
                    got.append(one)
                if got:
                    former = self.fleet._former
                    if former is not None and len(got) == 1 \
                            and former.eligible(self):
                        # cross-stream continuous batching: park the
                        # segment in the fleet's batch former (a
                        # window reservation in dispatch order); the
                        # former fills the slot when its plan family
                        # flushes — at fleet_batch_max, at the linger
                        # deadline, or on an idle scheduler
                        self.pending.append(former.offer(
                            self, got[0], self.dispatched))
                    else:
                        self.pending.extend(
                            self._dispatch_batch(got, b))
                    self._live_add(len(got))
                    self.dispatched += len(got)
                    self.pipe.stats.segments += len(got)
                    self.pipe.stats.samples += \
                        self.pipe.cfg.baseband_input_count * len(got)
                    self._park_t0 = None
                    return True
        # 3b) whole window parked behind the sink: a real-time lane
        #    must never stall on a wedged sink — past the deadline
        #    with zero per-push progress, keep draining the source
        #    and account each undispatched segment as this stream's
        #    loss (the solo engine's shed_ingest, lane-scoped)
        if self.real_time and self.deadline_s > 0 \
                and self._want_more() and not self.pending \
                and self._staged_emit is None \
                and self._live_count() >= self.window:
            self.sink_wait = True
            cur = (self.drained[0], self.pipe._sink_heartbeat)
            if self._park_t0 is None or cur != self._park_mark:
                self._park_t0 = time.perf_counter()
                self._park_mark = cur
            elif time.perf_counter() - self._park_t0 \
                    > self.deadline_s:
                one = self._ingest_one(self.dispatched)
                if one is not None:
                    self.dispatched += 1
                    log.error(
                        f"[fleet:{self.name}] sink wedged with a "
                        "full window: shedding ingested segment as "
                        "accounted loss")
                    self.pipe._account_dropped(
                        trace=getattr(one[0], "trace_id", 0))
                    self.pipe._ring_invalidate()
                    pool = getattr(self.pipe.source, "pool", None)
                    if pool is not None \
                            and self.pipe.cfg.input_file_path:
                        pool.release(one[0].data)
                    return True
            return False
        # 4) window full (or source done) with an unready head: only a
        #    blocking fetch makes progress — the fleet grants that to
        #    one lane per idle round
        if self.pending and allow_block:
            return self._drain_head(block=True)
        # 5) complete: everything dispatched, drained and handed to
        #    the sink — close the lane (sentinel + bounded join).  A
        #    wedged sink can hold the queue full forever; the
        #    sentinel push is bounded by shutdown_join_timeout_s like
        #    the solo engine's
        if not self.pending and self._staged_emit is None \
                and not self._want_more():
            if self._q_sink.push_lossy(fw.SENTINEL):
                self.status = "closing"
                self._t_close = time.perf_counter()
                self._sentinel_t0 = None
                return True
            if self._sentinel_t0 is None:
                self._sentinel_t0 = time.perf_counter()
            elif self.join_s > 0 and \
                    time.perf_counter() - self._sentinel_t0 \
                    > self.join_s:
                self._wedge_teardown()
                return True
        return False

    def _maybe_promote(self) -> None:
        h = self.pipe.healer
        if h is not None and h.promote_due():
            newp = h.promote()
            if newp is not None:
                self.pipe._swap_processor(newp)

    def _step_close(self) -> bool:
        """Closing: wait for the lane's sink pipe to drain + exit,
        bounded by shutdown_join_timeout_s (0 = wait as long as it
        takes — but never blocking the scheduler more than a poll)."""
        if self._sink_pipe.exception is not None:
            if not self._sink_alive():
                raise self._sink_pipe.exception
            # supervised restart mid-close: the sentinel is still on
            # the queue unless the crash consumed past it; repost
            # (lossy — a duplicate sentinel is harmless, the pipe
            # exits on the first)
            self._q_sink.push_lossy(fw.SENTINEL)
            return True
        if self._sink_pipe.join(0.002):
            self._finish()
            return True
        if self.join_s > 0 and \
                time.perf_counter() - self._t_close > self.join_s:
            self._wedge_teardown()
            return True
        return False

    def _wedge_teardown(self) -> None:
        """Bounded-shutdown giveup on a wedged sink: report the
        thread, account still-queued segments as this stream's loss,
        and finish with the pool abandoned (never drained)."""
        from srtb_tpu.utils import termination
        self.pipe._sink_wedged = True
        self.pipe._incident(
            "sink_wedge_shutdown",
            reason=f"fleet lane {self.name}: sink pipe still alive "
                   f"after the {self.join_s:g}s join budget")
        termination.report_wedged(
            [self._sink_pipe.thread],
            f"fleet lane {self.name} shutdown "
            f"({self.join_s:g}s join timeout)")
        while True:
            leftover = self._q_sink.try_pop()
            if leftover is None:
                break
            if leftover is fw.SENTINEL:
                continue
            self._shed_item(leftover)
        held = self._current[0]
        if held is not None and held is not fw.SENTINEL:
            with self.pipe._handoff_lock:
                if self.drained[0] == self._progress[0]:
                    held[-1].add("abandoned")
                    self.pipe._account_dropped()
                    self._live_add(-1)
        self._stop.request_stop()
        log.error(f"[fleet:{self.name}] wedged sink: queued segments "
                  "accounted as segments_dropped")
        self._finish()

    def _finish(self) -> None:
        if not self.pipe._sink_wedged:
            self.pipe._drain_sinks()
        self.pipe.stats.elapsed_s = time.perf_counter() - self._t_start
        self.pipe.stats.extras["stages"] = \
            self.pipe.stage_timer.summary()
        self.status = "done"
        self.done = True
        metrics.set("inflight_depth", 0, labels={"stream": self.name})
        telemetry.release_stream(self.name)
        log.info(f"[fleet:{self.name}] done: "
                 f"{self.pipe.stats.segments} segments, "
                 f"{self.drained[0] - self._drained0} drained")

    def _fail(self, exc: BaseException) -> None:
        """Bulkhead containment of a lane failure: every in-flight /
        queued segment becomes accounted per-stream loss, resources
        are released, neighbors never see the exception."""
        self.error = exc
        self.status = "failed"
        events.emit("fleet.lane_failed", trace=0, stream=self.name,
                    info=type(exc).__name__)
        self.pipe._incident("lane_failed",
                            reason=f"contained lane failure: {exc!r}")
        log.error(f"[fleet:{self.name}] stream failed (contained): "
                  f"{exc!r}")
        self._stop.request_stop()
        while True:
            leftover = self._q_sink.try_pop()
            if leftover is None:
                break
            if leftover is fw.SENTINEL:
                continue
            self._shed_item(leftover)
        if self._staged_emit is not None:
            self._shed_item(self._staged_emit)
            self._staged_emit = None
        if self.fleet._former is not None:
            self.fleet._former.drop_lane(self)
        while self.pending:
            item = self.pending.popleft()
            if isinstance(item, _BatchSlot):
                item.cancelled = True
                if item.item is None:
                    # never dispatched — the parked segment is still
                    # host-side, nothing staged to release
                    self.pipe._account_dropped(
                        trace=getattr(item.seg, "trace_id", 0))
                    self._live_add(-1)
                    continue
                item = item.item
            self.pipe._account_dropped(
                trace=getattr(item[0], "trace_id", 0))
            self._live_add(-1)
            rel = getattr(self.pipe.processor, "release_staging", None)
            if rel is not None:
                try:
                    rel(item[0].data)
                except Exception as e:  # noqa: BLE001 - teardown
                    log.debug(f"[fleet:{self.name}] staging release "
                              f"during teardown failed: {e!r}")
        self.pipe._ring_invalidate()
        self._q_sink.push_lossy(fw.SENTINEL)
        self._sink_pipe.join(1.0)
        self.done = True
        metrics.set("inflight_depth", 0, labels={"stream": self.name})
        telemetry.release_stream(self.name)

    def close(self) -> None:
        self.pipe.close()


class StreamFleet:
    """Serve N streams from one device (see module docstring).

    ``run()`` drives every admitted lane round-robin to completion and
    returns ``{name: StreamResult}`` — including rejected streams
    (status "rejected") and contained failures (status "failed" with
    the error attached); it raises only for fleet-level failures (an
    exhausted shared reinit budget escalating through a lane is
    contained to that lane's result).
    """

    def __init__(self, specs: list[StreamSpec],
                 fleet_cfg: Config | None = None):
        if not specs:
            raise ValueError("StreamFleet needs at least one stream")
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate stream names: {names}")
        for s in specs:
            # the lane label must reach the lane's telemetry/faults:
            # stamp the spec's config with its fleet name
            if getattr(s.cfg, "stream_name", "") not in ("", s.name):
                raise ValueError(
                    f"stream {s.name!r}: cfg.stream_name "
                    f"{s.cfg.stream_name!r} disagrees with the spec")
            s.cfg.stream_name = s.name
        self.specs = {s.name: s for s in specs}
        cfg0 = fleet_cfg if fleet_cfg is not None else specs[0].cfg
        # the elastic device pool (fleet_devices; a single-member pool
        # when unset — every code path below routes through it).
        # ``plans`` stays the member-0 cache for compatibility: the
        # single-device fleet's soak/tests read fleet.plans directly.
        self.pool = DevicePool.from_config(cfg0)
        self.plans = self.pool.devices[0].plans
        self._migrate_on_burn = bool(getattr(cfg0, "migrate_on_burn",
                                             False))
        self._drain_deadline = float(
            getattr(cfg0, "drain_deadline_s", 5.0) or 0.0)
        # rolling-restart queue: device indices awaiting a drain
        # (appended by the operator-facing rolling_restart(), drained
        # one per scheduler round; deque append/popleft are atomic)
        self._rolling: collections.deque = collections.deque()
        self._rebalance_t = 0.0
        self.admission = AdmissionController.from_config(cfg0)
        self.fairness = FleetShedPolicy.from_config(cfg0)
        # cross-tenant continuous batching (fleet-config knob, like
        # admission): 0/1 = off — every lane dispatches solo,
        # bit-identical to the pre-batching fleet
        batch_max = int(getattr(cfg0, "fleet_batch_max", 0) or 0)
        self._former = None
        if batch_max >= 2:
            self._former = _BatchFormer(
                self, batch_max,
                max(0.0, float(getattr(cfg0, "fleet_batch_linger_ms",
                                       2.0) or 0.0)) / 1e3)
        # opt-in runtime concurrency checker (analysis/tsan.py,
        # Config.tsan): None when off — every hook site is an
        # `if ts is not None`, and the locks below stay plain
        # threading objects, so the disabled path has zero wrapper
        # indirection
        self._tsan = None
        if getattr(cfg0, "tsan", False):
            from srtb_tpu.analysis.tsan import Tsan
            self._tsan = Tsan()
        # event-driven scheduler wakeup: sink threads notify when a
        # drain frees window/queue space, so an idle scheduler round
        # waits on the condition instead of polling on a fixed sleep
        self._wake = (self._tsan.condition("fleet._wake")
                      if self._tsan is not None
                      else threading.Condition())
        self._wake_seq = 0
        # the SHARED device-halt reinit budget (one device, one
        # budget): per-lane healers keep demotion only
        self._reinit_sup = None
        reinit_max = int(getattr(cfg0, "device_reinit_max", 0) or 0)
        if reinit_max > 0:
            self._reinit_sup = Supervisor(
                "fleet_device_reinit", max_restarts=reinit_max,
                window_s=float(getattr(cfg0, "device_reinit_window_s",
                                       300.0)),
                counter=None)
        self.lanes: dict[str, _StreamLane] = {}
        self.results: dict[str, StreamResult] = {}
        self._waitlist: dict[str, StreamSpec] = {}

    # ------------------------------------------------------- placement

    def _loads(self) -> dict[int, int]:
        """Live lane count per pool member index."""
        loads = {d.index: 0 for d in self.pool.devices}
        for ln in self.lanes.values():
            if not ln.done:
                loads[ln.device.index] = \
                    loads.get(ln.device.index, 0) + 1
        return loads

    def _tenants_by_device(self) -> dict[int, set]:
        """Tenant keys (stream-name prefix) per pool member index —
        the anti-affinity input."""
        out: dict[int, set] = {}
        for ln in self.lanes.values():
            if not ln.done:
                out.setdefault(ln.device.index, set()).add(
                    placement.tenant_of(ln.name))
        return out

    def _place(self, spec: StreamSpec) -> PoolDevice:
        """Initial placement for a starting lane (pure policy in
        pipeline/placement.py: pin honored, else least-loaded with
        soft same-tenant anti-affinity)."""
        dev = placement.choose_initial(
            spec, self.pool.healthy(), self._loads(),
            self._tenants_by_device())
        if dev is None:
            raise RuntimeError(
                f"stream {spec.name!r}: no healthy pool device to "
                "place on")
        return dev

    def _publish_lanes(self) -> None:
        """Per-device lane-count gauges (the /healthz + Prometheus
        twins of the placement state)."""
        loads = self._loads()
        for d in self.pool.devices:
            metrics.set("fleet_device_lanes", loads.get(d.index, 0),
                        labels={"device": d.label})

    # ---------------------------------------------------- lane control

    def _notify(self) -> None:
        """Wake an idle scheduler (called from lane sink threads after
        each drained item and at sink-pipe exit).  The sequence number
        closes the race between the scheduler's progress check and its
        wait: a notify landing in between bumps the sequence, and the
        scheduler skips the wait instead of missing the wakeup."""
        with self._wake:
            self._wake_seq += 1
            self._wake.notify_all()

    def _start(self, name: str) -> bool:
        spec = self.specs[name]
        try:
            self.lanes[name] = _StreamLane(self, spec)
            self._publish_lanes()
            return True
        except (KeyboardInterrupt, SystemExit):
            self.admission.release(name)
            raise
        except BaseException as e:  # noqa: BLE001 — contained
            log.error(f"[fleet] stream {name!r} failed to start: "
                      f"{e!r}")
            self.admission.release(name)
            self.results[name] = StreamResult(name, "failed", error=e)
            return False

    def _start_queued(self) -> None:
        """Start queued streams into freed capacity.  Loops PAST
        start failures: a lane whose constructor raises released its
        slot, and the next queued stream must get it — otherwise a
        failed start with a non-empty waitlist would leave run()
        spinning forever with no active lanes."""
        while True:
            nxt = self.admission.pop_ready()
            if nxt is None:
                return
            spec = self._waitlist.pop(nxt, None)
            if spec is None:
                # popped a stream the waitlist no longer holds (e.g.
                # recorded rejected after an eviction race): give the
                # slot back and try the next one
                self.admission.release(nxt)
                continue
            # a start failure released its slot; keep popping until
            # capacity is genuinely full or the queue is drained
            self._start(nxt)

    def _device_halt(self, exc: BaseException,
                     lane: "_StreamLane") -> bool:
        """Scoped HALT domain (driver (a) of the migration
        machinery): when the faulted lane's pool member has a healthy
        peer, mark it halted, force-retire ONLY its plan cache, and
        drain-migrate its lanes onto survivors — the neighbors'
        compiled programs keep dispatching untouched, no reinit
        budget is spent (a member halts at most once; it never
        returns except through a fleet-wide reinit).  With no peer,
        fall back to the budgeted fleet-wide reinit (today's
        behavior, now the last resort)."""
        dev = lane.device
        survivors = [d for d in self.pool.healthy() if d is not dev]
        if not survivors:
            return self._reinit_all(exc, faulting=lane.name)
        dev.set_state(STATE_HALTED)
        # only the faulted member's cache: a fleet-wide invalidate
        # would recompile every healthy tenant for a fault their
        # device never saw
        dev.plans.invalidate()
        metrics.add("device_drains")
        metrics.add("device_drains", labels={"device": dev.label})
        events.emit("fleet.device_halt", trace=0, stream=lane.name,
                    info=dev.label)
        log.warning(f"[fleet] device halt on {dev.label} (stream "
                    f"{lane.name!r}): draining its lanes onto "
                    f"{len(survivors)} survivor(s) ({exc!r})")
        for ln in [l for l in self.lanes.values()
                   if not l.done and l.device is dev]:
            target = placement.choose_target(
                ln.name, dev.index, self.pool.healthy(),
                self._loads(), self._tenants_by_device())
            # survivors is non-empty, so a target always exists
            ln.migrate_to(target, trusted=False)
        return True

    def _reinit_all(self, exc: BaseException, faulting: str) -> bool:
        """The no-peer failure domain: a device halt with nothing to
        migrate onto.  One budgeted decision (the fleet supervisor),
        then: drop the jax caches, retire + forget every pool
        member's shared plans, rebuild each lane's processor at its
        own ladder rung and re-dispatch each lane's in-flight window
        cold — journal order and checkpoint offsets unchanged per
        stream."""
        if self._reinit_sup is None or \
                not self._reinit_sup.should_restart(exc):
            return False
        metrics.add("device_reinits")
        metrics.add("device_reinits", labels={"stream": faulting})
        events.emit("fleet.reinit", trace=0, stream=faulting,
                    info=type(exc).__name__)
        log.warning(f"[fleet] device halt (stream {faulting!r}): "
                    "shared reinit — rebuilding every lane's plan "
                    f"({exc!r})")
        import jax
        try:
            jax.clear_caches()
        except Exception as e:  # pragma: no cover - version drift
            log.warning(f"[fleet] jax.clear_caches failed ({e!r}); "
                        "proceeding with the rebuild")
        self.pool.invalidate_all()
        for lane in self.lanes.values():
            if not lane.done:
                lane.reinit_cold()
        if self._former is not None:
            # every parked offer was re-dispatched cold (and
            # cancelled) by its lane's reinit_cold above
            self._former.reset()
        return True

    def _on_lane_done(self, lane: _StreamLane) -> None:
        self.admission.release(lane.name)
        dropped = int(metrics.get("segments_dropped",
                                  labels={"stream": lane.name}))
        self.results[lane.name] = StreamResult(
            lane.name,
            lane.status if lane.status in ("done", "failed")
            else "failed",
            stats=lane.pipe.stats, error=lane.error,
            drained=lane.drained[0] - lane._drained0,
            dropped=dropped,
            extras={"plan": getattr(lane.pipe.processor, "plan_name",
                                    None),
                    "device": lane.device.label,
                    "migrations": lane.migrations})
        self._publish_lanes()
        # capacity freed: start queued streams in priority order
        self._start_queued()

    def _observe_fairness(self) -> None:
        """One fleet-wide fairness observation, paced on emits (not
        scheduler rounds — an idle spin must not walk the hysteresis):
        pressure = fraction of running lanes that waited on their sink
        since the last observation."""
        running = [ln for ln in self.lanes.values() if not ln.done]
        emits = sum(ln._emitted_since_obs for ln in running)
        waits = sum(1 for ln in running if ln.sink_wait)
        if not running or (emits == 0 and waits == 0):
            return
        pressure = waits / len(running)
        loss = metrics.window("segments_dropped").sum() > 0
        shed = self.fairness.observe(
            pressure, loss,
            [(ln.name, ln.priority, ln.real_time,
              self._former is not None and self._former.eligible(ln),
              ln.device.label)
             for ln in running])
        for ln in running:
            ln.forced_shed = ln.name in shed
            ln._emitted_since_obs = 0

    # -------------------------------------------- migration drivers b+c

    def _maybe_rebalance(self) -> None:
        """SLO-driven escape hatch (driver (b), ``migrate_on_burn``):
        a stream whose burn-rate tracker verdict is not ok migrates
        onto a STRICTLY less-loaded healthy peer before its error
        budget is spent — paced (4 Hz), with a per-lane cooldown so
        a still-burning migrant cannot flap between members."""
        if not self._migrate_on_burn:
            return
        now = time.monotonic()
        if now - self._rebalance_t < 0.25:
            return
        self._rebalance_t = now
        healthy = self.pool.healthy()
        if len(healthy) < 2:
            return
        from srtb_tpu.utils import slo
        tr = slo.tracker
        if tr is None:
            return
        try:
            per = tr.evaluate()
        except Exception as e:  # noqa: BLE001 — advisory telemetry
            log.debug(f"[fleet] slo evaluate failed: {e!r}")
            return
        for ln in list(self.lanes.values()):
            if ln.done or ln.status != "running":
                continue
            verdict = per.get(ln.name)
            if verdict is None or verdict.get("ok", True):
                continue
            if now - ln._migrated_t < 5.0:
                continue
            loads = self._loads()
            target = placement.choose_target(
                ln.name, ln.device.index, healthy, loads,
                self._tenants_by_device())
            if target is None or loads.get(target.index, 0) \
                    >= loads.get(ln.device.index, 0):
                continue
            log.warning(f"[fleet] SLO burn on {ln.name!r}: "
                        f"rebalancing {ln.device.label} -> "
                        f"{target.label}")
            ln.migrate_to(target, trusted=True,
                          deadline_s=self._drain_deadline)

    def rolling_restart(self) -> None:
        """Operator-facing rolling restart (driver (c)): queue every
        pool member for a drain.  The scheduler drains ONE member per
        round — its lanes live-migrate onto peers, its plan cache is
        retired (the compiled handles die with the restart the drain
        is for), and it re-arms before the next member drains.
        Callable from any thread; the scheduler thread does the
        work."""
        self._rolling.extend(d.index for d in self.pool.devices)
        self._notify()

    def _pump_rolling(self) -> bool:
        """Drain at most one queued rolling-restart member (one at a
        time is the contract).  A member that would leave the pool
        without a healthy peer is skipped loudly — a one-member pool
        cannot roll."""
        if not self._rolling:
            return False
        # pace: the previous drain's migrants must RESUME (dispatch on
        # their new member) before the next member is pulled — the
        # operator contract is a live roll, not a simultaneous yank
        if any(not ln.done and not ln._resumed
               for ln in self.lanes.values()):
            return False
        idx = self._rolling.popleft()
        dev = self.pool.devices[idx]
        if dev.state != STATE_OK:
            return False
        if len(self.pool.healthy()) < 2:
            log.warning(f"[fleet] rolling restart: {dev.label} has "
                        "no healthy peer to drain onto; skipping")
            return False
        dev.set_state(STATE_DRAINING)
        metrics.add("device_drains")
        metrics.add("device_drains", labels={"device": dev.label})
        events.emit("fleet.device_drain", trace=0, stream=None,
                    info=dev.label)
        log.info(f"[fleet] rolling restart: draining {dev.label}")
        for ln in [l for l in self.lanes.values()
                   if not l.done and l.device is dev]:
            target = placement.choose_target(
                ln.name, dev.index, self.pool.healthy(),
                self._loads(), self._tenants_by_device())
            ln.migrate_to(target, trusted=True,
                          deadline_s=self._drain_deadline)
        dev.plans.invalidate()
        dev.set_state(STATE_OK)
        log.info(f"[fleet] rolling restart: {dev.label} drained "
                 "and re-armed")
        return True

    # ------------------------------------------------------------ run

    @staticmethod
    def _plan_key(spec: StreamSpec) -> str | None:
        """The spec's plan-family key for batch-aware admission (None
        when the config cannot project one — duck-typed test configs):
        the gate prefers evicting streams with no co-tenant family,
        keeping formed batches dense."""
        try:
            from srtb_tpu.pipeline import registry
            from srtb_tpu.utils.platform import on_accelerator
            return registry.plan_cache_key(
                spec.cfg, donate_input=on_accelerator())
        except Exception as e:  # noqa: BLE001 — admission must never fail
            log.debug(f"[fleet] no plan key for {spec.name}: {e!r}")
            return None

    def run(self) -> dict[str, StreamResult]:
        metrics.set("fleet_streams_total", len(self.specs))
        for spec in self.specs.values():
            decision = self.admission.request(
                spec.name, spec.priority,
                plan_key=self._plan_key(spec))
            if decision == ADMIT:
                self._start(spec.name)
            elif decision == QUEUE:
                self._waitlist[spec.name] = spec
        # queue evictions recorded by the controller surface as
        # rejected results too
        for name in self.admission.rejected:
            self._waitlist.pop(name, None)
            self.results.setdefault(
                name, StreamResult(name, "rejected"))
        # a start failure in the admission pass freed capacity: give
        # it to queued streams before the loop (otherwise nothing
        # active + a populated waitlist = an immediate idle spin)
        self._start_queued()
        try:
            while True:
                active = [ln for ln in self.lanes.values()
                          if not ln.done]
                if not active and not self._waitlist:
                    break
                if not active and self._waitlist:
                    # every running lane is gone but streams still
                    # wait: start them now; if none can start (all
                    # fail / inconsistent queue state), fail the
                    # remainder loudly instead of spinning forever
                    self._start_queued()
                    if not any(not ln.done
                               for ln in self.lanes.values()):
                        for name, spec in list(self._waitlist.items()):
                            del self._waitlist[name]
                            self.results.setdefault(name, StreamResult(
                                name, "failed",
                                error=RuntimeError(
                                    "queued stream never became "
                                    "startable")))
                        break
                    continue
                wake_seq = self._wake_seq
                progressed = False
                for lane in active:
                    if lane.step():
                        progressed = True
                    if lane.done:
                        self._on_lane_done(lane)
                if self._former is not None and self._former.pump():
                    # a linger deadline flushed a partial batch: the
                    # filled slots drain next round
                    progressed = True
                if self._pump_rolling():
                    progressed = True
                self._maybe_rebalance()
                self._observe_fairness()
                for name in self.admission.rejected:
                    if name in self._waitlist:
                        del self._waitlist[name]
                        self.results.setdefault(
                            name, StreamResult(name, "rejected"))
                if not progressed:
                    if self._former is not None \
                            and self._former.flush_all():
                        # idle scheduler: dispatch every pending
                        # offer now rather than waiting out a linger
                        # nothing else will fill
                        continue
                    blocker = next(
                        (ln for ln in self.lanes.values()
                         if not ln.done and ln.pending), None)
                    if blocker is not None:
                        blocker.step(allow_block=True)
                        if blocker.done:
                            self._on_lane_done(blocker)
                    else:
                        # event-driven idle: every lane is waiting on
                        # its sink side, so wait for a sink thread's
                        # notify instead of burning a fixed 2 ms poll
                        # (the round-15 toy-shape pitfall); the
                        # timeout bounds a lost wakeup, and the
                        # sequence check skips the wait when a drain
                        # landed since this round observed the lanes
                        metrics.add("fleet_idle_waits")
                        deadline = time.monotonic() + 0.05
                        with self._wake:
                            # predicate loop: a spurious wakeup
                            # re-checks the sequence instead of
                            # re-scanning idle lanes; the deadline
                            # bounds a lost wakeup
                            while self._wake_seq == wake_seq:
                                left = deadline - time.monotonic()
                                if left <= 0:
                                    break
                                self._wake.wait(left)
        finally:
            metrics.set("fleet_running", 0)
            if self._tsan is not None:
                # a later run() may be driven from a different thread;
                # claims are per-run, the order graph persists
                self._tsan.release_owners()
        return self.results

    def close(self) -> None:
        for lane in self.lanes.values():
            lane.close()
        self.pool.invalidate_all()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
