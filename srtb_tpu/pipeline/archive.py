"""Archive replay engine: recorded baseband at full device occupancy.

Real-time serving (``Pipeline`` on a UDP source, or a fleet of live
beams) is paced by the antenna: the device idles whenever the link
does.  Archive reprocessing is the opposite regime — the case study of
*Implementing CUDA Streams into AstroAccelerate* (PAPERS.md): a batch
job should saturate the device, not the wall clock.  This engine
replays a SET of recorded baseband files with every throughput
mechanism the repo has, composed:

- **no pacing**: file sources read as fast as the disk yields (the
  engine never sleeps on a source);
- **deep micro-batch**: each file's lane stacks B segments into one
  vmapped dispatch (``micro_batch_segments`` — file-mode fleet lanes
  accept it; real-time lanes still reject);
- **many files in parallel**: files fan out across
  :class:`~srtb_tpu.pipeline.fleet.StreamFleet` lanes
  (``fleet_max_streams`` lanes live at once, the rest queued behind
  admission control), sharing ONE compiled plan through the fleet's
  registry-keyed :class:`SharedPlanCache` — N files, one compile;
- **many SMALL files in one dispatch**: with ``fleet_batch`` set (and
  ``micro_batch=1`` — per-lane micro-batch and cross-stream batching
  are mutually exclusive per lane), the fleet's cross-tenant batch
  former folds ready segments from DIFFERENT files into one shared
  vmapped dispatch, so a directory of short captures — each too small
  to fill a per-lane micro-batch — still amortizes dispatch overhead
  across lanes;
- **exactly-once outputs + deterministic resume**: every file gets
  its own checkpoint + run-manifest namespace under the output
  directory, and timestamps are stamped from stream offsets
  (``Config.deterministic_timestamps``), so artifact names reproduce
  across runs — re-running the SAME replay after a crash resumes each
  file from its checkpoint, manifest recovery rolls back uncommitted
  artifacts, and the final output set is bit-identical (paths +
  SHA-256) to an uninterrupted run.  ``tools/archive_replay.py
  --selftest`` gates exactly that, with a mid-run SIGTERM.

The per-file bulkheads are the fleet's: one corrupt file fails its
own lane (``StreamResult.status = "failed"``) and the other files
complete untouched.
"""

from __future__ import annotations

import os
import re
import time
from dataclasses import dataclass, field

from srtb_tpu.config import Config
from srtb_tpu.utils.logging import log
from srtb_tpu.utils.metrics import metrics

# archive lanes have no latency contract: a deeper default in-flight
# window + micro-batch than the real-time engine's 2/1
DEFAULT_LANES = 2
DEFAULT_MICRO_BATCH = 4
DEFAULT_INFLIGHT = 8


def stream_name_for(path: str, taken: set) -> str:
    """Stable, filesystem-safe lane name for one archive file (the
    basename without extension, deduplicated with a numeric suffix) —
    it names the file's output/checkpoint/manifest namespace, so it
    must be deterministic across resumes of the same file list."""
    base = os.path.splitext(os.path.basename(path))[0]
    name = re.sub(r"[^A-Za-z0-9_.-]", "_", base) or "file"
    if name in taken:
        i = 1
        while f"{name}.{i}" in taken:
            i += 1
        name = f"{name}.{i}"
    taken.add(name)
    return name


@dataclass
class ArchiveReport:
    """Outcome of one replay run."""
    files: dict = field(default_factory=dict)  # name -> per-file dict
    segments: int = 0
    drained: int = 0
    elapsed_s: float = 0.0
    failed: int = 0
    plan_compiles: int = 0
    batched_dispatches: int = 0
    batched_segments: int = 0

    @property
    def segments_per_sec(self) -> float:
        return self.drained / self.elapsed_s if self.elapsed_s else 0.0

    def as_dict(self) -> dict:
        return {
            "files": self.files, "segments": self.segments,
            "drained": self.drained, "failed": self.failed,
            "elapsed_s": self.elapsed_s,
            "segments_per_sec": self.segments_per_sec,
            "plan_compiles": self.plan_compiles,
            "batched_dispatches": self.batched_dispatches,
            "batched_segments": self.batched_segments,
            "ok": self.failed == 0,
        }


class ArchiveReplay:
    """Replay ``files`` through a micro-batched file-lane fleet (see
    module docstring).  ``base_cfg`` carries the science config
    (format, segment size, DM, thresholds, search_mode, ...); this
    engine derives each file's lane config — input path, per-file
    output/checkpoint/manifest namespace under ``out_dir``,
    deterministic timestamps, batch depth — and never mutates the
    base.  Re-running with the same arguments resumes: completed
    files' checkpoints make their lanes no-ops, partial files resume
    from their checkpoint with manifest-recovered exactly-once
    outputs."""

    def __init__(self, base_cfg: Config, files: list[str],
                 out_dir: str, lanes: int = DEFAULT_LANES,
                 micro_batch: int = DEFAULT_MICRO_BATCH,
                 inflight: int = DEFAULT_INFLIGHT,
                 keep_waterfall: bool = True,
                 max_segments_per_file: int | None = None,
                 manifest: bool = True,
                 fleet_batch: int = 0):
        if not files:
            raise ValueError("archive replay needs at least one file")
        for f in files:
            if not os.path.isfile(f):
                raise FileNotFoundError(f"archive file not found: {f}")
        os.makedirs(out_dir, exist_ok=True)
        self.out_dir = out_dir
        self.keep_waterfall = keep_waterfall
        self.max_segments_per_file = max_segments_per_file
        mb = max(1, int(micro_batch))
        window = max(int(inflight), mb, 1)
        taken: set = set()
        self.names: list[str] = []
        self.cfgs: dict[str, Config] = {}
        for path in files:
            name = stream_name_for(path, taken)
            self.names.append(name)
            prefix = os.path.join(out_dir, name + "_")
            self.cfgs[name] = base_cfg.replace(
                input_file_path=path,
                stream_name=name,
                baseband_output_file_prefix=prefix,
                checkpoint_path=os.path.join(out_dir,
                                             name + ".ck.json"),
                run_manifest_path=(os.path.join(
                    out_dir, name + ".manifest.jsonl")
                    if manifest else ""),
                deterministic_timestamps=True,
                micro_batch_segments=mb,
                inflight_segments=window,
            )
        # the fleet-level config: lane capacity + a queue deep enough
        # that every file is admitted eventually, priorities equal
        # (FIFO by spec order).  fleet_batch arms the cross-tenant
        # batch former — the many-small-files case where per-lane
        # micro-batching has nothing to stack (its eligibility rule
        # keeps micro-batched lanes out, so the two modes never fight
        # over the same segment)
        fb = max(0, int(fleet_batch))
        if fb >= 2 and mb > 1:
            log.warning(
                f"[archive] fleet_batch={fb} with micro_batch={mb}: "
                "micro-batched lanes are ineligible for cross-stream "
                "batching; set --micro-batch 1 to use --fleet-batch")
        self.fleet_cfg = base_cfg.replace(
            fleet_max_streams=max(1, int(lanes)),
            fleet_queue_limit=len(files),
            fleet_batch_max=fb)

    def run(self) -> ArchiveReport:
        from srtb_tpu.pipeline.fleet import StreamFleet, StreamSpec

        specs = [StreamSpec(name=n, cfg=self.cfgs[n],
                            keep_waterfall=self.keep_waterfall,
                            max_segments=self.max_segments_per_file)
                 for n in self.names]
        t0 = time.perf_counter()
        compiles0 = int(metrics.get("fleet_plan_compiles"))
        bdisp0 = int(metrics.get("batched_dispatches"))
        bsegs0 = int(metrics.get("batched_segments"))
        report = ArchiveReport()
        with StreamFleet(specs, fleet_cfg=self.fleet_cfg) as fleet:
            results = fleet.run()
            report.plan_compiles = \
                int(metrics.get("fleet_plan_compiles")) - compiles0
            report.batched_dispatches = \
                int(metrics.get("batched_dispatches")) - bdisp0
            report.batched_segments = \
                int(metrics.get("batched_segments")) - bsegs0
        report.elapsed_s = time.perf_counter() - t0
        for name in self.names:
            res = results.get(name)
            if res is None:
                report.files[name] = {"status": "missing"}
                report.failed += 1
                continue
            stats = res.stats
            report.files[name] = {
                "status": res.status,
                "segments": stats.segments if stats else 0,
                "drained": res.drained,
                "dropped": res.dropped,
                "error": repr(res.error) if res.error else None,
            }
            report.segments += stats.segments if stats else 0
            report.drained += res.drained
            if res.status != "done":
                report.failed += 1
        log.info(
            f"[archive] {len(self.names)} file(s): {report.drained} "
            f"segment(s) drained in {report.elapsed_s:.1f}s "
            f"({report.segments_per_sec:.1f} seg/s, "
            f"{report.plan_compiles} plan compile(s), "
            f"{report.failed} failed)")
        return report
