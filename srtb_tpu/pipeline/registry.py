"""Plan-family registry: execution plans as DATA, not if-chains.

Before this module, four subsystems each carried their own enumeration
of the plan zoo and had to be edited in lockstep whenever a family was
added: ``segment.py`` (plan construction + resolvers),
``analysis/hlo_audit.py`` (the auditable family specs), ``demote.py``
(the self-healing ladder's step chain), and ``fleet.py`` (the shared
plan cache's key/build logic).  The FPGA pulsar-search composition
paper (PAPERS.md, *Combining Multiple Optimised FPGA-based Pulsar
Search Modules*) is the target architecture — independent search
modules registered behind one harness — and this registry is the one
table they all consume from, so the enumerations can never drift:

- :class:`PlanFamily` — one auditable plan family: the config
  projection that selects it, its declared ``hbm_passes`` floor, its
  search mode, and whether the demotion ladder may land on it
  (``ladder`` eligibility).  ``analysis/hlo_audit.py`` enumerates
  these (``plan_families()``) instead of keeping its own tuple, and
  ``plan_audit --selftest`` proves a family registered here WITHOUT a
  checked-in plan card fails the CI gate (``temp_family``).

- :class:`LadderStep` — one demotion-ladder step: its canonical
  position plus the apply rule (cfg -> cheaper cfg, or None when the
  step would not change the resolved plan).  ``resilience/demote.py``
  walks ``ladder_steps()`` instead of its own if-chain; the apply
  rules delegate to the SAME pure-config predicates the
  SegmentProcessor resolvers use (``pipeline/segment.py``
  ``ring_usable`` / ``fused_tail_resolves``), so a rung is skipped
  exactly when the feature would not resolve ON.

- :class:`SearchMode` — one registered search capability: the
  processor class that implements it and the Config field that selects
  it (``Config.search_mode``).  ``Pipeline``/``ThreadedPipeline``, the
  self-healing plan factory, the fleet's :class:`SharedPlanCache`, the
  archive replay engine and the HLO auditor all build processors
  through :func:`build_processor` / key them through
  :func:`plan_cache_key`, so a new mode lands in every consumer —
  auditor, demotion ladder, chaos soak, fleet — by registering here.

The registry deliberately imports nothing heavy at module level;
processor classes resolve lazily (``module:Class`` paths) so importing
the table costs nothing and no import cycles form (the processor
modules never import this one).
"""

from __future__ import annotations

import contextlib
import importlib
from dataclasses import dataclass, field

# ------------------------------------------------------------------
# search modes


@dataclass(frozen=True)
class SearchMode:
    """One registered search capability (``Config.search_mode``)."""

    name: str
    desc: str
    # lazy "module:Class" path of the SegmentProcessor (sub)class that
    # implements the mode — resolved on first build, never at import
    cls_path: str

    def resolve(self):
        mod, _, cls = self.cls_path.partition(":")
        return getattr(importlib.import_module(mod), cls)


_MODES: dict[str, SearchMode] = {}


def register_mode(mode: SearchMode) -> SearchMode:
    if mode.name in _MODES:
        raise ValueError(f"search mode {mode.name!r} already registered")
    _MODES[mode.name] = mode
    return mode


def search_modes() -> tuple[SearchMode, ...]:
    return tuple(_MODES.values())


def resolve_mode(cfg) -> SearchMode:
    """The registered mode selected by ``cfg.search_mode`` (missing
    attribute = the default single-pulse mode).  Unknown names raise at
    plan-build time — a typo must not silently run the wrong search."""
    name = str(getattr(cfg, "search_mode", "single_pulse")
               or "single_pulse").lower()
    mode = _MODES.get(name)
    if mode is None:
        raise ValueError(
            f"unknown search_mode {name!r} "
            f"(registered: {', '.join(sorted(_MODES))})")
    return mode


def build_processor(cfg, **kwargs):
    """Build the segment processor for ``cfg`` through the registry:
    the ONE constructor every consumer (Pipeline, healer plan factory,
    fleet shared-plan cache, archive engine, HLO auditor, bench) uses,
    so a registered mode reaches all of them.  ``kwargs`` pass through
    to the processor constructor (window_name / staged /
    donate_input)."""
    return resolve_mode(cfg).resolve()(cfg, **kwargs)


def plan_cache_key(cfg, donate_input: bool = False, **kwargs) -> str:
    """Mode-dispatched shared-plan cache key (see
    ``SegmentProcessor.plan_cache_key``): each mode's class projects
    its own trace-relevant config, so two configs share a compiled
    plan only when mode AND projection agree."""
    return resolve_mode(cfg).resolve().plan_cache_key(
        cfg, donate_input=donate_input, **kwargs)


# ------------------------------------------------------------------
# plan families (the auditable zoo)


@dataclass(frozen=True)
class PlanFamily:
    """One auditable plan family: the Config/constructor knobs that
    select it, the declared ``hbm_passes`` floor the family must
    report, its search mode, and its demotion-ladder eligibility
    (``ladder=False`` families — e.g. the periodicity mode, which the
    ladder demotes OUT of, never INTO — may not be landed on by a
    demotion; ``analysis/hlo_audit.audit_ladder`` enforces it)."""

    key: str
    desc: str
    cfg: dict = field(default_factory=dict)
    donate: bool = False
    staged: bool | None = None
    env: dict = field(default_factory=dict)
    hbm_passes: int | None = None
    mode: str = "single_pulse"
    ladder: bool = True


_FAMILIES: dict[str, PlanFamily] = {}


def register_family(fam: PlanFamily) -> PlanFamily:
    if fam.key in _FAMILIES:
        raise ValueError(f"plan family {fam.key!r} already registered")
    if fam.mode not in _MODES:
        raise ValueError(
            f"plan family {fam.key!r}: unregistered mode {fam.mode!r}")
    _FAMILIES[fam.key] = fam
    return fam


def plan_families() -> tuple[PlanFamily, ...]:
    return tuple(_FAMILIES.values())


def plan_keys() -> tuple[str, ...]:
    return tuple(_FAMILIES)


def family(key: str) -> PlanFamily | None:
    return _FAMILIES.get(key)


@contextlib.contextmanager
def temp_family(fam: PlanFamily):
    """Scoped registration for tests and the plan-audit selftest: the
    family exists (and is enumerated by every consumer) only inside
    the ``with`` block."""
    register_family(fam)
    try:
        yield fam
    finally:
        _FAMILIES.pop(fam.key, None)


# ------------------------------------------------------------------
# demotion-ladder steps


@dataclass(frozen=True)
class LadderStep:
    """One demotion step: canonical name + the apply rule.  ``apply``
    returns ``(cheaper_cfg, staged_override)`` or None when the step
    would not change the active RESOLVED plan (skipped rung — demoting
    onto an identical plan would burn a ladder level recovering
    nothing).  ``staged`` in/out is the explicit SegmentProcessor
    constructor override (None = resolve from segment size)."""

    name: str
    desc: str
    apply: object  # callable (cfg, staged) -> (cfg, staged) | None


_STEPS: dict[str, LadderStep] = {}


def register_step(step: LadderStep) -> LadderStep:
    if step.name in _STEPS:
        raise ValueError(f"ladder step {step.name!r} already registered")
    _STEPS[step.name] = step
    return step


def ladder_steps() -> tuple[LadderStep, ...]:
    return tuple(_STEPS.values())


def ladder_order() -> tuple[str, ...]:
    return tuple(_STEPS)


def ladder_step(name: str) -> LadderStep:
    step = _STEPS.get(name)
    if step is None:
        raise ValueError(
            f"unknown ladder step {name!r} "
            f"(steps: {', '.join(_STEPS)})")
    return step


# ------------------------------------------------------------------
# built-in registrations
# ------------------------------------------------------------------

register_mode(SearchMode(
    "single_pulse",
    "single-pulse search: boxcar cascade over the dedispersed "
    "time series (the reference pipeline's mode)",
    "srtb_tpu.pipeline.segment:SegmentProcessor"))

register_mode(SearchMode(
    "periodicity",
    "periodicity search: harmonic-summed power spectrum over the "
    "dedispersed time series + phase folding at detected candidates "
    "(the FPGA pulsar-search paper's module set), on top of the "
    "single-pulse chain",
    "srtb_tpu.pipeline.periodicity:PeriodicitySegmentProcessor"))


# ---- ladder steps, cheapest-to-drop first.  The apply rules import
# the shared pure-config predicates lazily: the SegmentProcessor
# resolvers and these rules are the same functions, so a rung can
# never demote onto an identical plan by rule drift.

def _resolved_staged(cfg, staged):
    from srtb_tpu.pipeline.segment import staged_resolves
    return staged_resolves(cfg, staged)


def _apply_quality(cfg, staged):
    if not getattr(cfg, "quality_stats", False):
        return None
    return cfg.replace(quality_stats=False), staged


def _apply_search_mode(cfg, staged):
    if str(getattr(cfg, "search_mode", "single_pulse")
           or "single_pulse").lower() == "single_pulse":
        return None
    return cfg.replace(search_mode="single_pulse"), staged


def _apply_micro_batch(cfg, staged):
    if int(getattr(cfg, "micro_batch_segments", 1) or 1) <= 1:
        return None
    return cfg.replace(micro_batch_segments=1), staged


def _apply_front_fuse(cfg, staged):
    from srtb_tpu.pipeline.segment import (_front_fuse_structural,
                                           front_fuse_resolves)
    resolved = _resolved_staged(cfg, staged)
    # structural precheck FIRST: a forced front_fuse="on" evaluated
    # under a stagedness where the fusion is impossible (e.g. the
    # healer's pre-bind rung scan on a small segment) must read as
    # "nothing to drop", not trip the knob's loud constructor check
    if not _front_fuse_structural(cfg, resolved):
        return None
    if not front_fuse_resolves(cfg, resolved):
        return None
    return cfg.replace(front_fuse="off"), staged


def _drop_forced_front_fuse(cfg):
    """Rungs that break a front-fuse prerequisite (fused tail,
    stagedness) also clear a FORCED front_fuse="on": the resulting
    config must construct cleanly instead of tripping the knob's
    loud structural check."""
    if str(getattr(cfg, "front_fuse", "auto")).lower() == "on":
        return cfg.replace(front_fuse="off")
    return cfg


def _apply_ring(cfg, staged):
    if str(getattr(cfg, "ingest_ring", "auto")).lower() == "off":
        return None
    from srtb_tpu.pipeline.segment import ring_usable
    if not ring_usable(cfg):
        return None
    return cfg.replace(ingest_ring="off"), staged


def _apply_skzap(cfg, staged):
    if not (getattr(cfg, "use_pallas_sk", False)
            and getattr(cfg, "use_pallas", False)):
        return None
    return cfg.replace(use_pallas_sk=False), staged


def _apply_fused_tail(cfg, staged):
    # drops the fused epilogue AND the Pallas kernels hosting it:
    # this rung is the Mosaic-free fallback, so a kernel compile
    # fault cannot survive it
    from srtb_tpu.pipeline.segment import fused_tail_resolves
    if not (fused_tail_resolves(cfg, _resolved_staged(cfg, staged))
            or getattr(cfg, "use_pallas", False)):
        return None
    cfg = _drop_forced_front_fuse(cfg)
    return cfg.replace(fused_tail="off", use_pallas=False), staged


def _apply_staged(cfg, staged):
    if _resolved_staged(cfg, staged):
        return None
    # staged forbids micro-batching; force it off even when an
    # explicit plan_ladder subset skipped the micro_batch rung
    if int(getattr(cfg, "micro_batch_segments", 1) or 1) > 1:
        cfg = cfg.replace(micro_batch_segments=1)
    return cfg, True


def _apply_monolithic(cfg, staged):
    from srtb_tpu.ops import fft as F
    n = int(getattr(cfg, "baseband_input_count", 0) or 0)
    already = (not _resolved_staged(cfg, staged) and n > 0
               and F.resolve_strategy(
                   n, getattr(cfg, "fft_strategy", "auto"))
               == "monolithic")
    if already:
        return None
    return _drop_forced_front_fuse(cfg).replace(
        fft_strategy="monolithic"), False


register_step(LadderStep(
    "quality", "drop the data-quality epilogue (telemetry, not "
    "science) — the very cheapest thing to shed",
    _apply_quality))
register_step(LadderStep(
    "search_mode", "drop the extra search mode (periodicity folding) "
    "back to single-pulse — the cheapest science to shed",
    _apply_search_mode))
register_step(LadderStep(
    "micro_batch", "drop micro-batching (B x program footprint)",
    _apply_micro_batch))
register_step(LadderStep(
    "front_fuse", "drop the front-fused pallas2 megakernel back to "
    "the classic staged front (the audited Mosaic-balks fallback)",
    _apply_front_fuse))
register_step(LadderStep(
    "ring", "drop the ingest ring's carry programs",
    _apply_ring))
register_step(LadderStep(
    "skzap", "drop the one-kernel SK-zap fusion",
    _apply_skzap))
register_step(LadderStep(
    "fused_tail", "drop the fused epilogue + every Pallas kernel "
    "(the Mosaic-free rung)", _apply_fused_tail))
register_step(LadderStep(
    "staged", "three small programs instead of one big one "
    "(the proven chain-OOM answer)", _apply_staged))
register_step(LadderStep(
    "monolithic", "the minimal-feature floor that must run anywhere "
    "XLA runs", _apply_monolithic))


# ---- plan families.  The audit shape (analysis/hlo_audit.py,
# default 2^16 samples / 8 channels) keeps every family lowerable in
# ~a second on CPU; the cfg dicts are overrides on that audit config.

_RING_CFG = {"baseband_reserve_sample": True, "dm": 0.1}

for _fam in (
    PlanFamily("monolithic", "one XLA R2C custom call, unfused 7-pass "
               "tail",
               {"fft_strategy": "monolithic", "fused_tail": "off"},
               hbm_passes=7),
    PlanFamily("monolithic_donate", "monolithic with the donated raw "
               "input",
               {"fft_strategy": "monolithic", "fused_tail": "off"},
               donate=True, hbm_passes=7),
    PlanFamily("four_step", "Bailey four-step R2C, unfused tail",
               {"fft_strategy": "four_step", "fused_tail": "off"},
               hbm_passes=7),
    PlanFamily("four_step_ftail", "four-step with the fused RFI+chirp "
               "tail",
               {"fft_strategy": "four_step", "fused_tail": "on"},
               hbm_passes=5),
    PlanFamily("four_step_ftail_donate", "fused tail + donated raw "
               "input",
               {"fft_strategy": "four_step", "fused_tail": "on"},
               donate=True, hbm_passes=5),
    PlanFamily("four_step_ftail_mb2", "fused tail, micro-batch of 2",
               {"fft_strategy": "four_step", "fused_tail": "on",
                "micro_batch_segments": 2},
               donate=True, hbm_passes=5),
    PlanFamily("mxu_ftail", "radix-128 MXU matmul FFT, fused tail",
               {"fft_strategy": "mxu", "fused_tail": "on"},
               hbm_passes=5),
    PlanFamily("pallas_ftail", "Pallas unpack/chirp kernels, fused tail",
               {"fft_strategy": "four_step", "fused_tail": "on",
                "use_pallas": True},
               hbm_passes=5),
    PlanFamily("pallas_fft_ftail", "Pallas VMEM row-FFT legs, fused "
               "tail",
               {"fft_strategy": "pallas", "fused_tail": "on",
                "use_pallas": True},
               hbm_passes=5),
    PlanFamily("pallas_skzap", "fully fused: one-kernel "
               "watfft+SK+detect",
               {"fft_strategy": "four_step", "fused_tail": "on",
                "use_pallas": True, "use_pallas_sk": True},
               hbm_passes=4),
    PlanFamily("pallas_skzap_donate", "skzap plan + donated raw input",
               {"fft_strategy": "four_step", "fused_tail": "on",
                "use_pallas": True, "use_pallas_sk": True},
               donate=True, hbm_passes=4),
    PlanFamily("staged", "three-program staged plan, fused tail, "
               "donation",
               {"fft_strategy": "four_step", "fused_tail": "on"},
               donate=True, staged=True, hbm_passes=5),
    PlanFamily("staged_unfused", "staged plan with the legacy 7-pass "
               "tail",
               {"fft_strategy": "four_step", "fused_tail": "off"},
               donate=True, staged=True, hbm_passes=7),
    PlanFamily("staged_pallas", "staged with Pallas row-FFT legs",
               {"fft_strategy": "four_step", "fused_tail": "on"},
               donate=True, staged=True,
               env={"SRTB_STAGED_ROWS_IMPL": "pallas"},
               hbm_passes=5),
    PlanFamily("staged_pallas2", "staged with fused two-pass pallas2 "
               "legs (downgrades to pallas legs below the 2^24 leg "
               "window)",
               {"fft_strategy": "four_step", "fused_tail": "on"},
               donate=True, staged=True,
               env={"SRTB_STAGED_ROWS_IMPL": "pallas2"},
               hbm_passes=5),
    # ---- ingest-ring (ring-v1) families: overlap-save reserves a
    # tail (baseband_reserve_sample + a small dm keeps 0 < reserved
    # < n at the audit shape), so the two-input carry ++ new assemble
    # programs exist and their carry donation must audit as a PROVEN
    # alias (checks.ring_alias_ok).
    PlanFamily("four_step_ftail_ring", "fused tail + ingest ring: "
               "carry donation proven aliased on the warm assemble "
               "program",
               {"fft_strategy": "four_step", "fused_tail": "on",
                **_RING_CFG},
               donate=True, hbm_passes=5),
    PlanFamily("monolithic_ring", "ring on the unfused monolithic "
               "fallback plan",
               {"fft_strategy": "monolithic", "fused_tail": "off",
                **_RING_CFG},
               donate=True, hbm_passes=7),
    PlanFamily("pallas_skzap_ring", "fully fused 4-pass plan + ring",
               {"fft_strategy": "four_step", "fused_tail": "on",
                "use_pallas": True, "use_pallas_sk": True,
                **_RING_CFG},
               donate=True, hbm_passes=4),
    PlanFamily("four_step_ftail_ring_mb2", "ring micro-batch: ONE "
               "carry + B stride uploads assemble B overlapped "
               "segments",
               {"fft_strategy": "four_step", "fused_tail": "on",
                "micro_batch_segments": 2, **_RING_CFG},
               donate=True, hbm_passes=5),
    PlanFamily("pallas_skzap_ring_mb2", "the fully-featured single-"
               "pulse plan: skzap + ring + micro-batch of 2 — the "
               "search_mode demotion rung's landing target",
               {"fft_strategy": "four_step", "fused_tail": "on",
                "use_pallas": True, "use_pallas_sk": True,
                "micro_batch_segments": 2, **_RING_CFG},
               donate=True, hbm_passes=4),
    PlanFamily("staged_ring", "staged plan + ring: stage_a_ring emits "
               "the carry alongside the canonical boundary",
               {"fft_strategy": "four_step", "fused_tail": "on",
                **_RING_CFG},
               donate=True, staged=True, hbm_passes=5),
    # ---- front-fused staged megakernel (staged_ffuse): unpack +
    # window + even/odd pack + FFT pass 1 fold into the pallas2 pass-1
    # kernel (raw bytes in, blocked intermediate out) and the whole
    # spectrum tail into pass 2's epilogue — the declared floor drops
    # to 2 (the two megakernel sweeps; pipeline/segment.py documents
    # the model).  front_fuse="on" forces the kernels so the audit
    # covers them on any backend; the demotion rung (front_fuse, the
    # step right after micro_batch) lands on today's staged plan.
    PlanFamily("staged_ffuse", "front-fused staged pallas2 megakernel: "
               "raw bytes -> blocked intermediate -> dedispersed "
               "spectrum in two kernel passes",
               {"fft_strategy": "four_step", "fused_tail": "on",
                "front_fuse": "on"},
               donate=True, staged=True,
               env={"SRTB_STAGED_ROWS_IMPL": "pallas2"},
               hbm_passes=2),
    PlanFamily("staged_ffuse_ring", "front-fused staged plan + ingest "
               "ring: the carry alias must survive the front fusion "
               "(the PR-7 aval lesson, re-proven per card)",
               {"fft_strategy": "four_step", "fused_tail": "on",
                "front_fuse": "on", **_RING_CFG},
               donate=True, staged=True,
               env={"SRTB_STAGED_ROWS_IMPL": "pallas2"},
               hbm_passes=2),
    # ---- data-quality epilogue (srtb_tpu/quality/): cheap jnp
    # reductions over the spectrum + waterfall ride the detect tail
    # as a side output.  The extra traffic is coarse-bin-sized, so
    # the spectrum-sized hbm_passes floor stays the base plan's;
    # ladder=False because the quality rung (FIRST in the order)
    # sheds the epilogue and must never demote INTO it.
    PlanFamily("four_step_ftail_quality", "fused-tail four-step plan "
               "with the data-quality epilogue side output",
               {"fft_strategy": "four_step", "fused_tail": "on",
                "quality_stats": True},
               donate=True, hbm_passes=5, ladder=False),
    # ---- periodicity search mode: the single-pulse chain PLUS the
    # harmonic-summed power spectrum + phase folding over the
    # dedispersed time series (pipeline/periodicity.py).  The extra
    # passes are time-series-sized (spectrum / channel_count), so the
    # spectrum-sized hbm_passes floor is the base plan's; ladder=False
    # because the demotion ladder sheds the mode (search_mode rung,
    # FIRST in the order) and must never demote INTO it.
    PlanFamily("periodicity_ftail", "periodicity mode on the fused-"
               "tail four-step plan: harmonic sum + fold over the "
               "detection time series",
               {"fft_strategy": "four_step", "fused_tail": "on",
                "search_mode": "periodicity"},
               donate=True, hbm_passes=5, mode="periodicity",
               ladder=False),
    PlanFamily("periodicity_ring_mb2", "the archive-replay shape: "
               "periodicity mode + ingest ring + micro-batch of 2",
               {"fft_strategy": "four_step", "fused_tail": "on",
                "micro_batch_segments": 2, "search_mode": "periodicity",
                **_RING_CFG},
               donate=True, hbm_passes=5, mode="periodicity",
               ladder=False),
):
    register_family(_fam)
del _fam
