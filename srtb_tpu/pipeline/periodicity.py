"""Periodicity/folding search mode as a registered plan family.

:class:`PeriodicitySegmentProcessor` extends the single-pulse
:class:`~srtb_tpu.pipeline.segment.SegmentProcessor` with the FPGA
pulsar-search paper's module set (ops/periodicity.py): after the
standard device chain produces the dedispersed detection time series,
the same traced program appends a harmonic-summed power-spectrum
search and phase-folds the top-K candidates — one plan, one dispatch,
every execution variant (fused / staged / ring / micro-batch) for
free, because the hook point is the shared ``_waterfall_detect`` tail
every plan funnels through.

The result type is a strict SUPERSET of ``DetectResult``: every
single-pulse consumer (``has_signal``, sinks, the journal, the chaos
soak's decision comparison) keeps working unchanged, and
periodicity-aware consumers read the extra candidate fields.  The
extra config knobs are trace-relevant (they shape the program), so
they extend the AOT/shared-plan projection — two streams share a
compiled periodicity plan only when the whole projection agrees, and
a restart with different knobs misses the cache cleanly.

Registered in ``pipeline/registry.py`` (mode "periodicity"), which is
what makes the auditor, the demotion ladder (the ``search_mode`` rung
sheds the mode FIRST on a device fault — the cheapest science to
drop), the chaos soak and the fleet cover it without knowing it
exists.
"""

from __future__ import annotations

import json
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from srtb_tpu.ops import periodicity as P
from srtb_tpu.pipeline.segment import SegmentProcessor


class PeriodicityResult(NamedTuple):
    """``DetectResult`` superset: the single-pulse fields first (same
    names, same shapes — existing consumers index by attribute), then
    the periodicity candidates, all batched over data streams."""

    # ---- single-pulse fields (ops/detect.DetectResult) ----
    zero_count: jnp.ndarray
    time_series: jnp.ndarray
    boxcar_lengths: tuple
    signal_counts: jnp.ndarray
    boxcar_series: jnp.ndarray
    snr_peaks: jnp.ndarray
    # ---- periodicity fields (ops/periodicity.py), per stream ----
    candidate_bins: jnp.ndarray        # [S, K] int32
    candidate_snr: jnp.ndarray         # [S, K] f32 (harmonic-summed)
    candidate_harmonics: jnp.ndarray   # [S, K] int32
    folded_profiles: jnp.ndarray       # [S, K, n_bins] f32
    # static (like boxcar_lengths): (searched bins, harmonic levels)
    # — the trial count the positive gate corrects for (the max of
    # ~exponential per-bin scores over M*L trials sits near
    # ln(M*L), NOT near 0, so an uncorrected sigma threshold fires
    # on pure noise at any realistic series length)
    candidate_trials: tuple = (1, 1)
    # data-quality epilogue side-output (same contract as
    # DetectResult.quality; kept LAST so positional construction of
    # the periodicity fields above stays stable)
    quality: jnp.ndarray | None = None

    # ---- mode hooks consumed by MODE-BLIND shared code: the engine
    # (runtime.has_signal), the candidate writer and the journal all
    # probe for these by name, so the next registered mode brings its
    # own rules by defining them on its result type — no per-mode
    # branches accrete in shared infrastructure (the registry
    # contract).  All three run drain-side on device_get-fetched host
    # data (NamedTuple methods survive the fetch: the tree unflattens
    # back into this class).

    def _host2d(self, x) -> np.ndarray:
        a = np.asarray(x)
        return a.reshape(1, -1) if a.ndim < 2 else a

    def positive_gate(self, cfg) -> np.ndarray:
        """Per-stream positive verdict, TRIALS-corrected: the per-bin
        score is ~exponential under noise, so its maximum over
        (searched bins x harmonic levels) trials concentrates near
        ln(trials) — ``periodicity_snr_threshold`` is the MARGIN
        above that expectation (Gumbel scale ~1 per unit), or every
        noise segment at a realistic series length reads positive."""
        # drain-side, post-fetch  # srtb-lint: disable=sync-hot-path
        snr = self._host2d(self.candidate_snr)
        thr = float(getattr(cfg, "periodicity_snr_threshold", 5.0))
        # static ints riding the result (0-d arrays after a batched
        # fetch)  # srtb-lint: disable=sync-hot-path
        m, levels = (int(np.asarray(t).reshape(-1)[0])
                     for t in self.candidate_trials)
        return (snr >= thr + float(np.log(max(m * levels, 2)))) \
            .any(axis=-1)

    def span_extra(self) -> dict:
        """Journal payload: the candidate table rides every segment's
        span, so the search outcome survives even when the positive
        gate withholds the file dumps."""
        # drain-side host lists  # srtb-lint: disable=sync-hot-path
        snr = self._host2d(self.candidate_snr)
        return {"periodicity": {
            # srtb-lint: disable=sync-hot-path
            "bins": self._host2d(self.candidate_bins).tolist(),
            "snr": [[round(float(x), 3) for x in row] for row in snr],
            # srtb-lint: disable=sync-hot-path
            "harmonics": self._host2d(
                self.candidate_harmonics).tolist()}}

    def extra_artifacts(self, base: str) -> list:
        """``(path, uint8/float payload array)`` pairs the candidate
        writer persists for a positive segment through its usual
        temp+rename(+manifest) machinery: per stream, the folded
        profiles ``<base>[.sN].fold.npy`` ([K, n_bins] f32 — the
        mode's science product) and a ``.cand.json`` candidate table.
        Deterministic bytes (same computation, same rounding, same
        key order), so the replay equality gates cover these files
        like any other."""
        # drain-side, post-fetch  # srtb-lint: disable=sync-hot-path
        prof = np.asarray(self.folded_profiles, dtype=np.float32)
        if prof.ndim == 2:
            prof = prof[None]
        bins = self._host2d(self.candidate_bins)
        snr = self._host2d(np.asarray(self.candidate_snr,
                                      dtype=np.float32))
        harm = self._host2d(self.candidate_harmonics)
        multi = prof.shape[0] > 1
        out = []
        for s in range(prof.shape[0]):
            stem = f"{base}.s{s}" if multi else base
            out.append((f"{stem}.fold.npy", prof[s]))
            meta = {"bins": [int(b) for b in bins[s]],
                    "snr": [round(float(x), 4) for x in snr[s]],
                    "harmonics": [int(h) for h in harm[s]]}
            payload = json.dumps(meta, sort_keys=True).encode() + b"\n"
            out.append((f"{stem}.cand.json",
                        np.frombuffer(payload, np.uint8)))
        return out


class PeriodicitySegmentProcessor(SegmentProcessor):
    """The single-pulse plan + in-trace periodicity search (see module
    docstring).  All the parent's plan machinery — staged boundaries,
    ring carries, micro-batch vmap, AOT lowerables, retirement — is
    inherited: the only override is the detection tail, plus the
    trace projection (mode + knobs) so plan signatures, cache keys and
    plan names honestly distinguish the mode."""

    MODE = "periodicity"

    # the periodicity knobs shape the traced program (harmonic ladder
    # depth, candidate count, fold bins are all static shapes), so
    # they join the AOT/shared-plan projection
    _TRACE_CFG_KEYS = SegmentProcessor._TRACE_CFG_KEYS + (
        "search_mode", "periodicity_harmonics",
        "periodicity_candidates", "periodicity_fold_bins",
        "periodicity_min_bin",
    )

    @property
    def plan_name(self) -> str:
        return super().plan_name + "+period"

    def _waterfall_detect(self, spec: jnp.ndarray):
        """Every plan variant funnels through here (fused tail, legacy
        spectrum tail, staged stage (c)) — append the periodicity
        module to the single-pulse result inside the same trace."""
        wf_ri, det = super()._waterfall_detect(spec)
        cfg = self.cfg
        harmonics = int(getattr(cfg, "periodicity_harmonics", 8) or 1)
        top_k = max(1, int(getattr(cfg, "periodicity_candidates", 4)
                           or 1))
        n_bins = max(2, int(getattr(cfg, "periodicity_fold_bins", 64)
                            or 2))
        min_bin = max(1, int(getattr(cfg, "periodicity_min_bin", 2)
                             or 1))
        cands = jax.vmap(
            lambda ts: P.periodicity_search(ts, harmonics, top_k,
                                            n_bins, min_bin=min_bin)
        )(det.time_series)  # [S, t] -> per-stream candidates
        m = det.time_series.shape[-1] // 2 + 1
        levels = P.harmonic_levels(harmonics)
        return wf_ri, PeriodicityResult(
            # single-pulse fields by position, epilogue fields by name
            # (DetectResult grew an optional quality tail — a bare
            # *det splat would land it on candidate_bins)
            *det[:6],
            candidate_bins=cands.bins,
            candidate_snr=cands.snr,
            candidate_harmonics=cands.harmonics,
            folded_profiles=cands.profiles,
            candidate_trials=(max(m - min_bin, 1), len(levels)),
            quality=det.quality)
