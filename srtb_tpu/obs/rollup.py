"""The fleet aggregator: journals + event dumps -> streaming rollups.

Tails every lane's v11 span journal (the active plaintext arm AND the
rotated ``.1.gz`` / legacy ``.1`` generation) plus flight-recorder
event dumps, and maintains:

- per-minute downsampled series per ``(stream, device, plan)`` —
  segments / samples / detections / dumps, loss DELTAS localized from
  the journal's cumulative counters, device-time and batch occupancy
  sums (``rollup_minute`` rows);
- mergeable quantile digests (obs/digest.py) for the stage wall-clock,
  device-time and batch-size distributions (``rollup_digest`` rows,
  cumulative over the aggregator's lifetime);
- the fleet event timeline — migrations, device halts, device drains
  — as identity-keyed ``fleet_event`` rows (event dumps are full
  rewrites, so rows dedup by identity in the store's last-wins merge
  instead of by offset);
- per-plan per-segment host seconds (the regression watch's sample
  sets, obs/regression.py).

Resume is BY OFFSET like the manifest WAL: a ``cursor.json`` in the
store directory records, per journal, the active arm's byte offset +
a first-line signature (a rotation swaps the file under the same
path — the signature detects it and resets the offset), and, per
ROTATED generation, a content signature + consumed-record count — so
re-reading a generation whose earlier read hit a torn gzip tail
ingests only the records beyond the ones already counted.  Kill the
aggregator at any point and restart it: no span is double-counted.

Schema tolerance: mixed v1–v11 journals summarize, never KeyError —
records simply lack the newer fields and drop out of the rollups that
need them (the same reader contract as tools/telemetry_report.py).
"""

from __future__ import annotations

import gzip
import hashlib
import json
import os
import zlib

from srtb_tpu.obs.digest import QuantileDigest
from srtb_tpu.obs.store import RollupStore

CURSOR_NAME = "cursor.json"
TMP_SUFFIX = ".srtb_tmp"

# fleet events worth a timeline row in the long-horizon store
FLEET_EVENT_TYPES = ("fleet.migrate", "fleet.device_halt",
                     "fleet.device_drain", "fleet.reinit",
                     "fleet.lane_failed", "incident")

# rotated-generation signatures kept in the cursor: bounds the cursor
# file however many rotations a long observation goes through
MAX_GEN_SIGS = 64


def _sig(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()[:16]


def _first_line_sig(path: str) -> str:
    """Signature of the active arm's first line (bounded read): a
    rotation replaces the file under the same path, and the first
    record of the NEW file differs from the old one's — the cursor's
    rotation detector.  "" while the file is empty or its first line
    is still torn (no newline yet)."""
    try:
        with open(path, "rb") as f:
            head = f.read(65536)
    except OSError:
        return ""
    nl = head.find(b"\n")
    if nl < 0:
        return ""
    return _sig(head[:nl])


def _read_gz_records(path: str) -> list[dict]:
    """Span records from a gzipped generation, tolerating a torn tail
    (crash / copy mid-write): the readable prefix parses, the torn
    remainder is dropped — the cursor's consumed count makes a later
    complete re-read ingest only what this read missed."""
    records = []
    try:
        with gzip.open(path, "rt") as f:
            for line in f:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("type") == "segment_span":
                    records.append(rec)
    except (OSError, EOFError, zlib.error):
        pass
    return records


class Aggregator:
    """One aggregation pass-holder over N journals + event dumps,
    writing rollups into a :class:`~srtb_tpu.obs.store.RollupStore`.

    Flushes write SNAPSHOTS of every touched rollup row (identity-
    keyed); the store's last-wins merge makes re-flushing an
    still-open minute safe.  The cursor persists at flush, so a
    restarted aggregator resumes from its offsets; the one documented
    gap: counts ingested after the last flush of a crashed aggregator
    re-ingest on restart (the cursor is the flush boundary), which
    last-wins resolves without double-counting.
    """

    def __init__(self, store: RollupStore, journals=(),
                 events_dumps=(), resolution_s: int = 60,
                 digest_alpha: float = 0.01,
                 max_plan_samples: int = 512):
        if resolution_s <= 0:
            raise ValueError("resolution_s must be positive")
        self.store = store
        self.journals = list(journals)
        self.events_dumps = list(events_dumps)
        self.resolution_s = int(resolution_s)
        self.digest_alpha = float(digest_alpha)
        self.max_plan_samples = max(8, int(max_plan_samples))
        self.cursor_path = os.path.join(store.directory, CURSOR_NAME)
        self._cursor = self._load_cursor()
        # rollup state (cumulative over this aggregator's lifetime)
        self._minutes: dict[str, dict] = {}
        self._digests: dict[tuple, QuantileDigest] = {}
        self._events: dict[str, dict] = {}
        self._plan_samples: dict[str, list] = {}
        self._prev: dict[str, dict] = {}  # per-stream previous record
        self._dirty: set = set()
        self.spans = 0

    @classmethod
    def from_config(cls, cfg, journals=(), events_dumps=()):
        """Build store + aggregator from the Config obs knobs; None
        when ``obs_store_dir`` is unset (the zero-cost-off pattern)."""
        d = str(getattr(cfg, "obs_store_dir", "") or "")
        if not d:
            return None
        store = RollupStore(
            d,
            retention_minutes=int(
                getattr(cfg, "obs_retention_minutes", 0) or 0))
        return cls(
            store, journals=journals, events_dumps=events_dumps,
            resolution_s=int(
                getattr(cfg, "obs_rollup_resolution_s", 60) or 60))

    # ------------------------------------------------------- cursor

    def _load_cursor(self) -> dict:
        try:
            with open(self.cursor_path) as f:
                cur = json.load(f)
            if isinstance(cur, dict):
                cur.setdefault("files", {})
                cur.setdefault("gens", {})
                return cur
        except (OSError, ValueError):
            pass
        return {"files": {}, "gens": {}}

    def _save_cursor(self) -> None:
        gens = self._cursor["gens"]
        if len(gens) > MAX_GEN_SIGS:
            # oldest-inserted first (dict order): drop the surplus
            for sig in list(gens)[:len(gens) - MAX_GEN_SIGS]:
                del gens[sig]
        tmp = self.cursor_path + TMP_SUFFIX
        with open(tmp, "w") as f:
            json.dump(self._cursor, f, sort_keys=True)
        os.replace(tmp, self.cursor_path)

    # ------------------------------------------------------ tailing

    def poll(self) -> dict:
        """One tail pass over every journal + event dump.  Returns
        ``{"spans": n, "events": m}`` newly ingested."""
        spans0, n_events = self.spans, 0
        for path in self.journals:
            self._poll_journal(path)
        for path in self.events_dumps:
            n_events += self._poll_events(path)
        return {"spans": self.spans - spans0, "events": n_events}

    def _poll_journal(self, path: str) -> None:
        from srtb_tpu.utils.telemetry import rotated_generation
        gen = rotated_generation(path)
        if gen:
            self._ingest_generation(gen, active_path=path)
        self._tail_active(path)

    def _ingest_generation(self, gen_path: str,
                           active_path: str = "") -> None:
        """A rotated generation, identified by its FIRST record (the
        same generation read torn then complete hashes identically,
        unlike the raw compressed bytes): consume only records beyond
        the cursor's count for that signature."""
        if gen_path.endswith(".gz"):
            records = _read_gz_records(gen_path)
        else:
            from srtb_tpu.tools.telemetry_report import load as _load
            records = _load(gen_path, include_rotated=False)
        if not records:
            return
        sig = _sig(json.dumps(records[0], sort_keys=True).encode())
        seen = int(self._cursor["gens"].get(sig, 0))
        if sig not in self._cursor["gens"] and active_path:
            # a generation seen for the FIRST time may be the old
            # active arm rotated out from under us: its leading spans
            # were already consumed through the offset tail — hand
            # that count off so they aren't ingested twice
            st = self._cursor["files"].get(active_path) or {}
            if st.get("rec_sig") == sig:
                seen = int(st.get("spans", 0))
        for rec in records[seen:]:
            self._ingest_span(rec)
        self._cursor["gens"][sig] = max(len(records), seen)

    def _tail_active(self, path: str) -> None:
        st = self._cursor["files"].setdefault(
            path, {"offset": 0, "sig": ""})
        sig = _first_line_sig(path)
        if not sig:
            return
        if sig != st.get("sig"):
            # rotation swapped a fresh file under this path (its old
            # contents are now the rotated generation, already
            # signature-tracked) — start over from byte 0
            st["offset"] = 0
            st["sig"] = sig
            st["rec_sig"] = ""
            st["spans"] = 0
        try:
            with open(path, "rb") as f:
                f.seek(st["offset"])
                chunk = f.read()
        except OSError:
            return
        # only complete lines: a torn tail stays for the next poll
        end = chunk.rfind(b"\n")
        if end < 0:
            return
        for raw in chunk[:end].split(b"\n"):
            raw = raw.strip()
            if not raw.startswith(b"{"):
                continue
            try:
                rec = json.loads(raw)
            except ValueError:
                continue
            if rec.get("type") == "segment_span":
                if not st.get("rec_sig"):
                    # canonical first-record signature: the identity
                    # this content will carry once rotated into a
                    # generation (see _ingest_generation's handoff)
                    st["rec_sig"] = _sig(
                        json.dumps(rec, sort_keys=True).encode())
                st["spans"] = int(st.get("spans", 0)) + 1
                self._ingest_span(rec)
        st["offset"] += end + 1

    def _poll_events(self, path: str) -> int:
        """Event dumps are FULL REWRITES (EventHub.dump_jsonl opens
        "w"), so offsets can't resume them; fleet events dedup by
        identity key instead — re-reading a dump re-derives the same
        rows and last-wins collapses them."""
        from srtb_tpu.tools.trace_export import load_events
        try:
            events = load_events(path)
        except OSError:
            return 0
        fresh = 0
        for e in events:
            if e.get("type") not in FLEET_EVENT_TYPES:
                continue
            ts = float(e.get("ts", 0.0))
            k = (f"e:{e.get('t', 0.0):.6f}:{e['type']}:"
                 f"{e.get('stream', '')}:{e.get('info', '')}")
            if k in self._events:
                continue
            fresh += 1
            self._events[k] = {
                "k": k, "type": "fleet_event",
                "minute": int(ts // self.resolution_s),
                "ts": round(ts, 3),
                "kind": e["type"],
                "stream": str(e.get("stream") or ""),
                "seg": int(e.get("seg", -1)),
                "info": str(e.get("info") or ""),
            }
            self._dirty.add(k)
        return fresh

    # ----------------------------------------------------- ingest

    def _ingest_span(self, rec: dict) -> None:
        self.spans += 1
        stream = str(rec.get("stream") or "")
        device = str(rec.get("device") or "")
        plan = str(rec.get("active_plan") or "")
        ts = float(rec.get("ts") or 0.0)
        minute = int(ts // self.resolution_s)
        k = f"m:{minute}:{stream}:{device}:{plan}"
        row = self._minutes.get(k)
        if row is None:
            row = self._minutes[k] = {
                "k": k, "type": "rollup_minute", "minute": minute,
                "t_start": minute * self.resolution_s,
                "stream": stream, "device": device, "plan": plan,
                "segments": 0, "samples": 0, "detections": 0,
                "dumps": 0, "loss_delta": 0,
                "packets_lost_delta": 0, "device_ms_sum": 0.0,
                "batch_segments": 0, "batch_waits_ms": 0.0,
            }
        row["segments"] += 1
        row["samples"] += int(rec.get("samples", 0))
        row["detections"] += int(rec.get("detections", 0))
        row["dumps"] += 1 if rec.get("dump") else 0
        # cumulative counters -> per-minute deltas (the journal's own
        # convention: consecutive-record differences localize a burst)
        prev = self._prev.get(stream)
        if prev is not None:
            for cum, delta in (("segments_dropped", "loss_delta"),
                               ("packets_lost", "packets_lost_delta")):
                a, b = prev.get(cum), rec.get(cum)
                if a is not None and b is not None:
                    row[delta] += max(0, int(b) - int(a))
        self._prev[stream] = rec
        dev_ms = rec.get("device_ms")
        if dev_ms is not None:
            row["device_ms_sum"] = round(
                row["device_ms_sum"] + float(dev_ms), 3)
            self._digest(("device_ms", device)).add(float(dev_ms))
        bs = rec.get("batch_size")
        if bs is not None:
            row["batch_segments"] += int(bs)
            self._digest(("batch_size", "")).add(int(bs))
        bw = rec.get("batch_wait_ms")
        if bw is not None:
            row["batch_waits_ms"] = round(
                row["batch_waits_ms"] + float(bw), 3)
        stage_sum = 0.0
        for name, ms in (rec.get("stages_ms") or {}).items():
            self._digest(("stage", str(name))).add(float(ms))
            stage_sum += float(ms)
        if stage_sum > 0.0:
            self._digest(("stage", "segment")).add(stage_sum)
        if plan and stage_sum > 0.0:
            # the regression watch's sample set: per-segment host
            # seconds per plan (the same quantity perf_gate captures),
            # bounded to the newest max_plan_samples
            samples = self._plan_samples.setdefault(plan, [])
            samples.append(round(stage_sum / 1e3, 6))
            if len(samples) > self.max_plan_samples:
                del samples[:len(samples) - self.max_plan_samples]
        self._dirty.add(k)

    def _digest(self, key: tuple) -> QuantileDigest:
        d = self._digests.get(key)
        if d is None:
            d = self._digests[key] = QuantileDigest(
                alpha=self.digest_alpha)
        return d

    # ------------------------------------------------------ outputs

    def flush(self) -> int:
        """Write snapshots of every dirty minute/event row + ALL
        digest rows (cumulative, identity-keyed — last-wins keeps the
        newest snapshot), then persist the cursor.  Returns rows
        written."""
        rows = []
        for k in sorted(self._dirty):
            row = self._minutes.get(k) or self._events.get(k)
            if row is not None:
                rows.append(row)
        for (kind, label), dig in sorted(self._digests.items()):
            rows.append({
                "k": f"d:{kind}:{label}", "type": "rollup_digest",
                "kind": kind, "label": label,
                "digest": dig.to_dict(),
            })
        n = self.store.append_many(rows)
        self._save_cursor()
        self._dirty.clear()
        return n

    def plans(self) -> list[str]:
        return sorted(self._plan_samples)

    def segment_seconds(self, plan: str) -> list[float]:
        """Per-segment host seconds for ``plan`` (newest
        max_plan_samples) — the regression watch's B side."""
        return list(self._plan_samples.get(plan, []))

    def rollup_median_s(self, plan: str) -> float:
        samples = sorted(self._plan_samples.get(plan, []))
        if not samples:
            return 0.0
        mid = len(samples) // 2
        if len(samples) % 2:
            return samples[mid]
        return (samples[mid - 1] + samples[mid]) / 2.0


def main(argv=None) -> int:
    """Operator CLI: one aggregation pass (or a follow loop) over the
    given journals/event dumps into a rollup store.  Resumable — the
    store's cursor.json makes re-runs ingest only what's new."""
    import argparse
    import time
    p = argparse.ArgumentParser(
        description="aggregate lane journals into a fleet rollup store")
    p.add_argument("journals", nargs="+",
                   help="v11 span journal paths (one per lane)")
    p.add_argument("--store", required=True,
                   help="rollup store directory (cursor lives here)")
    p.add_argument("--events", action="append", default=[],
                   help="event dump path (repeatable)")
    p.add_argument("--retention-minutes", type=int, default=0)
    p.add_argument("--resolution-s", type=int, default=60)
    p.add_argument("--follow", type=float, default=0.0, metavar="S",
                   help="poll every S seconds until interrupted "
                        "(0 = one pass)")
    p.add_argument("--compact", action="store_true",
                   help="compact the store after aggregating")
    args = p.parse_args(argv)
    store = RollupStore(args.store,
                        retention_minutes=args.retention_minutes)
    agg = Aggregator(store, journals=args.journals,
                     events_dumps=args.events,
                     resolution_s=args.resolution_s)
    spans = events = rows = 0
    try:
        while True:
            got = agg.poll()
            spans += got["spans"]
            events += got["events"]
            rows += agg.flush()
            if not args.follow:
                break
            time.sleep(args.follow)
    except KeyboardInterrupt:
        pass
    out = {"spans": spans, "events": events, "rows": rows,
           "plans": agg.plans(), "store": args.store}
    if args.compact:
        out["compact"] = store.compact()
    print(json.dumps(out, sort_keys=True))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
