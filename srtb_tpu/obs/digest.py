"""Mergeable quantile digest with a guaranteed relative error.

The control tower needs distributions (stage wall clock, device time,
batch size) that MERGE — across lanes, across devices, across
aggregator flushes — which rules out both raw sample lists (unbounded)
and the registry's fixed-bucket histograms (bucket edges tuned for
host stage times, useless for batch sizes; merging two histograms with
different edges is lossy in uncontrolled ways).

:class:`QuantileDigest` is a DDSketch-style sketch: geometric buckets
with relative accuracy ``alpha`` (bucket ``i`` covers
``(gamma^(i-1), gamma^i]`` with ``gamma = (1+alpha)/(1-alpha)``), a
sparse dict of non-empty buckets, and an exact zero/min/max/sum/count
sidecar.  Properties the tests pin:

- **accuracy**: any quantile estimate is within ``alpha`` RELATIVE
  error of some sample at that rank — for positive values,
  ``|est - exact| / exact <= alpha`` (the tests check against exact
  numpy percentiles on seeded data, with a one-order-statistic slack
  for interpolation-convention differences);
- **mergeable**: ``merge`` is bucket-wise addition — digesting a
  stream in three parts then merging equals digesting it whole,
  exactly (same buckets, same counts);
- **serializable**: ``to_dict``/``from_dict`` round-trip through the
  rollup store's canonical JSON without drift (integer bucket keys as
  strings, counts as ints).

Memory is O(log(max/min) / alpha) buckets — ~1.4k buckets span
nanoseconds to hours at the default 1% accuracy, and real stage-time
distributions touch a few dozen.
"""

from __future__ import annotations

import math

DEFAULT_ALPHA = 0.01

# values below this are counted in the exact zero bucket: stage times
# and batch sizes are never meaningfully sub-nanosecond, and a
# geometric sketch cannot bucket 0 (log(0))
MIN_TRACKABLE = 1e-9


class QuantileDigest:
    """Sparse DDSketch-style quantile sketch for non-negative values."""

    __slots__ = ("alpha", "gamma", "_log_gamma", "buckets", "zeros",
                 "count", "sum", "min", "max")

    def __init__(self, alpha: float = DEFAULT_ALPHA):
        if not 0.0 < alpha < 1.0:
            raise ValueError("alpha must be in (0, 1)")
        self.alpha = float(alpha)
        self.gamma = (1.0 + self.alpha) / (1.0 - self.alpha)
        self._log_gamma = math.log(self.gamma)
        self.buckets: dict[int, int] = {}
        self.zeros = 0
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    # ------------------------------------------------------- updates

    def add(self, value: float, n: int = 1) -> None:
        """Record ``value`` ``n`` times.  Negative / non-finite values
        raise — every digested quantity here (milliseconds, batch
        sizes) is non-negative by construction, and silently clamping
        would hide a producer bug."""
        v = float(value)
        n = int(n)
        if n <= 0:
            return
        if not math.isfinite(v) or v < 0.0:
            raise ValueError(f"digest values must be finite and >= 0, "
                             f"got {value!r}")
        self.count += n
        self.sum += v * n
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        if v < MIN_TRACKABLE:
            self.zeros += n
            return
        i = math.ceil(math.log(v) / self._log_gamma)
        self.buckets[i] = self.buckets.get(i, 0) + n

    def merge(self, other: "QuantileDigest") -> None:
        """Bucket-wise addition; digests must share ``alpha`` (merging
        across accuracies would silently degrade the bound)."""
        if abs(other.alpha - self.alpha) > 1e-12:
            raise ValueError(
                f"cannot merge digests with different alpha "
                f"({self.alpha} vs {other.alpha})")
        for i, c in other.buckets.items():
            self.buckets[i] = self.buckets.get(i, 0) + c
        self.zeros += other.zeros
        self.count += other.count
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)

    # ------------------------------------------------------- queries

    def quantile(self, q: float) -> float:
        """The q-quantile estimate (q in [0, 1]); NaN when empty.
        Estimates clamp to the exact [min, max] envelope, so q=0 / q=1
        are exact and no estimate can leave the observed range."""
        if self.count == 0:
            return math.nan
        q = min(1.0, max(0.0, float(q)))
        if q <= 0.0:
            return self.min
        if q >= 1.0:
            return self.max
        # 1-based target rank; walk zero bucket then geometric buckets
        rank = max(1, math.ceil(q * self.count))
        if rank <= self.zeros:
            return 0.0
        cum = self.zeros
        for i in sorted(self.buckets):
            cum += self.buckets[i]
            if cum >= rank:
                # bucket midpoint 2*gamma^i/(gamma+1): within alpha
                # relative of every value in (gamma^(i-1), gamma^i]
                est = 2.0 * self.gamma ** i / (self.gamma + 1.0)
                return min(self.max, max(self.min, est))
        return self.max

    def percentiles(self) -> dict:
        return {"p50": self.quantile(0.50), "p95": self.quantile(0.95),
                "p99": self.quantile(0.99)}

    # -------------------------------------------------- serialization

    def to_dict(self) -> dict:
        """Canonical JSON-able form (bucket keys as strings, sorted by
        json.dumps(sort_keys=True) downstream — the store's
        byte-identical compaction depends on this being stable)."""
        out = {
            "alpha": self.alpha,
            "count": int(self.count),
            "zeros": int(self.zeros),
            "sum": round(self.sum, 6),
            "b": {str(i): int(c)
                  for i, c in sorted(self.buckets.items())},
        }
        if self.count:
            out["min"] = self.min
            out["max"] = self.max
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "QuantileDigest":
        dig = cls(alpha=float(d.get("alpha", DEFAULT_ALPHA)))
        dig.count = int(d.get("count", 0))
        dig.zeros = int(d.get("zeros", 0))
        dig.sum = float(d.get("sum", 0.0))
        dig.buckets = {int(i): int(c)
                       for i, c in (d.get("b") or {}).items()}
        if dig.count:
            dig.min = float(d.get("min", 0.0))
            dig.max = float(d.get("max", 0.0))
        return dig
