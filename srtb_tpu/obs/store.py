"""Long-horizon rollup store: append-only JSONL + idempotent compaction.

The aggregator (obs/rollup.py) needs somewhere durable for its rollups
that (a) appends cheaply while a fleet is live, (b) survives any crash
with at worst a torn last line, (c) bounds disk via retention, and
(d) compacts DETERMINISTICALLY — the same rows in always produce the
same bytes out, so re-running compaction is a no-op and CI can assert
byte-identity instead of trusting a "compacted" flag.

Layout (one directory per store)::

    <dir>/active.jsonl        the append arm (one JSON object per line)
    <dir>/segments/seg*.jsonl compacted history, one file per
                              ``segment_minutes`` bucket of rollup
                              minutes (plus seg-meta.jsonl for
                              minute-less rows like cumulative digests)
    <dir>/cursor.json         the aggregator's resume cursor (owned by
                              obs/rollup.py, not this class)

Every row carries ``k`` — its identity key.  Appends are snapshots,
not deltas: a later row with the same ``k`` SUPERSEDES the earlier one
(last-wins), which is what makes re-flushing a still-open rollup
minute safe and compaction idempotent — duplicates collapse instead of
double-counting.

Compaction: read everything (segments oldest-first, then active),
last-wins by ``k``, drop rows whose ``minute`` is older than
``retention_minutes`` behind the NEWEST minute present (relative to
the data, not the wall clock — deterministic and testable), group by
minute bucket, write each bucket sorted by ``k`` as canonical JSON via
temp + rename, remove buckets retention emptied, truncate the active
arm.  Running it twice produces byte-identical files — the test
re-runs it and compares bytes.
"""

from __future__ import annotations

import json
import os

ACTIVE_NAME = "active.jsonl"
SEGMENT_DIR = "segments"
META_SEGMENT = "seg-meta.jsonl"
TMP_SUFFIX = ".srtb_tmp"  # matches the repo's atomic-rename convention


def _parse_lines(path: str) -> list[dict]:
    """Tolerant JSONL read: foreign lines and a torn tail (a crash
    mid-append) yield their readable prefix, never an exception."""
    rows = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if isinstance(row, dict) and "k" in row:
                    rows.append(row)
    except OSError:
        pass
    return rows


class RollupStore:
    """One rollup-store directory (see module docstring)."""

    def __init__(self, directory: str, retention_minutes: int = 0,
                 segment_minutes: int = 60):
        if segment_minutes <= 0:
            raise ValueError("segment_minutes must be positive")
        self.directory = directory
        self.retention_minutes = max(0, int(retention_minutes))
        self.segment_minutes = int(segment_minutes)
        self.active_path = os.path.join(directory, ACTIVE_NAME)
        self.segment_dir = os.path.join(directory, SEGMENT_DIR)
        os.makedirs(self.segment_dir, exist_ok=True)
        # sweep torn temp files from a crashed compaction (the rename
        # never happened, so the previous generation is still whole)
        for name in os.listdir(self.segment_dir):
            if name.endswith(TMP_SUFFIX):
                try:
                    os.unlink(os.path.join(self.segment_dir, name))
                except OSError:
                    pass

    # ------------------------------------------------------- appends

    def append(self, row: dict) -> None:
        self.append_many([row])

    def append_many(self, rows) -> int:
        """Append row snapshots to the active arm.  Rows must carry
        ``k`` (identity) — last-wins dedup is the store's whole
        consistency model, so an unkeyed row is a programming error."""
        lines = []
        for row in rows:
            if "k" not in row:
                raise ValueError(f"store row without identity key: "
                                 f"{row!r}")
            lines.append(json.dumps(row, sort_keys=True) + "\n")
        if not lines:
            return 0
        with open(self.active_path, "a") as f:
            f.writelines(lines)
        return len(lines)

    # --------------------------------------------------------- reads

    def _segment_files(self) -> list[str]:
        try:
            names = sorted(os.listdir(self.segment_dir))
        except OSError:
            names = []
        return [os.path.join(self.segment_dir, n) for n in names
                if n.endswith(".jsonl")]

    def rows(self) -> list[dict]:
        """Every row, compacted history first (oldest segment file
        first), then the active arm — so iterating in order and
        applying last-wins by ``k`` yields the current state."""
        out = []
        for p in self._segment_files():
            out.extend(_parse_lines(p))
        out.extend(_parse_lines(self.active_path))
        return out

    def latest(self) -> dict:
        """Current state: identity key -> winning row."""
        state: dict[str, dict] = {}
        for row in self.rows():
            state[row["k"]] = row
        return state

    # ---------------------------------------------------- compaction

    def compact(self) -> dict:
        """Merge + retain + rewrite (see module docstring).  Returns
        ``{"rows": kept, "dropped": retention_drops, "segments":
        file_count}``.  Deterministic: running it again with no new
        appends rewrites byte-identical files."""
        state = self.latest()
        minutes = [int(r["minute"]) for r in state.values()
                   if isinstance(r.get("minute"), int)
                   and r["minute"] >= 0]
        dropped = 0
        if self.retention_minutes and minutes:
            cutoff = max(minutes) - self.retention_minutes
            doomed = [k for k, r in state.items()
                      if isinstance(r.get("minute"), int)
                      and 0 <= r["minute"] < cutoff]
            for k in doomed:
                del state[k]
            dropped = len(doomed)
        buckets: dict[str, list] = {}
        for k in sorted(state):
            row = state[k]
            minute = row.get("minute")
            if isinstance(minute, int) and minute >= 0:
                start = minute - minute % self.segment_minutes
                name = f"seg{start:012d}.jsonl"
            else:
                name = META_SEGMENT
            buckets.setdefault(name, []).append(row)
        want = set(buckets)
        for name, rows in buckets.items():
            final = os.path.join(self.segment_dir, name)
            tmp = final + TMP_SUFFIX
            with open(tmp, "w") as f:
                for row in rows:
                    f.write(json.dumps(row, sort_keys=True) + "\n")
            os.replace(tmp, final)
        # buckets a previous compaction wrote that retention (or a
        # key-space change) emptied must not linger as phantom history
        for p in self._segment_files():
            if os.path.basename(p) not in want:
                try:
                    os.unlink(p)
                except OSError:
                    pass
        # truncate the active arm LAST: a crash before this point
        # leaves duplicates that the next compaction's last-wins merge
        # collapses — never lost rows
        with open(self.active_path, "w"):
            pass
        return {"rows": len(state), "dropped": dropped,
                "segments": len(buckets)}
