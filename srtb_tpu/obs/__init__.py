"""Fleet control tower: cross-device telemetry aggregation.

Every observability surface below this package is per-process and
per-lane — v11 span journals (utils/telemetry.py), event rings
(utils/events.py), /metrics (utils/metrics.py), the perf ledger
(utils/perf_ledger.py) each tell one lane's story.  This package is
the monitoring plane OVER them, the "one view over composed modules"
the FPGA pulsar-search stacks imply (PAPERS.md):

- :mod:`~srtb_tpu.obs.digest` — mergeable quantile digests
  (DDSketch-style relative-accuracy buckets) so distributions from
  many lanes/devices/runs merge without raw samples;
- :mod:`~srtb_tpu.obs.store` — the long-horizon rollup store:
  append-only JSONL segments with retention + idempotent compaction;
- :mod:`~srtb_tpu.obs.rollup` — the aggregator that tails journals
  (plaintext + rotated .gz) and event dumps, resumable by offset like
  the manifest WAL, and maintains the streaming rollups;
- :mod:`~srtb_tpu.obs.trace_join` — the cross-device Perfetto export:
  one trace with a process-track per pool member, where a migrated
  stream's flow arrows cross device tracks;
- :mod:`~srtb_tpu.obs.regression` — the mid-run regression watch:
  rollup medians through perf_stats.compare() against the perf
  ledger's history, escalating an incident bundle on a confirmed
  throughput regression;
- :mod:`~srtb_tpu.obs.status` — the ``/fleet`` payload
  (gui/server.py) and the data behind ``tools/console.py``.
"""

from __future__ import annotations

__all__ = ["digest", "store", "rollup", "trace_join", "regression",
           "status"]
