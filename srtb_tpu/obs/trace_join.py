"""Cross-device trace join: one Perfetto timeline for a whole fleet.

tools/trace_export.py renders one flight-recorder dump with a trace
*process* per stream — the right cut for a single host, but a fleet
question ("what did device 1 look like around the migration?") wants
the DEVICE cut: one trace process per pool member, each stream's
stages parked on whichever device executed that segment, so a
migrated stream's flow arrows visibly JUMP from one device's process
track to the other's at the migration boundary.

The join needs two sources, because neither alone knows the mapping:

- the event dumps carry per-segment stage timings + thread identity
  but no device (events are emitted host-side);
- the v11 span journals carry ``device`` per (stream, segment) — the
  pool member that executed it, switching exactly at the migration
  boundary.

So: build ``(stream, segment) -> device`` from every lane's journal
(mixed v1–v10 records simply lack ``device`` and fall through to the
host track), then re-render the merged event streams with device
process-tracks.  Three kinds of arrows come out:

- per-``trace_id`` segment chains (same as trace_export) — now
  crossing device tracks when a segment's stages split host/device;
- per-stream LANE chains over the device-mapped dispatch slices
  (flow ids from 10^9 up, clear of trace ids): THE migration
  visual — one arrow per consecutive dispatch pair, crossing process
  tracks at the boundary segment;
- fleet control events (migrate / drain / halt) as instants on the
  involved device's track, so the cause sits next to the effect.

The output passes the exact same :func:`trace_export.validate`
structural gate as the single-host exporter — CI asserts that, plus
that some stream's ``stream_devices`` spans >= 2 devices after a
migration soak.

Usage::

    python -m srtb_tpu.obs.trace_join EVENTS.jsonl... \
        --journals J1.jsonl J2.jsonl [--out OUT.json] [--validate]
"""

from __future__ import annotations

import argparse
import json
import sys

from srtb_tpu.tools.trace_export import (STAGE_TYPES, load_events,
                                         validate)

HOST_TRACK = "host"          # events with no device mapping
LANE_FLOW_BASE = 1_000_000_000  # lane-chain ids, clear of trace ids


def device_map(journal_paths) -> dict:
    """``(stream, segment) -> device`` from v11 span journals
    (rotated generations included).  Pre-v11 records carry no
    ``device`` and contribute nothing — the reader contract."""
    from srtb_tpu.tools.telemetry_report import load
    mapping: dict[tuple, str] = {}
    for path in journal_paths:
        try:
            records = load(path)
        except OSError:
            continue
        for rec in records:
            dev = rec.get("device")
            seg = rec.get("segment")
            if dev and seg is not None:
                mapping[(str(rec.get("stream") or ""), int(seg))] = \
                    str(dev)
    return mapping


def render(events: list[dict], mapping: dict) -> dict:
    """Merged events + device map -> Chrome-trace document with one
    process per device (plus ``host`` for unmapped events)."""
    if not events:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t0 = min(e["t"] for e in events)

    def us(t: float) -> float:
        return round((t - t0) * 1e6, 3)

    pids: dict[str, int] = {}
    tids: dict[tuple, int] = {}
    out: list[dict] = []

    def pid_of(track: str) -> int:
        if track not in pids:
            pids[track] = len(pids) + 1
            name = track if track == HOST_TRACK else f"device:{track}"
            out.append({"name": "process_name", "ph": "M",
                        "pid": pids[track], "tid": 0,
                        "args": {"name": name}})
        return pids[track]

    def tid_of(pid: int, lane: str) -> int:
        key = (pid, lane)
        if key not in tids:
            tids[key] = sum(1 for (p, _t) in tids if p == pid) + 1
            out.append({"name": "thread_name", "ph": "M", "pid": pid,
                        "tid": tids[key], "args": {"name": lane}})
        return tids[key]

    # devices in first-appearance order per stream: the migration
    # assertion ("some stream touched >= 2 devices") reads this
    stream_devices: dict[str, list] = {}
    trace_points: dict[int, list] = {}
    lane_points: dict[str, list] = {}

    for e in events:
        stream = str(e.get("stream") or "")
        thread = str(e.get("thread") or "?")
        seg = e.get("seg")
        etype = e["type"]
        device = mapping.get((stream, int(seg))) \
            if seg is not None and seg >= 0 else None
        if device is None and etype.startswith("fleet."):
            # control events name their device in info ("dev0->dev1"
            # for migrate, the member label for halt/drain) — park
            # them on the destination device's track
            info = str(e.get("info") or "")
            tail = info.rsplit("->", 1)[-1].strip()
            if tail in pids or any(tail == d for devs
                                   in stream_devices.values()
                                   for d in devs) \
                    or tail in set(mapping.values()):
                device = tail
        track = device or HOST_TRACK
        pid = pid_of(track)
        lane = f"{stream or 'pipeline'}:{thread}"
        tid = tid_of(pid, lane)
        if device and stream:
            devs = stream_devices.setdefault(stream, [])
            if not devs or devs[-1] != device:
                devs.append(device)
        trace = int(e.get("trace") or 0)
        args = {"trace_id": trace, "segment": e.get("seg", -1),
                "stream": stream or "pipeline"}
        if e.get("info"):
            args["info"] = e["info"]
        if etype in STAGE_TYPES:
            dur_us = max(float(e.get("dur_ms") or 0.0) * 1e3, 0.001)
            start = us(e["t"]) - dur_us  # emitted at stage END
            out.append({"name": etype.split(".", 1)[1], "cat": "stage",
                        "ph": "X", "ts": round(start, 3),
                        "dur": round(dur_us, 3), "pid": pid,
                        "tid": tid, "args": args})
            mid = us(e["t"]) - dur_us / 2
            if trace > 0:
                trace_points.setdefault(trace, []).append(
                    (mid, pid, tid))
            if etype == "stage.dispatch" and device and stream:
                lane_points.setdefault(stream, []).append(
                    (mid, pid, tid))
        else:
            out.append({"name": etype, "cat": "event", "ph": "i",
                        "s": "t", "ts": us(e["t"]), "pid": pid,
                        "tid": tid, "args": args})

    def chain(points: list, fid: int, name: str) -> None:
        if len(points) < 2:
            return
        points.sort()
        for i, (ts, pid, tid) in enumerate(points):
            ph = "s" if i == 0 else ("f" if i == len(points) - 1
                                     else "t")
            ev = {"name": name, "cat": "flow", "ph": ph, "id": fid,
                  "ts": round(ts, 3), "pid": pid, "tid": tid}
            if ph == "f":
                ev["bp"] = "e"
            out.append(ev)

    for trace, points in sorted(trace_points.items()):
        chain(points, trace, "segment")
    for i, stream in enumerate(sorted(lane_points)):
        # the migration arrows: consecutive device-mapped dispatches
        # of one stream, crossing process tracks at the boundary
        chain(lane_points[stream], LANE_FLOW_BASE + i,
              f"lane:{stream}")

    return {"traceEvents": out, "displayTimeUnit": "ms",
            "otherData": {"source": "srtb_tpu fleet trace join",
                          "devices": sorted(p for p in pids
                                            if p != HOST_TRACK),
                          "stream_devices": stream_devices}}


def join(events_paths, journal_paths) -> dict:
    """Load + merge event dumps, build the device map, render."""
    events: list[dict] = []
    for p in events_paths:
        events.extend(load_events(p))
    events.sort(key=lambda e: e["t"])
    return render(events, device_map(journal_paths))


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("events", nargs="+",
                   help="events JSONL dump(s) / incident bundle "
                        "dir(s)")
    p.add_argument("--journals", nargs="*", default=[],
                   help="v11 span journals supplying the "
                        "(stream, segment) -> device map")
    p.add_argument("--out", default="",
                   help="output path (default: fleet_trace.json)")
    p.add_argument("--validate", action="store_true",
                   help="schema-check only; exit 1 on problems")
    args = p.parse_args(argv)
    doc = join(args.events, args.journals)
    if not doc["traceEvents"]:
        print(json.dumps({"error": "no events"}), file=sys.stderr)
        return 1
    problems = validate(doc)
    if problems:
        for msg in problems:
            print(f"INVALID: {msg}", file=sys.stderr)
        return 1
    if args.validate:
        print(f"valid fleet trace: {len(doc['traceEvents'])} events, "
              f"devices={doc['otherData']['devices']}, "
              f"stream_devices="
              f"{json.dumps(doc['otherData']['stream_devices'])}")
        return 0
    out = args.out or "fleet_trace.json"
    with open(out, "w") as f:
        json.dump(doc, f)
    print(f"wrote {out}: {len(doc['traceEvents'])} trace events "
          f"across {len(doc['otherData']['devices'])} device "
          f"track(s)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
