"""Fleet status: one structured snapshot for the operator console.

:func:`fleet_status` assembles everything an operator scans during a
run — pool member states, per-stream SLO burn, roofline gauges, batch
occupancy, the migration timeline, drift alerts — into ONE dict, from
two sources:

- the live metrics registry + SLO tracker (in-process state: gauges
  the fleet publishes as it runs);
- optionally a rollup store directory (obs/store.py): recent
  per-minute rollups, the fleet event timeline, and the stage/device
  quantile digests the aggregator persisted — this is what makes the
  console work OUT of process (``tools/console.py --store DIR``
  against a store another host's aggregator wrote).

Consumers: ``gui/server.py``'s ``/fleet`` endpoint (JSON over HTTP)
and ``tools/console.py`` (rendered text).  Everything here is
read-only and allocation-light — safe to call from a request handler
mid-run.
"""

from __future__ import annotations

RECENT_MINUTES = 16      # rollup minutes surfaced to the console
RECENT_EVENTS = 32       # migration-timeline tail length


def _device_states() -> dict:
    """label -> decoded pool state from the fleet_device_state gauge
    (the pool publishes codes; decode them here so every consumer
    doesn't)."""
    from srtb_tpu.pipeline.pool import _STATE_CODE
    from srtb_tpu.utils.metrics import metrics
    code_name = {v: k for k, v in _STATE_CODE.items()}
    return {dev: code_name.get(int(code), f"code{int(code)}")
            for dev, code in
            metrics.by_label("fleet_device_state",
                             label="device").items()}


def fleet_status(store_dir: str = "") -> dict:
    """The control-tower snapshot (see module docstring)."""
    from srtb_tpu.utils import slo
    from srtb_tpu.utils.metrics import metrics

    states = _device_states()
    lanes = metrics.by_label("fleet_device_lanes", label="device")
    drains = metrics.by_label("device_drains", label="device")
    dev_migrations = metrics.by_label("migrations", label="device")
    devices = {}
    for dev in sorted(set(states) | set(lanes)):
        devices[dev] = {
            "state": states.get(dev, "unknown"),
            "lanes": int(lanes.get(dev, 0)),
            "drains": int(drains.get(dev, 0)),
            "migrations": int(dev_migrations.get(dev, 0)),
        }

    streams = {}
    per_stream = {
        "roofline_frac": metrics.by_label("roofline_frac"),
        "achieved_msamps": metrics.by_label("achieved_msamps"),
        "achieved_gbps": metrics.by_label("achieved_gbps"),
        "segments": metrics.by_label("segments"),
        "dropped": metrics.by_label("segments_dropped"),
        "signals": metrics.by_label("signals"),
        "migrations": metrics.by_label("migrations"),
        "drift_score": metrics.by_label("quality_drift_score"),
    }
    for key, by in per_stream.items():
        for stream, val in by.items():
            streams.setdefault(stream, {})[key] = (
                round(float(val), 4) if key.startswith(
                    ("roofline", "achieved", "drift"))
                else int(val))

    dispatches = metrics.get("batched_dispatches")
    segments = metrics.get("batched_segments")
    out = {
        "devices": devices,
        "pool": {
            "members": len(devices),
            "migrations": int(metrics.get("migrations")),
            "device_drains": int(metrics.get("device_drains")),
            "device_reinits": int(metrics.get("device_reinits")),
        },
        "streams": streams,
        "slo": slo.evaluate() or {},
        "roofline": {
            "frac": round(metrics.get("roofline_frac"), 4),
            "msamps": round(metrics.get("achieved_msamps"), 2),
            "gbps": round(metrics.get("achieved_gbps"), 3),
        },
        "batch": {
            "dispatches": int(dispatches),
            "segments": int(segments),
            # mean segments per device dispatch — THE continuous-
            # batching health number (1.0 = batching idle)
            "occupancy": round(segments / dispatches, 3)
            if dispatches else 0.0,
        },
        "drift": {
            "score": round(metrics.get("quality_drift_score"), 4),
            "alerts": int(metrics.get("quality_drift_alerts")),
        },
    }
    if store_dir:
        out["store"] = _store_section(store_dir)
    return out


def _store_section(store_dir: str) -> dict:
    """Rollup-store tail: recent minutes, the fleet event timeline,
    digest percentiles.  Tolerates a missing/empty store (the console
    may start before the aggregator's first flush)."""
    from srtb_tpu.obs.digest import QuantileDigest
    from srtb_tpu.obs.store import RollupStore
    try:
        state = RollupStore(store_dir).latest()
    except OSError:
        return {"error": f"unreadable store {store_dir}"}
    minutes, events, digests = [], [], {}
    for row in state.values():
        t = row.get("type")
        if t == "rollup_minute":
            minutes.append(row)
        elif t == "fleet_event":
            events.append(row)
        elif t == "rollup_digest":
            try:
                dig = QuantileDigest.from_dict(row.get("digest") or {})
            except (TypeError, ValueError):
                continue
            pcts = {k: round(v, 4)
                    for k, v in dig.percentiles().items()
                    if v == v}  # drop NaN (empty digest)
            pcts["n"] = dig.count
            digests[f"{row.get('kind')}:{row.get('label')}"] = pcts
    minutes.sort(key=lambda r: (r.get("minute", 0), r.get("k", "")))
    events.sort(key=lambda r: r.get("ts", 0.0))
    return {
        "rows": len(state),
        "minutes": minutes[-RECENT_MINUTES:],
        "timeline": events[-RECENT_EVENTS:],
        "digests": dict(sorted(digests.items())),
    }
