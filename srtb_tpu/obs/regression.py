"""Mid-run regression watch: rollups vs the perf ledger's history.

tools/perf_gate.py judges a finished capture against a checked-in
baseline — a CI-time verdict.  The control tower wants the same
statistics DURING a run: the aggregator (obs/rollup.py) already
accumulates per-segment host seconds per plan from the live journals,
and the perf ledger already holds this host's history for the same
``(plan, shape, host_fp)`` key — so every watch tick is one
:func:`perf_stats.compare` call, no extra benchmarking.

Escalation is an incident bundle (utils/incidents.py) of kind
``throughput_regression`` carrying the full statistical verdict, plus
an ``obs.regression`` flight-recorder event.  Two rules keep it from
crying wolf:

- the verdict must CONFIRM — Mann-Whitney significance AND the
  bootstrap CI clear of the computed noise floor, the same
  triple-agreement perf_gate requires;
- one bundle per plan per watch lifetime (the latch): a sustained
  regression is one incident, not one per poll tick.

``--selftest`` proves both directions end to end through the REAL
path (mini pipeline -> journal -> aggregator rollup -> ledger history
-> verdict): an injected ``dispatch:stall`` fault plan must trip
exactly one bundle, and a clean leg against the same baseline must
trip zero.

Usage::

    python -m srtb_tpu.obs.regression --selftest
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

from srtb_tpu.utils import perf_ledger as PL
from srtb_tpu.utils import perf_stats as PS

INCIDENT_KIND = "throughput_regression"


class RegressionWatch:
    """Compare live per-plan samples against ledger history; escalate
    at most one incident bundle per plan."""

    def __init__(self, ledger_path: str, incident_dir: str = "",
                 host_fp: str | None = None, alpha: float = 0.05,
                 min_effect: float = 0.0, min_samples: int = 8):
        self.ledger_path = ledger_path
        self.incident_dir = incident_dir
        # None = "this host" (the only raw-comparable history);
        # pass "" to disable the host filter (tests, imported data)
        self.host_fp = PL.host_fingerprint() if host_fp is None \
            else (host_fp or None)
        self.alpha = float(alpha)
        self.min_effect = float(min_effect)
        self.min_samples = max(2, int(min_samples))
        self._escalated: set[str] = set()
        self._recorder = None

    @classmethod
    def from_config(cls, cfg):
        ledger = str(getattr(cfg, "perf_ledger_path", "") or "")
        if not ledger:
            return None
        return cls(
            ledger,
            incident_dir=str(getattr(cfg, "incident_dir", "") or ""),
            min_effect=float(
                getattr(cfg, "obs_regression_min_effect", 0.0) or 0.0),
            min_samples=int(
                getattr(cfg, "obs_regression_min_samples", 8) or 8))

    def check(self, plan: str, samples_s, shape: dict | None = None,
              stream: str = "") -> dict:
        """One watch tick.  Returns the verdict dict; ``checked`` is
        False when either side lacks ``min_samples`` (a thin rollup or
        an unseen plan is not evidence of anything)."""
        samples = [float(s) for s in samples_s]
        if len(samples) < self.min_samples:
            return {"checked": False, "plan": plan,
                    "reason": f"only {len(samples)} live samples "
                              f"(< {self.min_samples})"}
        baseline = PL.history(PL.load(self.ledger_path), plan,
                              host_fp=self.host_fp, shape=shape)
        if len(baseline) < self.min_samples:
            return {"checked": False, "plan": plan,
                    "reason": f"only {len(baseline)} ledger samples "
                              f"for ({plan!r}, host="
                              f"{self.host_fp or 'any'})"}
        verdict = PS.compare(baseline, samples, alpha=self.alpha,
                             min_effect=self.min_effect)
        verdict.update(checked=True, plan=plan,
                       n_baseline=len(baseline), n_live=len(samples))
        if verdict["regression"]:
            verdict["escalated"] = self._escalate(plan, verdict,
                                                  stream=stream)
        return verdict

    def _escalate(self, plan: str, verdict: dict,
                  stream: str = "") -> bool:
        """One bundle per plan per watch lifetime (the latch)."""
        from srtb_tpu.utils import events
        if plan in self._escalated:
            return False
        self._escalated.add(plan)
        events.emit("obs.regression", stream=stream,
                    info=f"plan={plan} effect={verdict['effect']:+.3f}"
                         f" p={verdict['p']:.4f}")
        if not self.incident_dir:
            return True
        if self._recorder is None:
            from srtb_tpu.utils.incidents import IncidentRecorder
            self._recorder = IncidentRecorder(self.incident_dir)
        bundle = self._recorder.dump(
            INCIDENT_KIND,
            reason=(f"rollup medians for plan {plan!r} regressed "
                    f"{verdict['effect']:+.1%} vs ledger history "
                    f"(p={verdict['p']:.4f}, floor="
                    f"{verdict['noise_floor']:.3f})"),
            stream=stream, extra={"verdict": verdict})
        return bundle is not None


# --------------------------------------------------------- selftest

def _bundles(directory: str) -> list[str]:
    try:
        return sorted(n for n in os.listdir(directory)
                      if os.path.isdir(os.path.join(directory, n)))
    except OSError:
        return []


def _leg(tmp: str, segments: int, warmup: int, log2n: int,
         channels: int, fault_plan: str = ""):
    """One mini pipeline run whose journal is aggregated through the
    REAL rollup path; returns (plan, measured per-segment seconds).
    Reuses perf_gate's mini config so the injected stall travels the
    same guarded dispatch path the gate selftest proves out."""
    from srtb_tpu.io.synth import make_dispersed_baseband
    from srtb_tpu.obs.rollup import Aggregator
    from srtb_tpu.obs.store import RollupStore
    from srtb_tpu.pipeline.runtime import Pipeline
    from srtb_tpu.tools.perf_gate import _mini_cfg
    from srtb_tpu.utils.metrics import metrics

    n = 1 << log2n
    total = segments + warmup
    os.makedirs(tmp, exist_ok=True)
    cfg = _mini_cfg(tmp, n, channels, fault_plan=fault_plan)
    make_dispersed_baseband(
        n * total, 1405.0, 64.0, 0.0, pulse_positions=n // 2,
        nbits=8).tofile(cfg.input_file_path)
    metrics.reset()
    with Pipeline(cfg, sinks=[]) as pipe:
        stats = pipe.run()
        plan = getattr(pipe.processor, "plan_name", "")
    if stats.segments != total:
        raise RuntimeError(f"leg expected {total} segments, drained "
                           f"{stats.segments}")
    agg = Aggregator(RollupStore(os.path.join(tmp, "store")),
                     journals=[cfg.telemetry_journal_path])
    agg.poll()
    agg.flush()
    samples = agg.segment_seconds(plan)
    if len(samples) < total:
        raise RuntimeError(f"rollup saw {len(samples)} samples, "
                           f"expected {total}")
    # the serial mini config (inflight_segments=1) journals segments
    # in order: the first ``warmup`` carry trace/compile — drop them
    return plan, samples[warmup:]


def _clean_leg(tmp: str, name: str, ledger: str, plan: str,
               shape: dict, args, kw) -> tuple:
    """One clean leg judged by a FRESH watch with its own incident
    directory; returns (verdict, bundles written)."""
    _plan, clean = _leg(os.path.join(tmp, name), **kw)
    inc_dir = os.path.join(tmp, f"incidents_{name}")
    watch = RegressionWatch(ledger, incident_dir=inc_dir,
                            alpha=args.alpha,
                            min_samples=min(8, args.segments))
    verdict = watch.check(plan, clean, shape=shape)
    return verdict, len(_bundles(inc_dir))


def selftest(args) -> int:
    """End-to-end proof: pipeline -> journal -> aggregator -> ledger
    -> watch.  The stalled leg must escalate EXACTLY one bundle (and
    latch), the clean leg exactly zero."""
    shape = {"log2n": args.log2n, "channels": args.channels,
             "segments": args.segments, "warmup": args.warmup}
    kw = dict(segments=args.segments, warmup=args.warmup,
              log2n=args.log2n, channels=args.channels)
    with tempfile.TemporaryDirectory(prefix="srtb_obs_watch_") as tmp:
        ledger = os.path.join(tmp, "ledger.jsonl")
        plan, base = _leg(os.path.join(tmp, "leg_base"), **kw)
        med = sorted(base)[len(base) // 2]
        PL.PerfLedger(ledger).append(PL.make_record(
            "watch-selftest", med, "s/segment", plan=plan,
            shape=shape, samples_s=base))

        from srtb_tpu.tools.perf_gate import stall_plan
        stall_s = max(0.02, 2.0 * med)
        _plan_b, stalled = _leg(
            os.path.join(tmp, "leg_stall"),
            fault_plan=stall_plan(args.segments, args.warmup, stall_s),
            **kw)
        dir_stall = os.path.join(tmp, "incidents_stall")
        watch = RegressionWatch(ledger, incident_dir=dir_stall,
                                alpha=args.alpha,
                                min_samples=min(8, args.segments))
        v_stall = watch.check(plan, stalled, shape=shape)
        # the latch: a second tick on the same sustained regression
        # must NOT mint a second incident
        v_again = watch.check(plan, stalled, shape=shape)
        n_stall = len(_bundles(dir_stall))

        v_clean, n_clean = _clean_leg(tmp, "leg_clean", ledger, plan,
                                      shape, args, kw)
        if v_clean.get("regression"):
            # same flake bound as perf_gate's selftest: a clean/clean
            # comparison false-alarms with probability ~alpha/2 (plus
            # real mid-run throttling) — one independent recapture
            # (fresh leg, fresh watch) squares that away while a
            # genuine shift fails both legs
            v_clean, n_clean = _clean_leg(tmp, "leg_clean2", ledger,
                                          plan, shape, args, kw)
            v_clean["retried"] = True

    ok = (v_stall.get("regression") is True
          and v_stall.get("escalated") is True
          and v_again.get("escalated") is False
          and n_stall == 1
          and v_clean.get("checked") is True
          and not v_clean.get("regression")
          and n_clean == 0)
    print(json.dumps({
        "selftest": "ok" if ok else "FAILED",
        "plan": plan, "stall_s": round(stall_s, 4),
        "stalled": {k: v_stall.get(k) for k in
                    ("regression", "effect", "p", "noise_floor",
                     "escalated")},
        "clean": {k: v_clean.get(k) for k in
                  ("regression", "effect", "p", "noise_floor")},
        "bundles_stalled_leg": n_stall,
        "bundles_clean_leg": n_clean,
        "detail": ("injected stall escalated exactly one incident "
                   "bundle; clean leg escalated zero" if ok else
                   "watch verdicts did not match expectations"),
    }, sort_keys=True))
    sys.stdout.flush()
    return 0 if ok else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--selftest", action="store_true")
    p.add_argument("--alpha", type=float, default=0.05)
    p.add_argument("--segments", type=int, default=12)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--log2n", type=int, default=12)
    p.add_argument("--channels", type=int, default=32)
    args = p.parse_args(argv)
    if args.selftest:
        try:
            return selftest(args)
        except (OSError, ValueError, RuntimeError) as e:
            print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
            return 2
    p.print_usage(sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
