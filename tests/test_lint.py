"""srtb-lint rule fixtures: each rule fires on a minimal positive
snippet, stays quiet on the matching negative, and respects pragma /
baseline suppression — plus the acceptance gate that the real tree
lints clean against the checked-in baseline.
"""

import json
import os
import textwrap

import pytest

from srtb_tpu.analysis import lint
from srtb_tpu.analysis.core import Baseline

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write(tmp_path, rel, src):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return str(p)


def _run(tmp_path, *rels):
    return lint.run([str(tmp_path)] if not rels
                    else [str(tmp_path / r) for r in rels])


def _rules(findings):
    return [f.rule for f in findings]


# ------------------------------------------------------ sync-hot-path


class TestSyncHotPath:
    def test_jit_body_positive(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import jax
            import numpy as np

            def g(x):
                return np.asarray(x)

            f = jax.jit(g)
        """)
        fs = _run(tmp_path)
        assert _rules(fs) == ["sync-hot-path"]
        assert "np.asarray" in fs[0].message
        assert fs[0].context == "g"

    def test_dispatch_window_positive(self, tmp_path):
        _write(tmp_path, "pipeline/runtime.py", """
            import numpy as np

            class Pipeline:
                def _dispatch_segment(self, seg):
                    return np.asarray(seg.data)
        """)
        fs = _run(tmp_path)
        assert _rules(fs) == ["sync-hot-path"]
        assert "dispatch window" in fs[0].message

    def test_reaches_through_call_graph(self, tmp_path):
        # the hot root only *calls* the offender; the sync is two hops
        # away in another module imported by alias
        _write(tmp_path, "helpers.py", """
            def fetch(x):
                return x.block_until_ready()
        """)
        _write(tmp_path, "pipeline/runtime.py", """
            import helpers

            def fill_window(pending):
                return helpers.fetch(pending[0])
        """)
        fs = _run(tmp_path)
        assert _rules(fs) == ["sync-hot-path"]
        assert fs[0].rel.endswith("helpers.py")

    def test_item_and_float_in_jit_body(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import jax

            @jax.jit
            def g(x):
                a = x.item()
                return float(x) + a
        """)
        assert _rules(_run(tmp_path)) == ["sync-hot-path"] * 2

    def test_negative_unrooted(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import numpy as np

            def host_helper(x):
                return np.asarray(x)   # never jitted, never hot
        """)
        assert _run(tmp_path) == []

    def test_pragma_suppresses(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import jax
            import numpy as np

            def g(x):
                # host constant, not traced data
                # srtb-lint: disable=sync-hot-path
                return np.asarray(x)

            f = jax.jit(g)
        """)
        assert _run(tmp_path) == []


# --------------------------------------------------- use-after-donate


class TestUseAfterDonate:
    def test_wrapper_positive(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import jax

            def f(x):
                return x + 1

            w = jax.jit(f, donate_argnums=(0,))

            def use(buf):
                y = w(buf)
                return buf.sum()
        """)
        fs = _run(tmp_path)
        assert _rules(fs) == ["use-after-donate"]
        assert "'buf'" in fs[0].message

    def test_api_positive(self, tmp_path):
        _write(tmp_path, "mod.py", """
            def h(proc, buf):
                wf, det = proc.run_device(buf)
                return wf, buf[0]
        """)
        assert _rules(_run(tmp_path)) == ["use-after-donate"]

    def test_negative_reassigned(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import jax

            def f(x):
                return x + 1

            w = jax.jit(f, donate_argnums=(0,))

            def ok(buf):
                buf = w(buf)
                return buf.sum()
        """)
        assert _run(tmp_path) == []

    def test_negative_sibling_branch(self, tmp_path):
        _write(tmp_path, "mod.py", """
            def h(proc, buf, fast):
                if fast:
                    out = proc.run_device(buf)
                else:
                    out = buf[0]
                return out
        """)
        assert _run(tmp_path) == []

    def test_loop_iteration_positive(self, tmp_path):
        _write(tmp_path, "mod.py", """
            def h(proc, buf, n):
                outs = []
                for _ in range(n):
                    outs.append(buf.mean())      # stale on iter 2
                    proc.run_device(buf)
                return outs
        """)
        fs = _run(tmp_path)
        assert _rules(fs) == ["use-after-donate"]
        assert "loop iteration" in fs[0].message


# -------------------------------------------------- recompile-hazard


class TestRecompileHazard:
    def test_jit_in_loop(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import jax

            def sweep(fns, x):
                outs = []
                for f in fns:
                    outs.append(jax.jit(f)(x))
                return outs
        """)
        fs = _run(tmp_path)
        assert "inside a loop" in fs[0].message
        assert all(r == "recompile-hazard" for r in _rules(fs))

    def test_immediate_invoke_in_method(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import jax

            class R:
                def render(self, x):
                    return jax.jit(self._impl)(x)

                def _impl(self, x):
                    return x
        """)
        fs = _run(tmp_path)
        assert _rules(fs) == ["recompile-hazard"]
        assert "immediately invoked" in fs[0].message

    def test_bound_method_uncached(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import jax

            class R:
                def build(self):
                    f = jax.jit(self._impl)
                    return f

                def _impl(self, x):
                    return x
        """)
        fs = _run(tmp_path)
        assert _rules(fs) == ["recompile-hazard"]
        assert "bound method" in fs[0].message

    def test_negative_init_and_cached(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import jax

            class R:
                def __init__(self):
                    self._f = jax.jit(self._impl)
                    self._chirp = jax.jit(lambda: 1.0)()

                def lazy(self):
                    self._g = jax.jit(self._impl)  # cached on self
                    return self._g

                def _impl(self, x):
                    return x

            top = jax.jit(lambda x: x)  # module scope: one-time
        """)
        assert _run(tmp_path) == []


# ------------------------------------------------------- dtype-drift


class TestDtypeDrift:
    def test_jnp_float64_in_ops(self, tmp_path):
        _write(tmp_path, "ops/chirp.py", """
            import jax.numpy as jnp

            def phase(x):
                return x.astype(jnp.float64)
        """)
        fs = _run(tmp_path)
        assert _rules(fs) == ["dtype-drift"]

    def test_np64_inside_jit_body(self, tmp_path):
        _write(tmp_path, "ops/mod.py", """
            import jax
            import numpy as np

            def g(x):
                return x * np.float64(1.5)

            f = jax.jit(g)
        """)
        assert _rules(_run(tmp_path)) == ["dtype-drift"]

    def test_dtype_string_in_jit_body(self, tmp_path):
        _write(tmp_path, "ops/mod.py", """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def g(x):
                return jnp.zeros(4, dtype="float64") + x
        """)
        assert _rules(_run(tmp_path)) == ["dtype-drift"]

    def test_enable_x64_flagged(self, tmp_path):
        _write(tmp_path, "utils/setup.py", """
            import jax

            def enable():
                jax.config.update("jax_enable_x64", True)
        """)
        assert _rules(_run(tmp_path)) == ["dtype-drift"]

    def test_negative_host_precompute(self, tmp_path):
        _write(tmp_path, "ops/window.py", """
            import numpy as np

            def coefficients(n):
                # host-side f64 table, cast before the trace: sanctioned
                x = np.arange(n, dtype=np.float64)
                return np.cos(x).astype(np.float32)
        """)
        assert _run(tmp_path) == []


# ---------------------------------------- unguarded-shared-state


class TestUnguardedSharedState:
    def test_thread_vs_main_positive(self, tmp_path):
        _write(tmp_path, "io/pump.py", """
            import threading

            class Pump:
                def __init__(self):
                    self.count = 0
                    self._thread = threading.Thread(target=self._pump)

                def _pump(self):
                    self.count += 1

                def reset(self):
                    self.count = 0
        """)
        fs = _run(tmp_path)
        assert _rules(fs) == ["unguarded-shared-state"]
        assert "'Pump.count'" in fs[0].message

    def test_negative_locked(self, tmp_path):
        _write(tmp_path, "io/pump.py", """
            import threading

            class Pump:
                def __init__(self):
                    self.count = 0
                    self._lock = threading.Lock()
                    self._thread = threading.Thread(target=self._pump)

                def _pump(self):
                    with self._lock:
                        self.count += 1

                def reset(self):
                    with self._lock:
                        self.count = 0
        """)
        assert _run(tmp_path) == []

    def test_start_pipe_container_mutation(self, tmp_path):
        _write(tmp_path, "pipeline/engine.py", """
            from srtb_tpu.pipeline.framework import start_pipe

            class Engine:
                def run(self, q, stop):
                    done = []

                    def sink_f(_stop, item):
                        done.append(item)

                    pipe = start_pipe(sink_f, q, None, stop, "sink")
                    done.append(None)   # main thread, no lock
                    return pipe
        """)
        fs = _run(tmp_path)
        assert _rules(fs) == ["unguarded-shared-state"]


# ------------------------------------------------ swallowed-except


class TestSwallowedExcept:
    def test_bare_except_pass_positive(self, tmp_path):
        _write(tmp_path, "io/reader.py", """
            def read(f):
                try:
                    return f.read()
                except:
                    pass
        """)
        fs = _run(tmp_path)
        assert _rules(fs) == ["swallowed-except"]
        assert "everything" in fs[0].message
        assert fs[0].context == "read"

    def test_broad_except_dropped_positive(self, tmp_path):
        _write(tmp_path, "pipeline/engine.py", """
            def drain(item):
                try:
                    item.flush()
                except Exception:
                    return None
        """)
        fs = _run(tmp_path)
        assert _rules(fs) == ["swallowed-except"]
        assert "Exception" in fs[0].message

    def test_negative_logged_reraised_or_used(self, tmp_path):
        _write(tmp_path, "pipeline/engine.py", """
            from srtb_tpu.utils.logging import log

            def a(item):
                try:
                    item.flush()
                except Exception:
                    log.warning("flush failed")

            def b(item):
                try:
                    item.flush()
                except Exception:
                    raise RuntimeError("flush failed")

            def c(self, item):
                try:
                    item.flush()
                except BaseException as e:
                    self.exception = e
        """)
        assert _run(tmp_path) == []

    def test_negative_narrow_except(self, tmp_path):
        # a named exception type is a documented decision: out of scope
        _write(tmp_path, "io/reader.py", """
            def read(sock):
                try:
                    return sock.recv(1)
                except OSError:
                    pass
        """)
        assert _run(tmp_path) == []

    def test_negative_outside_pipeline_io_scope(self, tmp_path):
        _write(tmp_path, "gui/tap.py", """
            def tap(frame):
                try:
                    frame.render()
                except Exception:
                    pass
        """)
        assert _run(tmp_path) == []

    def test_pragma_suppresses(self, tmp_path):
        _write(tmp_path, "io/reader.py", """
            def probe(x):
                try:
                    return x.ready()
                except Exception:  # srtb-lint: disable=swallowed-except
                    return True
        """)
        assert _run(tmp_path) == []


# ------------------------------------------- baseline & CLI behavior


class TestBaselineAndCli:
    def _seed(self, tmp_path):
        _write(tmp_path, "src/mod.py", """
            import jax
            import numpy as np

            def g(x):
                return np.asarray(x)

            f = jax.jit(g)
        """)

    def test_baseline_accepts_then_new_fails(self, tmp_path):
        self._seed(tmp_path)
        bl = str(tmp_path / "baseline.json")
        src = str(tmp_path / "src")
        assert lint.main([src, "--baseline", bl]) == 1  # new finding
        assert lint.main([src, "--baseline", bl,
                          "--write-baseline"]) == 0
        assert lint.main([src, "--baseline", bl]) == 0  # accepted
        # notes survive a rewrite
        data = json.load(open(bl))
        key = next(iter(data["entries"]))
        data["entries"][key]["note"] = "accepted: host bytes"
        json.dump(data, open(bl, "w"))
        assert lint.main([src, "--baseline", bl,
                          "--write-baseline"]) == 0
        assert json.load(open(bl))["entries"][key]["note"] \
            == "accepted: host bytes"
        # a NEW finding still fails against the old baseline
        _write(tmp_path, "src/mod2.py", """
            import jax

            @jax.jit
            def h(x):
                return x.item()
        """)
        assert lint.main([src, "--baseline", bl]) == 1

    def test_stale_entries_reported(self, tmp_path):
        self._seed(tmp_path)
        src = str(tmp_path / "src")
        findings = lint.run([src])
        bl = Baseline.from_findings(findings)
        bl.entries["gone::sync-hot-path::f::x"] = {"count": 1}
        new, accepted, stale = bl.filter(findings)
        assert not new and len(accepted) == 1
        assert stale == ["gone::sync-hot-path::f::x"]

    def test_disable_file_pragma(self, tmp_path):
        _write(tmp_path, "src/mod.py", """
            # srtb-lint: disable-file=sync-hot-path
            import jax
            import numpy as np

            def g(x):
                return np.asarray(x)

            f = jax.jit(g)
        """)
        assert lint.run([str(tmp_path / "src")]) == []

    def test_list_rules(self, capsys):
        assert lint.main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rule in ("sync-hot-path", "use-after-donate",
                     "recompile-hazard", "dtype-drift",
                     "unguarded-shared-state"):
            assert rule in out

    def test_json_format(self, tmp_path, capsys):
        self._seed(tmp_path)
        lint.main([str(tmp_path / "src"), "--no-baseline",
                   "--format", "json"])
        data = json.loads(capsys.readouterr().out)
        assert data["new"] and data["new"][0]["rule"] == "sync-hot-path"


# -------------------------------------------- host-callback-in-jit


class TestHostCallbackInJit:
    def test_jit_body_positive(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import jax

            def g(x):
                jax.debug.print("x={x}", x=x)
                return x * 2

            f = jax.jit(g)
        """)
        fs = _run(tmp_path)
        assert "host-callback-in-jit" in _rules(fs)
        f = next(x for x in fs if x.rule == "host-callback-in-jit")
        assert "debug.print" in f.message and f.context == "g"

    def test_pure_callback_in_jit_body(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import jax
            import numpy as np

            def host_fn(x):
                return np.sort(x)

            @jax.jit
            def g(x):
                return jax.pure_callback(
                    host_fn, jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        """)
        fs = _run(tmp_path)
        assert "host-callback-in-jit" in _rules(fs)

    def test_io_callback_via_alias(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import jax
            from jax.experimental import io_callback as iocb

            def log_it(x):
                pass

            @jax.jit
            def g(x):
                iocb(log_it, None, x)
                return x
        """)
        fs = _run(tmp_path)
        assert "host-callback-in-jit" in _rules(fs)

    def test_dispatch_window_positive(self, tmp_path):
        _write(tmp_path, "pipeline/runtime.py", """
            import jax

            class Pipeline:
                def _dispatch_segment(self, seg):
                    jax.debug.callback(print, seg)
                    return seg
        """)
        fs = _run(tmp_path)
        assert "host-callback-in-jit" in _rules(fs)
        f = next(x for x in fs if x.rule == "host-callback-in-jit")
        assert "dispatch window" in f.message

    def test_outside_jit_negative(self, tmp_path):
        # a callback in plain host code (drain side) is sanctioned
        _write(tmp_path, "mod.py", """
            import jax

            def drain(x):
                jax.debug.print("x={x}", x=x)
                return x
        """)
        assert "host-callback-in-jit" not in _rules(_run(tmp_path))

    def test_pragma_suppression(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import jax

            @jax.jit
            def g(x):
                # sanctioned diagnostic
                # srtb-lint: disable=host-callback-in-jit
                jax.debug.print("x={x}", x=x)
                return x
        """)
        assert "host-callback-in-jit" not in _rules(_run(tmp_path))


# --------------------------------------------------- acceptance gate


def test_repo_lints_clean_against_baseline():
    """The acceptance criterion: the real tree, the real baseline,
    exit code 0 — and the baseline has no stale entries (every entry
    still fires, so it documents real accepted findings)."""
    pkg = os.path.join(REPO, "srtb_tpu")
    baseline = os.path.join(pkg, "analysis", "baseline.json")
    findings = lint.run([pkg])
    new, accepted, stale = Baseline.load(baseline).filter(findings)
    assert new == [], "\n".join(f.render() for f in new)
    assert stale == [], stale
    assert accepted, "baseline unexpectedly empty"


def test_repo_baseline_entries_have_notes():
    baseline = os.path.join(REPO, "srtb_tpu", "analysis",
                            "baseline.json")
    data = json.load(open(baseline))
    missing = [k for k, e in data["entries"].items()
               if not e.get("note")]
    assert not missing, missing
