"""Spectrum-pass fusion: fused vs unfused plans.

The fused spectrum tail (Config.fused_tail) folds RFI stage 1 + the
dedispersion chirp into the forward FFT's final (Hermitian post) pass,
and — with both Pallas knobs — the SK zap + detection time series into
the waterfall FFT's write (ops/pallas_fft.fft_rows_skzap_ri).  These
tests pin:

- numeric parity of fused vs unfused plans on synthetic dispersed
  pulses for the fused (four-step), blocked-subbyte, and staged plan
  families.  Tolerances are the documented fusion deltas, not slop:
  the RFI s1 mean comes from the Parseval identity over the packed C2C
  output (rfi.mean_power_packed, f32-rounding-level difference from the
  direct mean), the chirp·twiddle precombination reassociates one
  complex multiply, and the epilogue's df64 chirp uses the XLA
  anchored-Taylor evaluation (~1e-9 turns from the Pallas in-kernel
  one).  Detection *decisions* (signal counts, zero-channel counts)
  must match exactly at test thresholds.
- the Parseval mean-power identity itself against the direct mean;
- the in-kernel SK decision of the skzap kernel against the jnp chain,
  including a deliberately-zapped row;
- plan_signature changes whenever fusion toggles (AOT cache safety);
- the per-plan hbm_passes model (7 legacy, 5 fused tail, 4 skzap) and
  bench.roofline_model consuming it.
"""

import json
import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from srtb_tpu.config import Config
from srtb_tpu.io.synth import make_dispersed_baseband
from srtb_tpu.ops import fft as F
from srtb_tpu.ops import rfi
from srtb_tpu.pipeline.segment import SegmentProcessor, waterfall_to_numpy

N = 1 << 16


def _cfg(n=N, channels=1 << 5, nbits=2, **kw):
    base = dict(
        baseband_input_count=n,
        baseband_input_bits=nbits,
        baseband_format_type="simple",
        baseband_freq_low=1405.0,
        baseband_bandwidth=64.0,
        baseband_sample_rate=128e6,
        dm=30.0,
        spectrum_channel_count=channels,
        signal_detect_signal_noise_threshold=5.0,
        signal_detect_max_boxcar_length=8,
        mitigate_rfi_average_method_threshold=25.0,
        mitigate_rfi_spectral_kurtosis_threshold=1e9,
        mitigate_rfi_freq_list="1450-1460",
        baseband_reserve_sample=False,
        fft_strategy="four_step",
    )
    base.update(kw)
    return Config(**base)


def _pulse_bytes(cfg):
    return make_dispersed_baseband(
        cfg.baseband_input_count, cfg.baseband_freq_low,
        cfg.baseband_bandwidth, cfg.dm,
        pulse_positions=cfg.baseband_input_count // 2, pulse_amp=30.0,
        nbits=cfg.baseband_input_bits)


def _run(cfg, staged=None):
    proc = SegmentProcessor(cfg, staged=staged)
    raw = _pulse_bytes(cfg)
    wf_ri, res = proc.process(raw)
    return proc, waterfall_to_numpy(wf_ri), res


def _assert_parity(off, on, atol_scale=2e-4):
    """Fused vs unfused: identical decisions, documented-tolerance
    values."""
    _, wf_off, res_off = off
    _, wf_on, res_on = on
    np.testing.assert_array_equal(np.asarray(res_off.signal_counts),
                                  np.asarray(res_on.signal_counts))
    np.testing.assert_array_equal(np.asarray(res_off.zero_count),
                                  np.asarray(res_on.zero_count))
    scale = max(np.abs(wf_off).max(), 1e-30)
    np.testing.assert_allclose(wf_on, wf_off, atol=atol_scale * scale,
                               rtol=0)
    ts_off = np.asarray(res_off.time_series)
    ts_scale = max(np.abs(ts_off).max(), 1e-30)
    np.testing.assert_allclose(np.asarray(res_on.time_series), ts_off,
                               atol=5e-4 * ts_scale, rtol=0)


@pytest.mark.parametrize("n", [1 << 16, 1 << 18, 1 << 20])
def test_fused_vs_unfused_four_step(n):
    """Fused plan family: the bank + chirp·twiddle-precombination
    epilogue vs the legacy three-sweep tail, 2-bit blocked-subbyte
    composition (the production format)."""
    off = _run(_cfg(n=n, fused_tail="off"))
    on = _run(_cfg(n=n, fused_tail="on"))
    assert off[0].hbm_passes == 7 and on[0].hbm_passes == 5
    assert not off[0].fused_tail and on[0].fused_tail
    _assert_parity(off, on)


def test_fused_vs_unfused_int8_bank_premul():
    """Non-blocked unpack (8-bit) through segment_rfft: the bank premul
    path on the sample-order composition."""
    off = _run(_cfg(nbits=8, fused_tail="off"))
    on = _run(_cfg(nbits=8, fused_tail="on"))
    assert on[0].chirp_w is not None  # precombined bank exists
    _assert_parity(off, on)


def test_fused_vs_unfused_staged(monkeypatch):
    """Staged plan family: the epilogue folds into stage (b)'s Hermitian
    write (df64 in-trace chirp, no bank)."""
    off = _run(_cfg(fused_tail="off"), staged=True)
    on = _run(_cfg(fused_tail="on"), staged=True)
    assert off[0].staged and on[0].staged
    assert off[0].hbm_passes == 7 and on[0].hbm_passes == 5
    assert on[0].chirp is None and on[0].chirp_w is None
    _assert_parity(off, on, atol_scale=1e-3)


def test_fused_skzap_vs_unfused(caplog):
    """Fully-fused waterfall tail (one kernel: C2C + dewindow + SK +
    zap + ts) vs the legacy jnp chain — 4 modeled passes vs 7."""
    kw = dict(channels=8, use_pallas=True, use_pallas_sk=True)
    off = _run(_cfg(fused_tail="off", **kw))
    on = _run(_cfg(fused_tail="on", **kw))
    assert on[0]._skzap and on[0].hbm_passes == 4
    assert off[0].hbm_passes == 7
    assert on[0].plan_name.endswith("+ftail+skzap")
    _assert_parity(off, on, atol_scale=1e-3)


def test_skzap_kernel_zaps_like_jnp_chain():
    """In-kernel SK decision parity, including a row the threshold
    really zaps: a constant-amplitude row has SK ~ 1 < thr_low and must
    come out exactly zero, excluded from the time series, and counted
    as a zero channel — matching rfi.mitigate_rfi_spectral_kurtosis +
    detect on the same spectrum rows."""
    from srtb_tpu.ops import detect as det
    from srtb_tpu.ops import pallas_fft as pf

    nfreq, t_len = 16, 1 << 12
    rng = np.random.default_rng(3)
    spec = (rng.standard_normal((nfreq, t_len))
            + 1j * rng.standard_normal((nfreq, t_len))).astype(np.complex64)
    spec[5] = 0.7 + 0.2j  # constant row -> SK = m*T*p^2/(T*p)^2 « thr_low
    sk_thr = 1.05

    wr, wi, zapf, fs0, ts = pf.fft_rows_skzap_ri(
        jnp.real(jnp.asarray(spec)), jnp.imag(jnp.asarray(spec)),
        sk_thr, inverse=True, interpret=True)
    wf_fused = np.asarray(wr) + 1j * np.asarray(wi)

    wf_ref = np.asarray(jnp.fft.ifft(jnp.asarray(spec), axis=-1,
                                     norm="forward"))
    wf_ref_zap = np.asarray(rfi.mitigate_rfi_spectral_kurtosis(
        jnp.asarray(wf_ref), sk_thr))
    zapped_rows = np.abs(wf_ref_zap).sum(-1) == 0
    assert zapped_rows[5] and zapped_rows.sum() >= 1

    got_zap = np.asarray(zapf)[:, 0] != 0
    np.testing.assert_array_equal(got_zap, zapped_rows)
    assert np.all(wf_fused[5] == 0)
    scale = np.abs(wf_ref_zap).max()
    np.testing.assert_allclose(wf_fused, wf_ref_zap, atol=2e-4 * scale,
                               rtol=0)
    # time series over kept rows only
    ts_ref = np.asarray(det.tree_sum_freq(
        jnp.asarray(np.abs(wf_ref_zap).astype(np.float32) ** 2)))
    np.testing.assert_allclose(np.asarray(ts), ts_ref,
                               rtol=1e-4, atol=1e-3 * ts_ref.max())
    # zero-count inputs: zap flag OR first-sample power == 0
    zc = int(((np.asarray(zapf)[:, 0] != 0)
              | (np.asarray(fs0)[:, 0] == 0)).sum())
    assert zc == int(zapped_rows.sum())


def test_mean_power_packed_matches_direct_mean():
    """The Parseval identity over the packed C2C output equals the
    direct mean |spec|^2 over the dropped-Nyquist spectrum."""
    rng = np.random.default_rng(11)
    x = rng.standard_normal(1 << 14).astype(np.float32) * 3.0
    zf = jnp.fft.fft(F.pack_even_odd(jnp.asarray(x)))
    spec = F.hermitian_rfft_post(zf, drop_nyquist=True)
    direct = float(jnp.mean(jnp.abs(spec) ** 2))
    parseval = float(rfi.mean_power_packed(zf)[..., 0])
    np.testing.assert_allclose(parseval, direct, rtol=1e-5)


def test_rfi_s1_zap_decisions_match_through_parseval_mean():
    """At a real (non-degenerate) threshold the fused path's zap set
    must equal the unfused one's on representative data."""
    rng = np.random.default_rng(12)
    x = rng.standard_normal(1 << 14).astype(np.float32)
    x[64:96] += np.sin(np.arange(32) * 0.7).astype(np.float32) * 40.0
    zf = jnp.fft.fft(F.pack_even_odd(jnp.asarray(x)))
    spec = F.hermitian_rfft_post(zf, drop_nyquist=True)
    thr = 10.0
    unfused = np.asarray(rfi.mitigate_rfi_average_and_normalize(
        spec, thr, 0.5))
    fused = np.asarray(rfi.mitigate_rfi_s1_given_mean(
        spec, rfi.mean_power_packed(zf), thr, 0.5))
    np.testing.assert_array_equal(unfused == 0, fused == 0)
    np.testing.assert_allclose(fused, unfused, rtol=1e-6, atol=0)


def test_plan_signature_changes_when_fusion_toggles():
    """AOT cache safety: toggling fused_tail (or the skzap fusion) must
    change plan_signature so a restarted process misses cleanly."""
    sig_off = SegmentProcessor(_cfg(fused_tail="off")).plan_signature()
    sig_on = SegmentProcessor(_cfg(fused_tail="on")).plan_signature()
    assert sig_off != sig_on
    kw = dict(channels=8, use_pallas=True, use_pallas_sk=True)
    sig_sk_on = SegmentProcessor(
        _cfg(fused_tail="on", **kw)).plan_signature()
    sig_sk_off = SegmentProcessor(
        _cfg(fused_tail="off", **kw)).plan_signature()
    assert sig_sk_on != sig_sk_off != sig_off
    # chirp_exact shapes the traced chirp evaluation -> new signature
    assert SegmentProcessor(
        _cfg(fused_tail="on", chirp_exact=True)).plan_signature() != sig_on


def test_hbm_passes_model():
    """The per-plan modeled pass counts and their roofline consumption."""
    import bench

    assert SegmentProcessor(
        _cfg(fft_strategy="monolithic")).hbm_passes == 7
    assert SegmentProcessor(_cfg(fused_tail="off")).hbm_passes == 7
    assert SegmentProcessor(_cfg(fused_tail="auto")).hbm_passes == 5
    assert SegmentProcessor(
        _cfg(fused_tail="auto", channels=8, use_pallas=True,
             use_pallas_sk=True)).hbm_passes == 4
    n, ch = 1 << 20, 1 << 8
    _, legacy = bench.roofline_model(n, ch, 2, hbm_passes=7)
    _, fused = bench.roofline_model(n, ch, 2, hbm_passes=4)
    spectrum_bytes = 8.0 * (n // 2)
    np.testing.assert_allclose(legacy - fused, 3 * spectrum_bytes)


def test_fused_tail_auto_gates_bankless_sizes(monkeypatch):
    """auto keeps bankless plans (in-trace df64 chirp) unfused above
    the proven size range; bank plans carry no gate; "on" overrides
    (the hardware-queue staged legs)."""
    import srtb_tpu.pipeline.segment as seg
    monkeypatch.setattr(seg, "FUSED_TAIL_DF64_MAX_SPECTRUM", 1 << 10)
    gated = SegmentProcessor(_cfg(use_pallas=True))   # n_spec 2^15 > 2^10
    assert not gated.fused_tail and gated.hbm_passes == 7
    bank = SegmentProcessor(_cfg())                   # bank plan: no gate
    assert bank.fused_tail
    forced = SegmentProcessor(_cfg(use_pallas=True, fused_tail="on"))
    assert forced.fused_tail


def test_fused_tail_on_monolithic_raises():
    with pytest.raises(ValueError, match="monolithic"):
        SegmentProcessor(_cfg(fft_strategy="monolithic", fused_tail="on"))
    # and segment_rfft itself refuses an epilogue it cannot host
    with pytest.raises(ValueError, match="monolithic"):
        F.segment_rfft(jnp.zeros(256), "monolithic",
                       epilogue=lambda zf, s: s)


def test_chirp_exact_escape_hatch_matches_anchored():
    """Config.chirp_exact flips every df64 chirp to the per-element
    division chains; results must agree with the anchored default to
    the documented ~1e-9-turn phase budget."""
    on = _run(_cfg(fused_tail="on"))
    exact = _run(_cfg(fused_tail="on", chirp_exact=True))
    scale = np.abs(on[1]).max()
    np.testing.assert_allclose(exact[1], on[1], atol=1e-5 * scale, rtol=0)
    np.testing.assert_array_equal(np.asarray(on[2].signal_counts),
                                  np.asarray(exact[2].signal_counts))


@pytest.mark.slow
def test_bench_emits_plan_and_hbm_passes():
    """bench.py artifact lines are self-describing: plan + hbm_passes,
    7 on the legacy leg, 4 on the fully-fused leg (CPU interpret)."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "SRTB_BENCH_LOG2N": "16",
           "SRTB_BENCH_REPS": "1"}
    out = subprocess.run(
        [sys.executable, "bench.py", "--fused-tail", "off"],
        capture_output=True, text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["hbm_passes"] == 7 and rec["fused_tail"] == "off"
    assert rec["plan"].startswith("fused:")

    env.update({"SRTB_BENCH_FFT_STRATEGY": "four_step",
                "SRTB_BENCH_LOG2CHAN": "3", "SRTB_BENCH_USE_PALLAS": "1",
                "SRTB_BENCH_USE_PALLAS_SK": "1"})
    out = subprocess.run(
        [sys.executable, "bench.py", "--fused-tail", "on"],
        capture_output=True, text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["hbm_passes"] == 4 and rec["fused_tail"] == "on"
    assert rec["plan"].endswith("+ftail+skzap")
    # model_hbm_gb really is computed from the per-plan count
    m = (1 << 16) // 2
    expect = ((1 << 16) * 2 / 8.0 + 8.0 * m * 4) / 1e9
    np.testing.assert_allclose(rec["model_hbm_gb"], expect, atol=5e-4)
