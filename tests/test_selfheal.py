"""Self-healing compute tests (resilience/demote.py + the engine
wiring in pipeline/runtime.py).

Covers the acceptance criteria of the self-healing subsystem:
- device-fault classification from the REAL exception strings jax
  raises (RESOURCE_EXHAUSTED / Mosaic compile / device halt) plus the
  typed shortcut classes, and the retry policy never retrying them;
- the demotion ladder: rung order, resolution-aware skipping,
  cumulative configs, distinct plan signatures per rung;
- recovery end-to-end on a real plan: an injected OOM or compile
  fault demotes and re-dispatches the faulted segment from its
  retained host buffer with detection decisions identical to a
  fault-free run; an injected device halt reinitializes the backend
  (fresh processor, invalidated ring carry — the post-reinit dispatch
  goes COLD instead of assembling against a dead device buffer);
- budget escalation: the ladder exhausts, the reinit budget expires,
  and disabled healing all escalate loudly;
- the promotion probe steps back up after N healthy segments;
- interplay with the existing machinery: demotion of a segment the
  watchdog just requeued, demotion while the degradation ladder is
  active, checkpoint resume offsets unchanged by demotion;
- the chaos soak harness (tools/chaos_soak.py) gate + selftest;
- the plan-audit ladder-target guard (every demotion target is a
  carded plan family).
"""

import json
import os
import time
from typing import NamedTuple

import numpy as np
import pytest

from srtb_tpu.config import Config
from srtb_tpu.pipeline.runtime import Pipeline, ThreadedPipeline
from srtb_tpu.pipeline.work import SegmentWork
from srtb_tpu.resilience import errors as E
from srtb_tpu.resilience.demote import (ComputeHealer, ladder_rungs,
                                        parse_ladder)
from srtb_tpu.resilience.faults import parse_plan
from srtb_tpu.resilience.retry import RetryPolicy, retry_call
from srtb_tpu.utils.metrics import metrics


class _FakeXla(Exception):
    """Local stand-in with jaxlib's type name — classification must
    key on name + message, exactly as for the real class."""


_FakeXla.__name__ = "XlaRuntimeError"

_OOM_MSG = ("RESOURCE_EXHAUSTED: Out of memory while trying to "
            "allocate 68719476736 bytes.")
_COMPILE_MSG = "INTERNAL: Mosaic failed to compile TPU kernel: oops"
_HALT_MSG = ("INTERNAL: Accelerator device halted prematurely, "
             "perhaps due to an on-device check-failure.")


# ------------------------------------------------------ classification


def test_classify_device_real_strings():
    assert E.classify_device(_FakeXla(_OOM_MSG)) == E.DEVICE_OOM
    assert E.classify_device(_FakeXla(_COMPILE_MSG)) == E.DEVICE_COMPILE
    assert E.classify_device(_FakeXla(_HALT_MSG)) == E.DEVICE_HALT
    # CPU allocator phrasing
    assert E.classify_device(
        _FakeXla("Out of memory allocating 1024 bytes.")) == E.DEVICE_OOM
    # unrecognized XLA error: NOT a device fault (stays fatal)
    assert E.classify_device(_FakeXla("INVALID_ARGUMENT: bad")) is None
    assert E.classify(_FakeXla("INVALID_ARGUMENT: bad")) == E.FATAL
    # marker strings inside a NON-XLA exception must stay fatal: a
    # ValueError from user code mentioning OOM is not a device fault
    assert E.classify_device(ValueError(_OOM_MSG)) is None
    assert E.classify(ValueError(_OOM_MSG)) == E.FATAL
    # device classification feeds the DEVICE category
    assert E.classify(_FakeXla(_OOM_MSG)) == E.DEVICE


def test_classify_device_typed_and_compile_type_names():
    assert E.classify_device(E.DeviceOOM("x")) == E.DEVICE_OOM
    assert E.classify_device(E.CompileFault("x")) == E.DEVICE_COMPILE
    assert E.classify_device(E.DeviceHalt("x")) == E.DEVICE_HALT
    assert E.classify(E.DeviceHalt("x")) == E.DEVICE
    # typed non-device pipeline errors keep their category
    assert E.classify_device(E.FatalError(_OOM_MSG)) is None

    class MosaicError(Exception):
        pass

    assert E.classify_device(MosaicError("bad lowering")) \
        == E.DEVICE_COMPILE
    # escalation types are fatal
    assert E.classify(E.LadderExhausted("x")) == E.FATAL
    assert E.classify(E.ReinitBudgetExceeded("x")) == E.FATAL


def test_retry_never_retries_device_faults():
    metrics.reset()
    calls = []

    def oom():
        calls.append(1)
        raise _FakeXla(_OOM_MSG)

    p = RetryPolicy(max_attempts=5, backoff_base_s=0.001)
    with pytest.raises(_FakeXla):
        retry_call(oom, p, "t", sleep=lambda s: None)
    assert len(calls) == 1  # no retry: verbatim re-run OOMs verbatim
    assert metrics.get("retries_total") == 0
    metrics.reset()


def test_fault_plan_device_actions():
    specs = parse_plan("dispatch:oom@1,fetch:compile_fail@2,"
                       "h2d:device_halt@3")
    assert [s.action for s in specs] == ["oom", "compile_fail",
                                        "device_halt"]
    # device actions only at device sites
    with pytest.raises(ValueError, match="device site"):
        parse_plan("ingest:oom@1")
    with pytest.raises(ValueError, match="device site"):
        parse_plan("sink_write:device_halt@0")


# ------------------------------------------------------------- ladder


def _featured_cfg(n=1 << 16, **extra):
    base = dict(baseband_input_count=n, baseband_input_bits=2,
                baseband_freq_low=1405.0, baseband_bandwidth=64.0,
                baseband_sample_rate=128e6, dm=0.1,
                spectrum_channel_count=8,
                mitigate_rfi_average_method_threshold=25.0,
                mitigate_rfi_spectral_kurtosis_threshold=1.05,
                signal_detect_max_boxcar_length=8,
                fft_strategy="four_step", fused_tail="on",
                use_pallas=True, use_pallas_sk=True,
                micro_batch_segments=2, baseband_reserve_sample=True)
    base.update(extra)
    return Config(**base)


def test_ladder_rungs_order_and_cumulative():
    rungs = ladder_rungs(_featured_cfg())
    assert [r.step for r in rungs] == [
        "micro_batch", "ring", "skzap", "fused_tail", "staged",
        "monolithic"]
    # cumulative: the last rung carries every earlier demotion
    last = rungs[-1].cfg
    assert last.micro_batch_segments == 1
    assert last.ingest_ring == "off"
    assert not last.use_pallas_sk and not last.use_pallas
    assert last.fused_tail == "off"
    assert last.fft_strategy == "monolithic"
    assert rungs[-1].staged is False and rungs[-2].staged is True


def test_ladder_skips_unresolvable_rungs():
    # minimal config: no micro-batch, no reserved tail (ring dead), no
    # pallas, auto strategy resolves monolithic at small n, fused_tail
    # auto resolves off on monolithic -> only staged + monolithic left
    cfg = Config(baseband_input_count=1 << 12,
                 baseband_reserve_sample=False)
    assert [r.step for r in ladder_rungs(cfg)] == ["staged",
                                                   "monolithic"]
    # a processor ALREADY running staged skips the staged rung — but
    # gains the fused_tail rung (auto resolves ON for a staged plan,
    # which hosts the epilogue even where the strategy is monolithic)
    steps = [r.step for r in ladder_rungs(cfg, base_staged=True)]
    assert steps == ["fused_tail", "monolithic"]


def test_parse_ladder_modes():
    assert parse_ladder("auto") == parse_ladder("") \
        == parse_ladder(None)
    assert parse_ladder("off") == ()
    assert parse_ladder("ring, monolithic") == ("ring", "monolithic")
    with pytest.raises(ValueError, match="plan_ladder step"):
        parse_ladder("ring,warp_drive")


def test_ladder_rung_signatures_all_distinct():
    from srtb_tpu.pipeline.segment import SegmentProcessor
    cfg = _featured_cfg()
    sigs = {SegmentProcessor(cfg, donate_input=True).plan_signature()}
    for rung in ladder_rungs(cfg):
        proc = SegmentProcessor(rung.cfg, staged=rung.staged,
                                donate_input=True)
        sig = proc.plan_signature()
        # every rung's AOT/plan signature differs from every other
        # plan's: a demotion can never load a stale executable
        assert sig not in sigs, rung.step
        sigs.add(sig)


def test_config_knobs_parse():
    cfg = Config()
    assert cfg.set_option("plan_ladder", "ring,monolithic")
    assert cfg.plan_ladder == "ring,monolithic"
    assert cfg.set_option("promote_after_segments", "4")
    assert cfg.promote_after_segments == 4
    assert cfg.set_option("device_reinit_max", "0")
    assert cfg.device_reinit_max == 0
    assert cfg.set_option("device_reinit_window_s", "60")
    assert cfg.device_reinit_window_s == 60.0


# ------------------------------------------- real-plan recovery (e2e)

N_SEG = 1 << 13
SEGMENTS = 4


@pytest.fixture(scope="module")
def synth_file(tmp_path_factory):
    from srtb_tpu.io.synth import make_dispersed_baseband
    tmp = tmp_path_factory.mktemp("selfheal")
    path = tmp / "bb.bin"
    make_dispersed_baseband(
        N_SEG * SEGMENTS, 1405.0, 64.0, 0.05,
        pulse_positions=[N_SEG // 2 + i * N_SEG
                         for i in range(SEGMENTS)],
        pulse_amp=30.0, nbits=8).tofile(path)
    return str(path)


def _cfg(path, tmp_path, tag, **extra):
    return Config(
        baseband_input_count=N_SEG, baseband_input_bits=8,
        baseband_freq_low=1405.0, baseband_bandwidth=64.0,
        baseband_sample_rate=128e6, dm=0.05,
        input_file_path=path,
        baseband_output_file_prefix=str(tmp_path / f"{tag}_"),
        spectrum_channel_count=32,
        mitigate_rfi_average_method_threshold=100.0,
        mitigate_rfi_spectral_kurtosis_threshold=2.0,
        baseband_reserve_sample=True,  # the ring rung is live
        writer_thread_count=0, fft_strategy="four_step",
        inflight_segments=2, retry_backoff_base_s=0.001, **extra)


class _CaptureSink:
    def __init__(self):
        self.out = []
        self.positives = []

    def push(self, work, positive):
        det = work.detect
        self.out.append((np.asarray(det.signal_counts).copy(),
                         np.asarray(det.zero_count).copy(),
                         np.asarray(det.time_series).copy()))
        self.positives.append(bool(positive))


def _assert_decisions_equal(a: _CaptureSink, b: _CaptureSink,
                            ts_exact=True):
    assert len(a.out) == len(b.out)
    for (sc_a, zc_a, ts_a), (sc_b, zc_b, ts_b) in zip(a.out, b.out):
        np.testing.assert_array_equal(sc_a, sc_b)
        np.testing.assert_array_equal(zc_a, zc_b)
        if ts_exact:
            np.testing.assert_array_equal(ts_a, ts_b)
        else:  # demoted-plan documented tolerance (test_fusion.py)
            scale = float(np.abs(ts_b).max()) or 1.0
            np.testing.assert_allclose(ts_a, ts_b, rtol=0,
                                       atol=1e-3 * scale)
    assert a.positives == b.positives


@pytest.fixture(scope="module")
def clean_baseline(synth_file, tmp_path_factory):
    """Fault-free run with self-healing OFF: the parity reference."""
    tmp = tmp_path_factory.mktemp("clean")
    metrics.reset()
    sink = _CaptureSink()
    with Pipeline(_cfg(synth_file, tmp, "clean", plan_ladder="off",
                       device_reinit_max=0), sinks=[sink]) as pipe:
        stats = pipe.run()
    counters = {k: metrics.get(k) for k in ("h2d_bytes",
                                            "ring_cold_dispatches")}
    metrics.reset()
    assert stats.segments >= SEGMENTS  # overlap-save adds a tail seg
    return stats, sink, counters


def test_clean_run_with_ladder_armed_is_bit_identical(
        synth_file, tmp_path, clean_baseline):
    """Zero-cost off: arming the full self-healing stack on a healthy
    run changes nothing, bit for bit."""
    stats0, sink0, c0 = clean_baseline
    metrics.reset()
    sink = _CaptureSink()
    with Pipeline(_cfg(synth_file, tmp_path, "armed",
                       promote_after_segments=2),
                  sinks=[sink]) as pipe:
        stats = pipe.run()
        assert pipe.healer is not None
        assert [r.step for r in pipe.healer.rungs]  # rungs resolved
    assert stats.segments == stats0.segments
    _assert_decisions_equal(sink, sink0, ts_exact=True)
    assert metrics.get("plan_demotions") == 0
    assert metrics.get("device_reinits") == 0
    assert metrics.get("plan_ladder_level") == 0
    # identical H2D traffic too: healing must not perturb the ring
    assert metrics.get("h2d_bytes") == c0["h2d_bytes"]
    metrics.reset()


def test_oom_at_dispatch_demotes_and_recovers(synth_file, tmp_path,
                                              clean_baseline):
    _, sink0, _ = clean_baseline
    from srtb_tpu.tools import telemetry_report as TR
    metrics.reset()
    jpath = str(tmp_path / "oom.jsonl")
    sink = _CaptureSink()
    with Pipeline(_cfg(synth_file, tmp_path, "oom",
                       fault_plan="dispatch:oom@1",
                       telemetry_journal_path=jpath),
                  sinks=[sink]) as pipe:
        stats = pipe.run()
        assert pipe.faults.unfired() == []
        assert pipe.healer.level == 1
        assert pipe.healer.active_step == "ring"
    assert stats.segments == len(sink0.out)
    # ring rung drops the ring only — outputs stay BIT-identical
    _assert_decisions_equal(sink, sink0, ts_exact=True)
    assert metrics.get("plan_demotions") == 1
    assert metrics.get("segments_dropped") == 0
    assert metrics.get("plan_ladder_level") == 1
    # v4 journal: counters + the active-plan timeline
    recs = TR.load(jpath)
    assert recs and all(r["v"] == 11 for r in recs)
    assert recs[-1]["plan_demotions"] == 1
    assert recs[-1]["plan_ladder_level"] == 1
    plans = {r.get("active_plan") for r in recs}
    assert all(p is not None for p in plans)
    rep = TR.report(jpath)
    assert rep["compute"]["plan_demotions"] == 1
    assert rep["compute"]["ladder_level_max"] == 1
    metrics.reset()


def test_compile_fault_at_fetch_demotes_and_recovers(
        synth_file, tmp_path, clean_baseline):
    """A compile fault surfacing at the FETCH site (lazy compile /
    execution error materializing at the blocking device_get): the
    segment's device results are gone — it must be re-dispatched from
    the retained host buffer under the demoted plan."""
    _, sink0, _ = clean_baseline
    metrics.reset()
    sink = _CaptureSink()
    with Pipeline(_cfg(synth_file, tmp_path, "cfail",
                       fault_plan="fetch:compile_fail@2"),
                  sinks=[sink]) as pipe:
        stats = pipe.run()
        assert pipe.faults.unfired() == []
    assert stats.segments == len(sink0.out)
    _assert_decisions_equal(sink, sink0, ts_exact=True)
    assert metrics.get("plan_demotions") == 1
    assert metrics.get("segments_dropped") == 0
    metrics.reset()


def test_device_halt_reinit_goes_cold_and_rebuilds(
        synth_file, tmp_path, clean_baseline):
    """The reinit regression satellite: after a device halt the warm
    ingest-ring carry and the old processor's program handles are
    dead.  Recovery must rebuild the processor, and every post-reinit
    dispatch must go COLD (full upload) instead of warm-assembling
    against the dead carry."""
    _, sink0, c0 = clean_baseline
    metrics.reset()
    sink = _CaptureSink()
    with Pipeline(_cfg(synth_file, tmp_path, "halt",
                       fault_plan="dispatch:device_halt@2"),
                  sinks=[sink]) as pipe:
        proc0 = pipe.processor
        assert proc0.ring
        stats = pipe.run()
        assert pipe.faults.unfired() == []
        proc1 = pipe.processor
    assert stats.segments == len(sink0.out)
    _assert_decisions_equal(sink, sink0, ts_exact=True)
    assert metrics.get("device_reinits") == 1
    assert metrics.get("plan_demotions") == 0  # same rung, new backend
    assert metrics.get("plan_ladder_level") == 0
    # the processor was rebuilt, and the old one is retired: a stray
    # dispatch against the dead handles raises instead of running
    assert proc1 is not proc0
    with pytest.raises(RuntimeError, match="retired"):
        proc0.run_device(np.zeros(proc1._segment_bytes, np.uint8))
    # post-reinit dispatches went cold: strictly more cold uploads
    # than the clean run's single ring-arming one
    assert metrics.get("ring_cold_dispatches") \
        > c0["ring_cold_dispatches"]
    assert metrics.get("h2d_bytes") > c0["h2d_bytes"]
    metrics.reset()


def test_reinit_budget_escalates(synth_file, tmp_path):
    metrics.reset()
    with Pipeline(_cfg(synth_file, tmp_path, "flap",
                       fault_plan=("dispatch:device_halt@1,"
                                   "fetch:device_halt@2"),
                       device_reinit_max=1), sinks=[]) as pipe:
        # the escaped exception is the TYPED FatAL escalation (an
        # outer supervisor must see FATAL, never a restartable
        # DEVICE), still carrying the original device error text
        with pytest.raises(E.ReinitBudgetExceeded, match="halted"):
            pipe.run()
    assert metrics.get("device_reinits") == 1  # budget spent, then loud
    # reinit budgeting must NOT masquerade as worker restarts
    assert metrics.get("worker_restarts") == 0
    metrics.reset()


def test_ladder_exhausted_escalates(synth_file, tmp_path):
    """plan_ladder restricted to ONE rung: the second oom has nowhere
    to go and must escalate with the original device error."""
    metrics.reset()
    with Pipeline(_cfg(synth_file, tmp_path, "exh",
                       plan_ladder="monolithic",
                       fault_plan="dispatch:oom@1,dispatch:oom@2"),
                  sinks=[]) as pipe:
        assert [r.step for r in pipe.healer.rungs] == ["monolithic"]
        with pytest.raises(E.LadderExhausted,
                           match="RESOURCE_EXHAUSTED"):
            pipe.run()
    assert metrics.get("plan_demotions") == 1
    metrics.reset()


def test_healing_disabled_escalates(synth_file, tmp_path):
    metrics.reset()
    with Pipeline(_cfg(synth_file, tmp_path, "off",
                       plan_ladder="off", device_reinit_max=0,
                       fault_plan="dispatch:oom@1"),
                  sinks=[]) as pipe:
        assert pipe.healer is None
        with pytest.raises(Exception, match="RESOURCE_EXHAUSTED"):
            pipe.run()
    assert metrics.get("plan_demotions") == 0
    metrics.reset()


def test_promotion_probe_returns_to_full_plan(synth_file, tmp_path,
                                              clean_baseline):
    _, sink0, _ = clean_baseline
    metrics.reset()
    sink = _CaptureSink()
    with Pipeline(_cfg(synth_file, tmp_path, "promo",
                       fault_plan="dispatch:oom@1",
                       promote_after_segments=1),
                  sinks=[sink]) as pipe:
        stats = pipe.run()
        assert pipe.healer.level == 0  # probed back up and stayed
    assert stats.segments == len(sink0.out)
    _assert_decisions_equal(sink, sink0, ts_exact=True)
    assert metrics.get("plan_demotions") == 1
    assert metrics.get("plan_promotions") >= 1
    assert metrics.get("plan_ladder_level") == 0
    metrics.reset()


def test_threaded_pipeline_demotes_on_oom(synth_file, tmp_path,
                                          clean_baseline):
    _, sink0, _ = clean_baseline
    metrics.reset()
    sink = _CaptureSink()
    with ThreadedPipeline(_cfg(synth_file, tmp_path, "thr",
                               fault_plan="dispatch:oom@1"),
                          sinks=[sink]) as pipe:
        stats = pipe.run()
        assert pipe.faults.unfired() == []
    assert stats.segments == len(sink0.out)
    _assert_decisions_equal(sink, sink0, ts_exact=True)
    assert metrics.get("plan_demotions") == 1
    metrics.reset()


# --------------------------------- interplay with existing machinery


class _StubDetect(NamedTuple):
    signal_counts: object
    zero_count: object
    time_series: object


class _NeverReady:
    def is_ready(self) -> bool:
        return False

    def __array__(self, dtype=None, copy=None):
        raise AssertionError("a cancelled segment's results were read")


def _stub_result(raw):
    val = float(np.asarray(raw, dtype=np.float32).sum())
    return None, _StubDetect(
        signal_counts=np.zeros((1, 4), np.int64),
        zero_count=np.asarray(0),
        time_series=np.asarray([val], np.float32))


class _InstantProcessor:
    def process(self, raw):
        return _stub_result(raw)


class _WedgeThenOOMProcessor:
    """Segment 0's first dispatch: never-ready -> watchdog requeue.
    Segment 0's SECOND dispatch (the requeue) raises a device OOM ->
    demotion.  Keyed on the segment's bytes, not a global dispatch
    counter: other in-flight segments dispatch in between."""

    def __init__(self):
        self.seg0_dispatches = 0

    def process(self, raw):
        if int(np.asarray(raw)[0]) == 1:  # _CountingSource segment 0
            self.seg0_dispatches += 1
            if self.seg0_dispatches == 1:
                return None, _StubDetect(_NeverReady(), _NeverReady(),
                                         _NeverReady())
            if self.seg0_dispatches == 2:
                raise _FakeXla(_OOM_MSG)
        return _stub_result(raw)


class _CountingSource:
    def __init__(self, n_segments: int, seg_bytes: int = 64):
        self.n = n_segments
        self.seg_bytes = seg_bytes
        self._i = 0

    def __iter__(self):
        return self

    def __next__(self) -> SegmentWork:
        if self._i >= self.n:
            raise StopIteration
        self._i += 1
        return SegmentWork(
            data=np.full(self.seg_bytes, self._i, np.uint8),
            timestamp=self._i)


def _stub_cfg(tmp_path, tag, **extra):
    return Config(baseband_input_count=64,
                  baseband_reserve_sample=False,
                  writer_thread_count=0,
                  retry_backoff_base_s=0.001,
                  telemetry_journal_path=str(tmp_path / f"{tag}.jsonl"),
                  **extra)


def test_demotion_of_watchdog_requeued_segment(tmp_path):
    """The watchdog cancels a wedged segment and re-dispatches it;
    the re-dispatch hits an OOM.  The heal path inside the requeue
    must demote and retry the SAME segment — requeue and demotion
    compose, neither mechanism loses the segment."""
    metrics.reset()
    cfg = _stub_cfg(tmp_path, "wdheal", inflight_segments=2,
                    segment_deadline_s=0.12,
                    segment_watchdog_requeues=2)
    sink = _CaptureSink()
    pipe = Pipeline(cfg, source=_CountingSource(4), sinks=[sink],
                    processor=_WedgeThenOOMProcessor())
    # the demoted "plan" for a stub pipeline is another stub
    pipe.healer._factory = lambda cfg, staged: _InstantProcessor()
    with pipe:
        stats = pipe.run()
    assert stats.segments == 4 and len(sink.out) == 4
    assert metrics.get("watchdog_requeues") == 1
    assert metrics.get("plan_demotions") == 1
    assert metrics.get("segments_dropped") == 0
    # decisions: every segment's stub value is the sum of its bytes —
    # segment 0 (wedged, then demoted) included
    vals = [float(ts[0]) for _, _, ts in sink.out]
    assert vals == [64.0 * (i + 1) for i in range(4)]
    metrics.reset()


class _OOMOnceProcessor:
    def __init__(self, fault_at: int):
        self.fault_at = fault_at
        self.dispatches = 0
        self.faulted = False

    def process(self, raw):
        self.dispatches += 1
        if self.dispatches == self.fault_at and not self.faulted:
            self.faulted = True
            raise _FakeXla(_OOM_MSG)
        return _stub_result(raw)


class _SlowSink:
    """Real-time-slow sheddable sink: every push stalls long enough
    that the engine observes sink pressure and walks the degradation
    ladder."""

    sheddable = True

    def __init__(self, sink_s: float):
        self.sink_s = sink_s
        self.pushed = 0

    def push(self, work, positive):
        self.pushed += 1
        time.sleep(self.sink_s)


def test_demotion_under_active_degrade_ladder(tmp_path):
    """Both ladders at once: a real-time source with a slow sink
    drives the DEGRADATION ladder up while a device OOM demotes the
    COMPUTE ladder — independent state machines, both accounted, and
    the journal carries both levels."""
    from srtb_tpu.tools import telemetry_report as TR
    metrics.reset()
    n_seg = 10
    cfg = _stub_cfg(tmp_path, "dual", inflight_segments=2,
                    degrade_enable=True, degrade_queue_high=0.5,
                    degrade_hold_segments=1)
    proc = _OOMOnceProcessor(fault_at=4)
    pipe = Pipeline(cfg, source=_CountingSource(n_seg),
                    sinks=[_SlowSink(0.05)], processor=proc)
    pipe.healer._factory = lambda cfg, staged: _InstantProcessor()
    with pipe:
        stats = pipe.run()
    assert stats.segments == n_seg
    assert metrics.get("plan_demotions") == 1
    assert metrics.get("degrade_steps") >= 1
    recs = TR.load(str(tmp_path / "dual.jsonl"))
    assert any(r["degrade_level"] > 0 and r["plan_ladder_level"] > 0
               for r in recs), "both ladders never active together"
    metrics.reset()


def test_checkpoint_resume_after_demotion_offsets_unchanged(
        synth_file, tmp_path, clean_baseline):
    """A run that demoted mid-stream checkpoints the same offsets as
    one that never faulted — the demoted plan changes the compute,
    never the stream bookkeeping — and a resume completes the
    remainder with decision-identical output."""
    _, sink0, _ = clean_baseline
    ck_clean = str(tmp_path / "ck_clean.json")
    ck_heal = str(tmp_path / "ck_heal.json")
    # clean checkpointed run, first 2 segments
    metrics.reset()
    with Pipeline(_cfg(synth_file, tmp_path, "ckc",
                       checkpoint_path=ck_clean), sinks=[]) as pipe:
        pipe.run(max_segments=2)
    with open(ck_clean) as f:
        state_clean = json.load(f)
    # demoted run, same 2 segments (oom at segment 1)
    metrics.reset()
    sink = _CaptureSink()
    with Pipeline(_cfg(synth_file, tmp_path, "ckh",
                       checkpoint_path=ck_heal,
                       fault_plan="dispatch:oom@1"),
                  sinks=[sink]) as pipe:
        pipe.run(max_segments=2)
        assert pipe.healer.level == 1
    with open(ck_heal) as f:
        state_heal = json.load(f)
    assert state_heal == state_clean  # resume offsets unchanged
    # resume the demoted run to completion: a fresh process starts at
    # ladder level 0 (full plan) and finishes the stream
    metrics.reset()
    with Pipeline(_cfg(synth_file, tmp_path, "ckh",
                       checkpoint_path=ck_heal),
                  sinks=[sink]) as pipe:
        assert pipe.healer.level == 0
        pipe.run()
    assert len(sink.out) == len(sink0.out)
    _assert_decisions_equal(sink, sink0, ts_exact=True)
    metrics.reset()


def test_micro_batch_demotion_drops_batch_unit(tmp_path):
    """The first rung of a micro-batching run drops the batch: the
    engine's dispatch unit must follow (the demoted plan has no batch
    programs), and every segment still drains exactly once."""

    class _BatchOOMProcessor:
        """Stub micro-batch processor whose FIRST batch dispatch
        OOMs; the healed (stub) replacement is single-segment."""

        def __init__(self):
            self.batches = 0

        def process(self, raw):
            return _stub_result(raw)

        def process_batch(self, raws):
            self.batches += 1
            raise _FakeXla(_OOM_MSG)

        def stack_batch(self, datas, stride_only=False):
            return np.stack([np.ascontiguousarray(d) for d in datas])

    metrics.reset()
    cfg = _stub_cfg(tmp_path, "mb", inflight_segments=2,
                    micro_batch_segments=2)
    sink = _CaptureSink()
    pipe = Pipeline(cfg, source=_CountingSource(5), sinks=[sink],
                    processor=_BatchOOMProcessor())
    assert pipe.healer.micro_batch == 2
    pipe.healer._factory = lambda cfg, staged: _InstantProcessor()
    with pipe:
        stats = pipe.run()
    assert stats.segments == 5 and len(sink.out) == 5
    assert metrics.get("plan_demotions") == 1
    assert pipe.healer.active_step == "micro_batch"
    assert pipe.healer.micro_batch == 1  # the engine unit followed
    vals = [float(ts[0]) for _, _, ts in sink.out]
    assert vals == [64.0 * (i + 1) for i in range(5)]
    metrics.reset()


class _BatchStub:
    """Working micro-batch stub (the promoted plan)."""

    def process(self, raw):
        return _stub_result(raw)

    def process_batch(self, raws):
        vals = raws.astype(np.float32).sum(axis=1)
        det = _StubDetect(
            signal_counts=np.zeros((len(raws), 1, 4), np.int64),
            zero_count=np.zeros(len(raws), np.int64),
            time_series=vals.reshape(-1, 1).astype(np.float32))
        return [None] * len(raws), det


class _BatchOOMFirstStub(_BatchStub):
    """The initial plan: its FIRST batch dispatch OOMs."""

    def __init__(self):
        self.batches = 0

    def process_batch(self, raws):
        self.batches += 1
        if self.batches == 1:
            raise _FakeXla(_OOM_MSG)
        return super().process_batch(raws)


def test_promotion_restores_micro_batch_within_window(tmp_path):
    """Promotion restores the micro-batch rung mid-run: the engine's
    dispatch unit grows back to B, and the in-flight window bound
    must hold across the transition (the probe re-checks admission
    with the PROMOTED unit — regression for the probe dispatching a
    unit that overflows the window)."""
    from srtb_tpu.tools import telemetry_report as TR
    metrics.reset()
    window = 2
    cfg = _stub_cfg(tmp_path, "promo_mb", inflight_segments=window,
                    micro_batch_segments=2, promote_after_segments=1)
    sink = _CaptureSink()
    pipe = Pipeline(cfg, source=_CountingSource(8), sinks=[sink],
                    processor=_BatchOOMFirstStub())

    def factory(c, staged):
        mb = int(getattr(c, "micro_batch_segments", 1) or 1)
        return _BatchStub() if mb > 1 else _InstantProcessor()

    pipe.healer._factory = factory
    with pipe:
        stats = pipe.run()
    assert stats.segments == 8 and len(sink.out) == 8
    assert metrics.get("plan_demotions") == 1
    assert metrics.get("plan_promotions") >= 1
    assert pipe.healer.micro_batch == 2  # promoted plan batches again
    vals = [float(ts.ravel()[0]) for _, _, ts in sink.out]
    assert vals == [64.0 * (i + 1) for i in range(8)]
    # the window bound held through demotion AND promotion: no drain
    # ever observed more than `window` segments in flight
    recs = TR.load(str(tmp_path / "promo_mb.jsonl"))
    depths = [r["inflight_depth"] for r in recs
              if "inflight_depth" in r]
    assert depths and max(depths) <= window
    metrics.reset()


# ------------------------------------------------- chaos soak harness


def test_chaos_soak_gate_passes_on_seeded_plan(tmp_path):
    from srtb_tpu.tools import chaos_soak as CS
    report = CS.run_soak(seed=11, segments=3, faults=2, log2n=12,
                         tmpdir=str(tmp_path))
    assert report["ok"]
    assert report["drained"] + report["dropped"] == report["segments"]


def test_chaos_soak_plan_generator_is_seeded_and_capped():
    from srtb_tpu.tools import chaos_soak as CS
    a = CS.generate_plan(5, segments=8, faults=6, max_demotions=2,
                         max_halts=1)
    assert a == CS.generate_plan(5, segments=8, faults=6,
                                 max_demotions=2, max_halts=1)
    specs = parse_plan(a)
    assert sum(1 for s in specs
               if s.action in ("oom", "compile_fail")) <= 2
    assert sum(1 for s in specs if s.action == "device_halt") <= 1
    assert all(0 < s.index < 8 for s in specs)


@pytest.mark.slow
def test_chaos_soak_selftest_is_sharp():
    from srtb_tpu.tools import chaos_soak as CS
    assert CS.selftest(log2n=12) == []


# ------------------------------------------ plan-audit ladder targets


def test_audit_ladder_targets_are_carded():
    """Every demotion-ladder rung from the fully-featured audit config
    resolves to a checked-in plan card; an empty baseline makes the
    gate fire for every rung."""
    from srtb_tpu.analysis import hlo_audit as HA
    baseline = HA.CardBaseline.load(HA.DEFAULT_BASELINE)
    assert baseline.cards, "checked-in plan_cards.json missing"
    assert HA.audit_ladder(baseline) == []
    missing = HA.audit_ladder(HA.CardBaseline())
    assert missing and all("UNAUDITED" in m for m in missing)
