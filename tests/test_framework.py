"""Host pipeline-framework tests: bounded queues, stop tokens, composite
pipes, sentinel propagation, lossy push."""

import time

from srtb_tpu.pipeline import framework as fw


def test_queue_capacity_and_lossy():
    q = fw.WorkQueue(capacity=2)
    assert q.push_lossy(1) and q.push_lossy(2)
    assert not q.push_lossy(3)  # full -> dropped
    assert q.pop() == 1


def test_pipeline_chain():
    stop = fw.StopToken()
    q1, q2 = fw.WorkQueue(), fw.WorkQueue()
    results = []

    counter = {"n": 0}

    def source(stop_token, _):
        counter["n"] += 1
        if counter["n"] > 5:
            raise StopIteration
        return counter["n"]

    def double(stop_token, x):
        return 2 * x

    def sink(stop_token, x):
        results.append(x)
        return None

    pipes = [
        fw.start_pipe(source, None, q1, stop),
        fw.start_pipe(double, q1, q2, stop),
        fw.start_pipe(sink, q2, None, stop),
    ]
    deadline = time.time() + 5
    while len(results) < 5 and time.time() < deadline:
        time.sleep(0.01)
    fw.on_exit(stop, pipes)
    assert results == [2, 4, 6, 8, 10]
    assert all(p.exception is None for p in pipes)


def test_composite_fusion():
    f = fw.composite(lambda st, x: x + 1, lambda st, x: x * 10)
    assert f(None, 2) == 30
    g = fw.composite(lambda st, x: None, lambda st, x: x * 10)
    assert g(None, 2) is None  # drop propagates


def test_stop_token_unblocks():
    stop = fw.StopToken()
    q = fw.WorkQueue(capacity=1)

    def blocked_source(stop_token, _):
        return 1  # push side will block on full queue

    p = fw.start_pipe(blocked_source, None, q, stop)
    time.sleep(0.1)
    fw.on_exit(stop, [p], timeout=2.0)
    assert not p.thread.is_alive()
