"""bench.py is the driver's scoreboard: it must always emit one valid
JSON line, whatever backend it lands on.  Run it tiny on CPU."""

import json
import os
import subprocess
import sys


def test_bench_emits_one_json_line(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    env["SRTB_BENCH_LOG2N"] = "16"
    out = subprocess.run(
        [sys.executable, os.path.join(env["PYTHONPATH"], "bench.py")],
        env=env, capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [ln for ln in out.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert len(lines) == 1, out.stdout
    rec = json.loads(lines[0])
    assert set(rec) == {"metric", "value", "unit", "vs_baseline"}
    assert rec["value"] > 0 and rec["vs_baseline"] > 0


def test_kernel_bench_runs():
    from srtb_tpu.tools import kernel_bench
    import io
    from contextlib import redirect_stdout
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = kernel_bench.main(["--log2n", "16", "--reps", "1",
                                "--pixmap", "64x128"])
    assert rc == 0
    lines = [json.loads(ln) for ln in buf.getvalue().splitlines()]
    assert len(lines) >= 5
    assert all(rec["ms"] > 0 for rec in lines if "ms" in rec)


def test_bench_knob_variants(tmp_path):
    # the A/B knobs must not break the script (four_step + pallas path)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    env["SRTB_BENCH_LOG2N"] = "16"
    env["SRTB_BENCH_FFT_STRATEGY"] = "four_step"
    env["SRTB_BENCH_USE_PALLAS"] = "1"
    out = subprocess.run(
        [sys.executable, os.path.join(env["PYTHONPATH"], "bench.py")],
        env=env, capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads([ln for ln in out.stdout.strip().splitlines()
                      if ln.startswith("{")][0])
    assert rec["value"] > 0
