"""bench.py is the driver's scoreboard: it must always emit one valid
JSON line, whatever backend it lands on.  Run it tiny on CPU."""

import json
import os
import subprocess
import sys


def test_bench_emits_one_json_line(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    env["SRTB_BENCH_LOG2N"] = "16"
    out = subprocess.run(
        [sys.executable, os.path.join(env["PYTHONPATH"], "bench.py")],
        env=env, capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [ln for ln in out.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert len(lines) == 1, out.stdout
    rec = json.loads(lines[0])
    assert {"metric", "value", "unit", "vs_baseline"} <= set(rec)
    assert rec["value"] > 0 and rec["vs_baseline"] > 0, rec
    # roofline fields (PERF.md): fast must be falsifiable.  roofline_frac
    # itself only appears on accelerator runs (no v5e peak to compare a
    # CPU measurement against)
    assert {"achieved_gbps", "model_gflops", "model_hbm_gb"} <= set(rec)
    assert rec["achieved_gbps"] > 0
    # the BASELINE gate field: a CPU run can never pass the chip target
    assert rec["pass"] is False


def test_bench_survives_unreachable_accelerator(tmp_path):
    """The round-1 failure mode: accelerator backend init hangs/crashes.
    bench.py must still exit 0 with one JSON line (CPU fallback)."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    # pin the probe to a platform that cannot exist so the fallback branch
    # runs deterministically on any machine, healthy accelerator or not
    env["SRTB_BENCH_PROBE_PLATFORM"] = "no_such_platform"
    env["SRTB_BENCH_INIT_TIMEOUT"] = "30"
    env["SRTB_BENCH_RETRY_BUDGET"] = "0"  # no retry-over-minutes in CI
    env["SRTB_BENCH_LOG2N"] = "16"  # small on every platform
    out = subprocess.run(
        [sys.executable, os.path.join(env["PYTHONPATH"], "bench.py")],
        env=env, capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [ln for ln in out.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert len(lines) == 1, out.stdout
    rec = json.loads(lines[0])
    assert rec["value"] > 0, rec  # CPU fallback still measured something
    assert rec["platform"] == "cpu"
    assert rec.get("accelerator_error"), rec  # fallback branch really ran
    assert rec["pass"] is False


def test_bench_probes_preset_platform(tmp_path):
    """The round-2 failure mode: the driver *pins* JAX_PLATFORMS to a
    platform whose tunnel is down.  The old code trusted the preset and
    skipped the probe, so the main process died on backend init (value
    0.0).  Now the preset is probed and, on failure, the bench falls back
    to a real CPU measurement with the error attached."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "no_such_platform"  # preset, and unreachable
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    env["SRTB_BENCH_INIT_TIMEOUT"] = "30"
    env["SRTB_BENCH_RETRY_BUDGET"] = "0"
    env["SRTB_BENCH_LOG2N"] = "16"
    out = subprocess.run(
        [sys.executable, os.path.join(env["PYTHONPATH"], "bench.py")],
        env=env, capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [ln for ln in out.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert len(lines) == 1, out.stdout
    rec = json.loads(lines[0])
    assert rec["value"] > 0, rec  # fell back to a *measured* CPU run
    assert rec["platform"] == "cpu"
    assert "preset" in (rec.get("accelerator_error") or ""), rec


def test_kernel_bench_runs():
    from srtb_tpu.tools import kernel_bench
    import io
    from contextlib import redirect_stdout
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = kernel_bench.main(["--log2n", "16", "--reps", "1",
                                "--pixmap", "64x128"])
    assert rc == 0
    lines = [json.loads(ln) for ln in buf.getvalue().splitlines()]
    assert len(lines) >= 5
    assert all(rec["ms"] > 0 for rec in lines if "ms" in rec)


def test_bench_knob_variants(tmp_path):
    # the A/B knobs must not break the script (four_step + pallas path)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    env["SRTB_BENCH_LOG2N"] = "16"
    env["SRTB_BENCH_FFT_STRATEGY"] = "four_step"
    env["SRTB_BENCH_USE_PALLAS"] = "1"
    out = subprocess.run(
        [sys.executable, os.path.join(env["PYTHONPATH"], "bench.py")],
        env=env, capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads([ln for ln in out.stdout.strip().splitlines()
                      if ln.startswith("{")][0])
    assert rec["value"] > 0


def test_baseline_pass_gate():
    """VERDICT r3 #9: the >= 1x real-time gate, both branches — only an
    accelerator platform at >= 1x may report pass."""
    import bench
    assert bench.baseline_pass(True, 1.0) is True
    assert bench.baseline_pass(True, 13.6) is True
    assert bench.baseline_pass(True, 0.99) is False
    assert bench.baseline_pass(False, 5.0) is False
