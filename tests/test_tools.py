"""Tools + GUI service tests: correlator (vs numpy oracle), waterfall PNG
service (test-gui analog: synthetic spectra into the real renderer,
ref: src/test-gui.cpp), main CLI smoke test, filterbank header."""

import os
import struct
import zlib

import numpy as np

from srtb_tpu.config import Config
from srtb_tpu.io.writers import encode_angle_dms, write_filterbank_header
from srtb_tpu.tools.correlator import correlate
from srtb_tpu.gui.waterfall import WaterfallService, write_png


def test_correlator_peak_at_lag():
    """Cross-correlating a shifted copy peaks at the shift
    (ref math: correlator.cpp:109-140)."""
    rng = np.random.default_rng(0)
    n = 1 << 12
    lag = 37
    # zero-mean signed samples; with unsigned offset-binary data the DC bin
    # adds a constant baseline at every lag (same behavior as the reference,
    # which applies no mean removal either)
    base = rng.integers(-50, 50, size=n + lag).astype(np.int8)
    x1 = base[:n]
    x2 = base[lag:lag + n]
    corr = correlate(x1, x2)
    assert corr.shape == (n // 2,)
    # the correlation is computed on the half-spectrum (analytic signal),
    # as in the reference: n/2 output points span n samples, so the peak
    # appears at lag/2 with 2-sample resolution
    assert abs(int(np.argmax(corr)) - lag // 2) <= 1


def test_waterfall_service_png(tmp_path):
    cfg = Config(gui_pixmap_width=64, gui_pixmap_height=48)
    svc = WaterfallService(cfg, in_freq=128, in_time=256,
                           out_dir=str(tmp_path))
    rng = np.random.default_rng(1)
    wf_ri = rng.standard_normal((2, 128, 256)).astype(np.float32)
    svc.push(wf_ri, data_stream_id=0)
    svc.push(wf_ri * 2, data_stream_id=0)  # lossy: replaces frame 1
    path = svc.render_pending()
    assert path is not None and os.path.exists(path)
    assert svc.render_pending() is None  # nothing pending

    with open(path, "rb") as f:
        data = f.read()
    assert data[:8] == b"\x89PNG\r\n\x1a\n"
    w, h = struct.unpack(">II", data[16:24])
    assert (w, h) == (64, 48)
    # decode and spot-check a pixel is valid RGBA
    idat = data[data.index(b"IDAT") + 4:data.index(b"IEND") - 4]
    raw = zlib.decompress(idat)
    assert len(raw) == 48 * (64 * 4 + 1)


def test_write_png_roundtrip(tmp_path):
    argb = np.full((4, 5), 0xFF112233, dtype=np.uint32)
    p = str(tmp_path / "t.png")
    write_png(p, argb)
    with open(p, "rb") as f:
        data = f.read()
    raw = zlib.decompress(data[data.index(b"IDAT") + 4:
                               data.index(b"IEND") - 4])
    row0 = raw[1:21]
    assert row0[:4] == bytes([0x11, 0x22, 0x33, 0xFF])  # RGBA order


def test_filterbank_header(tmp_path):
    p = str(tmp_path / "fb.fil")
    with open(p, "wb") as f:
        write_filterbank_header(f, fch1=1469.0, foff=-0.03125, nchans=2048,
                                tsamp=3.2e-5, source_name="J1644-4559",
                                src_raj=encode_angle_dms(16, 44, 49.3),
                                src_dej=encode_angle_dms(-45, 59, 9.5))
    data = open(p, "rb").read()
    assert data.startswith(struct.pack("<i", 12) + b"HEADER_START")
    assert b"HEADER_END" in data
    assert b"source_name" in data
    # decode fch1
    i = data.index(b"fch1") + 4
    assert struct.unpack("<d", data[i:i + 8])[0] == 1469.0


def test_encode_angle_dms():
    assert encode_angle_dms(16, 44, 49.3) == 164449.3
    assert encode_angle_dms(-45, 59, 9.5) == -455909.5


def test_main_cli_on_file(tmp_path):
    """Smoke-test the main tool end to end on a small synthetic file."""
    from srtb_tpu.tools.main import main
    rng = np.random.default_rng(0)
    n = 1 << 14
    raw = rng.integers(0, 256, size=n, dtype=np.uint8)
    in_path = str(tmp_path / "in.bin")
    raw.tofile(in_path)
    rc = main([
        "--input_file_path", in_path,
        "--baseband_input_count", str(n),
        "--baseband_input_bits", "8",
        "--spectrum_channel_count", "2**6",
        "--signal_detect_max_boxcar_length", "16",
        "--baseband_output_file_prefix", str(tmp_path / "out_"),
        "--baseband_reserve_sample", "0",
        "--gui_enable", "1",
        "--gui_pixmap_width", "32",
        "--gui_pixmap_height", "24",
    ])
    assert rc == 0
    pngs = [f for f in os.listdir(tmp_path) if f.endswith(".png")]
    assert pngs, "gui_enable must produce waterfall PNGs"


def test_waterfall_spectrum_sum_count(tmp_path):
    """spectrum_sum_count: sum N segments' power before drawing
    (ref: config.hpp:196-200)."""
    cfg = Config(gui_pixmap_width=32, gui_pixmap_height=16,
                 spectrum_sum_count=3)
    svc = WaterfallService(cfg, in_freq=64, in_time=64,
                           out_dir=str(tmp_path))
    rng = np.random.default_rng(2)
    wf = rng.standard_normal((2, 64, 64)).astype(np.float32)
    svc.push(wf); assert svc.render_pending() is None
    svc.push(wf); assert svc.render_pending() is None
    svc.push(wf)
    path = svc.render_pending()
    assert path is not None and os.path.exists(path)


def test_waterfall_http_server(tmp_path):
    """Live viewer: index page lists the latest frame per stream and serves
    the PNG bytes."""
    import urllib.request
    from srtb_tpu.gui.server import WaterfallHTTPServer

    cfg = Config(gui_pixmap_width=16, gui_pixmap_height=8)
    svc = WaterfallService(cfg, in_freq=32, in_time=32,
                           out_dir=str(tmp_path))
    svc.push(np.random.default_rng(0)
             .standard_normal((2, 32, 32)).astype(np.float32))
    svc.render_pending()

    srv = WaterfallHTTPServer(str(tmp_path)).start()
    try:
        idx = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/").read().decode()
        assert "waterfall_s0_000000.png" in idx
        png = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/waterfall_s0_000000.png").read()
        assert png[:8] == b"\x89PNG\r\n\x1a\n"
    finally:
        srv.stop()


def test_scrolling_waterfall_and_scheduler():
    """Legacy scrolling provider analog: lines scroll through a persistent
    image; the 3n+1 scheduler grows while a backlog remains and halves
    once caught up (ref: gui/spectrum_image_provider.hpp:79-102)."""
    from srtb_tpu.gui.waterfall import RequestSizeScheduler, ScrollingWaterfall

    s = RequestSizeScheduler()
    assert s.get_next_request_size() == 1
    s.set_last_size_too_few(True)
    assert s.get_next_request_size() == 4      # 3*1+1
    s.set_last_size_too_few(True)
    assert s.get_next_request_size() == 13     # 3*4+1
    s.set_last_size_too_few(False)
    assert s.get_next_request_size() == 6
    for _ in range(5):
        s.set_last_size_too_few(False)
    assert s.get_next_request_size() == 1      # floor at 1

    in_freq, w, h = 64, 32, 16
    sw = ScrollingWaterfall(in_freq, width=w, height=h)
    rng = np.random.default_rng(0)
    for i in range(40):
        spec = np.zeros(in_freq, dtype=np.float32)
        spec[:] = 0.1
        spec[i % in_freq] = float(i + 1)       # marker per line
        sw.push_spectrum(spec)
    consumed = 0
    rounds = 0
    while consumed < 40 and rounds < 50:
        consumed += sw.consume()
        rounds += 1
    assert consumed == 40 and sw.lines_total == 40
    # newest line sits at the TOP of the scroll window (reference scrolls
    # down, painting new lines at y=0)
    assert abs(sw._img[0].max() - 40.1) < 1e-3
    pix = sw.render()
    assert pix.shape == (h, w) and pix.dtype == np.uint32
    # catching up took adaptive batches: fewer rounds than lines
    assert rounds < 40
    # partially-filled window must not paint data as overflow color
    from srtb_tpu.ops.spectrum import COLOR_OVERFLOW
    sw2 = ScrollingWaterfall(in_freq, width=w, height=h)
    sw2.push_spectrum(np.full(in_freq, 0.5, dtype=np.float32))
    sw2.consume()
    pix2 = sw2.render()
    assert not (pix2[0] == np.uint32(COLOR_OVERFLOW)).any()


def test_main_cli_scrolling_gui(tmp_path):
    """gui_scroll_lines selects the legacy scrolling provider through the
    real CLI and produces a scroll image."""
    from srtb_tpu.tools.main import main
    rng = np.random.default_rng(0)
    n = 1 << 14
    rng.integers(0, 256, size=2 * n, dtype=np.uint8).tofile(
        str(tmp_path / "in.bin"))
    rc = main([
        "--input_file_path", str(tmp_path / "in.bin"),
        "--baseband_input_count", str(n),
        "--baseband_input_bits", "8",
        "--spectrum_channel_count", "2**6",
        "--signal_detect_max_boxcar_length", "16",
        "--baseband_output_file_prefix", str(tmp_path / "out_"),
        "--baseband_reserve_sample", "0",
        "--gui_enable", "1",
        "--gui_scroll_lines", "4",
        "--gui_pixmap_width", "32",
        "--gui_pixmap_height", "24",
    ])
    assert rc == 0
    assert os.path.exists(str(tmp_path / "waterfall_s0_scroll.png"))


def test_test_gui_tool(tmp_path):
    """The test-gui analog (ref: src/test-gui.cpp): synthetic spectra
    through both real waterfall providers, PNGs on disk."""
    from srtb_tpu.tools.test_gui import main

    out = str(tmp_path / "gui")
    rc = main(["--out", out, "--frames", "2", "--streams", "1",
               "--freq", "64", "--time", "128", "--scroll-lines", "4"])
    assert rc == 0
    names = sorted(p.name for p in (tmp_path / "gui").iterdir())
    assert "waterfall_s0_000000.png" in names
    assert "waterfall_s0_scroll.png" in names


def test_e2e_live_harness_smoke(tmp_path):
    """The live UDP->device->candidates harness must run end to end on
    loopback: paced sender, segment assembly, threaded pipeline, live
    /metrics over HTTP, one JSON artifact line."""
    import json

    from srtb_tpu.tools import e2e_live

    out = tmp_path / "e2e.jsonl"
    rc = e2e_live.main([
        "--seconds", "1.5", "--rate_x", "0.05", "--log2n", "18",
        "--log2chan", "7", "--port", "42157", "--deadline_s", "60",
        "--gui", "--gui_min_interval_s", "0.2",
        "--prefix", str(tmp_path) + "/out_", "--out", str(out)])
    assert rc == 0
    rec = json.loads(out.read_text().splitlines()[-1])
    assert rec["segments"] >= 1
    assert rec["packets_total"] > 0
    assert rec["metrics_http"]["segments"] == rec["segments"]
    # the HTTP server must list the tap's rendered frames (regression:
    # serving the prefix instead of its directory kept /frames.json
    # empty forever)
    assert rec["gui_frames"] >= 1
    assert rec["gui_frames_served"] >= 1
    # both throughput denominators present and labeled (VERDICT r4 #5)
    assert rec["msamples_per_s_window"] > 0
    assert rec["lifetime_seconds"] >= rec["seconds"]
    # deadline armed for real above (60 s >> per-segment time): reaching
    # the artifact line at all is the no-hit evidence
    assert rec["deadline_s"] == 60


def test_e2e_live_overload_degrades_gracefully(tmp_path):
    """Overload mode (VERDICT r4 #5): offer wire-rate load far above the
    CPU compute rate and require the reference's never-stall-on-loss
    property (ref: io/udp/udp_receiver.hpp:129-164): the pipeline keeps
    draining segments, excess packets fall off the kernel socket buffer
    and surface as *accounted* counter-gap loss, and the run terminates
    cleanly instead of stalling or crashing."""
    import json

    from srtb_tpu.tools import e2e_live

    # The overload is statistical: the OS scheduler occasionally
    # starves the paced sender so thoroughly that the bounded 6-segment
    # run completes before any excess builds up — observed as a clean
    # zero-loss record (all offered packets consumed, no stall), i.e.
    # the HARNESS failed to create overload, not the pipeline failing
    # to account it.  Such inconclusive runs are retried on a fresh
    # port (bounded); a stall/crash/unaccounted-loss run still fails
    # immediately on its own assertions.
    for attempt, port in enumerate((42161, 42261, 42361)):
        out = tmp_path / f"e2e_overload_{attempt}.jsonl"
        rc = e2e_live.main([
            # rate_x 2.0 = twice the 128 MSa/s wire pace; single-core
            # CPU compute at 2^18 is far slower, so overload is
            # structural, and the 32 KB rcvbuf (= half of one
            # 16-packet block) makes the overflow near-deterministic
            # even when the OS scheduler starves the sender (observed
            # flaky at 256 KB on a 1-core host).  --seconds only paces
            # the sender; --max_segments bounds the run.
            "--seconds", "120", "--rate_x", "2.0", "--log2n", "18",
            "--log2chan", "7", "--port", str(port),
            "--deadline_s", "120",
            "--max_segments", "6", "--rcvbuf_bytes", str(1 << 15),
            "--prefix", str(tmp_path) + f"/out{attempt}_",
            "--out", str(out)])
        assert rc == 0
        rec = json.loads(out.read_text().splitlines()[-1])
        assert rec["segments"] == 6
        # the offered load genuinely exceeded what was drained...
        assert rec["vs_realtime_window"] < rec["rate_x"]
        dropped = rec["metrics_http"].get("segments_dropped", 0)
        if rec["packets_lost"] > 0 or dropped > 0:
            break  # overload materialized and was accounted
    else:
        raise AssertionError(
            f"no accounted loss in {attempt + 1} overload runs: {rec}")
    # the excess is visible as ACCOUNTED loss, not a stall.  Two
    # sanctioned loss channels exist: kernel-buffer overflow surfacing
    # as udp counter-gap loss (packets_lost), or — when the ingest
    # thread keeps draining the socket faster than compute (the
    # Python-receiver fallback on recvmmsg-less sandboxes does) — the
    # overlap engine's DropOldestSegmentBuffer (segments_dropped).
    if rec["packets_lost"]:
        assert 0 < rec["loss_rate"] < 1
        assert rec["packets_total"] > rec["packets_lost"]


def test_trace_summary_wire_parser():
    """The hand-rolled xplane wire parser against a hand-built message:
    XSpace{planes=[XPlane{name, event_metadata{1: "fusion.1"},
    lines=[XLine{events=[XEvent{metadata_id=1, duration_ps=...}]}]}]}."""
    from srtb_tpu.tools import trace_summary as TS

    def varint(x):
        out = b""
        while True:
            b7 = x & 0x7F
            x >>= 7
            out += bytes([b7 | (0x80 if x else 0)])
            if not x:
                return out

    def ld(field, payload):
        return varint((field << 3) | 2) + varint(len(payload)) + payload

    def vi(field, value):
        return varint(field << 3) + varint(value)

    meta = vi(1, 1) + ld(2, b"fusion.1")          # XEventMetadata
    entry = vi(1, 1) + ld(2, meta)                # map entry key/value
    smeta = vi(1, 9) + ld(2, b"hlo_category")     # XStatMetadata
    sentry = vi(1, 9) + ld(2, smeta)
    stat = vi(1, 9) + ld(5, b"convolution")       # XStat.str_value
    ev1 = vi(1, 1) + vi(3, 5_000_000) + ld(4, stat)   # XEvent 5 us
    ev2 = vi(1, 1) + vi(3, 7_000_000) + ld(4, stat)   # XEvent 7 us
    line = ld(4, ev1) + ld(4, ev2)                # XLine.events
    plane = (ld(2, b"/device:TPU:0") + ld(3, line) + ld(4, entry)
             + ld(5, sentry))
    space = ld(1, plane)

    import pathlib
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        p = pathlib.Path(td) / "t.xplane.pb"
        p.write_bytes(space)
        planes = TS.parse_xspace(str(p))
        assert planes == [("/device:TPU:0",
                           {("fusion.1", "convolution"): 12_000_000})]
        s = TS.summarize(str(p))
        assert s[0]["plane"] == "/device:TPU:0"
        assert s[0]["total_ms"] == 0.012
        assert s[0]["top_ops"][0]["cat"] == "convolution"
    assert TS.bucket("fusion.fft.3") == "fft"
    assert TS.bucket("rfi_s1_dedisperse_df64") == "rfi+chirp"
    assert TS.bucket("loop_transpose_fusion") == "transpose/copy"
    # opaque fusion name + semantic category -> category decides
    assert TS.bucket("fusion.42", "fft") == "fft"
    assert TS.bucket("fusion.42", "elementwise") == "hlo:elementwise"
    # round-3 advisor: a semantic category OUTRANKS a broad name match
    # (this fused op carries "slice" in its name but is categorially a
    # convert); an opaque category still falls through to the name
    assert TS.bucket("fusion.slice.7", "convert") == "unpack+pack"
    assert TS.bucket("pass1_kernel.slice", "loop fusion") == "pallas_fft"


def test_plot_dm_curve(tmp_path):
    """The DM-search acceptance plot renders from a trials record."""
    import json

    from srtb_tpu.tools import plot_dm_curve as PD

    rec = {"segment": 0, "timestamp": 0, "best_dm": -478.8,
           "best_snr": 60.0, "dm_list": [-400.0, -478.8, -550.0],
           "peak_snr": [5.0, 60.0, 6.0], "signal_counts": [0, 9, 0],
           "zero_counts": [0, 0, 0]}
    trials = tmp_path / "out_dm_trials.jsonl"
    trials.write_text(json.dumps(rec) + "\n")
    out = PD.plot(str(trials))
    data = open(out, "rb").read()
    assert data[:8] == b"\x89PNG\r\n\x1a\n"


def test_queue_decisions(tmp_path):
    """The hardware queue's decision tree, evaluated from rows: FLIP
    when the data clears the documented bars, KEEP otherwise, and no
    crash on error rows / missing variants."""
    import json

    from srtb_tpu.tools import queue_decisions as QD

    rows = [
        {"variant": "pallas2_mosaic_probe_24", "rc": 0,
         "result": {"probe": "pallas2_mosaic", "ok": True}},
        {"variant": "pallas2_mosaic_probe_29", "rc": 0,
         "result": {"probe": "pallas2_mosaic", "ok": True}},
        {"variant": "baseline", "result": {"value": 1746.0,
                                           "segment_time_s": 0.0769}},
        {"variant": "pallas2", "result": {"value": 2500.0,
                                          "segment_time_s": 0.054}},
        {"variant": "n2_30_pallas2", "result": {"value": 900.0,
                                                "segment_time_s": 1.2}},
        {"variant": "pallas_sk", "result": {"value": 1500.0}},
        {"variant": "cache_warm", "result": {"compile_s": 4.0}},
        {"variant": "mxu_precision_probe_highest",
         "result": {"prec": "highest", "rel_err": 4e-7, "ms": 9.0}},
        {"variant": "mxu_precision_probe_high",
         "result": {"prec": "high", "rel_err": 1.1e-6, "ms": 4.4}},
        {"variant": "planes_unpack_mosaic_probe", "rc": 1, "result": None},
        {"variant": "note", "note": "irrelevant"},
    ]
    perf = tmp_path / "perf.jsonl"
    perf.write_text("".join(json.dumps(r) + "\n" for r in rows))
    out = tmp_path / "DECISIONS.md"
    rc = QD.main(["--perf", str(perf), "--out", str(out)])
    assert rc == 0
    decisions = {d["decision"]: d
                 for d in QD.evaluate(QD.load_rows(str(perf)))}
    assert decisions["pallas2 auto-default"]["verdict"] == "FLIP"
    assert decisions["2^30 default plan"]["verdict"] == "FLIP"
    assert "n2_30_pallas2" in decisions["2^30 default plan"]["evidence"]
    # (the dense-vs-classic rows-helper decision retired in round 5:
    # one legal Mosaic spelling remains, so no flip to evaluate)
    assert "pallas rows helper default" not in decisions
    assert decisions["PLANES_UNPACK_MOSAIC_OK"]["verdict"] == "KEEP False"
    assert decisions["warm restart"]["verdict"] == "MET"
    assert decisions["SRTB_MXU_PRECISION default"]["verdict"] \
        == "FLIP to high"
    text = out.read_text()
    assert "pallas2 auto-default" in text and "| FLIP |" in text
    # empty log -> explicit no-data row, rc still 0
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert QD.evaluate(QD.load_rows(str(empty)))[0]["verdict"] == "NO DATA"


def test_queue_decisions_failed_and_aot_rows(tmp_path):
    """Round-5 review hardening: AOT warm verdicts require the cache to
    have actually engaged (aot_active); a failed (0.0) bench row is
    present evidence, never a flip justification (the rows-helper A/B
    that exercised that rule is retired — one Mosaic spelling remains)."""
    import json

    from srtb_tpu.tools import queue_decisions as QD

    rows = [
        # a failed bench row must not create spurious decisions
        {"variant": "pallas_sk", "result": {"value": 0.0}},
        # aot_warm fast but the cache never engaged -> INVALID
        {"variant": "aot_warm", "result": {"compile_s": 1.0,
                                           "aot_active": False}},
        # aot_warm_30 engaged and fast -> MET
        {"variant": "aot_warm_30", "result": {"compile_s": 6.0,
                                              "aot_active": True}},
    ]
    perf = tmp_path / "perf.jsonl"
    perf.write_text("".join(json.dumps(r) + "\n" for r in rows))
    decisions = {d["decision"]: d
                 for d in QD.evaluate(QD.load_rows(str(perf)))}
    assert "pallas rows helper default" not in decisions
    assert decisions["AOT warm restart (2^27)"]["verdict"].startswith(
        "INVALID")
    assert decisions["AOT warm restart (2^30 staged)"]["verdict"] == "MET"


def test_pallas2_pin_loud_at_dispatch(monkeypatch):
    """An SRTB_PALLAS2_N1 pin that cannot fit the actual segment size
    must fail loudly at the dispatch fallback (ops/fft and the staged
    plan) instead of silently benchmarking the non-pallas2 path — while
    the unpinned tiny-config fallback stays quiet."""
    import jax.numpy as jnp
    import numpy as np
    import pytest

    from srtb_tpu.ops import fft as F

    z = jnp.asarray(np.zeros(1 << 13, np.complex64))
    # unpinned: quiet fallback (the documented tiny-config path)
    F._pallas2_or_fallback(z, "pallas2_interpret")
    monkeypatch.setenv("SRTB_PALLAS2_N1", "8192")
    with pytest.raises(ValueError, match="SRTB_PALLAS2_N1"):
        F._pallas2_or_fallback(z, "pallas2_interpret")


def test_waterfall_service_per_receiver_stream_id(tmp_path):
    """data_stream_id names the PANE for per-receiver (S=1) segments —
    it must not be used as an S index (found live: MultiUdpSource
    receiver 1 crashed the GUI tap on an S=1 waterfall)."""
    cfg = Config(gui_pixmap_width=16, gui_pixmap_height=8)
    svc = WaterfallService(cfg, in_freq=32, in_time=32,
                           out_dir=str(tmp_path))
    wf = np.random.default_rng(3).standard_normal(
        (2, 1, 32, 32)).astype(np.float32)   # [2, S=1, F, T]
    svc.push(wf, data_stream_id=1)           # receiver 1's segment
    path = svc.render_pending()
    assert path is not None and path.endswith("waterfall_s1_000000.png")
    # interleaved formats (S>1) still index by stream
    wf2 = np.random.default_rng(4).standard_normal(
        (2, 2, 32, 32)).astype(np.float32)
    svc.push(wf2, data_stream_id=1)
    assert svc.render_pending().endswith("waterfall_s1_000001.png")
