"""UDP ingest tests over loopback: both the native C++ recvmmsg receiver
and the pure-Python fallback, including packet loss (counter-gap zero-fill)
and reordering — the failure modes the reference handles
(ref: io/udp/udp_receiver.hpp:129-164, 242-265)."""

import socket
import struct
import threading
import time

import numpy as np
import pytest

from srtb_tpu.config import Config
from srtb_tpu.io import formats, udp


def _send_packets(port, fmt, counters, payload_fn, delay=0.0):
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    time.sleep(0.1)  # let the receiver bind
    for c in counters:
        if fmt.name.startswith("gznupsr"):
            header = bytearray(64)
            struct.pack_into("<2I", header, 24, c & 0xFFFFFFFF, c >> 32)
        else:
            header = struct.pack("<Q", c)
        sock.sendto(bytes(header) + payload_fn(c), ("127.0.0.1", port))
        if delay:
            time.sleep(delay)
    sock.close()


@pytest.mark.parametrize("impl", ["native", "python"])
def test_block_assembly_with_loss_and_reorder(impl):
    fmt = formats.FASTMB_ROACH2
    payload = fmt.payload_bytes  # 4096
    port = 42000 + (0 if impl == "native" else 1)
    if impl == "native" and not udp.native_available():
        pytest.skip("native recvmmsg receiver unavailable "
                    "(lib not built or syscall sandboxed)")
    cls = (udp.NativeBlockReceiver if impl == "native"
           else udp.PythonBlockReceiver)
    rx = cls("127.0.0.1", port, fmt)

    packets_per_block = 4
    # block 0: counters 0..3 with 2 lost, 1,3 swapped; next block starts at 4
    counters = [0, 3, 1, 4]

    def payload_fn(c):
        return bytes([c % 251]) * payload

    sender = threading.Thread(
        target=_send_packets, args=(port, fmt, counters, payload_fn))
    sender.start()
    out = np.zeros(packets_per_block * payload, dtype=np.uint8)
    first, lost, total = rx.receive_block(out)
    sender.join()
    rx.close()

    assert first == 0
    assert total == packets_per_block
    assert lost == 1  # counter 2 missing
    np.testing.assert_array_equal(out[:payload], 0)          # c=0 payload 0
    np.testing.assert_array_equal(out[payload:2 * payload], 1)
    np.testing.assert_array_equal(out[2 * payload:3 * payload], 0)  # lost
    np.testing.assert_array_equal(out[3 * payload:4 * payload], 3)


@pytest.mark.parametrize("impl", ["native", "python"])
def test_udp_source_yields_segment(impl):
    if impl == "native" and not udp.native_available():
        pytest.skip("native recvmmsg receiver unavailable "
                    "(lib not built or syscall sandboxed)")
    fmt = formats.FASTMB_ROACH2
    payload = fmt.payload_bytes
    port = 42010 + (0 if impl == "native" else 1)
    cfg = Config(
        baseband_input_count=payload * 2,  # 2 packets per segment, 8-bit
        baseband_input_bits=8,
        baseband_format_type="fastmb_roach2",
        udp_receiver_address=["127.0.0.1"],
        udp_receiver_port=[port],
    )
    src = udp.UdpReceiverSource(cfg, use_native=(impl == "native"))

    def payload_fn(c):
        return bytes([c + 10]) * payload

    sender = threading.Thread(
        target=_send_packets, args=(port, fmt, [7, 8, 9], payload_fn))
    sender.start()
    seg = next(src)
    sender.join()
    src.close()
    assert seg.udp_packet_counter == 7
    assert seg.data.shape == (payload * 2,)
    np.testing.assert_array_equal(seg.data[:payload], 17)
    np.testing.assert_array_equal(seg.data[payload:], 18)


def test_continuous_worker_straddles_block_boundaries():
    """Continuous worker (ref: continuous_udp_receiver_worker,
    udp_receiver.hpp:42-168): payloads split across successive blocks and
    the delivered stream stays byte-continuous."""
    fmt = formats.FASTMB_ROACH2
    payload = fmt.payload_bytes
    port = 42040
    rx = udp.PythonContinuousReceiver("127.0.0.1", port, fmt)

    def payload_fn(c):
        return bytes(range(c * 7, c * 7 + 7)) * (payload // 7) \
            + bytes([c]) * (payload % 7)

    sender = threading.Thread(
        target=_send_packets, args=(port, fmt, [0, 1, 2], payload_fn))
    sender.start()
    # two blocks of 1.5 payloads each: the middle packet straddles them
    half = payload // 2
    out1 = np.zeros(payload + half, dtype=np.uint8)
    out2 = np.zeros(payload + half, dtype=np.uint8)
    first1, lost1, seen1 = rx.receive_block(out1)
    first2, lost2, seen2 = rx.receive_block(out2)
    sender.join()
    rx.close()

    stream = np.concatenate([out1, out2])
    expect = np.frombuffer(payload_fn(0) + payload_fn(1) + payload_fn(2),
                           np.uint8)[:stream.size]
    np.testing.assert_array_equal(stream, expect)
    assert (first1, lost1, seen1) == (0, 0, 2)  # packets 0 and 1 pulled
    # block 2 opens with the carried-over tail of packet 1, so it is
    # labeled 1 (not 2, the first packet received during the call)
    assert (first2, lost2, seen2) == (1, 0, 1)


def test_continuous_worker_zero_fills_loss_inline():
    """A counter gap injects exactly lost*payload zeros at the gap
    position, carried across block boundaries."""
    fmt = formats.FASTMB_ROACH2
    payload = fmt.payload_bytes
    port = 42041
    rx = udp.PythonContinuousReceiver("127.0.0.1", port, fmt)

    def payload_fn(c):
        return bytes([c + 1]) * payload

    # counters 0, 3: packets 1 and 2 lost -> 2*payload zeros in between
    sender = threading.Thread(
        target=_send_packets, args=(port, fmt, [0, 3, 4], payload_fn))
    sender.start()
    out1 = np.zeros(2 * payload, dtype=np.uint8)
    out2 = np.zeros(2 * payload, dtype=np.uint8)
    first1, lost1, _ = rx.receive_block(out1)
    first2, lost2, _ = rx.receive_block(out2)
    sender.join()
    rx.close()

    assert (first1, lost1) == (0, 2)
    np.testing.assert_array_equal(out1[:payload], 1)       # c=0
    np.testing.assert_array_equal(out1[payload:], 0)       # lost c=1
    np.testing.assert_array_equal(out2[:payload], 0)       # lost c=2
    np.testing.assert_array_equal(out2[payload:], 4)       # c=3
    assert lost2 == 0  # the gap was already accounted in call 1
    assert rx.lost_packets == 2


def test_continuous_worker_drops_late_packets():
    """Late/duplicate counters are dropped (guarded deviation from the
    reference's unsigned underflow)."""
    fmt = formats.FASTMB_ROACH2
    payload = fmt.payload_bytes
    port = 42042
    rx = udp.PythonContinuousReceiver("127.0.0.1", port, fmt)

    def payload_fn(c):
        return bytes([c + 1]) * payload

    sender = threading.Thread(
        target=_send_packets, args=(port, fmt, [5, 4, 5, 6], payload_fn))
    sender.start()
    out = np.zeros(2 * payload, dtype=np.uint8)
    first, lost, seen = rx.receive_block(out)
    sender.join()
    rx.close()
    assert (first, lost, seen) == (5, 0, 2)
    np.testing.assert_array_equal(out[:payload], 6)   # c=5
    np.testing.assert_array_equal(out[payload:], 7)   # c=6


def test_udp_source_continuous_mode():
    """udp_receiver_mode=continuous end to end through UdpReceiverSource,
    with a segment size that is NOT a payload multiple."""
    fmt = formats.FASTMB_ROACH2
    payload = fmt.payload_bytes
    port = 42043
    cfg = Config(
        baseband_input_count=payload + payload // 2,  # 1.5 packets, 8-bit
        baseband_input_bits=8,
        baseband_format_type="fastmb_roach2",
        udp_receiver_address=["127.0.0.1"],
        udp_receiver_port=[port],
        udp_receiver_mode="continuous",
    )
    src = udp.UdpReceiverSource(cfg)
    assert isinstance(src.receiver, udp.PythonContinuousReceiver)

    def payload_fn(c):
        return bytes([c + 20]) * payload

    sender = threading.Thread(
        target=_send_packets, args=(port, fmt, [0, 1, 2], payload_fn))
    sender.start()
    seg1 = next(src)
    seg2 = next(src)
    sender.join()
    src.close()
    assert seg1.udp_packet_counter == 0
    assert seg2.udp_packet_counter == 1  # opens with packet 1's tail
    half = payload // 2
    np.testing.assert_array_equal(seg1.data[:payload], 20)
    np.testing.assert_array_equal(seg1.data[payload:], 21)
    np.testing.assert_array_equal(seg2.data[:half], 21)   # straddled tail
    np.testing.assert_array_equal(seg2.data[half:half + payload], 22)


@pytest.mark.parametrize("impl", ["default", "packet_ring"])
def test_ingest_sustains_realtime_rate(impl):
    """Loopback soak at 2x the J1644-4559 wire rate (0.512 Gbps of
    payload) must be loss-free — the regression gate for the measured
    ingest ceiling recorded in PERF.md."""
    from srtb_tpu.tools.udp_soak import run_soak, REQUIRED_GBPS
    if impl == "default":
        impl = "native" if udp.native_available() else "python"
        port = 42150
    else:
        if udp._NATIVE is None:
            pytest.skip("native lib not built")
        port = 42152
        try:
            probe = udp.PacketRingReceiver("", 42199, formats.FASTMB_ROACH2)
            probe.close()
        except OSError:
            pytest.skip("AF_PACKET ring unavailable (needs CAP_NET_RAW)")
    res = run_soak(n_packets=8000, impl=impl, port=port,
                   pace_gbps=2 * REQUIRED_GBPS)
    assert res["lost"] == 0, res
    assert res["gbps"] >= 1.5 * REQUIRED_GBPS, res


def test_ingest_ceiling_exceeds_requirement():
    """Unpaced blast: the receiver's goodput ceiling must clear the
    0.256 Gbps real-time requirement with a wide margin (loss against a
    full-speed sender is expected and must be accounted, not hidden)."""
    from srtb_tpu.tools.udp_soak import run_soak, REQUIRED_GBPS
    impl = "native" if udp.native_available() else "python"
    res = run_soak(n_packets=8000, impl=impl, port=42151)
    assert res["gbps"] > 2 * REQUIRED_GBPS, res
    # loss accounting is self-consistent
    assert res["received"] + res["lost"] >= 0.9 * 8000 or \
        res["loss_rate"] >= 0, res


def test_vdif_counter_roundtrip():
    buf = bytearray(64)
    c = (123 << 32) | 456
    struct.pack_into("<2I", buf, 24, c & 0xFFFFFFFF, c >> 32)
    hdr = formats.parse_vdif_header(bytes(buf[:32]))
    counter, _ = formats.GZNUPSR_A1.parse_packet(bytes(buf))
    assert counter == c
    assert hdr.extended_user_data_3 == c & 0xFFFFFFFF


def test_gznupsr_block_assembly():
    """VDIF-headed gznupsr_a1 packets through the Python receiver."""
    fmt = formats.GZNUPSR_A1
    payload = fmt.payload_bytes  # 8192
    port = 42030
    rx = udp.PythonBlockReceiver("127.0.0.1", port, fmt)

    def payload_fn(c):
        return bytes([c % 100]) * payload

    sender = threading.Thread(
        target=_send_packets, args=(port, fmt, [5, 6, 7], payload_fn))
    sender.start()
    out = np.zeros(2 * payload, dtype=np.uint8)
    first, lost, total = rx.receive_block(out)
    sender.join()
    rx.close()
    assert (first, lost, total) == (5, 0, 2)
    np.testing.assert_array_equal(out[:payload], 5)
    np.testing.assert_array_equal(out[payload:], 6)


# ----------------------------------------------------------------
# AF_PACKET TPACKET_V3 ring provider (native/packet_ring.cpp)
# ----------------------------------------------------------------

def _make_ring(fmt, port):
    if udp._NATIVE is None:
        pytest.skip("native lib not built")
    try:
        return udp.PacketRingReceiver("", port, fmt, interface="lo")
    except OSError:
        pytest.skip("AF_PACKET ring unavailable (needs CAP_NET_RAW)")


def test_packet_ring_block_assembly_with_loss_and_reorder():
    """Mirror of the recvmmsg block case on the TPACKET_V3 ring: loss is
    zero-filled and accounted, reordering within a block is tolerated,
    and loopback's duplicate (outgoing) copies are filtered out."""
    fmt = formats.FASTMB_ROACH2
    payload = fmt.payload_bytes
    port = 42030
    rx = _make_ring(fmt, port)

    packets_per_block = 4
    counters = [0, 3, 1, 4]

    def payload_fn(c):
        return bytes([c % 251]) * payload

    sender = threading.Thread(
        target=_send_packets, args=(port, fmt, counters, payload_fn))
    sender.start()
    out = np.zeros(packets_per_block * payload, dtype=np.uint8)
    first, lost, total = rx.receive_block(out)
    sender.join()

    assert first == 0
    assert total == packets_per_block
    assert lost == 1  # counter 2 missing
    np.testing.assert_array_equal(out[:payload], 0)
    np.testing.assert_array_equal(out[payload:2 * payload], 1)
    np.testing.assert_array_equal(out[2 * payload:3 * payload], 0)  # lost
    np.testing.assert_array_equal(out[3 * payload:4 * payload], 3)

    # the overflow packet (counter 4) must open the next block
    sender2 = threading.Thread(
        target=_send_packets, args=(port, fmt, [5, 6, 7], payload_fn))
    sender2.start()
    out2 = np.zeros(packets_per_block * payload, dtype=np.uint8)
    first2, lost2, total2 = rx.receive_block(out2)
    sender2.join()
    rx.close()
    assert first2 == 4
    assert lost2 == 0
    np.testing.assert_array_equal(out2[:payload], 4)
    np.testing.assert_array_equal(out2[3 * payload:], 7)


def test_packet_ring_filters_foreign_traffic():
    """Datagrams to a different port or of the wrong size must not
    disturb block assembly (the ring sees every packet on the interface,
    so the port/size filter is load-bearing, not cosmetic)."""
    fmt = formats.FASTMB_ROACH2
    payload = fmt.payload_bytes
    port = 42031
    rx = _make_ring(fmt, port)

    def payload_fn(c):
        return bytes([c % 251]) * payload

    def send_mixed():
        noise = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        time.sleep(0.1)
        # wrong port, and wrong-size datagram to the right port
        noise.sendto(b"x" * 100, ("127.0.0.1", port + 1))
        noise.sendto(b"y" * 32, ("127.0.0.1", port))
        noise.close()
        _send_packets(port, fmt, [10, 11], payload_fn)

    sender = threading.Thread(target=send_mixed)
    sender.start()
    out = np.zeros(2 * payload, dtype=np.uint8)
    first, lost, total = rx.receive_block(out)
    sender.join()
    rx.close()
    assert (first, lost, total) == (10, 0, 2)
    np.testing.assert_array_equal(out[:payload], 10 % 251)
    np.testing.assert_array_equal(out[payload:], 11 % 251)


def test_udp_source_packet_ring_provider():
    """Config-level selection: udp_packet_provider=packet_ring yields
    segments through UdpReceiverSource like the recvmmsg provider."""
    if udp._NATIVE is None:
        pytest.skip("native lib not built")
    fmt = formats.FASTMB_ROACH2
    payload = fmt.payload_bytes
    port = 42032
    cfg = Config(
        baseband_input_count=payload * 2,
        baseband_input_bits=8,
        baseband_format_type="fastmb_roach2",
        udp_receiver_address=["127.0.0.1"],
        udp_receiver_port=[port],
        udp_packet_provider="packet_ring",
        udp_packet_ring_interface="lo",
        baseband_reserve_sample=False,
    )
    try:
        src = udp.UdpReceiverSource(cfg)
    except OSError:
        pytest.skip("AF_PACKET ring unavailable (needs CAP_NET_RAW)")
    assert isinstance(src.receiver, udp.PacketRingReceiver)

    def payload_fn(c):
        return bytes([c % 251]) * payload

    sender = threading.Thread(
        target=_send_packets, args=(port, fmt, [0, 1], payload_fn))
    sender.start()
    seg = next(src)
    sender.join()
    src.close()
    assert seg.udp_packet_counter == 0
    np.testing.assert_array_equal(seg.data[:payload], 0)
    np.testing.assert_array_equal(seg.data[payload:], 1)


def test_incompatible_provider_combos_are_refused():
    """Explicitly configured but contradictory provider combinations must
    error, not silently downgrade to a lossier receiver."""
    fmt_kwargs = dict(
        baseband_input_count=formats.FASTMB_ROACH2.payload_bytes,
        baseband_input_bits=8,
        baseband_format_type="fastmb_roach2",
        udp_receiver_address=["127.0.0.1"],
        udp_receiver_port=[42198],
        baseband_reserve_sample=False,
    )
    with pytest.raises(ValueError, match="packet_ring"):
        udp.UdpReceiverSource(Config(udp_receiver_mode="continuous",
                                     udp_packet_provider="packet_ring",
                                     **fmt_kwargs))
    with pytest.raises(ValueError, match="recvfrom"):
        udp.UdpReceiverSource(Config(udp_packet_provider="recvfrom",
                                     **fmt_kwargs), use_native=True)


# ----------------------------------------------------------------
# asyncio event-loop provider (the boost::asio analog)
# ----------------------------------------------------------------

def test_asyncio_block_assembly_with_loss():
    """The asyncio provider must assemble blocks (and zero-fill counter
    gaps) exactly like the plain recvfrom provider — same worker, other
    transport (ref: io/udp/asio_udp_packet_provider.hpp:1-66)."""
    fmt = formats.FASTMB_ROACH2
    payload = fmt.payload_bytes
    port = 42033
    rx = udp.AsyncioBlockReceiver("127.0.0.1", port, fmt)

    def payload_fn(c):
        return bytes([c % 100]) * payload

    # drop counter 2: receive_block must zero-fill its slot
    sender = threading.Thread(
        target=_send_packets, args=(port, fmt, [1, 3, 4], payload_fn))
    sender.start()
    out = np.zeros(3 * payload, dtype=np.uint8)
    first, lost, total = rx.receive_block(out)
    sender.join()
    rx.close()
    assert (first, lost, total) == (1, 1, 3)
    np.testing.assert_array_equal(out[:payload], 1)
    np.testing.assert_array_equal(out[payload:2 * payload], 0)
    np.testing.assert_array_equal(out[2 * payload:], 3)


def test_asyncio_provider_selection_and_refusals():
    fmt_kwargs = dict(
        baseband_input_count=formats.FASTMB_ROACH2.payload_bytes,
        baseband_input_bits=8,
        baseband_format_type="fastmb_roach2",
        udp_receiver_address=["127.0.0.1"],
        udp_receiver_port=[42034],
        baseband_reserve_sample=False,
    )
    src = udp.UdpReceiverSource(Config(udp_packet_provider="asyncio",
                                       **fmt_kwargs))
    try:
        assert isinstance(src.receiver, udp.AsyncioBlockReceiver)
    finally:
        src.close()
    with pytest.raises(ValueError, match="asyncio"):
        udp.UdpReceiverSource(Config(udp_receiver_mode="continuous",
                                     udp_packet_provider="asyncio",
                                     **fmt_kwargs))
    with pytest.raises(ValueError, match="asyncio"):
        udp.UdpReceiverSource(Config(udp_packet_provider="asyncio",
                                     **fmt_kwargs), use_native=True)


@pytest.mark.parametrize("impl", ["native", "python"])
def test_block_assembly_duplicate_counter_accounting(impl):
    """A duplicated packet counter must not inflate the fill count: the
    round-3 fuzz found duplicates closing the block early with a
    silently-zeroed slot and lost=0 — in all three assemblers.  Now the
    dup overwrites its slot (idempotent) and the block completes only
    when every distinct slot fills; a dup alongside a real gap still
    reports the loss."""
    if impl == "native" and not udp.native_available():
        pytest.skip("native recvmmsg receiver unavailable "
                    "(lib not built or syscall sandboxed)")
    fmt = formats.FASTMB_ROACH2
    payload = fmt.payload_bytes
    cls = (udp.NativeBlockReceiver if impl == "native"
           else udp.PythonBlockReceiver)

    def payload_fn(c):
        return bytes([c % 251]) * payload

    def run_case(counters, port):
        rx = cls("127.0.0.1", port, fmt)
        sender = threading.Thread(
            target=_send_packets,
            args=(port, fmt, counters, payload_fn, 0.001))
        sender.start()
        buf = np.zeros(4 * payload, dtype=np.uint8)
        try:
            first, lost, total = rx.receive_block(buf)
        finally:
            sender.join(timeout=5)
            rx.close()
        return first, lost, [int(buf[i * payload]) for i in range(4)]

    base = 42190 + (0 if impl == "native" else 4)
    # dup only: all four slots fill, no loss, no zeroed slot
    first, lost, heads = run_case([0, 1, 1, 2, 3], port=base)
    assert (first, lost, heads) == (0, 0, [0, 1, 2, 3])
    # dup + real gap (slot 2 missing): the loss must be reported
    first, lost, heads = run_case([0, 1, 1, 3, 4], port=base + 1)
    assert (first, lost) == (0, 1)
    assert heads == [0, 1, 0, 3]
