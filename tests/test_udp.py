"""UDP ingest tests over loopback: both the native C++ recvmmsg receiver
and the pure-Python fallback, including packet loss (counter-gap zero-fill)
and reordering — the failure modes the reference handles
(ref: io/udp/udp_receiver.hpp:129-164, 242-265)."""

import socket
import struct
import threading
import time

import numpy as np
import pytest

from srtb_tpu.config import Config
from srtb_tpu.io import formats, udp


def _send_packets(port, fmt, counters, payload_fn, delay=0.0):
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    time.sleep(0.1)  # let the receiver bind
    for c in counters:
        if fmt.name.startswith("gznupsr"):
            header = bytearray(64)
            struct.pack_into("<2I", header, 24, c & 0xFFFFFFFF, c >> 32)
        else:
            header = struct.pack("<Q", c)
        sock.sendto(bytes(header) + payload_fn(c), ("127.0.0.1", port))
        if delay:
            time.sleep(delay)
    sock.close()


@pytest.mark.parametrize("impl", ["native", "python"])
def test_block_assembly_with_loss_and_reorder(impl):
    fmt = formats.FASTMB_ROACH2
    payload = fmt.payload_bytes  # 4096
    port = 42000 + (0 if impl == "native" else 1)
    if impl == "native" and udp._NATIVE is None:
        pytest.skip("native lib not built")
    cls = (udp.NativeBlockReceiver if impl == "native"
           else udp.PythonBlockReceiver)
    rx = cls("127.0.0.1", port, fmt)

    packets_per_block = 4
    # block 0: counters 0..3 with 2 lost, 1,3 swapped; next block starts at 4
    counters = [0, 3, 1, 4]

    def payload_fn(c):
        return bytes([c % 251]) * payload

    sender = threading.Thread(
        target=_send_packets, args=(port, fmt, counters, payload_fn))
    sender.start()
    out = np.zeros(packets_per_block * payload, dtype=np.uint8)
    first, lost, total = rx.receive_block(out)
    sender.join()
    rx.close()

    assert first == 0
    assert total == packets_per_block
    assert lost == 1  # counter 2 missing
    np.testing.assert_array_equal(out[:payload], 0)          # c=0 payload 0
    np.testing.assert_array_equal(out[payload:2 * payload], 1)
    np.testing.assert_array_equal(out[2 * payload:3 * payload], 0)  # lost
    np.testing.assert_array_equal(out[3 * payload:4 * payload], 3)


@pytest.mark.parametrize("impl", ["native", "python"])
def test_udp_source_yields_segment(impl):
    if impl == "native" and udp._NATIVE is None:
        pytest.skip("native lib not built")
    fmt = formats.FASTMB_ROACH2
    payload = fmt.payload_bytes
    port = 42010 + (0 if impl == "native" else 1)
    cfg = Config(
        baseband_input_count=payload * 2,  # 2 packets per segment, 8-bit
        baseband_input_bits=8,
        baseband_format_type="fastmb_roach2",
        udp_receiver_address=["127.0.0.1"],
        udp_receiver_port=[port],
    )
    src = udp.UdpReceiverSource(cfg, use_native=(impl == "native"))

    def payload_fn(c):
        return bytes([c + 10]) * payload

    sender = threading.Thread(
        target=_send_packets, args=(port, fmt, [7, 8, 9], payload_fn))
    sender.start()
    seg = next(src)
    sender.join()
    src.close()
    assert seg.udp_packet_counter == 7
    assert seg.data.shape == (payload * 2,)
    np.testing.assert_array_equal(seg.data[:payload], 17)
    np.testing.assert_array_equal(seg.data[payload:], 18)


def test_vdif_counter_roundtrip():
    buf = bytearray(64)
    c = (123 << 32) | 456
    struct.pack_into("<2I", buf, 24, c & 0xFFFFFFFF, c >> 32)
    hdr = formats.parse_vdif_header(bytes(buf[:32]))
    counter, _ = formats.GZNUPSR_A1.parse_packet(bytes(buf))
    assert counter == c
    assert hdr.extended_user_data_3 == c & 0xFFFFFFFF


def test_gznupsr_block_assembly():
    """VDIF-headed gznupsr_a1 packets through the Python receiver."""
    fmt = formats.GZNUPSR_A1
    payload = fmt.payload_bytes  # 8192
    port = 42030
    rx = udp.PythonBlockReceiver("127.0.0.1", port, fmt)

    def payload_fn(c):
        return bytes([c % 100]) * payload

    sender = threading.Thread(
        target=_send_packets, args=(port, fmt, [5, 6, 7], payload_fn))
    sender.start()
    out = np.zeros(2 * payload, dtype=np.uint8)
    first, lost, total = rx.receive_block(out)
    sender.join()
    rx.close()
    assert (first, lost, total) == (5, 0, 2)
    np.testing.assert_array_equal(out[:payload], 5)
    np.testing.assert_array_equal(out[payload:], 6)
